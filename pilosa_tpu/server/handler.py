"""HTTP handler: the public + internal REST surface (reference
http/handler.go:276-314 route table).

Wraps only the API façade, like the reference (handler.go:60 Handler wraps
*pilosa.API).  stdlib ThreadingHTTPServer + a regex route table replaces
gorilla/mux; JSON replaces protobuf on the public surface (the reference
already speaks JSON for DDL and query responses; bulk imports also accept
the pilosa-roaring binary format for compatibility).
"""

from __future__ import annotations

import json
import re
import time
import traceback

import numpy as np
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from urllib.parse import parse_qs, urlparse

from .. import __version__
from ..api import (
    API, ApiError, ConflictError, DisallowedError, NotFoundError,
    UnsupportedMediaTypeError,
)
from ..storage.fragment import FragmentQuarantinedError
from ..utils import degraded
from ..utils import explain as qexplain
from ..utils.locks import make_lock
from ..utils import profile as qprof
from ..utils import tenant as qtenant
from ..utils.deadline import (DEADLINE_HEADER, DeadlineExceeded,
                              QueryContext, activate)
from ..utils.tracing import (GLOBAL_TRACER, PROBE_HEADER, TRACE_HEADER,
                             parse_trace_header)
from ..executor import RowResult, ValCount, RowIdentifiers
from ..executor.results import GroupCount, Pair
from .admission import AdmissionRejected, decorrelated_retry_after


def _ingest_retry_after(req) -> float:
    """Computed Retry-After for ingest-side 503s: the ingest pool's
    pressure-scaled, jittered backoff (a fixed constant re-stampedes a
    synchronized client cohort); bare test handlers without a pool still
    get the jitter."""
    adm = getattr(req, "admission_ingest", None)
    if adm is not None:
        return adm.retry_after()
    return decorrelated_retry_after(1.0)


def serialize_result(r) -> object:
    """Query result -> JSON-able (reference http/response.go)."""
    if isinstance(r, RowResult):
        return r.to_dict()
    if isinstance(r, ValCount):
        return r.to_dict()
    if isinstance(r, RowIdentifiers):
        return r.to_dict()
    if isinstance(r, list):
        if r and isinstance(r[0], Pair):
            return [p.to_dict() for p in r]
        if r and isinstance(r[0], GroupCount):
            return [g.to_dict() for g in r]
        return [serialize_result(x) for x in r]
    return r


from contextlib import nullcontext as _nullcontext

_NULL_CTX = _nullcontext()


def _profile_shards(node: dict):
    """Best-effort shard count from a profile tree: the first stage
    tagged with one (the executor's dispatch stage, or a fan-out peer
    event on the coordinator)."""
    tags = node.get("tags") or {}
    if "shards" in tags:
        return tags["shards"]
    for c in node.get("children", ()):
        n = _profile_shards(c)
        if n is not None:
            return n
    return None


class ClientAbort(Exception):
    """The client went away mid-response (broken pipe / reset while
    writing).  Expected serving noise, not a server error: counted as
    ``http.client_abort`` and the connection is dropped quietly instead
    of spewing a traceback per disconnect (the BENCH_r05 run log was full
    of them from load-generator teardown)."""


class Router:
    """Method+regex route table.

    ``gate`` marks routes that run query execution and therefore pass
    admission control: "query" rides the public slot pool, "internal"
    rides the separate node-to-node pool (a coordinator holding a public
    slot fans out to peers whose internal handling must never queue
    behind their public traffic — otherwise concurrent coordinators
    could deadlock the cluster against itself); "ingest" rides a third
    pool so sustained writes can never starve reads of their slots
    (docs/ingest.md).

    ``stream`` routes read their body incrementally off the socket
    themselves (``req.rfile`` + ``req._stream_len``) — the handler never
    buffers it, so a multi-GB ingest stream costs one frame of memory."""

    def __init__(self):
        self.routes: list[tuple] = []

    def add(self, method: str, pattern: str, fn, gate: str | None = None,
            stream: bool = False):
        rx = re.compile("^" + re.sub(
            r"\{(\w+)\}", r"(?P<\1>[^/]+)", pattern) + "$")
        self.routes.append((method, rx, fn, gate, stream))

    def match(self, method: str, path: str):
        found_path = False
        for m, rx, fn, gate, stream in self.routes:
            mt = rx.match(path)
            if mt:
                found_path = True
                if m == method:
                    return fn, mt.groupdict(), gate, stream
        return ("method_not_allowed" if found_path else None), {}, \
            None, False


def build_debug_vars(api: API, server=None) -> dict:
    """The /debug/vars snapshot body — module-level so the fleet rollup
    (parallel/rollup.py) builds the LOCAL node's summary from exactly
    the surface peers serve over the wire (golden agreement between
    /debug/cluster and per-node /debug/vars is by construction)."""
    from ..storage.membudget import DEFAULT_BUDGET, HOST_STAGE_BUDGET
    out = api.stats.snapshot()
    # deviceBudget carries the streaming-pipeline counters too:
    # uploadBytes / prefetchHits / prefetchMisses / pinnedBytes
    out["deviceBudget"] = DEFAULT_BUDGET.stats()
    out["hostStage"] = HOST_STAGE_BUDGET.stats()
    ex = api.executor
    if ex.result_cache is not None:
        out["resultCache"] = ex.result_cache.snapshot()
    if ex.prepared is not None:
        out["preparedCache"] = {
            "entries": len(ex.prepared._entries),
            "hits": ex.prepared.hits,
            "misses": ex.prepared.misses,
            "guardMisses": ex.prepared.guard_misses,
        }
    if ex.mesh_exec is not None:
        out["stackCache"] = {
            "entries": len(ex.mesh_exec._stack_cache),
            "executables": len(ex.mesh_exec._cache),
        }
    # cross-query dynamic batching (docs/batching.md): fused/single
    # launch counters, the batch-size histogram, and the queue-wait
    # p50/p99 — the knobs' feedback loop for tuning window/max
    if ex.batcher is not None:
        out["dispatchBatcher"] = ex.batcher.snapshot()
    # whole-query pjit programs (docs/whole-query.md): requests
    # served as one program vs fallbacks to the legacy per-stage
    # path, with the last fallback's unsupported-node name
    if ex.wholequery is not None:
        out["wholeQuery"] = {
            "enabled": ex.whole_query,
            "requests": ex.wq_requests,
            "fallbacks": ex.wq_fallbacks,
            "lastFallback": ex.wq_last_fallback,
        }
    # overload armor: slot/queue state, per-peer breaker state, armed
    # failpoints (docs/robustness.md); deadline-abort and admission
    # rejection COUNTERS live in "counts" via the stats client
    if server is not None and getattr(server, "admission",
                                      None) is not None:
        out["admission"] = {
            "public": server.admission.snapshot(),
            "internal": server.admission_internal.snapshot(),
        }
    # tenant isolation plane (docs/robustness.md "Tenant isolation"):
    # per-tenant qps/p50/p99/shed/hedge-denied/quota columns — the
    # registry is process-wide, so bare-API servers report it too
    tenants = qtenant.REGISTRY.snapshot()
    if tenants:
        out["tenants"] = tenants
    if server is not None and getattr(server, "cluster",
                                      None) is not None:
        out["breakers"] = server.cluster.client.breaker_snapshot()
        # elastic serving (docs/cluster.md "Read routing &
        # rebalancing"): per-peer routing state (EWMA RTT, in-flight,
        # residency summary age, breaker state), the placement
        # overlay, and the balancer's hot-shard view
        cl = server.cluster
        out["cluster"] = {
            "routing": cl.router.snapshot(),
            "overlay": cl.overlay_snapshot(),
            "balancer": cl.balancer.snapshot(),
        }
    from ..utils.faults import FAULTS
    armed = FAULTS.snapshot()
    if armed:
        out["failpoints"] = armed
    slog = getattr(server, "slowlog", None) if server is not None \
        else None
    if slog is not None:
        out["slowLog"] = {"thresholdS": slog.threshold_s,
                          "size": slog.size,
                          "textMax": slog.text_max,
                          "recorded": slog.recorded}
    # event journal (docs/observability.md "Cluster plane"): counters
    # only — the timeline itself is /debug/events
    from ..utils.events import EVENTS
    out["events"] = {"seq": EVENTS.last_seq(), "emitted": EVENTS.emitted,
                     "writeErrors": EVENTS.write_errors}
    # durability & recovery (docs/robustness.md): quarantine state,
    # torn-tail/repair event counters, anti-entropy health
    from ..storage.fragment import storage_events
    container_stats = api.holder.container_stats()
    out["storage"] = {
        "events": storage_events(),
        "quarantined": api.holder.quarantined_fragments(),
        "corruptAttrStores": api.holder.corrupt_attr_stores(),
        # compressed residency (docs/memory-budget.md): per-holder
        # container-type histogram + device-form census; the
        # compressed/dense byte split rides deviceBudget above
        "containers": container_stats,
    }
    if server is not None:
        server.update_storage_gauges(container_stats=container_stats)
        if getattr(server, "cluster", None) is not None:
            out["storage"]["antiEntropy"] = server.cluster.ae_snapshot()
    # device runtime (docs/observability.md "Device runtime"):
    # compile-registry + launch-ledger aggregates and the
    # time-series summary; full detail at /debug/compiles,
    # /debug/launches, /debug/timeseries
    from ..utils import devobs
    out["device"] = {"compiles": devobs.COMPILES.totals(),
                     "launches": devobs.LEDGER.aggregates()}
    # warm start (docs/warmup.md): phase, replay progress, and the
    # compile-seconds-saved headline for the deploy dashboard
    warm = getattr(server, "warmup", None) if server is not None else None
    if warm is not None:
        out["warmup"] = warm.status()
    # streaming ingest (docs/ingest.md): group-commit backlog, flush
    # counters, and the delta-overlay journal footprint
    committer = getattr(server, "committer", None) \
        if server is not None else None
    if committer is not None:
        out["ingest"] = committer.snapshot()
    ts = getattr(server, "timeseries", None) if server is not None \
        else None
    if ts is not None:
        snap_ts = ts.snapshot()
        out["timeseries"] = {
            k: snap_ts[k] for k in ("intervalS", "windowS",
                                    "capacity", "samplesTotal",
                                    "coveredS")}
    # SLOs & alerting (docs/observability.md): the compact active-alert
    # table — folded into /debug/cluster per node by the fleet rollup;
    # the full lifecycle view is /debug/alerts
    slo_eng = getattr(server, "slo", None) if server is not None \
        else None
    if slo_eng is not None:
        out["alerts"] = slo_eng.vars_summary()
    flightrec = getattr(server, "flightrec", None) if server is not None \
        else None
    if flightrec is not None:
        out["flightRecorder"] = flightrec.snapshot()
    return out


def build_router(api: API, server=None) -> Router:
    r = Router()

    # -- public (handler.go:276-300) --------------------------------------
    def home(req, args):
        return {"message": "pilosa-tpu " + __version__}

    r.add("GET", "/", home)
    r.add("GET", "/version", lambda req, a: {"version": api.version()})
    r.add("GET", "/info", lambda req, a: api.info())
    r.add("GET", "/status", lambda req, a: api.status())
    r.add("GET", "/schema", lambda req, a: {"indexes": api.schema()})

    def post_schema(req, args):
        api.apply_schema(req.json().get("indexes", []))
        return {}

    r.add("POST", "/schema", post_schema)

    def get_indexes(req, args):
        return {"indexes": api.schema()}

    r.add("GET", "/index", get_indexes)

    def get_index(req, args):
        for idx in api.schema():
            if idx["name"] == args["index"]:
                return idx
        raise NotFoundError(f"index not found: {args['index']}")

    r.add("GET", "/index/{index}", get_index)

    def post_index(req, args):
        body = req.json()
        opts = body.get("options", {})
        api.create_index(args["index"], keys=opts.get("keys", False),
                         track_existence=opts.get("trackExistence", True))
        return {}

    r.add("POST", "/index/{index}", post_index)

    def delete_index(req, args):
        api.delete_index(args["index"])
        return {}

    r.add("DELETE", "/index/{index}", delete_index)

    def post_field(req, args):
        body = req.json()
        api.create_field(args["index"], args["field"],
                         body.get("options", {}))
        return {}

    r.add("POST", "/index/{index}/field/{field}", post_field)

    def delete_field(req, args):
        api.delete_field(args["index"], args["field"])
        return {}

    r.add("DELETE", "/index/{index}/field/{field}", delete_field)

    def post_query(req, args):
        query = req.body.decode()
        shards = None
        if "shards" in req.query:
            shards = [int(s) for s in req.query["shards"][0].split(",")]
        # Partial-results opt-in (docs/robustness.md "Partial
        # results"): ?partialResults=true (or the partial-results
        # server default) lets a READ succeed when shards are truly
        # unservable — the degraded object below then names exactly the
        # missing shards, so partial can never masquerade as complete.
        # the per-request parameter wins in BOTH directions: an
        # explicit ?partialResults=false demands the loud failure even
        # on a partial-results=true deployment
        pq = req.query.get("partialResults", [None])[0]
        partial = (pq == "true") if pq is not None else req.partial_results
        # Degraded-state collection (utils/degraded.py): quarantined
        # fragments answer as EMPTY — the response must say so.  The
        # coordinator notes peer-reported counts during fan-out; the
        # local holder's count is added here.
        with degraded.collect(allow_partial=partial) as deg:
            results = api.query(args["index"], query, shards)
            degraded.note(
                len(api.holder.quarantined_fragments(args["index"])))
        out = {"results": [serialize_result(x) for x in results]}
        deg_out = degraded.to_response(deg)
        if deg_out is not None:
            out["degraded"] = deg_out
        # top-level ColumnAttrSets, deduplicated by column id across the
        # query's calls like the reference's single set
        # (http/response.go QueryResponse)
        col_attrs: dict = {}
        for r in results:
            for a in getattr(r, "column_attrs", []):
                col_attrs.setdefault(a.get("id"), a)
        if col_attrs:
            out["columnAttrs"] = list(col_attrs.values())
        return out

    r.add("POST", "/index/{index}/query", post_query, gate="query")

    def post_import(req, args):
        body = req.json()
        if "values" in body or (body.get("clear")
                                and "rowIDs" not in body
                                and "rowKeys" not in body):
            api.import_values(args["index"], args["field"],
                              body.get("columnIDs"), body.get("values"),
                              clear=body.get("clear", False),
                              column_keys=body.get("columnKeys"))
        else:
            api.import_bits(args["index"], args["field"],
                            body.get("rowIDs"), body.get("columnIDs"),
                            body.get("timestamps"),
                            clear=body.get("clear", False),
                            row_keys=body.get("rowKeys"),
                            column_keys=body.get("columnKeys"))
        return {}

    r.add("POST", "/index/{index}/field/{field}/import", post_import)

    def post_import_roaring(req, args):
        clear = req.query.get("clear", ["false"])[0] == "true"
        ctype = req.headers.get("Content-Type", "")
        # Content-Type sniff: the base64-JSON envelope stays for
        # compatibility, but a raw roaring body (it can never start with
        # "{" — the roaring cookie's low byte is 0x3A..0x3C) is imported
        # directly even under a lying JSON header, so no client is ever
        # forced through the 4/3 base64 blowup + JSON parse.
        is_json = ctype.startswith("application/json") and \
            req.body.lstrip()[:1] == b"{"
        if is_json:
            import base64
            body = req.json()
            views = {k: base64.b64decode(v)
                     for k, v in body.get("views", {}).items()}
        else:
            view = req.query.get("view", ["standard"])[0]
            views = {view: req.body}
        api.import_roaring(args["index"], args["field"],
                           int(args["shard"]), views, clear=clear)
        return {}

    r.add("POST", "/index/{index}/field/{field}/import-roaring/{shard}",
          post_import_roaring)

    # -- streaming ingest (docs/ingest.md) ---------------------------------

    def _ingest_stream(req, args, forward: bool):
        """Shared body of the public and /internal/ ingest routes: read
        binary frames incrementally off the socket, route records to
        shard owners (public only), group-commit local records, and ack
        only after the covering flush hit the WAL."""
        from ..ingest import wire
        from ..parallel.cluster import IngestBackpressure

        index, field = args["index"], args["field"]
        ftype = api.check_ingest(index, field)
        committer = getattr(server, "committer", None) \
            if server is not None else None
        if committer is None:
            raise ApiError("streaming ingest requires a running server")
        cluster = getattr(server, "cluster", None)
        from ..core import SHARD_WIDTH
        reader = wire.FrameReader(req.rfile.read, req._stream_len,
                                  max_frame_bytes=req.ingest_max_frame_bytes)
        frames = records = fwd_records = 0
        last_seq = 0
        # per-peer forward buffers: re-encoded frames accumulate until
        # FWD_FLUSH_BYTES, then ship as one /internal/ingest POST (the
        # peer acks after ITS group commit, so the ack chain holds
        # end-to-end)
        fwd: dict[str, list[bytes]] = {}
        fwd_bytes: dict[str, int] = {}
        FWD_FLUSH_BYTES = 1 << 20
        local_id = cluster.node_id if cluster is not None else None

        def submit(recs, rectype) -> None:
            nonlocal last_seq
            if rectype == wire.REC_VALS:
                last_seq = committer.submit(index, field,
                                            cols=recs["col"],
                                            values=recs["value"])
            else:
                ts = recs["ts"] if rectype == wire.REC_BITS_TS else None
                last_seq = committer.submit(index, field,
                                            rows=recs["row"],
                                            cols=recs["col"], ts=ts)

        def ship(host: str):
            payload = b"".join([wire.MAGIC] + fwd.pop(host))
            fwd_bytes.pop(host, None)
            try:
                cluster.client.ingest_frames(host, index, field, payload)
            except IngestBackpressure as e:
                # the owner's backlog is full: propagate the 503 so the
                # client backs off the whole stream (frames are
                # idempotent — resending is safe)
                raise AdmissionRejected(
                    str(e), retry_after=_ingest_retry_after(req))

        try:
            while True:
                # backpressure: a slow device merge keeps the committer
                # backlog high, which parks the socket read here and
                # eventually turns into a retryable 503
                if not committer.wait_capacity():
                    if req.stats is not None:
                        req.stats.count("ingest.rejected")
                    raise AdmissionRejected(
                        "ingest backlog over high-water; retry",
                        retry_after=_ingest_retry_after(req))
                item = reader.next_frame()
                if item is None:
                    break
                rectype, recs, nbytes = item
                # per-frame validation at the socket: the committer
                # applies asynchronously and shares a flush across
                # producers, so bad records must 400 HERE, not poison a
                # flush.  Negative ids are rejected outright — a
                # negative row would wrap through the device overlay
                # scatter into the wrong rows of resident state.
                if (rectype == wire.REC_VALS) != (ftype == "int"):
                    raise ApiError(
                        f"record type {rectype} does not match field "
                        f"type {ftype!r} (values frames require an int "
                        f"field, bit frames a non-int field)")
                if len(recs):
                    if int(recs["col"].min()) < 0:
                        raise ApiError("negative column id in ingest "
                                       "frame")
                    if rectype != wire.REC_VALS \
                            and int(recs["row"].min()) < 0:
                        raise ApiError("negative row id in ingest frame")
                    if rectype == wire.REC_BITS_TS \
                            and int(recs["ts"].min()) < 0:
                        raise ApiError("negative timestamp in ingest "
                                       "frame")
                frames += 1
                records += len(recs)
                if req.stats is not None:
                    req.stats.count("ingest.frames")
                    req.stats.count("ingest.records", len(recs))
                    req.stats.count("ingest.bytes", nbytes)
                if cluster is None or not forward:
                    submit(recs, rectype)
                    continue
                shards = recs["col"] // SHARD_WIDTH
                idx_obj = api.holder.index(index)
                f_obj = idx_obj.field(field) if idx_obj is not None \
                    else None
                by_node: dict[str, list[int]] = {}
                for s in np.unique(shards):
                    # overlay-aware owners: a balancer-added replica
                    # receives ingest writes like any other owner
                    for nid in cluster.shard_owner_nodes(index, int(s)):
                        by_node.setdefault(nid, []).append(int(s))
                cluster.note_peer_write(index, by_node)
                for nid, nshards in by_node.items():
                    sub = recs[np.isin(shards, nshards)]
                    if nid == local_id:
                        submit(sub, rectype)
                        continue
                    fwd_records += len(sub)
                    host = cluster.by_id[nid].host
                    payload = wire.encode_frame(bytes([rectype])
                                                + sub.tobytes())
                    fwd.setdefault(host, []).append(payload)
                    fwd_bytes[host] = fwd_bytes.get(host, 0) \
                        + len(payload)
                    if f_obj is not None:
                        f_obj.remote_available_shards.update(
                            s for s in nshards
                            if not cluster.owns_shard(local_id, index, s))
                    if fwd_bytes[host] >= FWD_FLUSH_BYTES:
                        ship(host)
            for host in list(fwd):
                ship(host)
        except Exception:
            # Drain a bounded amount of the unread stream first: closing
            # with unread receive data resets the connection, and the
            # RST would destroy the 400/503 response (and its
            # Retry-After) before the client reads it — the same
            # courtesy the 413 path extends.  The connection still
            # closes (mid-stream state cannot be resynced).
            remaining = min(reader.remaining, 64 << 20)
            while remaining > 0:
                chunk = req.rfile.read(min(remaining, 1 << 20))
                if not chunk:
                    break
                remaining -= len(chunk)
            req.close_connection = True
            raise
        if last_seq and not committer.wait_flushed(last_seq):
            req.close_connection = True
            raise AdmissionRejected(
                "ingest flush did not complete in time; retry",
                retry_after=_ingest_retry_after(req))
        return {"frames": frames, "records": records,
                "forwarded": fwd_records}

    def post_ingest(req, args):
        return _ingest_stream(req, args, forward=True)

    r.add("POST", "/index/{index}/field/{field}/ingest", post_ingest,
          gate="ingest", stream=True)

    def post_ingest_internal(req, args):
        # receive side of the ingest forward: the sender already routed,
        # never re-forward
        return _ingest_stream(req, args, forward=False)

    r.add("POST", "/internal/ingest/{index}/{field}", post_ingest_internal,
          gate="ingest", stream=True)

    def get_export(req, args):
        index = req.query.get("index", [""])[0]
        field = req.query.get("field", [""])[0]
        shard = int(req.query.get("shard", ["0"])[0])
        return ("text/csv", api.export_csv(index, field, shard))

    r.add("GET", "/export", get_export)

    r.add("POST", "/recalculate-caches",
          lambda req, a: api.recalculate_caches() or {})

    def cache_clear(req, args):
        """Admin flush of the query cache subsystem (docs/caching.md):
        drops every result-cache entry and marks every rank cache for
        lazy rebuild.  Node-local, like the other /internal/ admin
        surfaces."""
        from ..cache.rank import iter_rank_caches
        out = {"resultEntries": 0, "rankCaches": 0}
        rc = api.executor.result_cache
        if rc is not None:
            out["resultEntries"] = rc.clear()
        n = 0
        for _frag, cache in iter_rank_caches(api.holder):
            cache.invalidate()
            n += 1
        out["rankCaches"] = n
        return out

    r.add("POST", "/internal/cache/clear", cache_clear)

    # -- observability (handler.go:280-282) -------------------------------
    def debug_vars(req, args):
        """expvar-style snapshot: stats + HBM budget + query-cache state,
        so perf work can attribute latency to phases (r3 verdict #10).
        Body shared with the fleet rollup's local-node path
        (build_debug_vars) so /debug/cluster agrees with this surface by
        construction."""
        return build_debug_vars(api, server)

    def metrics(req, args):
        if server is not None:
            # refresh the storage.* + device.* gauges so scrapes see
            # current values
            server.update_storage_gauges()
        # trace-id exemplars are OpenMetrics-only syntax: a classic
        # 0.0.4 parser rejects the `# {...}` suffix and the whole
        # scrape goes dark.  They attach ONLY on the explicit
        # `?exemplars=true` opt-in (docs/observability.md "Trace
        # exemplars") — deliberately NOT Accept-header negotiation:
        # stock Prometheus advertises application/openmetrics-text by
        # default, and answering it with this exposition (whose counter
        # names predate the OpenMetrics `_total` rule) would break the
        # default scrape that works today.
        exemplars = req.query.get("exemplars", [""])[0] == "true"
        text = api.stats.prometheus_text(exemplars=exemplars)
        # the batcher's and launch ledger's histogram/summary series
        # don't fit the stats client's counter/gauge model; they export
        # their own lines
        if api.executor.batcher is not None:
            text += api.executor.batcher.prometheus_text()
        from ..utils import devobs
        text += devobs.LEDGER.prometheus_text()
        # fleet rollup (docs/observability.md "Cluster plane"): the
        # pilosa_tpu_cluster_* family with node labels.  Exported by
        # the COORDINATOR's scrape only — every node exporting it would
        # ingest each series N times and turn a scrape-all-nodes setup
        # into N*(N-1) peer pulls per interval.  refresh() is
        # TTL-cached and never blocks on a dead peer, so the scrape
        # stays bounded.
        rollup = getattr(server, "rollup", None) if server is not None \
            else None
        if rollup is not None and server.cluster.is_coordinator:
            rollup.refresh()
            text += rollup.prometheus_text()
        if exemplars:
            return ("application/openmetrics-text; version=1.0.0; "
                    "charset=utf-8", text + "# EOF\n")
        return ("text/plain; version=0.0.4", text)

    if api.stats is not None:
        r.add("GET", "/metrics", metrics)
        r.add("GET", "/debug/vars", debug_vars)

    def debug_traces(req, args):
        """Span ring (bounded retention).  ``?trace=<id>`` returns one
        trace's spans; ``?index=`` / ``?minMs=`` / ``?status=`` search
        ROOT spans and return trace summaries — the drill-down behind a
        histogram exemplar (docs/observability.md "Trace exemplars")."""
        from ..utils.tracing import GLOBAL_TRACER
        tid = req.query.get("trace", [None])[0]
        if tid is not None:
            return {"spans": GLOBAL_TRACER.spans(tid)}
        index = req.query.get("index", [None])[0]
        min_ms = req.query.get("minMs", [None])[0]
        status_q = req.query.get("status", [None])[0]
        if index is not None or min_ms is not None \
                or status_q is not None:
            try:
                min_s = float(min_ms) / 1e3 if min_ms is not None \
                    else None
                status_i = int(status_q) if status_q is not None else None
            except (TypeError, ValueError):
                raise ApiError("minMs/status must be numbers")
            return {"traces": GLOBAL_TRACER.search(
                index=index, min_duration_s=min_s, status=status_i)}
        return {"spans": GLOBAL_TRACER.spans(None)}

    r.add("GET", "/debug/traces", debug_traces)

    def debug_events(req, args):
        """Event journal (utils/events.py): ``?since=<seq>`` returns
        only newer events — the cursor the fleet rollup merges per-node
        journals with."""
        from ..utils.events import EVENTS
        since = req.query.get("since", [None])[0]
        limit = req.query.get("limit", [None])[0]
        try:
            since_i = int(since) if since is not None else None
            limit_i = int(limit) if limit is not None else None
        except (TypeError, ValueError):
            raise ApiError("since/limit must be integers")
        if since_i is None:
            out = EVENTS.snapshot()
            if limit_i is not None:
                # newest entries for the no-cursor browse form (the
                # cursor form below keeps oldest); guard limit=0 — a
                # [-0:] slice would return everything
                out["events"] = out["events"][-limit_i:] \
                    if limit_i > 0 else []
            return out
        return {"seq": EVENTS.last_seq(),
                "events": EVENTS.since(since_i, limit=limit_i)}

    r.add("GET", "/debug/events", debug_events)

    def debug_cluster(req, args):
        """Fleet rollup (docs/observability.md "Cluster plane"):
        per-node summaries with staleness stamps + the merged event
        timeline.  Single-node servers answer with their own summary so
        dashboards work unchanged."""
        rollup = getattr(server, "rollup", None) if server is not None \
            else None
        if rollup is None:
            from ..parallel.rollup import summarize_vars
            info = {"state": "READY", "stale": False,
                    "qps": 0.0}
            info.update(summarize_vars(build_debug_vars(api, server)))
            from ..utils.events import EVENTS
            from ..parallel.rollup import FleetRollup
            # same top-level keys FleetRollup.snapshot() emits: the
            # fleet dashboard renders refreshes/fetchErrors/ttlS
            # unconditionally, and "dashboards work unchanged" is this
            # fallback's whole point
            # lint: allow(wall-clock) — display-only snapshot stamp,
            # never subtracted (mirrors FleetRollup._wall_stamp)
            return {"wall": time.time(), "ttlS": FleetRollup.TTL_S,
                    "refreshes": 0, "fetchErrors": 0,
                    "coordinator": "local", "overlayEpoch": 0,
                    "epoch": 0, "nodes": {"local": info},
                    "timeline": EVENTS.since(0), "hotShards": {}}
        rollup.refresh(
            force=req.query.get("refresh", [""])[0] == "true")
        return rollup.snapshot()

    r.add("GET", "/debug/cluster", debug_cluster)

    def debug_slow(req, args):
        """Slow-query log ring (docs/observability.md): queries that ran
        past slow-query-threshold, newest last, each with its trace id
        and profile tree for drill-down via /debug/traces."""
        slog = getattr(server, "slowlog", None) if server is not None \
            else None
        if slog is None:
            return {"thresholdS": 0, "entries": []}
        return slog.snapshot()

    r.add("GET", "/debug/slow", debug_slow)

    # -- device runtime (docs/observability.md "Device runtime") -----------

    def debug_compiles(req, args):
        """Compile registry: per-executable-signature compile counts,
        trace+compile wall time, last argument-shape fingerprint — a
        signature with compiles > 1 is a retrace (the PR-7-class silent
        red flag this surface exists for)."""
        from ..utils import devobs
        return devobs.COMPILES.snapshot()

    r.add("GET", "/debug/compiles", debug_compiles)

    def debug_launches(req, args):
        """Launch ledger: the ring of recent device launches (padding,
        decode workspace, queue-vs-dispatch split, slice position) plus
        its lifetime aggregates."""
        from ..utils import devobs
        return devobs.LEDGER.snapshot()

    r.add("GET", "/debug/launches", debug_launches)

    def debug_timeseries(req, args):
        """In-process time-series ring (utils/timeseries.py): the last
        timeseries-window seconds of runtime samples."""
        ts = getattr(server, "timeseries", None) if server is not None \
            else None
        if ts is None:
            return {"intervalS": 0, "windowS": 0, "capacity": 0,
                    "samplesTotal": 0, "coveredS": 0, "samples": []}
        return ts.snapshot()

    r.add("GET", "/debug/timeseries", debug_timeseries)

    # -- SLOs & alerting (docs/observability.md "SLOs & alerting") ---------

    def debug_alerts(req, args):
        """SLO engine state (utils/slo.py): objectives, burn-rate
        windows, the active-alert table with durations, recent
        fire/resolve transitions, and the evaluated rule list — plus
        the flight recorder's capture accounting."""
        slo_eng = getattr(server, "slo", None) if server is not None \
            else None
        if slo_eng is None:
            out = {"enabled": False, "active": {}, "history": [],
                   "rules": [], "evaluations": 0, "firedTotal": 0,
                   "resolvedTotal": 0}
        else:
            out = slo_eng.snapshot()
        flightrec = getattr(server, "flightrec", None) \
            if server is not None else None
        if flightrec is not None:
            out["flightRecorder"] = flightrec.snapshot()
        return out

    r.add("GET", "/debug/alerts", debug_alerts)

    def debug_bundle(req, args):
        """On-demand flight-recorder capture (``pilosa-tpu bundle``):
        snapshots every debug surface into one JSON bundle on disk.
        Bypasses the on-fire rate limit — an operator asking twice
        wants two bundles."""
        if server is None or getattr(server, "flightrec", None) is None:
            raise ApiError(
                "flight recorder disabled (flight-recorder-mb = 0)")
        reason = req.json().get("reason", "manual")
        if not isinstance(reason, str):
            raise ApiError("reason must be a string")
        path = server.capture_bundle(reason, force=True)
        if path is None:
            raise ApiError("bundle capture failed (see server log)")
        return {"path": path, "last": server.flightrec.last}

    r.add("POST", "/debug/bundle", debug_bundle)

    def debug_dashboard(req, args):
        from .dashboard import DASHBOARD_HTML
        return ("text/html; charset=utf-8", DASHBOARD_HTML)

    r.add("GET", "/debug/dashboard", debug_dashboard)

    def debug_dashboard_cluster(req, args):
        """Fleet page: per-node table + merged timeline rendered from
        /debug/cluster (docs/observability.md "Cluster plane")."""
        from .dashboard import CLUSTER_DASHBOARD_HTML
        return ("text/html; charset=utf-8", CLUSTER_DASHBOARD_HTML)

    r.add("GET", "/debug/dashboard/cluster", debug_dashboard_cluster)

    def debug_locks(req, args):
        """Lock-order race detector dump (docs/static-analysis.md):
        the acquisition-order graph over named lock classes plus any
        order-inversion/same-class-nesting violations.  Populated only
        when the process runs with PILOSA_TPU_LOCKCHECK set; unarmed it
        reports armed=false with empty tables."""
        from ..utils import locks
        return locks.report()

    r.add("GET", "/debug/locks", debug_locks)

    # -- pprof-style profiling (handler.go:280 /debug/pprof) ---------------

    def pprof_threads(req, args):
        """All-thread stack dump — the goroutine-profile analog."""
        import sys
        import traceback
        names = {t.ident: t.name for t in __import__("threading").enumerate()}
        out = []
        for tid, frame in sys._current_frames().items():
            out.append(f"thread {tid} ({names.get(tid, '?')}):\n"
                       + "".join(traceback.format_stack(frame)))
        return ("text/plain", "\n".join(out))

    r.add("GET", "/debug/pprof/threads", pprof_threads)

    import threading as _threading
    profile_lock = make_lock("pprof-profile")

    def pprof_profile(req, args):
        """Sampling CPU profile: aggregate all-thread stacks at ~100 Hz
        for ?seconds=N (default 2, clamped to [0.1, 30]); returns
        collapsed stacks in flamegraph-folded text (one
        `frame;frame;frame count` per line).  One profile at a time —
        concurrent requests would each busy-sample every stack and
        multiply the overhead on a serving node."""
        import sys
        import time as _time
        try:
            seconds = float(req.query.get("seconds", ["2"])[0])
        except (TypeError, ValueError):
            raise ApiError("seconds must be a number")
        seconds = min(max(seconds, 0.1), 30.0)
        if not profile_lock.acquire(blocking=False):
            raise ConflictError("a profile is already running")
        interval = 0.01
        try:
            counts: dict = {}
            me = _threading.get_ident()
            deadline = _time.perf_counter() + seconds
            while _time.perf_counter() < deadline:
                for tid, frame in sys._current_frames().items():
                    if tid == me:
                        continue
                    stack = []
                    f = frame
                    while f is not None:
                        code = f.f_code
                        stack.append(
                            f"{code.co_name} "
                            f"({code.co_filename.rsplit('/', 1)[-1]}"
                            f":{f.f_lineno})")
                        f = f.f_back
                    key = ";".join(reversed(stack))
                    counts[key] = counts.get(key, 0) + 1
                _time.sleep(interval)
            lines = [f"{k} {v}" for k, v in
                     sorted(counts.items(), key=lambda kv: -kv[1])]
            return ("text/plain", "\n".join(lines))
        finally:
            profile_lock.release()

    r.add("GET", "/debug/pprof/profile", pprof_profile)

    # -- internal (handler.go:302-314) ------------------------------------
    r.add("GET", "/internal/shards/max",
          lambda req, a: {"standard": api.max_shards()})

    def fragment_nodes(req, args):
        index = req.query.get("index", [""])[0]
        shard = int(req.query.get("shard", ["0"])[0])
        return api.shard_nodes(index, shard)

    r.add("GET", "/internal/fragment/nodes", fragment_nodes)

    if server is not None:
        server.register_internal_routes(r)

    return r


class _HandlerClass(BaseHTTPRequestHandler):
    router: Router = None
    protocol_version = "HTTP/1.1"
    # Socket read timeout: an idle keep-alive connection (or a client
    # that opens a socket and sends nothing) must not pin a handler
    # thread forever; pooled internal clients reconnect transparently
    # on a closed stale socket (InternalClient stale-retry).
    timeout = 120
    # Request-body ceiling: bounds a hostile/buggy client's ability to
    # allocate host memory with one POST (bulk imports of a dense shard
    # legitimately run to hundreds of MB, hence the generous default).
    # <= 0 means unlimited, matching device-budget-mb's 0 convention.
    max_body_bytes: int = 1 << 30
    # Optional higher — but still bounded — ceiling for /internal/
    # routes (max-body-internal-mb): the node-to-node plane (roaring
    # import fan-out, resize fragment copies) can legitimately ship
    # payloads beyond the public cap.  0 (the default) inherits the
    # public ceiling: the path prefix alone is NOT authentication, so a
    # bigger internal ceiling is OPT-IN and belongs behind mutual TLS —
    # an unauthenticated default exemption would re-open the
    # memory-exhaustion hole the public cap closes.
    max_body_bytes_internal: int = 0
    # Overload armor (docs/robustness.md).  admission/admission_internal:
    # AdmissionController slot pools for gate="query"/"internal" routes
    # (None = ungated).  default_query_timeout: seconds applied to public
    # queries that carry no explicit ?timeout=; 0 = unlimited.  stats:
    # StatsClient for the 503/504 counters.
    admission = None
    admission_internal = None
    # Streaming ingest (docs/ingest.md): its own slot pool (writes must
    # not starve reads or the /internal/ plane) and the per-frame byte
    # ceiling (ingest-max-frame-mb).
    admission_ingest = None
    ingest_max_frame_bytes: int = 32 << 20
    default_query_timeout: float = 0.0
    # Partial-results server default (docs/robustness.md "Partial
    # results"): when true, every public query behaves as if it carried
    # ?partialResults=true.  Off by default — losing shards should fail
    # loudly unless the deployment explicitly prefers availability.
    partial_results: bool = False
    stats = None
    # Observability (docs/observability.md).  slowlog: SlowQueryLog ring
    # capturing queries past slow-query-threshold (None = off).
    # profile_default: return the stage-timing tree on every query even
    # without ?profile=true.
    slowlog = None
    profile_default: bool = False

    # request helpers
    def json(self):
        if not self.body:
            return {}
        try:
            return json.loads(self.body)
        except json.JSONDecodeError as e:
            raise ApiError(f"invalid JSON body: {e}")

    @property
    def query(self):
        return self._query

    def _handle(self, method: str):
        parsed = urlparse(self.path)
        self._query = parse_qs(parsed.query)
        try:
            length = int(self.headers.get("Content-Length") or 0)
        except ValueError:
            # any body bytes in flight would desync the keep-alive
            # stream (the next "request line" would be body garbage)
            self.close_connection = True
            self._send(400, {"error": "invalid Content-Length"})
            return
        fn, args, gate, stream = self.router.match(method, parsed.path)
        stream = stream and not isinstance(fn, str) and fn is not None
        if stream:
            # streaming route (ingest): the handler fn reads frames
            # incrementally off the socket itself — the whole-body
            # ceiling doesn't apply (per-frame bounds do, wire.py); the
            # fn closes the connection on any mid-stream failure rather
            # than trying to resync the keep-alive stream
            self.body = b""
            self._stream_len = length
        else:
            # /internal/ routes trade the public ceiling for the
            # (bounded) internal one — see max_body_bytes_internal above
            # (docs/configuration.md max-body-mb)
            limit = self.max_body_bytes
            if limit > 0 and parsed.path.startswith("/internal/"):
                # 0 on the internal knob = same ceiling as the public
                # surface
                if self.max_body_bytes_internal > 0:
                    limit = max(limit, self.max_body_bytes_internal)
            if 0 < limit < length:
                # answer 413, then drain a bounded amount of the
                # in-flight body so the client sees the response instead
                # of an RST (closing with unread receive data resets the
                # connection); bodies beyond the drain cap close hard
                # anyway
                self._send(413, {"error": f"request body {length} bytes "
                                 f"exceeds limit {limit}"})
                self.close_connection = True
                remaining = min(length, 64 << 20)
                while remaining > 0:
                    chunk = self.rfile.read(min(remaining, 1 << 20))
                    if not chunk:
                        break
                    remaining -= len(chunk)
                return
            self.body = self.rfile.read(length) if length > 0 else b""
        # handler.go:231 extract — the header carries
        # trace_id:parent_span_id[:0], so a remote hop's spans parent
        # under the coordinator's rpc span (docs/observability.md)
        tid, parent_id, sampled = parse_trace_header(
            self.headers.get(TRACE_HEADER))
        # Probe/background tagging: health probes (wire-tagged by
        # InternalClient) and the status/metrics/debug surfaces never
        # reach the latency histograms or the slow-query log — background
        # cadence must not pollute p99.
        background = (self.headers.get(PROBE_HEADER) is not None
                      or parsed.path in ("/status", "/metrics")
                      or parsed.path.startswith("/debug/"))
        ctx = None
        status = 200
        prof = None
        erec = None
        self._tenant = None
        want_profile = False
        want_explain = False
        trace_out = None
        t_req0 = time.perf_counter()
        try:
            if fn is None:
                status = 404
                self._send(404, {"error": f"path not found: {parsed.path}"})
                return
            if fn == "method_not_allowed":
                status = 405
                self._send(405, {"error": "method not allowed"})
                return
            # Deadline: an internal hop's header (the coordinator's
            # REMAINING budget) > explicit ?timeout= > the configured
            # query-timeout default for public queries.  <= 0 disables.
            budget = None
            try:
                hdr = self.headers.get(DEADLINE_HEADER)
                if hdr is not None:
                    budget = float(hdr)
                elif "timeout" in self._query:
                    budget = float(self._query["timeout"][0])
            except (TypeError, ValueError):
                raise ApiError(
                    "timeout/deadline must be a number of seconds")
            if budget is None and gate == "query" \
                    and self.default_query_timeout > 0:
                budget = self.default_query_timeout
            if budget is not None and budget > 0:
                ctx = QueryContext(budget)
            # Per-query profile (utils/profile.py): collected when the
            # client asked for one (?profile=true / profile-default) OR
            # the slow-query log is on (slow entries carry the tree);
            # embedded in the response only when requested.
            if gate == "query":
                want_profile = (self._query.get("profile", [""])[0]
                                == "true" or self.profile_default)
                # EXPLAIN (utils/explain.py): the decision record rides
                # the same collection discipline as the profile —
                # assembled when the client asked (?explain=true) OR
                # silently for slow-log entries; embedded only when
                # requested.  Explain implies profile collection: the
                # launches section reads the profile tree.
                want_explain = self._query.get("explain", [""])[0] \
                    == "true"
                slow_on = (self.slowlog is not None
                           and self.slowlog.enabled)
                if want_profile or want_explain or slow_on:
                    prof = qprof.QueryProfile()
                if want_explain or slow_on:
                    erec = qexplain.ExplainRecord()
            # Tenant identity (docs/robustness.md "Tenant isolation"):
            # derived for every GATED route — index name by default,
            # explicit X-Pilosa-Tpu-Tenant token override.  A malformed
            # token is a TenantError (ValueError) -> clean 400 below,
            # BEFORE any admission/stat carries the garbage as a label.
            tenant = None
            tenant_explicit = False
            if gate is not None:
                tenant, tenant_explicit = qtenant.derive(
                    self.headers.get(qtenant.TENANT_HEADER),
                    args.get("index"))
                self._tenant = tenant
            adm = self.admission if gate == "query" else \
                self.admission_internal if gate == "internal" else \
                self.admission_ingest if gate == "ingest" else None
            admitted = False
            with qtenant.activate(tenant, tenant_explicit):
                if adm is not None:
                    # slot wait is the first profile stage: under
                    # overload it IS the latency story
                    with (prof.stage("admission") if prof is not None
                          else _NULL_CTX):
                        # raises AdmissionRejected -> 503
                        waited = adm.acquire(tenant=tenant)
                    admitted = True
                    if erec is not None:
                        # EXPLAIN names the tenant queue the query
                        # waited in and for how long
                        erec.note("admission", {
                            "tenant": tenant, "pool": adm.name,
                            "queuedMs": round(waited * 1e3, 3)})
                try:
                    # /internal/ continuations collect this request's
                    # finished spans so /internal/query can piggyback
                    # them back to the coordinator (cluster.py reads
                    # these attrs)
                    collect = [] if (tid is not None
                                     and parsed.path.startswith(
                                         "/internal/")) \
                        else None
                    with activate(ctx):
                        if ctx is not None:
                            ctx.check("admission")
                        # background requests with no inbound trace must
                        # not root new sampled traces: probe cadence x
                        # peers would continuously evict real query
                        # traces from the bounded span ring
                        root_sampled = sampled if tid is not None \
                            else (False if background else None)
                        with GLOBAL_TRACER.span(
                                f"{method} {parsed.path}", trace_id=tid,
                                parent_id=parent_id, sampled=root_sampled,
                                collect=collect) as span, \
                                qprof.activate(prof), \
                                qexplain.activate(erec):
                            self._trace_span = span
                            self._span_collect = collect
                            trace_out = span.trace_id
                            if "index" in args:
                                # searchable root-span tags:
                                # /debug/traces?index=... filters on them
                                span.set_tag("index", args["index"])
                            out = fn(self, args)
                finally:
                    if admitted:
                        adm.release()
            if isinstance(out, tuple):
                ctype, payload = out
                self._send_raw(200, ctype, payload.encode()
                               if isinstance(payload, str) else payload)
            else:
                resp_headers = None
                if gate == "query" and trace_out is not None:
                    # echo the trace id so any client can jump straight
                    # to /debug/traces?trace=<id>
                    resp_headers = {TRACE_HEADER: trace_out}
                if want_profile and prof is not None:
                    prof.finish()
                    out = dict(out)
                    out["traceID"] = trace_out
                    out["profile"] = prof.to_dict()
                if want_explain and erec is not None:
                    # the record rides the response ENVELOPE: results
                    # stay byte-identical with explain on
                    erec.set_info("traceID", trace_out)
                    out = dict(out)
                    out["explain"] = erec.to_dict(
                        profile=prof.to_dict() if prof is not None
                        else None)
                self._send(200, out, headers=resp_headers)
        except AdmissionRejected as e:
            # overload/drain rejection: bounded, explicit, retryable
            status = 503
            self._send(503, {"error": str(e)},
                       headers={"Retry-After": str(e.retry_after)})
        except DeadlineExceeded as e:
            status = 504
            if self.stats is not None:
                self.stats.count("query.deadline_abort")
            body = {"error": str(e)}
            if ctx is not None:
                body["elapsedS"] = round(ctx.elapsed(), 4)
                body["budgetS"] = ctx.budget
            self._send(504, body)
        except FragmentQuarantinedError as e:
            # write refused on a quarantined fragment: RETRYABLE —
            # replica repair restores it on the repair-interval cadence
            status = 503
            if self.stats is not None:
                self.stats.count("storage.write_refused")
            self._send(503, {"error": str(e), "retryable": True},
                       headers={"Retry-After": "30"})
        except NotFoundError as e:
            status = 404
            self._send(404, {"error": str(e)})
        except ConflictError as e:
            status = 409
            self._send(409, {"error": str(e)})
        except DisallowedError as e:
            status = 400
            self._send(400, {"error": str(e)})
        except UnsupportedMediaTypeError as e:
            # internal-wire negotiation: a binary /internal/query POST
            # to a node pinned to json — the caller downgrades the peer
            # and retries over the JSON wire (docs/cluster.md)
            status = 415
            self._send(415, {"error": str(e)})
        except ClientAbort:
            # the client hung up mid-response: already counted, nothing
            # left to send — just let the connection close
            status = 499
        except (ApiError, ValueError) as e:
            status = 400
            self._send(400, {"error": str(e)})
        except Exception as e:  # panic guard (handler.go:325 recover)
            status = 500
            traceback.print_exc()
            self._send(500, {"error": f"internal error: {e}"})
        finally:
            self._observe(gate, args, time.perf_counter() - t_req0,
                          status, background, prof, erec, trace_out)

    def _observe(self, gate, args, dur_s, status, background, prof,
                 erec, trace_id):
        """Post-request accounting (docs/observability.md): latency
        histograms (with the trace id attached as the landing bucket's
        exemplar) + the slow-query log.  Background traffic (probes,
        status/metrics/debug) was tagged by the caller and is excluded
        from both."""
        # status stamped post-finish onto the root span: the ring holds
        # Span objects and renders tags lazily, so /debug/traces search
        # by status sees it
        sp = getattr(self, "_trace_span", None)
        if sp is not None and trace_id is not None:
            sp.tags["status"] = status
        if background:
            return
        # exemplars must RESOLVE at /debug/traces — only sampled traces
        # qualify (docs/observability.md "Trace exemplars")
        exemplar = trace_id if (sp is not None and sp.sampled
                                and trace_id is not None) else None
        if self.stats is not None:
            self.stats.timing("http.request", dur_s, exemplar=exemplar)
            if gate == "query":
                self.stats.timing("http.query", dur_s, exemplar=exemplar)
                if status >= 500:
                    # availability SLO numerator (utils/slo.py): 5xx
                    # query responses, sheds and deadline aborts
                    # included — the client saw a failure either way
                    self.stats.count("http.query_5xx")
        # per-tenant accounting: latency/qps/error columns for the
        # /debug/vars "tenants" table and the fleet rollup
        tenant = getattr(self, "_tenant", None)
        if tenant is not None and gate == "query":
            qtenant.REGISTRY.note_request(tenant, dur_s, status)
            if self.stats is not None:
                self.stats.timing(f"tenant.{tenant}.query", dur_s)
        slog = self.slowlog
        if (gate == "query" and slog is not None and slog.enabled
                and dur_s >= slog.threshold_s):
            profile = shards = None
            if prof is not None:
                prof.finish()
                profile = prof.to_dict()
                shards = _profile_shards(profile)
            slog.record(index=args.get("index", ""),
                        query=self.body.decode("utf-8", "replace"),
                        duration_s=dur_s, shards=shards,
                        trace_id=trace_id, status=status, profile=profile,
                        explain=erec.to_dict(profile=profile)
                        if erec is not None else None)

    def _send(self, code: int, obj, headers: dict | None = None):
        self._send_raw(code, "application/json",
                       (json.dumps(obj) + "\n").encode(), headers)

    def _send_raw(self, code: int, ctype: str, payload: bytes,
                  headers: dict | None = None):
        try:
            self.send_response(code)
            self.send_header("Content-Type", ctype)
            self.send_header("Content-Length", str(len(payload)))
            if headers:
                for k, v in headers.items():
                    self.send_header(k, v)
            self.end_headers()
            self.wfile.write(payload)
        except (BrokenPipeError, ConnectionResetError,
                TimeoutError) as e:
            # client disconnected mid-write: a stat, not a stack trace
            if self.stats is not None:
                self.stats.count("http.client_abort")
            self.close_connection = True
            raise ClientAbort(str(e)) from e

    def do_GET(self):
        self._handle("GET")

    def do_POST(self):
        self._handle("POST")

    def do_DELETE(self):
        self._handle("DELETE")

    def log_message(self, fmt, *args):  # quiet by default
        pass


class TrackingHTTPServer(ThreadingHTTPServer):
    """ThreadingHTTPServer whose live connections can be severed.

    ``shutdown()`` only stops the accept loop: per-connection handler
    threads stay parked on keep-alive reads and keep serving the CLOSED
    server's object graph.  After a same-port restart, a peer's pooled
    internal-client connection would then write into the dead holder —
    the write reports success and vanishes (found by the r5 cluster
    differential fuzz as a one-bit divergence on a restarted node).
    ``close_connections()`` severs every tracked socket so those threads
    exit and clients reconnect to the live server."""

    def server_bind(self):
        self._conns: set = set()
        self._conns_lock = make_lock("server-conns")
        super().server_bind()

    def process_request(self, request, client_address):
        with self._conns_lock:
            self._conns.add(request)
        super().process_request(request, client_address)

    def shutdown_request(self, request):
        with self._conns_lock:
            self._conns.discard(request)
        super().shutdown_request(request)

    def handle_error(self, request, client_address):
        # disconnect-while-reading surfaces here (the write path maps to
        # ClientAbort inside the handler): expected client churn, not a
        # traceback per dropped connection
        import sys
        exc = sys.exc_info()[1]
        if isinstance(exc, (BrokenPipeError, ConnectionResetError,
                            TimeoutError, ClientAbort)):
            return
        super().handle_error(request, client_address)

    def close_connections(self):
        import socket as _socket
        with self._conns_lock:
            conns = list(self._conns)
        for c in conns:
            try:
                c.shutdown(_socket.SHUT_RDWR)
            except OSError:
                pass


def make_http_server(api: API, host: str = "localhost", port: int = 10101,
                     server=None, tls=None,
                     max_body_bytes: int | None = None,
                     max_body_bytes_internal: int | None = None,
                     admission=None, admission_internal=None,
                     admission_ingest=None,
                     ingest_max_frame_bytes: int | None = None,
                     default_query_timeout: float | None = None,
                     partial_results: bool | None = None,
                     slowlog=None, profile_default: bool | None = None,
                     ) -> ThreadingHTTPServer:
    """``tls``: optional (certificate, key, ca_certificate|None) paths —
    serves HTTPS, requiring client certificates (mutual TLS) when a CA is
    given (reference server/tlsconfig.go, server/server.go GetTLSConfig).

    ``admission``/``admission_internal``: AdmissionController pools for
    the public and node-to-node query routes; ``default_query_timeout``:
    deadline applied to public queries without an explicit ?timeout=."""
    router = build_router(api, server)
    attrs = {"router": router, "stats": api.stats}
    if max_body_bytes is not None:
        attrs["max_body_bytes"] = max_body_bytes
    if max_body_bytes_internal is not None:
        attrs["max_body_bytes_internal"] = max_body_bytes_internal
    if admission is not None:
        attrs["admission"] = admission
    if admission_internal is not None:
        attrs["admission_internal"] = admission_internal
    if admission_ingest is not None:
        attrs["admission_ingest"] = admission_ingest
    if ingest_max_frame_bytes is not None:
        attrs["ingest_max_frame_bytes"] = ingest_max_frame_bytes
    if default_query_timeout is not None:
        attrs["default_query_timeout"] = default_query_timeout
    if partial_results is not None:
        attrs["partial_results"] = partial_results
    if slowlog is not None:
        attrs["slowlog"] = slowlog
    if profile_default is not None:
        attrs["profile_default"] = profile_default
    cls = type("Handler", (_HandlerClass,), attrs)
    if tls is None:
        return TrackingHTTPServer((host, port), cls)
    import ssl
    cert, key, ca = tls
    ctx = ssl.SSLContext(ssl.PROTOCOL_TLS_SERVER)
    ctx.load_cert_chain(cert, key)
    if ca:
        ctx.load_verify_locations(ca)
        ctx.verify_mode = ssl.CERT_REQUIRED  # mutual TLS

    class _TLSServer(TrackingHTTPServer):
        """Per-connection TLS: the handshake runs in the HANDLER thread
        (finish_request), never the accept loop — a stalled or plain-TCP
        client must not block every other connection."""

        def finish_request(self, request, client_address):
            request.settimeout(30)  # bound the handshake
            tls_sock = ctx.wrap_socket(request, server_side=True)
            try:
                tls_sock.settimeout(None)
                super().finish_request(tls_sock, client_address)
            finally:
                # shutdown_request later runs on the detached raw socket;
                # close the SSLSocket here so the fd and TLS state are
                # released deterministically, not on refcount GC
                try:
                    tls_sock.close()
                except OSError:
                    pass

        def handle_error(self, request, client_address):
            # handshake failures (port scans, cert-less clients) are
            # expected noise, not tracebacks
            import sys
            exc = sys.exc_info()[1]
            if not isinstance(exc, (ssl.SSLError, OSError)):
                super().handle_error(request, client_address)

    return _TLSServer((host, port), cls)
