"""/debug/dashboard: a zero-dependency single-file HTML view of the
in-process time-series ring (docs/observability.md "Device runtime").

The page polls /debug/timeseries (and /debug/vars for the header line)
on the ring's own cadence and renders inline-SVG sparklines — no
external scripts, fonts, or build step, so "what happened in the last
10 minutes" is answerable from the node itself with nothing but a
browser pointed at it.  All numbers come from the ring's samples; the
page does no aggregation beyond per-sample ratios."""

DASHBOARD_HTML = """<!doctype html>
<html lang="en">
<head>
<meta charset="utf-8">
<title>pilosa-tpu dashboard</title>
<style>
  :root { color-scheme: dark; }
  body { margin: 0; padding: 16px 20px; background: #14161a;
         color: #d6d9de; font: 13px/1.45 system-ui, sans-serif; }
  h1 { font-size: 15px; margin: 0 0 2px; font-weight: 600; }
  #meta { color: #8a8f98; margin-bottom: 14px; }
  #grid { display: grid; gap: 12px;
          grid-template-columns: repeat(auto-fill, minmax(330px, 1fr)); }
  .card { background: #1b1e24; border: 1px solid #262a31;
          border-radius: 6px; padding: 10px 12px 6px; }
  .card h2 { font-size: 12px; margin: 0 0 4px; font-weight: 600;
             color: #aab0b9; }
  .card .now { float: right; color: #e8eaed; font-variant-numeric:
               tabular-nums; }
  svg { width: 100%; height: 64px; display: block; }
  .axis { color: #6b7077; font-size: 10px; display: flex;
          justify-content: space-between; }
  .err { color: #e07a5f; }
  #alerts { margin: 0 0 12px; }
  #alerts:empty { display: none; }
  .alert { display: inline-block; margin: 0 8px 4px 0; padding: 3px 9px;
           border-radius: 4px; font-size: 12px; background: #2a1e22;
           border: 1px solid #f7768e; color: #f7768e; }
  .alert.ticket { background: #2a2620; border-color: #e0af68;
                  color: #e0af68; }
</style>
</head>
<body>
<h1>pilosa-tpu &middot; device runtime
  <a href="/debug/dashboard/cluster" style="font-size:11px;
     color:#7aa2f7; margin-left:10px">fleet view &rarr;</a></h1>
<div id="meta">loading&hellip;</div>
<div id="alerts"></div>
<div id="grid"></div>
<script>
"use strict";
const COLORS = ["#7aa2f7", "#9ece6a", "#e0af68", "#f7768e", "#bb9af7"];
const MB = b => b / 1048576;
const CHARTS = [
  {title: "qps", unit: "q/s",
   series: [{label: "queries", f: (s, dt) => s.httpQueriesDelta / dt}]},
  {title: "p99 latency", unit: "ms",
   series: [{label: "http.query", f: s => s.httpQueryP99Ms}]},
  {title: "HBM residency", unit: "MB",
   series: [{label: "compressed", f: s => MB(s.hbmCompressedBytes)},
            {label: "dense", f: s => MB(s.hbmDenseBytes)},
            {label: "pinned", f: s => MB(s.hbmPinnedBytes)}]},
  {title: "evictions / uploads", unit: "/s",
   series: [{label: "evictions", f: (s, dt) => s.evictionsDelta / dt},
            {label: "upload MB", f: (s, dt) =>
                MB(s.uploadBytesDelta) / dt}]},
  {title: "compiles &amp; retraces", unit: "/interval",
   series: [{label: "compiles", f: s => s.compilesDelta},
            {label: "retraces", f: s => s.retracesDelta}]},
  {title: "compile seconds", unit: "s/interval",
   series: [{label: "compile s", f: s => s.compileSDelta}]},
  {title: "queue depth", unit: "",
   series: [{label: "admission", f: s => s.admissionInUse +
                s.admissionWaiting},
            {label: "batcher", f: s => s.batcherQueued}]},
  {title: "launch padding waste", unit: "%",
   series: [{label: "padded", f: s => {
       const t = s.rowsActualDelta + s.rowsPaddedDelta;
       return t ? 100 * s.rowsPaddedDelta / t : 0; }}]},
  {title: "decode workspace peak", unit: "MB",
   series: [{label: "peak", f: s => MB(s.decodePeakBytes)}]},
  {title: "cluster health", unit: "/interval",
   series: [{label: "hedges", f: s => s.hedgesDelta},
            {label: "retry waves", f: s => s.retryWavesDelta},
            {label: "partial", f: s => s.partialResultsDelta},
            {label: "route fallback", f: s => s.routingFallbacksDelta},
            {label: "handoffs", f: s => s.balancerHandoffsDelta}]},
  {title: "fleet events", unit: "/interval",
   series: [{label: "events", f: s => s.fleetEventsDelta}]},
  {title: "kernel launches", unit: "/s",
   series: [{label: "launches", f: (s, dt) => s.kernelLaunchesDelta / dt},
            {label: "tiles", f: (s, dt) => s.kernelTilesDelta / dt}]},
  {title: "tenant sheds", unit: "/interval",
   series: [{label: "sheds", f: s => s.tenantShedsDelta}]},
];
function fmt(v) {
  if (!isFinite(v)) return "-";
  if (Math.abs(v) >= 1000) return v.toFixed(0);
  if (Math.abs(v) >= 10) return v.toFixed(1);
  return v.toFixed(2);
}
function spark(rows) {
  const w = 320, h = 60, n = rows[0].length;
  let lo = Infinity, hi = -Infinity;
  for (const r of rows) for (const v of r) {
    if (isFinite(v)) { lo = Math.min(lo, v); hi = Math.max(hi, v); }
  }
  if (!isFinite(lo)) { lo = 0; hi = 1; }
  if (hi - lo < 1e-9) { hi = lo + 1; }
  const x = i => n < 2 ? w : i * w / (n - 1);
  const y = v => h - 4 - (v - lo) * (h - 8) / (hi - lo);
  let paths = "";
  rows.forEach((r, k) => {
    const pts = r.map((v, i) =>
      `${x(i).toFixed(1)},${y(isFinite(v) ? v : lo).toFixed(1)}`);
    paths += `<polyline fill="none" stroke="${COLORS[k % 5]}"
      stroke-width="1.5" points="${pts.join(" ")}"/>`;
  });
  return {svg: `<svg viewBox="0 0 ${w} ${h}"
    preserveAspectRatio="none">${paths}</svg>`, lo, hi};
}
function render(ts, vars) {
  const s = ts.samples || [];
  const dt = ts.intervalS || 1;
  const last = s[s.length - 1] || {};
  const counts = (vars && vars.counts) || {};
  document.getElementById("meta").textContent =
    `interval ${ts.intervalS}s · window ${ts.windowS}s · ` +
    `${s.length}/${ts.capacity} samples (${ts.coveredS}s covered) · ` +
    `queries served ${counts["query"] || 0} · ` +
    `up ${Math.round(last.uptimeS || 0)}s`;
  const active = ((vars && vars.alerts) || {}).active || {};
  document.getElementById("alerts").innerHTML =
    Object.keys(active).sort().map(id => {
      const a = active[id];
      return `<span class="alert ${a.severity}" title="${a.detail ||
        ""}">&#9888; ${id}</span>`;
    }).join("");
  const grid = document.getElementById("grid");
  grid.innerHTML = "";
  for (const c of CHARTS) {
    const rows = c.series.map(ser => s.map(p => ser.f(p, dt)));
    const {svg, lo, hi} = spark(rows.length ? rows : [[0]]);
    const now = rows.map((r, k) =>
      `<span style="color:${COLORS[k % 5]}">${c.series[k].label} ` +
      `${fmt(r[r.length - 1] ?? 0)}</span>`).join(" &middot; ");
    const card = document.createElement("div");
    card.className = "card";
    card.innerHTML = `<h2>${c.title} <span class="now">${now}` +
      ` ${c.unit}</span></h2>${svg}` +
      `<div class="axis"><span>${fmt(lo)}</span>` +
      `<span>${fmt(hi)} ${c.unit}</span></div>`;
    grid.appendChild(card);
  }
}
async function tick() {
  try {
    const [ts, vars] = await Promise.all([
      fetch("/debug/timeseries").then(r => r.json()),
      fetch("/debug/vars").then(r => r.json()).catch(() => null),
    ]);
    render(ts, vars);
    setTimeout(tick, Math.max((ts.intervalS || 5) * 1000, 1000));
  } catch (e) {
    document.getElementById("meta").innerHTML =
      `<span class="err">fetch failed: ${e}</span>`;
    setTimeout(tick, 5000);
  }
}
tick();
</script>
</body>
</html>
"""

# /debug/dashboard/cluster: the fleet page (docs/observability.md
# "Cluster plane") — a per-node table of the rollup summaries (stale
# nodes dimmed and flagged) plus the merged event timeline, polled from
# /debug/cluster on its TTL cadence.  Same zero-dependency discipline
# as the node page.
CLUSTER_DASHBOARD_HTML = """<!doctype html>
<html lang="en">
<head>
<meta charset="utf-8">
<title>pilosa-tpu fleet</title>
<style>
  :root { color-scheme: dark; }
  body { margin: 0; padding: 16px 20px; background: #14161a;
         color: #d6d9de; font: 13px/1.45 system-ui, sans-serif; }
  h1 { font-size: 15px; margin: 0 0 2px; font-weight: 600; }
  h2 { font-size: 13px; margin: 18px 0 6px; font-weight: 600;
       color: #aab0b9; }
  #meta { color: #8a8f98; margin-bottom: 14px; }
  table { border-collapse: collapse; width: 100%;
          font-variant-numeric: tabular-nums; }
  th, td { text-align: right; padding: 3px 10px;
           border-bottom: 1px solid #262a31; font-size: 12px; }
  th { color: #8a8f98; font-weight: 500; }
  th:first-child, td:first-child { text-align: left; }
  tr.stale td { color: #6b7077; }
  .down { color: #f7768e; }
  .flag { color: #e0af68; }
  #timeline { font: 11px/1.6 ui-monospace, monospace; color: #aab0b9;
              max-height: 320px; overflow-y: auto; background: #1b1e24;
              border: 1px solid #262a31; border-radius: 6px;
              padding: 8px 12px; }
  .ev { color: #7aa2f7; }
  .err { color: #e07a5f; }
</style>
</head>
<body>
<h1>pilosa-tpu &middot; fleet
  <a href="/debug/dashboard" style="font-size:11px; color:#7aa2f7;
     margin-left:10px">&larr; node view</a></h1>
<div id="meta">loading&hellip;</div>
<h2>nodes</h2>
<table id="nodes"><thead><tr>
  <th>node</th><th>state</th><th>qps</th><th>p99 ms</th>
  <th>HBM MB</th><th>evict</th><th>retrace</th><th>hedges</th>
  <th>waves</th><th>partial</th><th>quar</th><th>ingest MB</th>
  <th>alerts</th><th>stale s</th>
</tr></thead><tbody></tbody></table>
<h2>fleet timeline</h2>
<div id="timeline"></div>
<script>
"use strict";
const MB = b => (b / 1048576).toFixed(0);
function render(c) {
  const nodes = c.nodes || {};
  document.getElementById("meta").textContent =
    `coordinator ${c.coordinator} · epoch ${c.epoch} · ` +
    `overlay ${c.overlayEpoch} · refreshes ${c.refreshes} · ` +
    `fetch errors ${c.fetchErrors}`;
  const tb = document.querySelector("#nodes tbody");
  tb.innerHTML = "";
  for (const nid of Object.keys(nodes).sort()) {
    const n = nodes[nid];
    const tr = document.createElement("tr");
    if (n.stale) tr.className = "stale";
    const cells = [
      nid,
      n.state === "READY" ? "READY" :
        `<span class="down">${n.state}</span>`,
      (n.qps ?? 0).toFixed(1),
      n.p99Ms ?? "-",
      MB(n.hbmResidentBytes || 0),
      n.evictions ?? "-",
      n.retraces ?? "-",
      `${n.hedges ?? "-"}/${n.hedgeWins ?? "-"}`,
      n.retryWaves ?? "-",
      n.partialResults ?? "-",
      n.quarantinedFragments ?
        `<span class="flag">${n.quarantinedFragments}</span>` : 0,
      MB(n.ingestBacklogBytes || 0),
      n.activeAlerts ? `<span class="down" title="${
        (n.alertIds || []).join(", ")}">${n.activeAlerts}</span>` : 0,
      n.stale ? `<span class="flag">${
        n.staleS != null ? n.staleS.toFixed(0) : "?"}</span>` : "",
    ];
    tr.innerHTML = cells.map(x => `<td>${x}</td>`).join("");
    tb.appendChild(tr);
  }
  const tl = document.getElementById("timeline");
  tl.innerHTML = (c.timeline || []).slice(-200).reverse().map(e => {
    const when = e.wall ?
      new Date(e.wall * 1000).toISOString().slice(11, 19) : "-";
    const rest = Object.entries(e).filter(
      ([k]) => !["event", "node", "wall", "seq"].includes(k))
      .map(([k, v]) => `${k}=${JSON.stringify(v)}`).join(" ");
    return `${when} <b>${e.node || "?"}</b> ` +
      `<span class="ev">${e.event}</span> ${rest}`;
  }).join("<br>") || "no events yet";
}
async function tick() {
  try {
    const c = await fetch("/debug/cluster").then(r => r.json());
    render(c);
    setTimeout(tick, Math.max((c.ttlS || 2) * 1000, 1000));
  } catch (e) {
    document.getElementById("meta").innerHTML =
      `<span class="err">fetch failed: ${e}</span>`;
    setTimeout(tick, 5000);
  }
}
tick();
</script>
</body>
</html>
"""
