"""Admission control: bounded concurrent-query slots + weighted-fair
per-tenant wait queues in front of the executor.

The stdlib ThreadingHTTPServer spawns a thread per connection, so without
a gate a burst of queries all execute at once: device dispatch contends,
every query slows down, and the burst's tail piles onto an already-losing
position (congestion collapse).  The slot pool bounds concurrency; a
short bounded wait queue absorbs jitter; everything beyond that is
rejected IMMEDIATELY with 503 + Retry-After so clients back off instead
of queueing invisibly inside the server (the reference relies on Go's
scheduler + fixed worker pools, executor.go:80-110; here the pool is
explicit).

Fairness (docs/robustness.md "Tenant isolation"): the wait queue is
per-TENANT, drained by deficit round-robin — each tenant queue earns
``weight`` credits per scheduling visit (capped at ``weight * burst``,
the burst allowance an idle tenant banks for its return) and spends one
per admitted query, so slot grants converge to the weight ratio no
matter how hard one tenant floods.  When the total queue is full, the
tenant most over its fair share of the queue sheds FIRST — its own
newest waiter is evicted (or the arriving request rejected, when the
arriver IS the over-quota tenant) — so a polite tenant's waiters are
untouched by a neighbor's flood.  ``fair=False`` restores the single
FIFO queue and reject-the-arrival shedding exactly (the pre-isolation
behavior, kept for differential benches).

Rejections carry a COMPUTED Retry-After: the queue-timeout base scaled
by queue pressure, with decorrelated jitter so a synchronized client
cohort cannot re-stampede the queue on the same tick (clients honor
fractional values — cli.py ingest).

The ``/internal/`` query plane gets its OWN controller instance: a
coordinator holding a public slot fans out to peers whose internal
handling must never compete with (or be starved by) their public
traffic — otherwise N coordinators' fan-outs could fill every node's
public pool and deadlock the cluster against itself.

``begin_drain`` flips the controller into drain mode: new work is
rejected (503, Retry-After) while ``wait_drained`` lets in-flight queries
finish under a deadline — the graceful-shutdown half of the overload
armor (Server.close/drain)."""

from __future__ import annotations

import random
import time
from collections import OrderedDict, deque

from ..utils import tenant as qtenant
from ..utils.events import EVENTS
from ..utils.locks import make_condition

RETRY_AFTER_CAP_S = 30.0
MIN_WEIGHT = 0.05        # a zero/negative weight must not stall a queue
TENANT_STATS_MAX = 128   # per-tenant counter table LRU cap
SHED_EVENT_MIN_S = 1.0   # journal rate limit per (tenant, pool)


class AdmissionRejected(Exception):
    """Query rejected at admission (HTTP 503 + Retry-After)."""

    def __init__(self, msg: str, retry_after: float = 1.0):
        super().__init__(msg)
        self.retry_after = retry_after


def decorrelated_retry_after(base: float,
                             cap: float = RETRY_AFTER_CAP_S) -> float:
    """Jittered client backoff: uniform in [base, 3*base] (capped) so a
    cohort rejected on the same tick spreads its retries instead of
    re-stampeding in phase.  Fractional seconds on purpose — clients
    parse floats."""
    base = min(max(base, 1.0), cap)
    return round(min(cap, random.uniform(base, 3.0 * base)), 2)


class _TenantQueue:
    """One tenant's FIFO of waiters + its DRR scheduling state."""

    __slots__ = ("name", "weight", "deficit", "waiters")

    def __init__(self, name: str, weight: float, burst: float):
        self.name = name
        self.weight = weight
        # burst credits: a (re)appearing tenant starts with its full
        # allowance banked, so short bursts ride through un-queued-on
        self.deficit = weight * burst
        self.waiters: deque[dict] = deque()


class AdmissionController:
    """Slot pool + bounded weighted-fair wait queues.

    ``max_slots <= 0`` means unlimited concurrency — in-flight tracking
    still runs so draining works.  The wait queues hold at most
    ``2 * max_slots`` waiters TOTAL (beyond that the server is
    definitively overloaded and queueing only adds latency); each waiter
    gives up after ``queue_timeout`` seconds.  ``weights`` maps tenant
    name -> relative share (unlisted tenants weigh 1.0); ``burst`` is
    the banked-credit multiple; ``fair=False`` restores the legacy
    single-FIFO queue."""

    def __init__(self, max_slots: int = 0, queue_timeout: float = 0.5,
                 max_queue: int | None = None, stats=None,
                 name: str = "public",
                 weights: dict[str, float] | None = None,
                 burst: float = 8.0, fair: bool = True):
        self.max_slots = max_slots
        self.queue_timeout = queue_timeout
        self.max_queue = max_queue if max_queue is not None \
            else max(1, 2 * max_slots)
        self.stats = stats
        self.name = name
        self.weights = dict(weights or {})
        self.burst = max(float(burst), 1.0)
        self.fair = bool(fair)
        self._cond = make_condition("admission")
        self.in_use = 0
        self.waiting = 0
        self.draining = False
        # counters (surfaced at /debug/vars and, via stats, /metrics)
        self.admitted = 0
        self.queued = 0
        self.rejected_busy = 0       # waited queue_timeout, no slot freed
        self.rejected_queue_full = 0  # wait queue overflow
        self.rejected_draining = 0
        self.shed_over_quota = 0     # queue-full evictions of the most
        #                              over-share tenant's newest waiter
        # per-tenant queues live only while non-empty; counters persist
        self._queues: dict[str, _TenantQueue] = {}
        self._rr: list[str] = []    # DRR visit order (active queues)
        self._rr_idx = 0
        self._tenants: OrderedDict[str, dict] = OrderedDict()
        self._last_shed_event: dict[str, float] = {}

    # -- small helpers ------------------------------------------------------

    def _weight(self, tenant: str) -> float:
        return max(float(self.weights.get(tenant, 1.0)), MIN_WEIGHT)

    def _retry_after(self) -> float:
        """Computed, jittered backoff: base = queue timeout scaled by
        how full the wait queue already is."""
        base = max(1.0, self.queue_timeout
                   * (1.0 + self.waiting / max(self.max_queue, 1)))
        return decorrelated_retry_after(base)

    def retry_after(self) -> float:
        """Public alias for callers outside the controller (the ingest
        backpressure 503s reuse the pool's computed backoff)."""
        return self._retry_after()

    def _count(self, metric: str):
        if self.stats is not None:
            self.stats.count(f"admission.{self.name}.{metric}")

    def _tstats(self, tenant: str) -> dict:
        st = self._tenants.get(tenant)
        if st is None:
            while len(self._tenants) >= TENANT_STATS_MAX:
                self._tenants.popitem(last=False)
            st = self._tenants[tenant] = {
                "admitted": 0, "queued": 0, "shed": 0, "waitS": 0.0}
        else:
            self._tenants.move_to_end(tenant)
        return st

    def _queue_for(self, tenant: str) -> _TenantQueue:
        key = tenant if self.fair else ""
        q = self._queues.get(key)
        if q is None:
            q = self._queues[key] = _TenantQueue(
                key, self._weight(tenant), self.burst)
            self._rr.append(key)
        return q

    def _drop_queue(self, key: str):
        self._queues.pop(key, None)
        if key in self._rr:
            i = self._rr.index(key)
            self._rr.pop(i)
            if i < self._rr_idx:
                self._rr_idx -= 1
            if self._rr:
                self._rr_idx %= len(self._rr)

    def _reject(self, counter: str, msg: str, tenant: str):
        setattr(self, counter, getattr(self, counter) + 1)
        self._count("rejected")
        self._tstats(tenant)["shed"] += 1
        raise AdmissionRejected(msg, retry_after=self._retry_after())

    # -- acquire / release --------------------------------------------------

    def acquire(self, tenant: str | None = None) -> float:
        """Take a slot (returns seconds spent queued, 0.0 for immediate
        admission) or raise AdmissionRejected.  Every successful acquire
        MUST be paired with release().  The tenant defaults to the
        request context (utils/tenant.py)."""
        t = tenant if tenant is not None else qtenant.current()
        try:
            return self._acquire(t)
        except AdmissionRejected:
            # attribution OUTSIDE the condition: the registry/stats/
            # journal take their own locks
            self._attribute_shed(t, time.monotonic())
            raise

    def _acquire(self, t: str) -> float:
        with self._cond:
            if self.draining:
                self._reject("rejected_draining", "server is draining", t)
            if self.max_slots <= 0 or self.in_use < self.max_slots:
                self.in_use += 1
                self.admitted += 1
                self._tstats(t)["admitted"] += 1
                self._count("admitted")
                return 0.0
            if self.waiting >= self.max_queue \
                    and not self._make_room(t):
                self._reject(
                    "rejected_queue_full",
                    f"too many concurrent queries "
                    f"({self.in_use} running, {self.waiting} queued)", t)
            q = self._queue_for(t)
            w = {"tenant": t, "granted": False, "shed": False}
            q.waiters.append(w)
            self.waiting += 1
            self.queued += 1
            st = self._tstats(t)
            st["queued"] += 1
            t0 = time.monotonic()
            deadline = t0 + self.queue_timeout
            try:
                while True:
                    if w["granted"]:
                        waited = time.monotonic() - t0
                        self.admitted += 1
                        st["admitted"] += 1
                        st["waitS"] += waited
                        self._count("admitted")
                        return waited
                    if w["shed"]:
                        # evicted at queue-full time as the most
                        # over-share tenant (already off the queue)
                        self.shed_over_quota += 1
                        self._reject(
                            "rejected_queue_full",
                            f"shed: tenant {t!r} over its fair share "
                            f"of the wait queue", t)
                    if self.draining:
                        self._unlink(q, w)
                        self._reject("rejected_draining",
                                     "server is draining", t)
                    left = deadline - time.monotonic()
                    if left <= 0:
                        self._unlink(q, w)
                        self._reject(
                            "rejected_busy",
                            f"no query slot freed within "
                            f"{self.queue_timeout:.3g}s "
                            f"({self.in_use} running)", t)
                    self._cond.wait(left)
            finally:
                self.waiting -= 1

    def _unlink(self, q: _TenantQueue, w: dict):
        try:
            q.waiters.remove(w)
        except ValueError:
            pass
        if not q.waiters:
            self._drop_queue(q.name)

    def _make_room(self, arriving: str) -> bool:
        """Queue-full policy (fair mode): shed from the tenant most
        over its weight-normalized share of the queue.  If that's the
        arriver, reject it (return False); otherwise evict the
        over-share tenant's NEWEST waiter and admit the arrival to the
        queue (True) — a polite tenant is untouched by a flood."""
        if not self.fair or not self._rr:
            return False
        key = arriving  # fair mode keys queues by tenant
        shares = {k: len(self._queues[k].waiters)
                  / self._weight(self._queues[k].waiters[0]["tenant"])
                  for k in self._rr if self._queues[k].waiters}
        arriving_share = (shares.get(key, 0) + 1) / self._weight(arriving)
        victim = max(shares, key=lambda k: shares[k], default=None)
        if victim is None or shares[victim] < arriving_share:
            return False  # the arriver is the over-quota tenant
        vq = self._queues[victim]
        w = vq.waiters.pop()  # newest waiter: least sunk wait cost
        w["shed"] = True
        if not vq.waiters:
            self._drop_queue(victim)
        self._cond.notify_all()
        return True

    def _grant_locked(self):
        """Hand freed slots to waiters by deficit round-robin: each
        visit banks ``weight`` credits (capped at weight*burst), each
        grant spends one — service converges to the weight ratio."""
        while self._rr and (self.max_slots <= 0
                            or self.in_use < self.max_slots):
            if self.fair:
                guard = 0
                while True:
                    key = self._rr[self._rr_idx % len(self._rr)]
                    q = self._queues[key]
                    if q.deficit >= 1.0:
                        break
                    q.deficit = min(q.deficit + q.weight,
                                    q.weight * self.burst)
                    self._rr_idx = (self._rr_idx + 1) % len(self._rr)
                    guard += 1
                    if guard > 64 * len(self._rr):  # unreachable: the
                        break  # MIN_WEIGHT floor bounds refill rounds
                q.deficit -= 1.0
            else:
                q = self._queues[self._rr[0]]  # legacy: one FIFO queue
            w = q.waiters.popleft()
            if not q.waiters:
                self._drop_queue(q.name)
            w["granted"] = True
            self.in_use += 1
        self._cond.notify_all()

    def release(self):
        with self._cond:
            self.in_use -= 1
            # grant under the SAME lock hold: an arrival can never
            # steal the freed slot past a queued waiter.  notify_all
            # (via _grant_locked): granted waiters AND wait_drained may
            # be parked on the same condition (tiny scale, not hot).
            self._grant_locked()

    def _attribute_shed(self, tenant: str, now: float):
        """Per-tenant shed accounting outside the condition: stats
        series, the tenant registry, and a rate-limited journal event
        (a flood must not write one event per rejected request)."""
        if self.stats is not None:
            self.stats.count(f"tenant.{tenant}.shed")
        qtenant.REGISTRY.note_shed(tenant, self.name)
        last = self._last_shed_event.get(tenant, 0.0)
        if now - last >= SHED_EVENT_MIN_S:
            self._last_shed_event[tenant] = now
            EVENTS.emit("tenant.shed", tenant=tenant, pool=self.name)

    # -- drain -------------------------------------------------------------

    def begin_drain(self):
        """Stop admitting; queued waiters are rejected immediately."""
        with self._cond:
            self.draining = True
            self._cond.notify_all()

    def wait_drained(self, timeout: float) -> bool:
        """Block until in-flight work finishes (True) or the drain
        deadline passes (False — the caller closes anyway)."""
        deadline = time.monotonic() + timeout
        with self._cond:
            while self.in_use > 0:
                left = deadline - time.monotonic()
                if left <= 0:
                    return False
                self._cond.wait(left)
            return True

    def snapshot(self) -> dict:
        with self._cond:
            tenants = {}
            for t, st in self._tenants.items():
                q = self._queues.get(t) if self.fair else None
                tenants[t] = {
                    "weight": self._weight(t),
                    "admitted": st["admitted"],
                    "queued": st["queued"],
                    "shed": st["shed"],
                    "waiting": len(q.waiters) if q is not None else 0,
                    "deficit": round(q.deficit, 3)
                    if q is not None else None,
                    "avgWaitMs": round(
                        st["waitS"] / st["queued"] * 1e3, 3)
                    if st["queued"] else 0.0,
                }
            return {
                "maxSlots": self.max_slots,
                "queueTimeoutS": self.queue_timeout,
                "maxQueue": self.max_queue,
                "inUse": self.in_use,
                "waiting": self.waiting,
                "draining": self.draining,
                "admitted": self.admitted,
                "queued": self.queued,
                "rejectedBusy": self.rejected_busy,
                "rejectedQueueFull": self.rejected_queue_full,
                "rejectedDraining": self.rejected_draining,
                "shedOverQuota": self.shed_over_quota,
                "fair": self.fair,
                "tenants": tenants,
            }
