"""Admission control: bounded concurrent-query slots + a bounded wait
queue in front of the executor.

The stdlib ThreadingHTTPServer spawns a thread per connection, so without
a gate a burst of queries all execute at once: device dispatch contends,
every query slows down, and the burst's tail piles onto an already-losing
position (congestion collapse).  The slot pool bounds concurrency; a
short bounded wait queue absorbs jitter; everything beyond that is
rejected IMMEDIATELY with 503 + Retry-After so clients back off instead
of queueing invisibly inside the server (the reference relies on Go's
scheduler + fixed worker pools, executor.go:80-110; here the pool is
explicit).

The ``/internal/`` query plane gets its OWN controller instance: a
coordinator holding a public slot fans out to peers whose internal
handling must never compete with (or be starved by) their public
traffic — otherwise N coordinators' fan-outs could fill every node's
public pool and deadlock the cluster against itself.

``begin_drain`` flips the controller into drain mode: new work is
rejected (503, Retry-After) while ``wait_drained`` lets in-flight queries
finish under a deadline — the graceful-shutdown half of the overload
armor (Server.close/drain)."""

from __future__ import annotations

import math
import time

from ..utils.locks import make_condition


class AdmissionRejected(Exception):
    """Query rejected at admission (HTTP 503 + Retry-After)."""

    def __init__(self, msg: str, retry_after: int = 1):
        super().__init__(msg)
        self.retry_after = retry_after


class AdmissionController:
    """Slot pool + bounded wait queue.

    ``max_slots <= 0`` means unlimited concurrency — in-flight tracking
    still runs so draining works.  The wait queue holds at most
    ``2 * max_slots`` waiters (beyond that the server is definitively
    overloaded and queueing only adds latency); each waiter gives up
    after ``queue_timeout`` seconds."""

    def __init__(self, max_slots: int = 0, queue_timeout: float = 0.5,
                 max_queue: int | None = None, stats=None,
                 name: str = "public"):
        self.max_slots = max_slots
        self.queue_timeout = queue_timeout
        self.max_queue = max_queue if max_queue is not None \
            else max(1, 2 * max_slots)
        self.stats = stats
        self.name = name
        self._cond = make_condition("admission")
        self.in_use = 0
        self.waiting = 0
        self.draining = False
        # counters (surfaced at /debug/vars and, via stats, /metrics)
        self.admitted = 0
        self.queued = 0
        self.rejected_busy = 0       # waited queue_timeout, no slot freed
        self.rejected_queue_full = 0  # wait queue overflow
        self.rejected_draining = 0

    def _retry_after(self) -> int:
        return max(1, math.ceil(self.queue_timeout))

    def _count(self, metric: str):
        if self.stats is not None:
            self.stats.count(f"admission.{self.name}.{metric}")

    def _reject(self, counter: str, msg: str):
        setattr(self, counter, getattr(self, counter) + 1)
        self._count("rejected")
        raise AdmissionRejected(msg, retry_after=self._retry_after())

    def acquire(self):
        """Take a slot or raise AdmissionRejected.  Every successful
        acquire MUST be paired with release()."""
        with self._cond:
            if self.draining:
                self._reject("rejected_draining", "server is draining")
            if self.max_slots <= 0 or self.in_use < self.max_slots:
                self.in_use += 1
                self.admitted += 1
                self._count("admitted")
                return
            if self.waiting >= self.max_queue:
                self._reject(
                    "rejected_queue_full",
                    f"too many concurrent queries "
                    f"({self.in_use} running, {self.waiting} queued)")
            self.waiting += 1
            self.queued += 1
            deadline = time.monotonic() + self.queue_timeout
            try:
                while True:
                    if self.draining:
                        self._reject("rejected_draining",
                                     "server is draining")
                    if self.in_use < self.max_slots:
                        self.in_use += 1
                        self.admitted += 1
                        self._count("admitted")
                        return
                    left = deadline - time.monotonic()
                    if left <= 0:
                        self._reject(
                            "rejected_busy",
                            f"no query slot freed within "
                            f"{self.queue_timeout:.3g}s "
                            f"({self.in_use} running)")
                    self._cond.wait(left)
            finally:
                self.waiting -= 1

    def release(self):
        with self._cond:
            self.in_use -= 1
            # notify_all: waiters race for the slot AND wait_drained may
            # be parked on the same condition (tiny scale, not a hot path)
            self._cond.notify_all()

    # -- drain -------------------------------------------------------------

    def begin_drain(self):
        """Stop admitting; queued waiters are rejected immediately."""
        with self._cond:
            self.draining = True
            self._cond.notify_all()

    def wait_drained(self, timeout: float) -> bool:
        """Block until in-flight work finishes (True) or the drain
        deadline passes (False — the caller closes anyway)."""
        deadline = time.monotonic() + timeout
        with self._cond:
            while self.in_use > 0:
                left = deadline - time.monotonic()
                if left <= 0:
                    return False
                self._cond.wait(left)
            return True

    def snapshot(self) -> dict:
        with self._cond:
            return {
                "maxSlots": self.max_slots,
                "queueTimeoutS": self.queue_timeout,
                "maxQueue": self.max_queue,
                "inUse": self.in_use,
                "waiting": self.waiting,
                "draining": self.draining,
                "admitted": self.admitted,
                "queued": self.queued,
                "rejectedBusy": self.rejected_busy,
                "rejectedQueueFull": self.rejected_queue_full,
                "rejectedDraining": self.rejected_draining,
            }
