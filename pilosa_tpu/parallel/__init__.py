"""Distribution: shard placement + device-mesh execution + cluster
(reference cluster.go / executor.go mapReduce, rebuilt on jax.sharding)."""

from .placement import JmpHasher, ModHasher, Placement, jump_hash  # noqa: F401
from .mesh_exec import MeshExecutor, default_mesh  # noqa: F401
