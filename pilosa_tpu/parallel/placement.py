"""Shard placement: shard -> partition -> owner ring (cluster.go:871-959).

The same placement logic serves two layers:

* cluster level — shards to *nodes* (hosts), with ReplicaN successors on the
  ring, exactly like the reference;
* device level — a node's local shards to *TPU devices* in its mesh, where
  the "nodes" are device ordinals.

partition = FNV-1a(index, shard BE bytes) mod partition_n (cluster.go:871);
partition -> node via jump consistent hash (cluster.go:951 jmphasher), then
ReplicaN successors (cluster.go:902 partitionNodes).
"""

from __future__ import annotations

import struct

from ..core import DEFAULT_PARTITION_N

_FNV64_OFFSET = 0xCBF29CE484222325
_FNV64_PRIME = 0x100000001B3
_MASK64 = (1 << 64) - 1


def fnv1a64(data: bytes) -> int:
    h = _FNV64_OFFSET
    for b in data:
        h ^= b
        h = (h * _FNV64_PRIME) & _MASK64
    return h


def jump_hash(key: int, n: int) -> int:
    """Jump consistent hash: key -> bucket in [0, n)
    (cluster.go:951-959 jmphasher.Hash)."""
    key &= _MASK64
    b, j = -1, 0
    while j < n:
        b = j
        key = (key * 2862933555777941757 + 1) & _MASK64
        j = int((b + 1) * ((1 << 31) / ((key >> 33) + 1)))
    return b


class ModHasher:
    """Deterministic key%n hasher for tests (test/cluster.go:18 ModHasher)."""

    def hash(self, key: int, n: int) -> int:
        return key % n


class JmpHasher:
    def hash(self, key: int, n: int) -> int:
        return jump_hash(key, n)


class Placement:
    """Maps (index, shard) to an ordered owner list over a node list."""

    def __init__(self, nodes: list[str], replica_n: int = 1,
                 partition_n: int = DEFAULT_PARTITION_N, hasher=None):
        if not nodes:
            raise ValueError("placement requires at least one node")
        self.nodes = list(nodes)
        self.replica_n = replica_n
        self.partition_n = partition_n
        self.hasher = hasher or JmpHasher()

    def partition(self, index: str, shard: int) -> int:
        """(cluster.go:871 partition)"""
        data = index.encode() + struct.pack(">Q", shard)
        return fnv1a64(data) % self.partition_n

    def partition_nodes(self, partition_id: int) -> list[str]:
        """(cluster.go:902 partitionNodes)"""
        n = len(self.nodes)
        replica_n = min(self.replica_n, n) or 1
        start = self.hasher.hash(partition_id, n)
        return [self.nodes[(start + i) % n] for i in range(replica_n)]

    def shard_nodes(self, index: str, shard: int) -> list[str]:
        """Ordered owners (primary first) of a shard (cluster.go:883)."""
        return self.partition_nodes(self.partition(index, shard))

    def primary(self, index: str, shard: int) -> str:
        return self.shard_nodes(index, shard)[0]

    def owns_shard(self, node: str, index: str, shard: int) -> bool:
        """(cluster.go:895 ownsShard)"""
        return node in self.shard_nodes(index, shard)

    def owned_shards(self, node: str, index: str,
                     shards) -> list[int]:
        """Shards (incl. replicas) this node holds
        (cluster.go:927 containsShards)."""
        return [s for s in shards if self.owns_shard(node, index, s)]

    def shards_by_node(self, index: str, shards) -> dict[str, list[int]]:
        """Group shards by primary owner (executor.go:2435 shardsByNode)."""
        out: dict[str, list[int]] = {}
        for s in shards:
            out.setdefault(self.primary(index, s), []).append(s)
        return out
