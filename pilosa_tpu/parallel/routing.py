"""Load-aware replica read routing (docs/cluster.md "Read routing &
rebalancing"; ROADMAP item 5a/5c).

The static half of the reference design groups read fan-out shards by
their jump-hash PRIMARY (cluster.go:883, executor.go:2435 shardsByNode):
replicas only absorb failures, so one hot index saturates one node while
its replicas idle.  This module owns the read-side placement decision
instead: every coordinator fan-out asks the :class:`ReadRouter` which
replica answers each shard, scored from what the cluster already
measures —

* per-peer EWMA RTT and coordinator-observed in-flight RPC depth (fed by
  ``Cluster._fan_out_multi``'s existing per-peer timing);
* peer admission-pool depth, piggybacked on ``/internal/query`` responses
  and ``/status`` probes (the same piggyback pattern as the PR 3 gen
  summaries);
* per-shard residency tiers (HBM-resident / host-staged / disk-only)
  advertised by each node from its ``DeviceBudget``/staging state
  (``Cluster.residency_summary``), so the router prefers the replica
  that can answer without an upload — PR 1's residency-aware scheduling
  extended across the cluster.

Policies (``read-routing`` knob):

* ``primary``      — the pre-PR behavior, byte-for-byte: self if an
  owner, else the first READY owner in placement order.
* ``round-robin``  — rotate among READY owners per shard.
* ``loaded``       — scored selection as above; with no load data yet it
  falls back to the primary choice, counted ``routing.fallback``.

Replica choice never changes answers: writes fan to every replica
synchronously and anti-entropy converges the rest, so any READY owner
holds the same bits (the differential suite in tests/test_routing.py
proves byte-identity).  Writes and anti-entropy do NOT route through
this module — only the read fan-out does.

Breaker pre-skip: a peer whose circuit breaker is open is excluded
BEFORE dispatch (counted ``routing.breaker_skip`` and marked DOWN, the
same convergence the fail-fast path produced) instead of burning a
``CircuitOpenError`` round through the fan-out's retry machinery first.
When every candidate's breaker is open the skip is waived so the
fail-fast error still surfaces loudly.
"""

from __future__ import annotations

import time

from ..utils import explain as qexplain
from ..utils.locks import make_lock

# EWMA smoothing for per-peer RTT: new = (1-a)*old + a*sample.
EWMA_ALPHA = 0.25
# Residency summaries older than this (seconds since last piggyback) are
# ignored — a stale map must not keep routing to a node that already
# evicted the shard.
RESIDENCY_TTL_S = 30.0
# Score discount for a fully HBM-resident shard (host-staged counts
# half): 1.0 would make residency override load entirely; 0.6 keeps an
# overloaded-but-resident replica beatable by an idle cold one.
RESIDENCY_DISCOUNT = 0.6
# Local execution skips the wire: its score gets this factor so that at
# equal load the coordinator still prefers itself (the primary policy's
# self-preference, kept as a bias instead of an absolute).
LOCAL_BIAS = 0.8

POLICIES = ("primary", "round-robin", "loaded")

# Hedged reads (docs/robustness.md "Tail-tolerant fan-out"): with
# hedge-delay-ms = 0 (auto) the hedge fires at this multiple of the
# CLUSTER's cheapest known EWMA RTT — "how long should this RPC take if
# a healthy replica served it", the Dean & Barroso quantile idea on the
# signal the router already keeps.  Deliberately NOT the dispatched
# peer's own EWMA: a persistently straggling peer would inflate its own
# hedge delay until hedging never fires, exactly when it matters most.
HEDGE_EWMA_MULT = 4.0
# Floor so micro-RTT local clusters don't turn every read into two.
HEDGE_MIN_DELAY_S = 0.01


def tier_fraction(tiers: dict | None, shard: int) -> float:
    """Residency fraction for scoring — the ONE tier mapping (1.0
    HBM-resident, 0.5 host-staged, 0.0 disk-only/unknown), shared by the
    peer (piggybacked-summary) and local paths so a tier-weight change
    can never skew local-vs-remote scoring."""
    if not tiers:
        return 0.0
    if shard in tiers.get("hbm", ()):
        return 1.0
    if shard in tiers.get("host", ()):
        return 0.5
    return 0.0


class PeerLoad:
    """Routing state for one node, folded from RPC timings and
    piggybacked load/residency summaries."""

    __slots__ = ("ewma_rtt_s", "last_rtt_s", "inflight", "reported_inflight",
                 "reported_queued", "residency", "residency_ts",
                 "dispatches", "errors", "hedges", "hedge_wins")

    def __init__(self):
        self.ewma_rtt_s: float | None = None
        self.last_rtt_s: float | None = None
        self.inflight = 0           # coordinator-observed in-flight RPCs
        self.reported_inflight = 0  # peer's own admission in-use (piggyback)
        self.reported_queued = 0    # peer's admission wait-queue depth
        # index -> {"hbm": set[int], "host": set[int]} shard tiers
        self.residency: dict[str, dict[str, set[int]]] = {}
        self.residency_ts: float | None = None  # monotonic, for staleness
        self.dispatches = 0
        self.errors = 0
        # hedged reads: speculative duplicates dispatched TO this peer,
        # and how many of those answered first (per-peer hedge state for
        # /debug/vars cluster.routing)
        self.hedges = 0
        self.hedge_wins = 0

    def note_rtt(self, rtt_s: float):
        self.last_rtt_s = rtt_s
        if self.ewma_rtt_s is None:
            self.ewma_rtt_s = rtt_s
        else:
            self.ewma_rtt_s = ((1 - EWMA_ALPHA) * self.ewma_rtt_s
                               + EWMA_ALPHA * rtt_s)

    def shard_tier(self, index: str, shard: int,
                   now: float) -> float:
        """tier_fraction over the piggybacked summary, 0.0 when the
        summary is stale (older than RESIDENCY_TTL_S)."""
        if self.residency_ts is None or \
                now - self.residency_ts > RESIDENCY_TTL_S:
            return 0.0
        return tier_fraction(self.residency.get(index), shard)


class ReadRouter:
    """Per-shard replica selection for the read fan-out.

    Owned by :class:`~pilosa_tpu.parallel.cluster.Cluster`; the cluster
    feeds it dispatch/completion events and piggybacked peer summaries,
    and calls :meth:`group_shards` wherever it used to group by primary.
    All mutable state lives behind one leaf lock (never held across I/O
    or another lock)."""

    def __init__(self, cluster, policy: str = "loaded",
                 residency_routing: bool = True, stats=None):
        if policy not in POLICIES:
            raise ValueError(
                f"read-routing must be one of {POLICIES}, got {policy!r}")
        self.cluster = cluster
        self.policy = policy
        self.residency_routing = residency_routing
        self.stats = stats
        self._peers: dict[str, PeerLoad] = {}
        self._lock = make_lock("routing")
        self._rr = 0  # round-robin rotation cursor
        self.fallbacks = 0
        self.breaker_skips = 0

    # -- state feeds -------------------------------------------------------

    def _peer(self, nid: str) -> PeerLoad:
        p = self._peers.get(nid)
        if p is None:
            with self._lock:
                p = self._peers.setdefault(nid, PeerLoad())
        return p

    def note_dispatch(self, nid: str, n_shards: int):
        """A shard group was handed to ``nid`` (RPC submitted or local
        execution started)."""
        p = self._peer(nid)
        with self._lock:
            p.inflight += 1
            p.dispatches += 1

    def note_done(self, nid: str, rtt_s: float | None, ok: bool = True):
        p = self._peer(nid)
        with self._lock:
            if p.inflight > 0:
                p.inflight -= 1
            if ok and rtt_s is not None:
                p.note_rtt(rtt_s)
            elif not ok:
                p.errors += 1

    def note_hedge(self, nid: str):
        """A speculative duplicate was dispatched to ``nid``."""
        p = self._peer(nid)
        with self._lock:
            p.hedges += 1

    def note_hedge_win(self, nid: str):
        """``nid``'s hedged answer arrived before the original's."""
        p = self._peer(nid)
        with self._lock:
            p.hedge_wins += 1

    def note_query_load(self, nid: str, load: dict | None):
        """Admission depth piggybacked on an /internal/query response."""
        if not load:
            return
        p = self._peer(nid)
        with self._lock:
            p.reported_inflight = int(load.get("inFlight", 0))
            p.reported_queued = int(load.get("queued", 0))

    def note_status(self, nid: str, status: dict):
        """Fold a /status probe's piggybacked load + residency summary."""
        p = self._peer(nid)
        load = status.get("load") or {}
        res = status.get("residency")
        with self._lock:
            if load:
                p.reported_inflight = int(load.get("inFlight", 0))
                p.reported_queued = int(load.get("queued", 0))
            if res is not None:
                p.residency = {
                    iname: {"hbm": set(t.get("hbm", ())),
                            "host": set(t.get("host", ()))}
                    for iname, t in res.items()}
                p.residency_ts = time.monotonic()

    # -- selection ---------------------------------------------------------

    def group_shards(self, index: str, shards, exclude=frozenset()
                     ) -> dict[str, list[int]]:
        """shard -> chosen replica, grouped (the read fan-out's
        replacement for grouping by jump-hash primary).  Raises
        ClusterError with the legacy message when a shard has no
        available node, so the fan-out's re-admit machinery is
        unchanged."""
        from .cluster import ClusterError

        cluster = self.cluster
        now = time.monotonic()
        local_res = None
        policy = self.policy
        rr = 0
        if policy == "round-robin":
            with self._lock:
                rr = self._rr
                self._rr += 1
        groups: dict[str, list[int]] = {}
        scores: dict[str, float | None] = {}
        fell_back = False
        # EXPLAIN (utils/explain.py): per-shard choice + score breakdown
        # collected only when a record is active, and only WHILE the
        # routing section has capacity — past the cap a minimal note
        # keeps the truncation counted without building the per-
        # candidate breakdowns the record would drop anyway
        explain_active = qexplain.active()
        for s in shards:
            want_explain = explain_active and qexplain.wants("routing")
            # legacy candidate order exactly (the cluster's
            # _ready_owner_order — overlay-aware — plus the exclude
            # filter): ready owners, or ALL owners when none are ready.
            # An all-excluded ready set raises so the fan-out's re-admit
            # machinery decides, rather than this layer quietly
            # targeting a DOWN node.
            candidates = [o for o in cluster._ready_owner_order(index, s)
                          if o not in exclude]
            if not candidates:
                raise ClusterError(
                    f"no available node for shard {s} of {index!r}")
            pre_skip = list(candidates)
            candidates = self._skip_open_breakers(candidates)
            primary_pick = cluster.node_id \
                if cluster.node_id in candidates else candidates[0]
            breakdown = None
            if policy == "primary" or len(candidates) == 1:
                pick = primary_pick
            elif policy == "round-robin":
                pick = candidates[(rr + int(s)) % len(candidates)]
            else:  # loaded
                if local_res is None and self.residency_routing:
                    local_res = cluster.residency_summary()
                breakdown = {} if want_explain else None
                pick, fb = self._pick_loaded(index, int(s), candidates,
                                             primary_pick, scores, now,
                                             local_res,
                                             breakdown=breakdown)
                fell_back = fell_back or fb
            if want_explain:
                entry = {"shard": int(s), "chosen": pick,
                         "policy": policy,
                         "candidates": list(candidates)}
                skipped = [nid for nid in pre_skip
                           if nid not in candidates]
                if skipped:
                    entry["breakerSkipped"] = skipped
                if breakdown:
                    entry["scores"] = breakdown
                qexplain.note("routing", entry)
            elif explain_active:
                # over the section cap: dropped by note(), but counted
                # in the record's `truncated` so overflow stays visible
                qexplain.note("routing", {"shard": int(s)})
            groups.setdefault(pick, []).append(s)
        if fell_back:
            with self._lock:
                self.fallbacks += 1
            if self.stats is not None:
                self.stats.count("routing.fallback")
        return groups

    def _skip_open_breakers(self, candidates: list[str]) -> list[str]:
        """Drop breaker-open peers BEFORE dispatch (counted
        ``routing.breaker_skip``; the skipped node is marked DOWN, the
        same convergence the fail-fast path produced).  Waived when every
        candidate is open — the fan-out must still surface the failure
        rather than invent 'no available node'."""
        cluster = self.cluster
        client = cluster.client
        open_ = [nid for nid in candidates
                 if nid != cluster.node_id
                 and client.breaker_open(cluster.by_id[nid].host)]
        if not open_ or len(open_) == len(candidates):
            return candidates
        for nid in open_:
            with self._lock:
                self.breaker_skips += 1
            if self.stats is not None:
                self.stats.count("routing.breaker_skip")
            cluster._mark_down(nid)
        return [nid for nid in candidates if nid not in open_]

    def _pick_loaded(self, index: str, shard: int, candidates: list[str],
                     primary_pick: str, score_cache: dict, now: float,
                     local_res, breakdown: dict | None = None
                     ) -> tuple[str, bool]:
        """Scored choice: EWMA RTT x queue pressure, discounted for
        residency.  A candidate with no RTT history yet scores with the
        cheapest KNOWN candidate's EWMA (optimistic default — a
        never-tried replica must stay explorable, or the first-served
        node would keep every shard forever); when EVERY candidate is
        unknown the router falls back to the primary choice (returned
        flag counts ``routing.fallback``).  ``breakdown``: optional dict
        filled with each candidate's score components (the EXPLAIN
        routing section)."""
        infos = []
        for nid in candidates:
            if nid not in score_cache:
                score_cache[nid] = self._load_factors(nid)
            infos.append((nid,) + score_cache[nid])
        known = [ewma for _, ewma, _ in infos if ewma is not None]
        if not known:
            if breakdown is not None:
                breakdown["fallback"] = "no-rtt-history"
            return primary_pick, True
        default_ewma = min(known)
        local_id = self.cluster.node_id
        best = None
        best_score = None
        for nid, ewma, pressure in infos:
            score = (ewma if ewma is not None else default_ewma) * pressure
            if nid == local_id:
                score *= LOCAL_BIAS
            frac = 0.0
            if self.residency_routing:
                if nid == local_id:
                    frac = self._local_tier(local_res, index, shard)
                else:
                    with self._lock:
                        frac = self._peers[nid].shard_tier(index, shard,
                                                           now) \
                            if nid in self._peers else 0.0
                score = score * (1.0 - RESIDENCY_DISCOUNT * frac)
            if breakdown is not None:
                breakdown[nid] = {
                    "ewmaMs": round((ewma if ewma is not None
                                     else default_ewma) * 1e3, 3),
                    "ewmaDefaulted": ewma is None,
                    "pressure": round(pressure, 3),
                    "residencyTier": frac,
                    "localBias": nid == local_id,
                    "score": round(score * 1e3, 4)}
            if best_score is None or score < best_score:
                best, best_score = nid, score
        return best, False

    def _load_factors(self, nid: str) -> tuple[float | None, float]:
        """(ewma_rtt or None, queue-pressure factor) — the residency-
        independent parts of the score, cached per group_shards call."""
        with self._lock:
            p = self._peers.get(nid)
            if p is None:
                return None, 1.0
            return p.ewma_rtt_s, (1.0 + p.inflight
                                  + p.reported_inflight
                                  + 2.0 * p.reported_queued)

    @staticmethod
    def _local_tier(local_res, index: str, shard: int) -> float:
        # the local summary is TTL-fresh by construction
        # (Cluster.residency_summary caches for 2s) — no staleness gate
        return tier_fraction((local_res or {}).get(index), shard)

    # -- hedged reads (docs/robustness.md "Tail-tolerant fan-out") ---------

    def hedge_delay(self, fixed_s: float = 0.0) -> float | None:
        """Seconds an in-flight read RPC may run before a speculative
        duplicate fires.  ``fixed_s > 0`` (hedge-delay-ms) wins; auto
        mode derives HEDGE_EWMA_MULT x the cheapest KNOWN peer EWMA (see
        the constant's comment for why not the dispatched peer's own).
        None = no history yet — a cold cluster must not hedge blind."""
        if fixed_s > 0:
            return fixed_s
        with self._lock:
            known = [p.ewma_rtt_s for p in self._peers.values()
                     if p.ewma_rtt_s is not None]
        if not known:
            return None
        return max(HEDGE_MIN_DELAY_S, HEDGE_EWMA_MULT * min(known))

    def hedge_candidate(self, index: str, shards,
                        exclude=frozenset()) -> str | None:
        """Best replica to receive a speculative duplicate of a whole
        dispatched shard group: must be READY, own EVERY shard of the
        group (a partial hedge could double-count shards against the
        original's aggregate answer), not excluded, not breaker-open,
        and not the local node (local execution is not a network
        straggler).  Cheapest load score wins; None = nobody qualifies
        and the group goes unhedged."""
        cluster = self.cluster
        cand: set[str] | None = None
        for s in shards:
            owners = {o for o in cluster._ready_owner_order(index, s)
                      if cluster.by_id[o].state == "READY"}
            cand = owners if cand is None else cand & owners
            if not cand:
                return None
        cand -= set(exclude)
        cand.discard(cluster.node_id)
        cand = {nid for nid in cand
                if not cluster.client.breaker_open(
                    cluster.by_id[nid].host)}
        if not cand:
            return None
        # same optimistic default as _pick_loaded: a no-history
        # candidate scores with the cheapest KNOWN candidate's EWMA so
        # it stays explorable WITHOUT unconditionally beating a known-
        # fast idle replica (and its queue pressure still counts —
        # hedges fire exactly when latency matters most).  All-unknown
        # degenerates to pure pressure ordering.
        infos = [(nid,) + self._load_factors(nid) for nid in sorted(cand)]
        known = [ewma for _, ewma, _ in infos if ewma is not None]
        default_ewma = min(known) if known else 1.0
        best = None
        best_score = None
        for nid, ewma, pressure in infos:
            score = (ewma if ewma is not None else default_ewma) * pressure
            if best_score is None or score < best_score:
                best, best_score = nid, score
        return best

    # -- observability -----------------------------------------------------

    def snapshot(self) -> dict:
        """Per-peer routing state for /debug/vars ``cluster.routing``."""
        now = time.monotonic()
        with self._lock:
            peers = {}
            for nid, p in self._peers.items():
                peers[nid] = {
                    "ewmaRttMs": round(p.ewma_rtt_s * 1e3, 3)
                    if p.ewma_rtt_s is not None else None,
                    "lastRttMs": round(p.last_rtt_s * 1e3, 3)
                    if p.last_rtt_s is not None else None,
                    "inFlight": p.inflight,
                    "reportedInFlight": p.reported_inflight,
                    "reportedQueued": p.reported_queued,
                    "residencyAgeS": round(now - p.residency_ts, 3)
                    if p.residency_ts is not None else None,
                    "residentShards": {
                        iname: {"hbm": len(t.get("hbm", ())),
                                "host": len(t.get("host", ()))}
                        for iname, t in p.residency.items()},
                    "dispatches": p.dispatches,
                    "errors": p.errors,
                    "hedges": p.hedges,
                    "hedgeWins": p.hedge_wins,
                }
            out = {
                "policy": self.policy,
                "residencyRouting": self.residency_routing,
                "fallbacks": self.fallbacks,
                "breakerSkips": self.breaker_skips,
                "peers": peers,
            }
        # breaker state rides along so one surface answers "why was this
        # peer skipped"; wire mode likewise answers "which internal
        # query wire would the next fan-out to this peer speak"
        # (docs/cluster.md "Internal query wire")
        for nid, info in out["peers"].items():
            node = self.cluster.by_id.get(nid)
            if node is not None:
                info["breakerOpen"] = \
                    self.cluster.client.breaker_open(node.host)
                info["state"] = node.state
                info["wire"] = \
                    self.cluster.client.peer_wire_mode(node.host)
        return out

    def peer_states(self) -> list[tuple[str, dict]]:
        """(nid, flat-gauge dict) pairs for the /metrics exporter."""
        snap = self.snapshot()
        out = []
        for nid, p in snap["peers"].items():
            out.append((nid, {
                "ewma_rtt_ms": p["ewmaRttMs"] or 0.0,
                "inflight": p["inFlight"] + p["reportedInFlight"],
                "queued": p["reportedQueued"],
                "residency_age_s": p["residencyAgeS"]
                if p["residencyAgeS"] is not None else -1.0,
                "breaker_open": 1 if p.get("breakerOpen") else 0,
                "dispatches": p["dispatches"],
            }))
        return out
