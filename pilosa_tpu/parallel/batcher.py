"""Cross-query dynamic batching for device dispatch.

Concurrent request threads used to launch one shard_map executable per
query behind the process-wide collective-launch lock
(mesh_exec._DISPATCH_LOCK): under load the device serialized one
dispatch-floor launch per query, which is why the served HTTP path peaked
~two orders of magnitude below the hand-batched engine path (BENCH_r05
``2_http_path`` vs ``1_count_row_1shard``).  The reference amortizes
per-query overhead by fanning shard jobs into a shared goroutine pool
(executor.go:2455 mapReduce); the TPU-native analog is to coalesce
compatible in-flight queries into ONE fused device launch — the
continuous/dynamic-batching shape serving stacks use to amortize kernel
dispatch.

Mechanics: each per-shard reducer call (``count``, ``row_counts``,
``bsi_sum``, ``segments``) is enqueued as a ticket keyed by its
executable signature (reducer kind, slotted-plan repr, primary
field/view, index, shard set, holder); a dispatcher thread drains
compatible tickets — stacking their parametrized row/filter argument
rows along a leading query axis, launching one jitted shard_map
executable vmapped over that axis (mesh_exec's ``*_batch_async``
executables), and scattering per-query result slices back to waiting
futures.  Launch policy is adaptive: fire when the queue reaches
``max_batch`` tickets or the oldest ticket has waited ``window_us``
microseconds; fused query-axis sizes pad up to powers of two so
compile-cache churn stays bounded.  A group that drains to a single
singleton ticket falls through to the existing un-vmapped executables,
so solo-query latency is unchanged (modulo the window wait).

Deadlines (docs/robustness.md): time queued here counts against the
query budget — tickets carry their QueryContext, and an expired or
cancelled ticket is dropped from the batch BEFORE launch (its waiter
gets DeadlineExceeded -> HTTP 504), never after.  Composition with the
other serving layers (docs/batching.md): over-budget working sets (the
PR1 shard-streaming path) bypass fusion and stream per ticket;
result-cache lookups (PR3) happen before a ticket is ever created;
admission control (PR2) gates the HTTP edge upstream of the queue.
Multi-process meshes bypass the batcher entirely — independent
per-process windows would fuse different batch shapes and wedge the
collectives.
"""

from __future__ import annotations

import itertools
import threading
import time
from concurrent.futures import Future
from contextlib import contextmanager

import numpy as np

from ..core import SHARD_WORDS
from ..executor.plan import parametrize, plan_inputs
from ..utils import devobs
from ..utils import profile as qprof
from ..utils.deadline import DeadlineExceeded, activate, current
from ..utils.faults import FAULTS
from ..utils.locks import make_condition
from ..utils.stats import BucketHistogram, NopStatsClient, ReservoirTimer
from ..utils.tracing import GLOBAL_TRACER
from .mesh_exec import _DISPATCH_LOCK

_EMPTY_PARAMS = np.zeros(0, dtype=np.int32)

# Total fused query-axis rows per launch: matrix tickets are pre-chunked
# by executor._batch_chunks to keep per-device gather temps bounded, but
# fusing k of them multiplies those temps by k — cap the fused row count
# so a burst of large prepared batches cannot OOM the device.  A ticket
# that alone exceeds the cap launches un-fused.
FUSED_ROWS_MAX = 4096


class _Ticket:
    __slots__ = ("kind", "key", "params", "scalar", "payload", "ctx",
                 "enq", "future", "background", "trace", "prof",
                 "prof_node", "temp_weight")

    def __init__(self, kind, key, params, scalar, payload, background,
                 temp_weight: int = 0):
        self.kind = kind
        self.key = key
        self.params = params          # [B_local, P] int32
        self.scalar = scalar          # True: un-vmapped caller, scatter p[i]
        self.payload = payload
        # device-temp bytes one fused B-row of this ticket costs (the
        # [B, rows, W] masked temp of filtered row_counts; 0 = only the
        # FUSED_ROWS_MAX row cap applies).  The fusion packer bounds
        # SUM(rows x weight) by the batch-temp workspace — fusing k
        # over-sized tickets multiplied the temp k-fold and OOM'd
        # small-RAM hosts (the BENCH_r07 sizing gap).
        self.temp_weight = temp_weight
        self.ctx = current()          # the submitting query's deadline
        # trace + profile context cross the dispatcher-thread boundary
        # with the ticket (a thread-local would silently drop them):
        # spans/stage events recorded at launch parent under the
        # submitting query (docs/observability.md)
        self.trace = GLOBAL_TRACER.capture()
        self.prof, self.prof_node = qprof.capture()
        self.enq = time.monotonic()
        self.future = Future()
        self.background = background


class DispatchBatcher:
    """Front door for every mesh reducer dispatch (docs/batching.md).

    Request threads call the same-named wrappers below instead of the
    MeshExecutor entry points; when batching is enabled the call becomes
    a ticket and blocks until the dispatcher thread has LAUNCHED it
    (results stay unfetched device arrays, preserving the executor's
    dispatch-all-then-fetch-once pipeline).  Disabled (``dispatch-batch =
    off``), every wrapper is a plain delegation — the explicit fallback
    the check.sh dispatch lint allows."""

    def __init__(self, mesh, enabled: bool = True, max_batch: int = 32,
                 window_us: float = 200.0, stats=None):
        self.mesh = mesh
        self.enabled = enabled
        self.max_batch = max(int(max_batch), 1)
        self.window_s = max(float(window_us), 0.0) / 1e6
        self.stats = stats if stats is not None else NopStatsClient()
        self._cond = make_condition("batcher", rlock=True)
        self._queue: list[_Ticket] = []
        self._thread: threading.Thread | None = None
        self._tid: int | None = None
        self._closed = False
        self._bg_local = threading.local()
        # observability (surfaced at /debug/vars + /metrics)
        self.fused_launches = 0
        self.single_launches = 0
        self.stream_fallbacks = 0
        self.expired_drops = 0
        self.temp_splits = 0  # fusion packs split by the temp workspace
        self.batch_size_hist = BucketHistogram([1, 2, 4, 8, 16, 32, 64])
        self.window_wait = ReservoirTimer(512)

    # -- lifecycle ---------------------------------------------------------

    def _ensure_thread(self):
        if self._thread is None:
            t = threading.Thread(target=self._loop, daemon=True,
                                 name="ptpu-dispatch")
            self._thread = t
            self._tid = None
            t.start()

    def close(self):
        """Stop accepting tickets, drain the queue (remaining tickets
        still launch — their waiters are blocked on the futures), and
        join the dispatcher."""
        with self._cond:
            self._closed = True
            self._cond.notify_all()
            t = self._thread
        if t is not None:
            t.join(timeout=10)

    # -- routing -----------------------------------------------------------

    def _use_ticket(self) -> bool:
        # multiprocess: per-process windows would fuse DIFFERENT batch
        # shapes across processes and wedge the collectives; dispatcher
        # re-entrance would deadlock on its own queue
        return (self.enabled and not self.mesh.multiprocess
                and threading.get_ident() != self._tid)

    def _submit(self, kind, key, params, scalar, payload,
                temp_weight: int = 0):
        bg = getattr(self._bg_local, "flag", False)
        t = _Ticket(kind, key, np.ascontiguousarray(params, dtype=np.int32),
                    scalar, payload, bg, temp_weight=temp_weight)
        with self._cond:
            if self._closed:
                return None
            self._ensure_thread()
            self._queue.append(t)
            self._cond.notify_all()
        return t.future.result()

    @contextmanager
    def background(self):
        """Mark this thread's submissions as background work (cache
        rebuilds, maintenance): counted separately, and the thread is
        expected to interleave ``yield_to_foreground()`` between units so
        it never starves foreground queries of the dispatcher."""
        self._bg_local.flag = True
        try:
            yield self
        finally:
            self._bg_local.flag = False

    def yield_to_foreground(self, max_wait: float = 0.05):
        """Bounded wait while foreground tickets are queued — background
        loops (recalculate-caches rank rebuilds) call this between
        fragments so a long rebuild can't monopolize the GIL/dispatcher
        while queries wait."""
        deadline = time.monotonic() + max_wait
        while time.monotonic() < deadline:
            with self._cond:
                busy = any(not t.background for t in self._queue)
            if not busy:
                return
            time.sleep(0.001)

    def pending(self) -> int:
        with self._cond:
            return len(self._queue)

    # -- public reducer surface (executor-facing) --------------------------

    def count_async(self, plan, holder, index, shards) -> list:
        if not self._use_ticket():
            return self.mesh.count_async(plan, holder, index, shards)
        slotted, params = parametrize(plan)
        out = self._submit(
            "count",
            ("count", repr(slotted), index, tuple(shards), id(holder)),
            np.asarray(params, dtype=np.int32).reshape(1, -1), True,
            {"plan": plan, "slotted": slotted, "holder": holder,
             "index": index, "shards": list(shards)})
        if out is None:  # closed mid-flight: direct
            return self.mesh.count_async(plan, holder, index, shards)
        return out

    def segments(self, plan, holder, index, shards) -> dict:
        if not self._use_ticket():
            return self.mesh.segments(plan, holder, index, shards)
        slotted, params = parametrize(plan)
        out = self._submit(
            "segments",
            ("segments", repr(slotted), index, tuple(shards), id(holder)),
            np.asarray(params, dtype=np.int32).reshape(1, -1), True,
            {"plan": plan, "slotted": slotted, "holder": holder,
             "index": index, "shards": list(shards)})
        if out is None:
            return self.mesh.segments(plan, holder, index, shards)
        return out

    def _filter_slotted(self, filter_plan):
        if filter_plan is None:
            return None, _EMPTY_PARAMS
        return parametrize(filter_plan)

    def _rowcount_weight(self, field, view, slotted, holder, index,
                         shards) -> int:
        """Per-fused-B-row device-temp bytes of a filtered row_counts
        launch ([rows, W] masked temp per stacked shard per device) —
        the fusion packer's batch-temp workspace unit.  0 for the
        filter-less broadcast pass (B-independent)."""
        if slotted is None:
            return 0
        from .mesh_exec import field_rows
        rows = field_rows(holder, index, field, view)
        per_dev = self.mesh.stacked_per_device(max(len(shards), 1))
        return rows * per_dev * SHARD_WORDS * 4

    def row_counts_async(self, field, view, filter_plan, holder, index,
                         shards) -> list:
        if not self._use_ticket():
            return self.mesh.row_counts_async(field, view, filter_plan,
                                              holder, index, shards)
        slotted, params = self._filter_slotted(filter_plan)
        out = self._submit(
            "row_counts",
            ("row_counts", field, view, repr(slotted), index,
             tuple(shards), id(holder)),
            np.asarray(params, dtype=np.int32).reshape(1, -1), True,
            {"filter_plan": filter_plan, "slotted": slotted, "field": field,
             "view": view, "holder": holder, "index": index,
             "shards": list(shards)},
            temp_weight=self._rowcount_weight(field, view, slotted,
                                              holder, index, shards))
        if out is None:
            return self.mesh.row_counts_async(field, view, filter_plan,
                                              holder, index, shards)
        return out

    def row_counts(self, field, view, filter_plan, holder, index,
                   shards) -> np.ndarray:
        return self.mesh.merge_counts(self.row_counts_async(
            field, view, filter_plan, holder, index, shards))

    def bsi_sum_async(self, field, view, filter_plan, holder, index,
                      shards) -> list:
        if not self._use_ticket():
            return self.mesh.bsi_sum_async(field, view, filter_plan,
                                           holder, index, shards)
        slotted, params = self._filter_slotted(filter_plan)
        out = self._submit(
            "bsi_sum",
            ("bsi_sum", field, view, repr(slotted), index, tuple(shards),
             id(holder)),
            np.asarray(params, dtype=np.int32).reshape(1, -1), True,
            {"filter_plan": filter_plan, "slotted": slotted, "field": field,
             "view": view, "holder": holder, "index": index,
             "shards": list(shards)})
        if out is None:
            return self.mesh.bsi_sum_async(field, view, filter_plan,
                                           holder, index, shards)
        return out

    # untouched-by-fusion reducers: explicit fallbacks so every dispatch
    # still flows through one front door (check.sh lint)
    def bsi_min_max(self, *args, **kwargs):
        return self.mesh.bsi_min_max(*args, **kwargs)

    def group_counts_batch_async(self, *args, **kwargs):
        return self.mesh.group_counts_batch_async(*args, **kwargs)

    # -- whole-query programs (docs/whole-query.md) ------------------------

    _wq_nofuse = itertools.count()

    def whole_query(self, runner, program, mats, holder, index, shards):
        """One whole-query program launch.  Concurrent requests whose
        programs share a shape (same reducer tuple, index, shard set)
        fuse by concatenating each node's params matrix along the batch
        axis — the batched parameter axis rides the SAME compiled
        program, so the fused-launch economics of the reducer tickets
        carry over to whole requests.  Programs with non-batchable
        nodes (bsi_minmax, group_counts) launch un-fused."""
        if not self._use_ticket():
            return runner.run(program, mats, holder, index, shards)
        key = ("wholequery", repr(program), index, tuple(shards),
               id(holder))
        if not runner.fusible(program):
            # unique key: never coalesced with another ticket
            key = key + ("nofuse", next(self._wq_nofuse))
        rows = sum(m[0].shape[0] if isinstance(m, tuple) else m.shape[0]
                   for m in mats)
        # batch-temp weight: every FILTERED row_counts node of the
        # program adds a [B, rows, W] masked temp per stacked shard —
        # fusing programs multiplies them, so the packer must see it
        from .mesh_exec import field_rows
        weight = 0
        for node in program:
            if node.kind == "row_counts" and node.plan is not None:
                f_name, v_name = node.primary
                weight += (field_rows(holder, index, f_name, v_name)
                           * self.mesh.stacked_per_device(
                               max(len(shards), 1))
                           * SHARD_WORDS * 4)
        out = self._submit(
            "wholequery", key,
            np.zeros((max(rows, 1), 0), dtype=np.int32), False,
            {"runner": runner, "program": program, "mats": mats,
             "holder": holder, "index": index, "shards": list(shards)},
            temp_weight=weight)
        if out is None:  # closed mid-flight: direct
            return runner.run(program, mats, holder, index, shards)
        return out

    # -- matrix surface (_run_batched_groups / prepared replay) ------------

    def count_batch(self, slotted, params_mat, holder, index, shards,
                    fuse: bool = True) -> list:
        params_mat = np.asarray(params_mat, dtype=np.int32)
        if fuse and self._use_ticket():
            out = self._submit(
                "count",
                ("count", repr(slotted), index, tuple(shards), id(holder)),
                params_mat, False,
                {"slotted": slotted, "holder": holder, "index": index,
                 "shards": list(shards)})
            if out is not None:
                return out
        return self.mesh.count_batch_async(slotted, params_mat, holder,
                                           index, shards)

    def row_counts_batch(self, field, view, slotted, params_mat, holder,
                         index, shards, fuse: bool = True) -> list:
        params_mat = np.asarray(params_mat, dtype=np.int32)
        if fuse and self._use_ticket():
            out = self._submit(
                "row_counts",
                ("row_counts", field, view, repr(slotted), index,
                 tuple(shards), id(holder)),
                params_mat, False,
                {"slotted": slotted, "field": field, "view": view,
                 "holder": holder, "index": index, "shards": list(shards)},
                temp_weight=self._rowcount_weight(field, view, slotted,
                                                  holder, index, shards))
            if out is not None:
                return out
        return self.mesh.row_counts_batch_async(
            field, view, slotted, params_mat, holder, index, shards)

    def bsi_sum_batch(self, field, view, slotted, params_mat, holder,
                      index, shards, fuse: bool = True) -> list:
        params_mat = np.asarray(params_mat, dtype=np.int32)
        if fuse and self._use_ticket():
            out = self._submit(
                "bsi_sum",
                ("bsi_sum", field, view, repr(slotted), index,
                 tuple(shards), id(holder)),
                params_mat, False,
                {"slotted": slotted, "field": field, "view": view,
                 "holder": holder, "index": index, "shards": list(shards)})
            if out is not None:
                return out
        return self.mesh.bsi_sum_batch_async(
            field, view, slotted, params_mat, holder, index, shards)

    # -- dispatcher --------------------------------------------------------

    def _loop(self):
        self._tid = threading.get_ident()
        while True:
            with self._cond:
                while not self._queue and not self._closed:
                    self._cond.wait()
                if not self._queue:
                    return  # closed and drained
                # adaptive window: launch when full OR the oldest ticket
                # has waited its window (new arrivals re-check the gate)
                limit = self._queue[0].enq + self.window_s
                while not self._closed and \
                        len(self._queue) < self.max_batch:
                    now = time.monotonic()
                    if now >= limit:
                        break
                    self._cond.wait(limit - now)
                batch, self._queue = self._queue, []
            try:
                self._dispatch(batch)
            except BaseException as e:  # the loop must survive anything
                err = e if isinstance(e, Exception) else RuntimeError(
                    f"dispatcher aborted: {e!r}")
                for t in batch:
                    if not t.future.done():
                        t.future.set_exception(err)

    def _dispatch(self, batch):
        now = time.monotonic()
        groups: dict[tuple, list[_Ticket]] = {}
        for t in batch:
            self.window_wait.observe(now - t.enq)
            if t.prof is not None:
                # queue + coalesce wait, attributed under the stage the
                # query was in when it submitted (its dispatch node)
                t.prof.event("batcher.queue", now - t.enq,
                             node=t.prof_node, kind=t.kind)
            if t.background:
                self.stats.count("dispatch.background")
            ctx = t.ctx
            if ctx is not None and ctx.expired():
                # queued time counted against the budget: drop BEFORE the
                # launch — the waiter maps this to 504 at the HTTP edge
                try:
                    ctx.check("dispatch batch window")
                except DeadlineExceeded as e:
                    t.future.set_exception(e)
                else:  # pragma: no cover — expired() implies check raises
                    t.future.set_exception(DeadlineExceeded(
                        "query deadline exceeded in dispatch batch window"))
                self.expired_drops += 1
                self.stats.count("dispatch.expired_drop")
                continue
            groups.setdefault(t.key, []).append(t)
        from ..executor import executor as _exec_mod
        for key, tickets in groups.items():
            # foreground first, then pack under the ticket, fused-row,
            # and batch-temp-workspace caps; an over-cap ticket launches
            # alone (un-fused)
            tickets.sort(key=lambda t: t.background)
            pack: list[_Ticket] = []
            rows = 0
            temp = 0
            for t in tickets:
                n = t.params.shape[0]
                cost = n * t.temp_weight
                over_temp = pack and t.temp_weight > 0 and \
                    temp + cost > _exec_mod.BATCH_TEMP_BYTES
                if over_temp:
                    # fusing this ticket would exceed the batch-temp
                    # workspace ([B, rows, W] temps scale with the
                    # fused row count): split the pack, visibly
                    self.temp_splits += 1
                    self.stats.count("dispatch.fused_temp_split")
                if pack and (len(pack) >= self.max_batch
                             or rows + n > FUSED_ROWS_MAX
                             or over_temp):
                    self._launch(key[0], pack)
                    pack, rows, temp = [], 0, 0
                pack.append(t)
                rows += n
                temp += cost
            if pack:
                self._launch(key[0], pack)

    def _fail_all(self, tickets, exc):
        for t in tickets:
            if not t.future.done():
                t.future.set_exception(exc)

    def _launch(self, kind, tickets):
        self.batch_size_hist.observe(len(tickets))
        if len(tickets) == 1:
            t = tickets[0]
            try:
                # the ticket's QueryContext rides into the direct path so
                # shard-slice deadline checks + failpoints behave exactly
                # as an un-batched call would; trace + profile context
                # re-attach so slice events/spans parent under the query;
                # the launch-ledger context carries the queued wait into
                # the device launches this ticket drives
                ltok = devobs.set_launch_ctx(
                    queue_s=max(time.monotonic() - t.enq, 0.0),
                    tickets=1, rows=t.params.shape[0])
                try:
                    with activate(t.ctx), GLOBAL_TRACER.attach(t.trace), \
                            qprof.activate(t.prof):
                        t0 = time.perf_counter()
                        result = self._direct(t)
                        if t.prof is not None:
                            t.prof.event("batcher.launch",
                                         time.perf_counter() - t0,
                                         node=t.prof_node, kind=t.kind,
                                         fused=False)
                finally:
                    devobs.reset_launch_ctx(ltok)
            except BaseException as e:
                t.future.set_exception(
                    e if isinstance(e, Exception)
                    else RuntimeError(repr(e)))
                return
            self.single_launches += 1
            self.stats.count("dispatch.launch.single")
            t.future.set_result(result)
            return
        self._launch_fused(kind, tickets)

    def _direct(self, t):
        """Un-fused launch: scalar tickets take the existing un-vmapped
        executables (solo-query latency unchanged); matrix tickets take
        their batch executable directly."""
        p = t.payload
        mesh = self.mesh
        if t.kind == "wholequery":
            return p["runner"].run(p["program"], p["mats"], p["holder"],
                                   p["index"], p["shards"])
        if t.scalar:
            if t.kind == "count":
                return mesh.count_async(p["plan"], p["holder"], p["index"],
                                        p["shards"])
            if t.kind == "segments":
                return mesh.segments(p["plan"], p["holder"], p["index"],
                                     p["shards"])
            if t.kind == "row_counts":
                return mesh.row_counts_async(
                    p["field"], p["view"], p["filter_plan"], p["holder"],
                    p["index"], p["shards"])
            return mesh.bsi_sum_async(
                p["field"], p["view"], p["filter_plan"], p["holder"],
                p["index"], p["shards"])
        if t.kind == "count":
            return mesh.count_batch_async(p["slotted"], t.params,
                                          p["holder"], p["index"],
                                          p["shards"])
        if t.kind == "row_counts":
            return mesh.row_counts_batch_async(
                p["field"], p["view"], p["slotted"], t.params, p["holder"],
                p["index"], p["shards"])
        return mesh.bsi_sum_batch_async(
            p["field"], p["view"], p["slotted"], t.params, p["holder"],
            p["index"], p["shards"])

    def _group_key_lists(self, kind, p):
        if kind in ("count", "segments"):
            return [plan_inputs(p["slotted"])]
        return [self.mesh.batch_keys((p["field"], p["view"]),
                                     p["slotted"])]

    def _note_fused(self, tickets, dur_s, batch_rows=0, padded_rows=0):
        """Attribute one fused launch back to EVERY participating query:
        a profile event under each ticket's captured node and a
        synthesized span under each sampled trace (there is no single
        owner to nest a live span under) — so warm profiles of batched
        queries stop under-reporting device time.  Each ticket's event
        carries the fused batch size, its own row share, and its share
        of the pow-2 padding rows the launch computed for nobody."""
        pad_share = round(padded_rows / len(tickets), 2) if padded_rows \
            else 0
        for t in tickets:
            if t.prof is not None:
                t.prof.event("batcher.launch", dur_s, node=t.prof_node,
                             kind=t.kind, fused=True,
                             batchTickets=len(tickets),
                             batchRows=batch_rows,
                             ticketRows=t.params.shape[0],
                             paddedRowsShare=pad_share)
            if t.trace is not None and t.trace.sampled:
                GLOBAL_TRACER.record_span(
                    "dispatch.fused_launch", t.trace.trace_id,
                    t.trace.span_id, dur_s,
                    {"kind": t.kind, "tickets": len(tickets),
                     "batchRows": batch_rows,
                     "paddedRows": padded_rows},
                    collect=t.trace.collect)

    def _launch_fused_whole(self, tickets):
        """Fuse same-shape whole-query programs: concatenate each
        node's params matrix along the batch axis and launch the shared
        compiled program ONCE; per-ticket results are batch-axis slices
        (WholeOut.slice_batch).  Fusibility (batch-kind nodes only) was
        decided at ticket creation via the key."""
        from .wholequery import WholeQueryUnsupported
        p0 = tickets[0].payload
        runner = p0["runner"]
        program = p0["program"]
        t_launch0 = time.perf_counter()
        try:
            # no pre-schedule here: runner.run's precheck walks the
            # shard schedule exactly once; an over-budget working set
            # raises WholeQueryUnsupported into every waiter below and
            # the executors reroute to the legacy streaming path
            n_nodes = len(program)
            node_mats, node_lo = [], []
            for ni in range(n_nodes):
                mats_n = [t.payload["mats"][ni] for t in tickets]
                lows, lo = [], 0
                for m in mats_n:
                    lows.append(lo)
                    lo += m.shape[0]
                node_lo.append(lows)
                node_mats.append(np.concatenate(mats_n)
                                 if len(mats_n) > 1 else mats_n[0])
            B = sum(m.shape[0] for m in node_mats)
            pad_total = sum(
                (1 << max(0, m.shape[0] - 1).bit_length()) - m.shape[0]
                for m in node_mats)
            # no FAULTS.hit here: runner.run gates the launch (one
            # mesh.slice hit per launch, matching the direct path)
            queue_s = max(time.monotonic()
                          - min(t.enq for t in tickets), 0.0)
            ltok = devobs.set_launch_ctx(queue_s=queue_s,
                                         tickets=len(tickets), rows=B)
            try:
                out = runner.run(program, node_mats, p0["holder"],
                                 p0["index"], p0["shards"])
            finally:
                devobs.reset_launch_ctx(ltok)
            self._note_fused(tickets, time.perf_counter() - t_launch0,
                             batch_rows=B, padded_rows=pad_total)
            with _DISPATCH_LOCK:
                for ti, t in enumerate(tickets):
                    t.future.set_result(out.slice_batch(
                        program,
                        [node_lo[ni][ti] for ni in range(n_nodes)],
                        [t.payload["mats"][ni].shape[0]
                         for ni in range(n_nodes)]))
        except BaseException as e:
            if isinstance(e, WholeQueryUnsupported) and \
                    e.node == "streamed-working-set":
                self.stream_fallbacks += 1
                self.stats.count("dispatch.launch.stream_fallback")
            self._fail_all(tickets, e if isinstance(e, Exception)
                           else RuntimeError(repr(e)))
            return
        self.fused_launches += 1
        self.stats.count("dispatch.launch.fused")
        self.stats.count("dispatch.fused_queries", len(tickets))

    def _launch_fused(self, kind, tickets):
        if kind == "wholequery":
            return self._launch_fused_whole(tickets)
        p0 = tickets[0].payload
        mesh = self.mesh
        t_launch0 = time.perf_counter()
        try:
            # PR1 composition: an over-budget working set streams in shard
            # slices — the fused single-slice path would stage it whole,
            # so stream each ticket through its direct path instead
            sched = mesh.shard_schedule(
                p0["holder"], p0["index"],
                self._group_key_lists(kind, p0), p0["shards"])
            if len(sched.slices) > 1:
                self.stream_fallbacks += 1
                self.stats.count("dispatch.launch.stream_fallback")
                for t in tickets:
                    self._launch(kind, [t])
                return
            mats = [t.params for t in tickets]
            mat = np.concatenate(mats) if len(mats) > 1 else mats[0]
            B = mat.shape[0]
            pad = 1 << max(0, B - 1).bit_length()
            if pad != B:  # pow-2 query axis bounds compile-cache churn
                mat = np.concatenate(
                    [mat, np.repeat(mat[-1:], pad - B, axis=0)])
            # one failpoint/chaos gate per fused launch, matching the
            # per-slice gate of the direct path
            FAULTS.hit("mesh.slice", key=p0["index"])
            # launch ledger context: the queued wait and the ACTUAL fused
            # row count ride into the device launch so padding waste is
            # measured, not inferred (docs/observability.md)
            queue_s = max(time.monotonic()
                          - min(t.enq for t in tickets), 0.0)
            ltok = devobs.set_launch_ctx(queue_s=queue_s,
                                         tickets=len(tickets), rows=B)
            try:
                if kind == "count":
                    parts = mesh.count_batch_async(
                        p0["slotted"], mat, p0["holder"], p0["index"],
                        p0["shards"])
                elif kind == "row_counts":
                    parts = mesh.row_counts_batch_async(
                        p0["field"], p0["view"], p0["slotted"], mat,
                        p0["holder"], p0["index"], p0["shards"])
                elif kind == "bsi_sum":
                    parts = mesh.bsi_sum_batch_async(
                        p0["field"], p0["view"], p0["slotted"], mat,
                        p0["holder"], p0["index"], p0["shards"])
                else:  # segments
                    self._scatter_segments(tickets, mat, p0, pad - B)
                    return
            finally:
                devobs.reset_launch_ctx(ltok)
            # attribute the launch BEFORE resolving any future: once a
            # future resolves, its owner thread may serialize the profile
            # tree, and late appends would race that (profile.py's
            # owner-blocked invariant)
            self._note_fused(tickets, time.perf_counter() - t_launch0,
                             batch_rows=B, padded_rows=pad - B)
            # scatter: per-ticket views into the fused device results.
            # Outputs are replicated (psum, P() specs), so slicing is a
            # local per-device gather — but hold the collective-launch
            # lock anyway to keep one global program-enqueue order.
            with _DISPATCH_LOCK:
                lo = 0
                for t in tickets:
                    n = t.params.shape[0]
                    if t.scalar:
                        t.future.set_result([part[lo] for part in parts])
                    else:
                        t.future.set_result(
                            [part[lo: lo + n] for part in parts])
                    lo += n
        except BaseException as e:
            self._fail_all(tickets, e if isinstance(e, Exception)
                           else RuntimeError(repr(e)))
            return
        self.fused_launches += 1
        self.stats.count("dispatch.launch.fused")
        self.stats.count("dispatch.fused_queries", len(tickets))

    def _scatter_segments(self, tickets, mat, p0, padded_rows=0):
        t_launch0 = time.perf_counter()
        by_shard = self.mesh.segments_batch(
            p0["slotted"], mat, p0["holder"], p0["index"], p0["shards"])
        # as in _launch_fused: attribute before any future resolves
        self._note_fused(tickets, time.perf_counter() - t_launch0,
                         batch_rows=mat.shape[0] - padded_rows,
                         padded_rows=padded_rows)
        lo = 0
        for t in tickets:  # segments tickets are always scalar (B=1)
            t.future.set_result(
                {shard: arr[lo] for shard, arr in by_shard.items()})
            lo += t.params.shape[0]
        self.fused_launches += 1
        self.stats.count("dispatch.launch.fused")
        self.stats.count("dispatch.fused_queries", len(tickets))

    # -- observability ------------------------------------------------------

    def snapshot(self) -> dict:
        return {
            "enabled": self.enabled,
            "maxBatch": self.max_batch,
            "windowUs": round(self.window_s * 1e6, 1),
            "queued": self.pending(),
            "fusedLaunches": self.fused_launches,
            "singleLaunches": self.single_launches,
            "streamFallbacks": self.stream_fallbacks,
            "expiredDrops": self.expired_drops,
            "tempSplits": self.temp_splits,
            "batchSize": self.batch_size_hist.snapshot(),
            "windowWaitS": self.window_wait.snapshot(),
        }

    def prometheus_text(self) -> str:
        lines = self.batch_size_hist.prometheus_lines(
            "pilosa_tpu_dispatch_batch_size")
        ws = self.window_wait.snapshot()
        lines.append("# TYPE pilosa_tpu_dispatch_window_wait_seconds "
                     "summary")
        for q, v in (("0.5", ws["p50"]), ("0.99", ws["p99"])):
            if v is not None:
                lines.append(
                    f'pilosa_tpu_dispatch_window_wait_seconds'
                    f'{{quantile="{q}"}} {v:.6g}')
        lines.append("pilosa_tpu_dispatch_window_wait_seconds_count "
                     f"{ws['count']}")
        return "\n".join(lines) + "\n"
