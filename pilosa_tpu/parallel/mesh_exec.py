"""Multi-device shard execution over a jax Mesh.

The reference fans per-shard jobs to a goroutine pool and a star reduce
(executor.go:2455 mapReduce, :2482 coordinator-side reduce).  Here shards
with identical plan input shapes are STACKED into [S, rows, W] tensors,
sharded over a 1-d "shards" mesh axis, and the whole batch executes as one
XLA computation under shard_map: each device runs the vmapped plan on its
local shard block and cross-shard reductions ride ICI collectives (psum)
instead of host gather — the star reduce becomes an all-reduce.

Reducers (each one compiled executable per input-shape signature):

* ``count``      — popcount-sum of the plan result, psum over shards
                   (Count; executor.go:1790).
* ``segments``   — raw per-shard plan results (bitmap calls).
* ``row_counts`` — per-row popcounts of a field fragment masked by an
                   optional filter plan, psum over shards (TopN phase,
                   Rows, MinRow/MaxRow; fragment.go:1570 top).
* ``bsi_sum``    — per-bit-slice popcounts of a BSI fragment under an
                   optional filter, psum over shards; host does the exact
                   2^i weighting (Sum; fragment.go:1111).
* ``bsi_min_max``— per-shard MSB-first extremum scan, gathered to host
                   for the final (tiny) cross-shard reduce (Min/Max;
                   fragment.go:1147).
* ``group_counts`` — per-row popcounts of a field fragment masked by the
                   intersection of dynamically-indexed prefix rows + an
                   optional filter plan, psum over shards (GroupBy inner
                   loop; executor.go:1068).  Prefix row ids are dynamic
                   arguments so every combo of a GroupBy shares ONE
                   compiled executable.

On a single device this degrades gracefully to one stacked call (still
better than per-shard dispatch given the ~100 ms tunnel round-trip floor).

When a query's stacked working set exceeds the device budget, execution
STREAMS: the shard list is carved into slices of at most half the budget,
slices whose stacks are already resident are drained first, and while the
current slice's dispatch runs, a background uploader stages the next one
(sparse->dense expansion via the host staging cache + ``jax.device_put``
off the critical path) — double-buffering within the budget so over-budget
queries run at upload bandwidth instead of serialized miss latency (the
HBM analog of the reference's page-cache read-ahead over mmap'd fragments,
fragment.go:311).  In-use and prefetched slices are pinned in the budget
so concurrent staging cannot evict them mid-use (docs/memory-budget.md).

Compressed residency (ops/containers.py): fragments whose density
heuristic picks the packed container form stage as stacked
key/type/count/offset tables + payload words instead of dense tensors,
and the compiled executables decode them to dense tiles INSIDE the
vmapped per-shard body — decode-at-op-time, fused with the op.  The
stacked blocks register with the budget at their compressed bytes, so
residency, eviction, prefetch, and the slice planner are all sized by
the compressed footprint and an over-budget dense working set becomes a
resident compressed one.  The transient dense tiles a launch decodes are
bounded separately: the slice planner also cuts when a slice's decoded
bytes would exceed DECODE_WORKSPACE_BYTES, so the XLA temp buffer the
decode reuses per launch stays small even when the whole (compressed)
working set is resident.
"""

from __future__ import annotations

import itertools as _itertools
import time as _time
from concurrent import futures

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..core import CONTAINER_WORDS, SHARD_WORDS
from ..ops import bsi
from ..executor.plan import eval_plan, parametrize, plan_inputs
from ..utils import devobs as _devobs
from ..utils import profile as qprof
from ..utils.deadline import check_current
from ..utils.faults import FAULTS
from ..utils.locks import make_lock, make_rlock
from ..utils.tracing import GLOBAL_TRACER

# shard_map moved from jax.experimental (kwarg check_rep) to the jax
# namespace (kwarg check_vma) across jax releases; gate on what this
# runtime provides so both work.
if hasattr(jax, "shard_map"):
    _shard_map, _SM_CHECK_KW = jax.shard_map, "check_vma"
else:  # jax < 0.5
    from jax.experimental.shard_map import shard_map as _shard_map
    _SM_CHECK_KW = "check_rep"

SHARD_AXIS = "shards"

# Per-launch dense decode workspace ceiling (docs/memory-budget.md
# "Compressed residency"): a shard slice whose compressed stacks decode
# to more dense bytes than this is cut into smaller slices, bounding the
# transient dense tiles one executable materialises.  Process-wide, set
# from the server config (decode-workspace-mb) like DEFAULT_BUDGET.
DECODE_WORKSPACE_BYTES = 1 << 30


def _sig_rows(shape) -> int:
    """Row count of a per-key group-signature entry — dense entries are
    (rows, words), compressed ones ('z', rows, C, P, A, R)."""
    return shape[1] if shape[0] == "z" else shape[0]


def _flatten_present(present):
    """Flatten present (key, placed, sig) entries into the device-arg
    list a compiled executable takes: a compressed entry contributes its
    five stacked container arrays, a dense one a single tensor.  Returns
    (flat_args, layout); ``layout`` drives _unpack_frags inside the
    executable and is fully determined by the entries' sigs (which key
    the executable cache), so one compiled body always sees one layout."""
    flat, layout = [], []
    for k, a, s in present:
        if isinstance(a, tuple):
            flat.extend(a)
            layout.append((k, len(a), s))
        else:
            flat.append(a)
            layout.append((k, 1, s))
    return flat, tuple(layout)


def _unpack_frags(layout, arrays):
    """Inside a per-shard (vmapped) body: decode compressed inputs to
    dense [rows, W] tiles — the decode-at-op-time step, fused into the
    op's own executable so dense tiles exist only as launch-local XLA
    temporaries — and map every key to its dense fragment.  Each entry's
    signature carries the container-kernels backend it was planned under
    (storage/fragment.py device_sig), so the dispatch here is static per
    layout: 'pallas' entries decode through the ops/kernels.py Pallas
    kernel (tile-by-tile in VMEM), the rest through the jnp gather path."""
    from ..ops import containers, kernels
    out = {}
    i = 0
    for k, n, s in layout:
        if n == 1:
            out[k] = arrays[i]
        else:
            dec = kernels.decode_block \
                if kernels.sig_backend(s) == "pallas" \
                else containers.decode_block
            out[k] = dec(
                *arrays[i: i + n], rows=s[1], words=SHARD_WORDS,
                a_bucket=s[4], r_bucket=s[5])
        i += n
    return out


def _fused_entry(layout, key):
    """(flat-arg index, sig) of ``key``'s layout entry when it is a
    compressed entry planned for the Pallas backend — the condition
    under which a per-shard body may route the whole decode+op+popcount
    chain through one fused kernel (kernels.fused_row_counts) instead of
    decode-then-op.  None otherwise (dense entry, jnp backend, or the
    bucket failed the VMEM rule).  Static per layout, so the per-shard
    body's branch is resolved at trace time."""
    from ..ops import kernels
    i = 0
    for k, n, s in layout:
        if k == key:
            if (n > 1 and kernels.sig_backend(s) == "pallas"
                    and kernels.fits_vmem(s[3], s[4], s[5])):
                return i, s
            return None
        i += n
    return None

# Multi-device collective programs must be ENQUEUED in one consistent
# order across all device queues: two threads (concurrent server
# requests, or the prefetch uploader racing a dispatch) interleaving
# psum/all_gather program launches wedge the per-device queues into a
# circular rendezvous wait (reproduced on the 8-virtual-device CPU
# platform: rank k stuck on RunId A while the rest wait on RunId B —
# XLA collective_ops_utils "may be stuck").  One process-wide lock
# around every collective-program LAUNCH (shard_map executables and
# sharded-output indexing) restores a global enqueue order; execution
# itself stays async and overlapped, only the enqueue serializes.
_DISPATCH_LOCK = make_lock("dispatch")


def field_rows(holder, index: str, field: str, view: str) -> int:
    """Max fragment row count for (field, view) — the ``rows`` axis of
    a batched/fused row_counts launch's [B, rows, W] masked temp, fed
    into the batch-temp workspace sizing (executor.batch_chunk_size and
    the batcher's fusion cap).  0 when the view holds no fragments."""
    idx = holder.index(index)
    f = idx.field(field) if idx is not None else None
    v = f.view(view) if f is not None else None
    if v is None:
        return 0
    return max((fr.n_rows for fr in v.fragments.values()), default=0)


class _InstrumentedExec:
    """One compiled shard_map executable plus its device-runtime
    telemetry (utils/devobs.py, docs/observability.md "Device runtime").

    The wrapped block_fn marks the compile registry whenever jax TRACES
    it (the python body only runs while tracing), so every call knows
    whether it compiled; a signature tracing more than once is the
    retrace red flag the PR 7 bug never raised.  Every invocation also
    lands in the launch ledger: padded sizes read off the args
    themselves, the actual stacked shard count passed by the call site
    as ``_launch_meta``, queue/ticket context installed by the dispatch
    batcher, and the streaming slice position installed by
    _ShardSchedule."""

    __slots__ = ("fn", "sig", "kind", "detail", "n_fixed",
                 "decode_per_shard", "kernels_per_shard",
                 "kernel_tiles_per_shard")

    def __init__(self, fn, key, layout):
        from ..ops import kernels as _kernels
        self.fn = fn
        self.kind = key[0] if key and isinstance(key[0], str) else "exec"
        self.sig = _devobs.sig_of(key)
        self.detail = repr(key[1])[:120] if len(key) > 1 else ""
        # leading replicated (P()) args before the stacked fragment args
        self.n_fixed = 2 if self.kind == "group_countsB" else 1
        # transient dense tiles this executable decodes per stacked
        # shard row (compressed layout entries expand inside the launch).
        # Pallas-backend entries don't materialise that workspace — they
        # stream VMEM container tiles — so they count as embedded kernel
        # launches + tiles instead of decode bytes.
        self.decode_per_shard = sum(
            s[1] * SHARD_WORDS * 4 for _, n, s in layout
            if n > 1 and _kernels.sig_backend(s) != "pallas")
        pallas = [s for _, n, s in layout
                  if n > 1 and _kernels.sig_backend(s) == "pallas"]
        self.kernels_per_shard = len(pallas)
        self.kernel_tiles_per_shard = sum(
            s[1] * (SHARD_WORDS // CONTAINER_WORDS) for s in pallas)

    def __call__(self, *args, _launch_meta=None):
        reg = _devobs.COMPILES
        reg.begin_call()
        t0 = _time.perf_counter()
        out = self.fn(*args)
        dt = _time.perf_counter() - t0
        compiled = reg.traced()
        if compiled:  # fingerprinting is only paid on compiles
            reg.note_call(self.sig, self.kind, dt,
                          _devobs.fingerprint(args), detail=self.detail)
        # call-site meta: actual shard count, or (shards, actual batch
        # rows) where the call site pads its own batch axis outside the
        # batcher (group_countsB's pow-2 combo padding)
        meta_rows = None
        if isinstance(_launch_meta, tuple):
            _launch_meta, meta_rows = _launch_meta
        params = args[0] if self.kind == "group_countsB" \
            else args[self.n_fixed - 1]
        b_pad = params.shape[0] if getattr(params, "ndim", 0) == 2 else 1
        stacked = args[self.n_fixed] if len(args) > self.n_fixed else None
        shards_pad = stacked.shape[0] if stacked is not None else 0
        shards = _launch_meta if _launch_meta is not None else shards_pad
        ctx = _devobs.launch_ctx() or {}
        rows = ctx.get("rows")
        if rows is None:
            rows = meta_rows
        _devobs.LEDGER.record(
            sig=self.sig, kind=self.kind, shards=shards,
            shards_padded=shards_pad,
            batch_rows=rows if rows is not None else b_pad,
            batch_rows_padded=b_pad,
            queue_s=ctx.get("queue_s", 0.0),
            tickets=ctx.get("tickets", 1),
            dispatch_s=dt, compiled=compiled,
            decode_bytes=self.decode_per_shard * shards,
            slice_pos=_devobs.current_slice(),
            kernel_launches=self.kernels_per_shard * shards,
            kernel_tiles=self.kernel_tiles_per_shard * shards)
        prof = qprof.current()
        if prof is not None:
            # rows/padding/decode tags feed the EXPLAIN launches section
            # (utils/explain.py) — the same numbers the ledger records,
            # so an explain record cross-checks the ledger by sig
            prof.event("device.launch", dt, kind=self.kind, sig=self.sig,
                       shards=shards, shardsPadded=shards_pad,
                       batchRows=rows if rows is not None else b_pad,
                       batchRowsPadded=b_pad,
                       decodeBytes=self.decode_per_shard * shards,
                       compiled=compiled)
        return out


def default_mesh(devices=None) -> Mesh:
    devices = devices if devices is not None else jax.devices()
    return Mesh(np.array(devices), axis_names=(SHARD_AXIS,))


_EXEC_SEQ = _itertools.count()


class MeshExecutor:
    """Executes resolved plans over stacked shard groups on a device mesh."""

    def __init__(self, mesh: Mesh | None = None):
        self.mesh = mesh or default_mesh()
        # monotonic per-process instance number: disambiguates this
        # executor's plan keys (and thus compile-registry signatures)
        # from any earlier executor's — see _plan_key
        self._exec_seq = next(_EXEC_SEQ)
        self.n_devices = self.mesh.devices.size
        # A mesh spanning >1 jax process (multihost mode 2,
        # parallel/multihost.py): shard-axis-sharded OUTPUTS are not
        # addressable from any single process, so executables that
        # return per-shard results gather them over the shard axis
        # (all_gather rides ICI/DCN) and replicate — aggregations
        # (psum) are replicated already.
        self.multiprocess = len(
            {d.process_index for d in self.mesh.devices.flat}) > 1
        # Fragment mirrors must live on the mesh's platform (e.g. a virtual
        # CPU mesh while the default backend is a TPU).  When the mesh IS on
        # the default platform we stage with target=None so the mesh path
        # and the per-shard executor share one cached upload per fragment
        # instead of holding two copies in device memory.
        stage = self.mesh.devices.flat[0]
        cfg_default = jax.config.jax_default_device
        default_platform = (cfg_default.platform if cfg_default is not None
                            else jax.devices()[0].platform)
        self.stage_device = None if stage.platform == default_platform \
            else stage
        self._cache: dict = {}
        # (index, keys, shards) -> (mirror-id token, groups) — the stacked
        # + mesh-placed input blocks, rebuilt only when a fragment's device
        # mirror changes (a write re-uploads it).  Without this every query
        # would re-stack its input fragments on device.  LRU-bounded: a
        # stale entry (shard set grew, index deleted) pins a full stacked
        # copy of its fragments in device memory until evicted.
        from collections import OrderedDict
        from ..storage.membudget import DEFAULT_BUDGET
        self._stack_cache: OrderedDict = OrderedDict()
        self.stack_cache_max = 64
        self._budget = DEFAULT_BUDGET
        # single-worker background uploader for streamed shard slices
        # (created on first over-budget query; one worker serializes
        # prefetch transfers so they never contend with each other)
        self._uploader = None
        # Leaf lock for _stack_cache dict ops ONLY (never held across any
        # other lock acquisition): budget-eviction callbacks and query
        # threads race on the dict, and a callback taking the main
        # executor lock could deadlock two executors evicting each other's
        # entries.
        self._sc_lock = make_lock("stack-cache")
        import weakref
        self._finalizer = weakref.finalize(
            self, MeshExecutor._cleanup_budget, self._budget, id(self),
            self._stack_cache)
        # Concurrent request threads share this executor (the server
        # overlaps in-flight query batches to hide the dispatch round
        # trip); the lock covers the python-side cache bookkeeping only —
        # device dispatch runs outside it.
        self._lock = make_rlock("mesh-exec")

    # -- compiled executables ---------------------------------------------

    def _jit_shard_map(self, key, block_fn, in_specs, out_specs,
                       check_vma: bool = True, layout=()):
        """``check_vma=False`` for multiprocess gather executables: their
        P() outputs ARE replicated (all_gather over the shard axis), but
        shard_map's static varying-axes checker cannot infer that.
        ``layout`` (from _flatten_present) sizes the launch ledger's
        decode-workspace attribution; the cached object is the
        executable wrapped in its telemetry hooks (_InstrumentedExec)."""
        fn = self._cache.get(key)
        if fn is None:
            from ..ops import kernels as _kernels
            if any(n > 1 and _kernels.sig_backend(s) == "pallas"
                   for _, n, s in layout):
                # shard_map's replication checker has no rule for
                # pallas_call (jax suggests check_rep=False as the
                # workaround); these bodies' outputs follow the same
                # psum/P(SHARD_AXIS) patterns the checker validates on
                # the jnp variants of the identical layouts
                check_vma = False

            def traced_body(*a, _fn=block_fn):
                # runs ONLY while jax traces: an exact compile detector
                _devobs.COMPILES.mark_traced()
                return _fn(*a)

            fn = _InstrumentedExec(
                jax.jit(_shard_map(
                    traced_body, mesh=self.mesh,
                    in_specs=in_specs, out_specs=out_specs,
                    **{_SM_CHECK_KW: check_vma})),
                key, layout)
            self._cache[key] = fn
        return fn

    def _plan_key(self, kind, plan, input_keys, shapes, extra=()):
        # _exec_seq, not id(self.mesh): a GC'd mesh's id can be REUSED by
        # the next one, and a byte-identical key would then make the
        # process-global compile registry read a fresh executor's first
        # compile as a PR-7-class retrace (a false alarm on the one
        # signal that must stay trustworthy)
        return (kind, repr(plan), tuple(input_keys), tuple(shapes),
                tuple(extra), self._exec_seq)

    def _compiled(self, slotted_plan, input_keys, shapes, layout, reducer):
        """``slotted_plan`` comes from ``parametrize``: the executable is
        keyed by plan SHAPE; row ids / predicate bits ride in the params
        vector (replicated across the mesh, P() spec).  ``layout`` (from
        _flatten_present, fully determined by ``shapes``) maps the flat
        device args back to per-key dense fragments, decoding compressed
        entries inside the executable."""
        key = self._plan_key(reducer or "segments", slotted_plan, input_keys,
                             shapes)
        fn = self._cache.get(key)
        if fn is not None:
            return fn
        n_args = sum(n for _, n, _ in layout)

        # input_keys here are only the PRESENT fragments; missing ones are
        # omitted from the arg list entirely (shard_map specs must map 1:1
        # to array args)
        def per_shard(params, *arrays):
            frags = _unpack_frags(layout, arrays)
            return eval_plan(slotted_plan, frags, params)

        vmapped = jax.vmap(per_shard,
                           in_axes=(None,) + (0,) * n_args)

        if reducer == "count":
            def block_fn(params, *arrays):
                segs = vmapped(params, *arrays)  # [S_local, W]
                local = jnp.sum(
                    jax.lax.population_count(segs).astype(jnp.int32))
                return jax.lax.psum(local, axis_name=SHARD_AXIS)

            out_specs = P()
        elif self.multiprocess:
            def block_fn(params, *arrays):
                segs = vmapped(params, *arrays)    # [S_local, W]
                return jax.lax.all_gather(segs, SHARD_AXIS, tiled=True)

            in_specs = (P(),) + tuple(P(SHARD_AXIS)
                                      for _ in range(n_args))
            return self._jit_shard_map(key, block_fn, in_specs, P(),
                                       check_vma=False, layout=layout)
        else:
            def block_fn(params, *arrays):
                return vmapped(params, *arrays)    # [S_local, W]

            out_specs = P(SHARD_AXIS)

        in_specs = (P(),) + tuple(P(SHARD_AXIS) for _ in range(n_args))
        return self._jit_shard_map(key, block_fn, in_specs, out_specs,
                                   layout=layout)

    # -- shard grouping ----------------------------------------------------

    def _placed_groups(self, keys, holder, index, shards):
        """Group shards by input-shape signature over fragment keys
        [(field, view), ...] and stack+place each group's fragments over
        the mesh axis.  Returns [(shard_list, placed_per_key, shapes)];
        ``placed_per_key[i]`` is None when key i's fragment is absent in
        the whole group.

        Results are cached against the fragments' data-generation stamps
        (fragment.gen) so repeat queries reuse the resident stacked blocks
        without touching (or pinning) the per-fragment mirrors at all; the
        stacked bytes register with the DeviceBudget so HBM pressure can
        evict whole stacks (r3 advisor).  A budget-eviction callback may
        pop entries concurrently from outside ``self._lock`` (it must not
        lock: two executors evicting each other's entries would deadlock),
        so every cache op here tolerates a vanished key."""
        frags, token, epochs = self._stack_token(keys, holder, index, shards)
        ckey = (index, tuple(keys), tuple(shards))
        skey = ("stack", id(self), ckey)
        with self._sc_lock:
            cached = self._stack_cache.get(ckey)
            if cached is not None and cached[0] == token:
                self._stack_cache.move_to_end(ckey)
        if cached is not None and cached[0] == token:
            if cached[2] != epochs:
                # ingest delta overlay (docs/ingest.md): the stack is
                # current at its device_gen token but member fragments
                # have journaled flushes since — OR the missing chunks
                # into the resident stacked blocks on device instead of
                # rebuilding/re-uploading them.  Multi-process meshes
                # rebuild instead (their staging must stay deterministic
                # across processes).
                if self.multiprocess:
                    cached = None
                else:
                    self._refresh_overlays(ckey, token, frags, shards,
                                           keys, epochs)
            if cached is not None:
                self._budget.touch(skey)
                return cached[1]

        groups: dict[tuple, list[tuple[int, list]]] = {}
        for shard, row in zip(shards, frags):
            sig = tuple(None if fr is None
                        else self._frag_sig(fr) for fr in row)
            groups.setdefault(sig, []).append((shard, row))
        out = []
        nbytes = 0
        comp_bytes = 0
        for sig, members in groups.items():
            shard_list = [m[0] for m in members]
            placed = []
            for i, shape in enumerate(sig):
                if shape is None:
                    placed.append(None)
                    continue
                frs = [m[1][i] for m in members]
                if shape[0] == "z":
                    # compressed staging: the resident form IS the
                    # packed stream; bytes registered below are the
                    # compressed footprint
                    pk = self._place_packed_block(frs, shape)
                    pb = sum(a.nbytes for a in pk)
                    nbytes += pb
                    comp_bytes += pb
                    placed.append(pk)
                    continue
                # Two staging paths.  Warm (mirrors already resident):
                # stack on device — no host transfer at all.  Cold: build
                # the dense [S, rows, W] block on host and ship it as ONE
                # transfer — per-fragment uploads pay a ~100 ms dispatch
                # round trip each through a remote-device tunnel, while
                # bulk transfers run at full bandwidth (measured: 36 MB/s
                # at 8 MB vs 1.3 GB/s at 128 MB).
                resident = sum(
                    1 for fr in frs
                    if not fr._device_dirty
                    and fr._mirrors.get(self.stage_device) is not None)
                if self.multiprocess:
                    # per-process staging: each process supplies only its
                    # addressable shards (device_put would assert the
                    # whole host block equal across processes)
                    p = self._place_host_block(frs, shape)
                elif 5 * resident >= 4 * len(frs):
                    arrs = [fr.device(self.stage_device) for fr in frs]
                    if all(a.shape == shape for a in arrs):
                        p = self._pad_and_place(arrs, shape, len(frs))
                    else:
                        # a concurrent write grew a fragment's capacity
                        # after the shape signature was read — the host
                        # path slices to the signature's shape
                        p = self._place_host_block(frs, shape)
                else:
                    p = self._place_host_block(frs, shape)
                nbytes += p.nbytes
                placed.append(p)
            out.append((shard_list, placed, sig))

        import weakref
        wself = weakref.ref(self)  # entries must not pin the executor

        def _evict(ck=ckey, tok=token):
            # Guard on the registration's token VALUE, under the leaf
            # lock: a deferred callback that lost a race with a rebuild
            # after a data change must not drop the fresh entry (its token
            # differs — gens are unique per mutation).  Value equality, not
            # identity: a concurrent double-miss stores one thread's tuple
            # while the budget holds the other's, and both describe the
            # same data.
            s = wself()
            if s is not None:
                with s._sc_lock:
                    cur = s._stack_cache.get(ck)
                    if cur is not None and cur[0] == tok:
                        del s._stack_cache[ck]

        with self._sc_lock:
            self._stack_cache[ckey] = (token, out, epochs)
            trimmed = []
            while len(self._stack_cache) > self.stack_cache_max:
                trimmed.append(self._stack_cache.popitem(last=False)[0])
        self._budget.register(skey, nbytes, _evict,
                              compressed_bytes=comp_bytes)
        for old_key in trimmed:
            self._budget.unregister(("stack", id(self), old_key))
        return out

    def _stack_token(self, keys, holder, index, shards):
        """(per-shard fragment rows, device-generation token, ingest
        epochs) for a stacked block.  The token keys cache validity
        against ``fr.device_gen`` — the generation the device-resident
        form reflects — so an ingest flush (which bumps ``gen`` but
        journals its delta instead of invalidating device state,
        docs/ingest.md) does NOT rebuild the stack; the epochs vector
        tells ``_placed_groups`` which journal chunks to overlay in.
        Any non-ingest mutation re-anchors device_gen = gen and the
        token mismatch rebuilds as before.  The FULL signature rides
        along: a budget-limit change can flip a fragment between dense
        and compressed residency, and a container-kernels flip changes
        the compressed signature's backend axis — either way a stale
        stack would feed plans keyed on signatures the current config
        no longer produces, so the token mismatch rebuilds it."""
        frags = [[holder.fragment(index, field, view, shard)
                  for field, view in keys] for shard in shards]
        token = tuple(
            -1 if fr is None else (fr.device_gen, self._frag_sig(fr))
            for row in frags for fr in row)
        epochs = tuple(
            0 if fr is None else fr.ingest_epoch
            for row in frags for fr in row)
        return frags, token, epochs

    def _is_resident(self, keys, holder, index, shards) -> bool:
        """Whether this (keys, shards) stack is cached AND current — the
        residency signal the streaming scheduler orders slices by."""
        _, token, _epochs = self._stack_token(keys, holder, index, shards)
        with self._sc_lock:
            cached = self._stack_cache.get(
                (index, tuple(keys), tuple(shards)))
        # an epoch lag still counts as resident: the overlay scatter is
        # a few KB of device work, not a re-stage
        return cached is not None and cached[0] == token

    # -- ingest delta overlay (docs/ingest.md) -----------------------------

    def _refresh_overlays(self, ckey, token, frags, shards, keys,
                          new_epochs):
        """OR journaled ingest flushes into the resident stacked blocks
        of a token-valid cache entry.  Per dense group/key: gather every
        member fragment's unseen journal chunks, dedupe host-side, and
        run one scatter-OR shard_map program over the stacked array —
        KBs of overlay transfer instead of a full re-stage.  Compressed
        ('z') entries never appear here (their fragments fold instead
        of journaling).  Serialized under the executor lock; a racing
        duplicate application is harmless (OR of already-present bits
        contributes nothing)."""
        from ..ingest.delta import merge_chunks
        nk = len(keys)
        row_of = {s: i for i, s in enumerate(shards)}
        with self._lock:
            with self._sc_lock:
                cur = self._stack_cache.get(ckey)
            if cur is None or cur[0] != token or cur[2] == new_epochs:
                return
            out, old_epochs = cur[1], cur[2]
            for shard_list, placed, sig in out:
                for ki in range(nk):
                    s_k = sig[ki]
                    if s_k is None or s_k[0] == "z":
                        continue
                    members, idxs, vals = [], [], []
                    for j, shard in enumerate(shard_list):
                        fr = frags[row_of[shard]][ki]
                        if fr is None:
                            continue
                        ep = old_epochs[row_of[shard] * nk + ki]
                        di, dv = merge_chunks(fr.delta_chunks(ep))
                        if di.size:
                            members.append(
                                np.full(di.size, j, dtype=np.int32))
                            idxs.append(di)
                            vals.append(dv)
                    if not members:
                        continue
                    placed[ki] = self._overlay_stack(
                        placed[ki], np.concatenate(members),
                        np.concatenate(idxs), np.concatenate(vals))
            with self._sc_lock:
                cur2 = self._stack_cache.get(ckey)
                if cur2 is not None and cur2[0] == token:
                    self._stack_cache[ckey] = (token, out, new_epochs)

    def _overlay_stack(self, stacked, member, flat_idx, vals):
        """One scatter-OR launch: ``stacked`` is the mesh-sharded
        [S, rows, W] block; (member, flat_idx, vals) name the overlay
        words.  Indices ship as (member, row, word) int32 triples (a
        flattened int64 offset would exceed jax's default index width on
        large fragments) and the add-of-missing-bits formulation keeps
        padding collisions harmless (ingest/delta.py).  Not routed
        through _InstrumentedExec: its shard/padding attribution reads
        reducer-shaped args, and a KB-scale maintenance scatter would
        only pollute the launch ledger."""
        from ..ingest.delta import pad_overlay
        m, r, w, v = pad_overlay(flat_idx, vals, SHARD_WORDS,
                                 member=member)
        key = ("overlay", tuple(stacked.shape), m.size)
        fn = self._cache.get(key)
        if fn is None:
            def block_fn(block, m_, r_, w_, v_):
                s_local = block.shape[0]
                base = jax.lax.axis_index(SHARD_AXIS) * s_local
                loc = m_ - base
                ok = (loc >= 0) & (loc < s_local)
                loc = jnp.where(ok, loc, 0)
                cur = block[loc, r_, w_]
                contrib = jnp.where(ok, v_ & ~cur, jnp.uint32(0))
                return block.at[loc, r_, w_].add(contrib)

            fn = jax.jit(_shard_map(
                block_fn, mesh=self.mesh,
                in_specs=(P(SHARD_AXIS), P(), P(), P(), P()),
                out_specs=P(SHARD_AXIS),
                **{_SM_CHECK_KW: True}))
            self._cache[key] = fn
        with _DISPATCH_LOCK:
            return fn(stacked, m, r, w, v)

    @staticmethod
    def _cleanup_budget(budget, exec_id, stack_cache):
        """Drop this executor's budget accounting (runs on close() or GC —
        without it, accounting-only budgets would grow phantom resident
        bytes for every discarded executor)."""
        for ck in list(stack_cache):
            budget.unregister(("stack", exec_id, ck))
        stack_cache.clear()

    def close(self):
        """Unregister budget entries and drop cached device state (also
        runs automatically when an un-closed executor is GC'd)."""
        with self._lock:
            if self._uploader is not None:
                self._uploader.shutdown(wait=True, cancel_futures=True)
                self._uploader = None
            self._finalizer()
            self._cache.clear()

    def _uploader_pool(self):
        with self._lock:
            if self._uploader is None:
                from concurrent.futures import ThreadPoolExecutor
                self._uploader = ThreadPoolExecutor(
                    max_workers=1, thread_name_prefix="ptpu-prefetch")
            return self._uploader

    def _bucket(self, n: int) -> int:
        """Stacked shard counts round UP to n_devices * 2^k: executables
        are keyed by shape, and a one-shard difference between two shard
        sets (resize, Options(shards=...), working-set rotation) must not
        pay a multi-second XLA recompile.  Padding shards are zero blocks
        — they contribute nothing to counts/reductions."""
        b = self.n_devices
        while b < n:
            b *= 2
        return b

    def stacked_per_device(self, n_shards: int) -> int:
        """Per-device rows of a stacked dispatch after _bucket padding —
        the multiplier batched-dispatch chunk sizing must use (padded
        zero shards still materialize gather temps)."""
        return self._bucket(max(1, n_shards)) // self.n_devices

    def _pad_and_place(self, arrays_list, shape, n: int):
        """Stack n member arrays, pad the shard axis to its bucket, and
        place sharded over the mesh axis."""
        pad = self._bucket(n) - n
        mats = list(arrays_list)
        if pad:
            zero = jax.device_put(
                np.zeros(shape, dtype=np.uint32), self.stage_device)
            mats += [zero] * pad
        stacked = jnp.stack(mats)
        sharding = NamedSharding(self.mesh, P(SHARD_AXIS))
        return jax.device_put(stacked, sharding)

    def _place_host_block(self, frs, shape):
        """Cold-path staging: densify the group's fragments into one host
        block and place it mesh-sharded in a single transfer (bypassing
        per-fragment mirrors entirely).  On a multi-process mesh each
        process materializes ONLY the shard rows jax asks it for (its
        addressable devices) — the per-host import pipeline fills just
        the local slice (multihost.import_process_slice), and remote
        shards' placeholder fragments densify to zeros that are never
        consulted."""
        n = len(frs)
        sharding = NamedSharding(self.mesh, P(SHARD_AXIS))
        bucket = self._bucket(n)

        def fill(block, lo):
            for i in range(lo, min(lo + block.shape[0], n)):
                # staged_dense: re-stages after an HBM eviction copy from
                # the host staging cache instead of re-expanding the
                # sparse store (read-only — the slice-assign copies)
                dense = frs[i].staged_dense()
                r = min(dense.shape[0], shape[0])  # cap may race a grow
                block[i - lo, :r] = dense[:r]
            return block

        if self.multiprocess:
            def cb(index):
                s = index[0]
                lo = s.start or 0
                hi = s.stop if s.stop is not None else bucket
                return fill(np.zeros((hi - lo,) + shape, np.uint32), lo)

            return jax.make_array_from_callback(
                (bucket,) + shape, sharding, cb)
        return jax.device_put(
            fill(np.zeros((bucket,) + shape, np.uint32), 0), sharding)

    def _frag_sig(self, fr) -> tuple:
        """Per-fragment group-signature entry.  Multi-process meshes pin
        the dense form — their staging must stay deterministic across
        processes, and remote placeholder fragments have no packed data
        to ship."""
        if self.multiprocess:
            return (fr.n_rows, SHARD_WORDS)
        return fr.device_sig()

    def _place_packed_block(self, frs, sig):
        """Compressed staging: pad each member fragment's packed
        container stream to the group's pow2 buckets and place the five
        stacked table/payload arrays mesh-sharded (ops/containers.py).
        Transfers move compressed bytes, so there is no warm-mirror
        stacking variant — re-shipping a packed stream is already far
        cheaper than a dense stack ever was."""
        cb, pb = sig[2], sig[3]
        n = len(frs)
        bucket = self._bucket(n)
        keys = np.full((bucket, cb), -1, dtype=np.int32)
        types = np.full((bucket, cb), -1, dtype=np.int32)
        counts = np.zeros((bucket, cb), dtype=np.int32)
        offsets = np.zeros((bucket, cb), dtype=np.int32)
        payload = np.zeros((bucket, pb), dtype=np.uint32)
        for i, fr in enumerate(frs):
            p = fr.packed_host()
            # a concurrent write may race the signature; clamping to the
            # signature's buckets mirrors the dense path's slice-to-shape
            # (the stale token rebuilds the stack on the next query)
            c = min(p.keys.size, cb)
            pw = min(p.payload.size, pb)
            keys[i, :c] = p.keys[:c]
            types[i, :c] = p.types[:c]
            counts[i, :c] = p.counts[:c]
            offsets[i, :c] = p.offsets[:c]
            payload[i, :pw] = p.payload[:pw]
        sharding = NamedSharding(self.mesh, P(SHARD_AXIS))
        return tuple(jax.device_put(a, sharding)
                     for a in (keys, types, counts, offsets, payload))

    @staticmethod
    def _present(keys, placed, sig):
        return [(k, a, s) for k, a, s in zip(keys, placed, sig)
                if s is not None]

    def _filter_keys(self, filter_plan) -> list[tuple[str, str]]:
        return plan_inputs(filter_plan) if filter_plan is not None else []

    def batch_keys(self, primary: tuple[str, str],
                   filter_plan) -> list[tuple[str, str]]:
        """The exact stacked key list for a primary-fragment dispatch
        with an optional (slotted) filter plan.  The ONLY definition —
        executor._group_key_list calls this so the shard schedule
        prefetches and pins precisely the stacks the dispatch reads; a
        divergent copy would silently turn prefetching into waste."""
        return [primary] + [k for k in self._filter_keys(filter_plan)
                            if k != primary]

    # -- out-of-core shard streaming --------------------------------------

    # Slice target as a fraction of the budget: half, so the next slice
    # can stage (double-buffered) while the current one computes without
    # the pair exceeding the limit.
    STREAM_SLICE_FRACTION = 0.5

    def _estimate_shard_bytes(self, keys, holder, index, shards):
        """Per-shard (resident, decode-workspace) byte estimates over
        ``keys`` (bucket padding excluded: this sizes slices, padding is
        zeros shared across them).  Resident counts each fragment's
        device-resident form — compressed bytes for compressed-form
        fragments, the dense tensor otherwise — which is what occupies
        the budget between launches; decode counts the transient dense
        tiles a launch materialises while decoding compressed inputs
        (bounded separately by DECODE_WORKSPACE_BYTES)."""
        res, dec = [], []
        for shard in shards:
            b = d = 0
            for field, view in keys:
                fr = holder.fragment(index, field, view, shard)
                if fr is not None:
                    dense = fr.n_rows * SHARD_WORDS * 4
                    nb = fr.device_nbytes() if not self.multiprocess \
                        else dense
                    b += nb
                    if nb < dense:
                        d += dense
            res.append(b)
            dec.append(d)
        return res, dec

    def shard_schedule(self, holder, index, key_lists, shards):
        """Residency-aware shard-group schedule for a dispatch that will
        stack ``key_lists`` (one key list per distinct stacked block) over
        ``shards``.

        Fits-in-budget working sets (or an unlimited budget, or a
        multi-process mesh, whose staging must stay deterministic across
        processes) get ONE slice — the whole shard list, with cache keys
        identical to the pre-streaming path.  Over-budget sets are carved
        into contiguous slices of at most STREAM_SLICE_FRACTION of the
        budget; slices already resident are ordered FIRST so a batch
        drains all work against staged data before rotating the budget,
        and iteration prefetches slice k+1 while slice k dispatches."""
        shards = list(shards)
        # bytes are estimated per key LIST occurrence, not the union:
        # each list stages its own stacked block, so a key shared by two
        # lists occupies device memory twice — union-sizing would let
        # the pinned current+prefetched pair exceed the budget
        all_keys: list = [k for kl in key_lists for k in kl]
        limit = self._budget.limit_bytes
        slices = [shards]
        if limit and not self.multiprocess and \
                len(shards) > self.n_devices:
            per, dec = self._estimate_shard_bytes(all_keys, holder, index,
                                                  shards)
            ws = max(1, DECODE_WORKSPACE_BYTES)
            if sum(per) > limit or sum(dec) > ws:
                target = max(1, int(limit * self.STREAM_SLICE_FRACTION))
                # contiguous cuts, deterministic for a given (shards,
                # limit) so repeat queries hit the same slice cache keys;
                # never below n_devices shards per slice — _bucket would
                # pad a smaller slice back to a full mesh width of zero
                # blocks, re-inflating the memory the cut tried to save.
                # Two ceilings: resident bytes against the streaming
                # target (rotating the budget) and decoded dense bytes
                # against the per-launch workspace — a fully-resident
                # compressed working set still slices by the latter, so
                # one launch never materialises more dense tiles than
                # the workspace allows (rotation is then free: every
                # slice's compressed stack stays resident).
                slices, cur, cur_b, cur_d = [], [], 0, 0
                for s, b, d in zip(shards, per, dec):
                    if (cur_b + b > target or cur_d + d > ws) and \
                            len(cur) >= self.n_devices:
                        slices.append(cur)
                        cur, cur_b, cur_d = [], 0, 0
                    cur.append(s)
                    cur_b += b
                    cur_d += d
                if slices and len(cur) < self.n_devices:
                    slices[-1].extend(cur)  # tail can't fill the mesh
                elif cur:
                    slices.append(cur)
                if len(slices) > 1:
                    # drain resident slices first (stable within each
                    # class so rotation order stays deterministic)
                    res = [all(self._is_resident(kl, holder, index, sl)
                               for kl in key_lists) for sl in slices]
                    slices = [sl for sl, r in zip(slices, res) if r] + \
                        [sl for sl, r in zip(slices, res) if not r]
        return _ShardSchedule(self, holder, index, key_lists, slices)

    def _pin_stack(self, keys, index, shard_slice) -> tuple | None:
        skey = ("stack", id(self),
                (index, tuple(keys), tuple(shard_slice)))
        return skey if self._budget.pin(skey) else None

    def _stream_groups(self, keys, holder, index, shards):
        """``_placed_groups`` over the streaming schedule: the default
        iteration surface for every dispatch entry point.  Single-slice
        schedules (the common, fits-in-budget case) behave exactly like a
        direct ``_placed_groups`` call."""
        for sl in self.shard_schedule(holder, index, [keys], shards):
            yield from self._placed_groups(keys, holder, index, sl)

    # -- public entry points ----------------------------------------------

    def count_async(self, plan, holder, index, shards) -> list:
        """Dispatch the count computation; returns unblocked device scalars
        (one per shape group).  jax's async dispatch lets a batch of calls
        overlap on device; block once via int() at the end
        (``Executor.execute`` resolves all calls' pendings after dispatch)."""
        keys = plan_inputs(plan)
        slotted, params = parametrize(plan)
        params = jnp.asarray(params)
        parts = []
        for shard_list, placed, sig in self._stream_groups(
                keys, holder, index, shards):
            if all(s is None for s in sig):
                continue  # no fragments -> plan evaluates to empty
            present = self._present(keys, placed, sig)
            flat, layout = _flatten_present(present)
            fn = self._compiled(slotted, tuple(k for k, _, _ in present),
                                tuple(s for _, _, s in present), layout,
                                "count")
            with _DISPATCH_LOCK:
                parts.append(fn(params, *flat,
                                _launch_meta=len(shard_list)))
        return parts

    def count(self, plan, holder, index, shards) -> int:
        return sum(int(x) for x in self.count_async(
            plan, holder, index, shards))

    def segments(self, plan, holder, index, shards) -> dict[int, np.ndarray]:
        from ..core import SHARD_WORDS

        keys = plan_inputs(plan)
        slotted, params = parametrize(plan)
        params = jnp.asarray(params)
        out: dict[int, np.ndarray] = {}
        for shard_list, placed, sig in self._stream_groups(
                keys, holder, index, shards):
            if all(s is None for s in sig):
                zero = np.zeros(SHARD_WORDS, dtype=np.uint32)
                for shard in shard_list:
                    out[shard] = zero
                continue
            present = self._present(keys, placed, sig)
            flat, layout = _flatten_present(present)
            fn = self._compiled(slotted, tuple(k for k, _, _ in present),
                                tuple(s for _, _, s in present), layout,
                                None)
            with _DISPATCH_LOCK:
                segs = fn(params, *flat, _launch_meta=len(shard_list))
            # ONE addressable-shard host assembly.  Indexing the sharded
            # output per row (`segs[i]`) launched a collective reshard
            # program per shard, and per-row collectives from concurrent
            # request threads wedged XLA's device queues (rendezvous
            # circular wait); device_get copies shards with no collective.
            # Consumers (serialization, Store, filter masks) all coerce
            # to host or mix numpy into jnp ops anyway.
            host = np.asarray(jax.device_get(segs))
            for i, shard in enumerate(shard_list):
                out[shard] = host[i]
        return out

    def segments_batch(self, slotted, params_mat, holder, index,
                       shards) -> dict[int, np.ndarray]:
        """B same-shape bitmap plans in one executable invocation: the
        query-axis variant of ``segments`` for the dispatch batcher
        (parallel/batcher.py).  Returns {shard: [B, W] host array};
        caller b's segment for a shard is ``out[shard][b]``.  Host
        assembly mirrors ``segments`` (one device_get per shape group, no
        per-row collectives)."""
        keys = plan_inputs(slotted)
        params = jnp.asarray(params_mat)
        B = params.shape[0]
        out: dict[int, np.ndarray] = {}
        # pre-scheduled single-slice callers only (the batcher checks the
        # shard schedule before fusing); multi-slice working sets stream
        # through the un-fused ``segments`` path instead
        for shard_list, placed, sig in self._placed_groups(
                keys, holder, index, shards):
            if all(s is None for s in sig):
                zero = np.zeros((B, SHARD_WORDS), dtype=np.uint32)
                for shard in shard_list:
                    out[shard] = zero
                continue
            present = self._present(keys, placed, sig)
            pkeys = tuple(k for k, _, _ in present)
            pshapes = tuple(s for _, _, s in present)
            flat, layout = _flatten_present(present)
            key = self._plan_key("segmentsB", slotted, pkeys, pshapes)
            fn = self._cache.get(key)
            if fn is None:
                # Loop-local values (layout, per_shard, len(flat)) are
                # FROZEN into the closures as keyword defaults, here and
                # in every executable builder below: jax re-traces a
                # cached executable when a later call changes the stacked
                # group size, and a re-trace reads the closure CELLS —
                # which a later loop iteration has rebound to the next
                # group's values.  A compressed group re-traced with
                # another group's layout decodes with the wrong
                # container buckets (e.g. r_bucket=0 silently drops
                # every run container).
                def per_shard(params_, *arrays, _layout=layout):
                    frags = _unpack_frags(_layout, arrays)
                    return jax.vmap(
                        lambda p: eval_plan(slotted, frags, p))(
                            params_)                   # [B, W]

                vmapped = jax.vmap(per_shard,
                                   in_axes=(None,) + (0,) * len(flat))
                if self.multiprocess:
                    def block_fn(params_, *arrays, _vm=vmapped):
                        segs = _vm(params_, *arrays)   # [S_local, B, W]
                        return jax.lax.all_gather(segs, SHARD_AXIS,
                                                  tiled=True)

                    fn = self._jit_shard_map(
                        key, block_fn,
                        (P(),) + tuple(P(SHARD_AXIS) for _ in flat),
                        P(), check_vma=False, layout=layout)
                else:
                    def block_fn(params_, *arrays, _vm=vmapped):
                        return _vm(params_, *arrays)   # [S_local, B, W]

                    fn = self._jit_shard_map(
                        key, block_fn,
                        (P(),) + tuple(P(SHARD_AXIS) for _ in flat),
                        P(SHARD_AXIS), layout=layout)
            with _DISPATCH_LOCK:
                segs = fn(params, *flat, _launch_meta=len(shard_list))
            host = np.asarray(jax.device_get(segs))    # [S, B, W]
            for i, shard in enumerate(shard_list):
                out[shard] = host[i]
        return out

    # -- row_counts: TopN/Rows/MinRow/MaxRow (fragment.go:1570 top) --------

    @staticmethod
    def merge_counts(parts) -> np.ndarray:
        """Sum per-group count vectors of differing lengths (shape groups
        have different row capacities)."""
        from ..executor.results import acc_counts
        acc = np.zeros(0, dtype=np.int64)
        for p in parts:
            acc = acc_counts(acc, np.asarray(p, dtype=np.int64))
        return acc

    def row_counts_async(self, field: str, view: str, filter_plan, holder,
                         index, shards) -> list:
        """Dispatch per-row popcounts of (field, view) fragments across all
        shards, masked by ``filter_plan``'s result when given.  Returns
        unblocked per-group device vectors; combine with
        ``merge_counts``."""
        keys = self.batch_keys((field, view), filter_plan)
        slotted, params = (None, np.zeros(0, dtype=np.int32)) \
            if filter_plan is None else parametrize(filter_plan)
        params = jnp.asarray(params)
        parts = []
        for shard_list, placed, sig in self._stream_groups(
                keys, holder, index, shards):
            if sig[0] is None:
                continue  # field fragment absent everywhere in this group
            present = self._present(keys, placed, sig)
            pkeys = tuple(k for k, _, _ in present)
            pshapes = tuple(s for _, _, s in present)
            flat, layout = _flatten_present(present)
            key = self._plan_key("row_counts", slotted, pkeys, pshapes)
            fn = self._cache.get(key)
            if fn is None:
                fplan = slotted

                # loop-local captures frozen as defaults (re-trace safety;
                # see segments_batch)
                def per_shard(params_, *arrays, _layout=layout,
                              _k0=pkeys[0],
                              _fused=_fused_entry(layout, pkeys[0])):
                    if _fused is not None:
                        # the headline fusion (ops/kernels.py): decode +
                        # filter-AND + per-row popcount in ONE Pallas
                        # kernel; the field fragment's dense words never
                        # leave the kernel's VMEM tile.  Other layout
                        # entries still decode normally for the filter
                        # plan (XLA drops the unused field decode).
                        from ..ops import kernels
                        i0, fs = _fused
                        filt = None
                        if fplan is not None:
                            frags = _unpack_frags(_layout, arrays)
                            filt = eval_plan(fplan, frags, params_)
                        return kernels.fused_row_counts(
                            *arrays[i0: i0 + 5], filt, rows=fs[1],
                            words=SHARD_WORDS, a_bucket=fs[4],
                            r_bucket=fs[5])        # [rows]
                    frags = _unpack_frags(_layout, arrays)
                    frag = frags[_k0]              # [rows, W]
                    if fplan is None:
                        masked = frag
                    else:
                        seg = eval_plan(fplan, frags, params_)   # [W]
                        masked = frag & seg[None, :]
                    return jnp.sum(
                        jax.lax.population_count(masked).astype(jnp.int32),
                        axis=-1)                   # [rows]

                def block_fn(params_, *arrays, _ps=per_shard,
                             _n=len(flat)):
                    counts = jnp.sum(jax.vmap(
                        _ps, in_axes=(None,) + (0,) * _n)(
                            params_, *arrays), axis=0)
                    return jax.lax.psum(counts, axis_name=SHARD_AXIS)

                fn = self._jit_shard_map(
                    key, block_fn,
                    (P(),) + tuple(P(SHARD_AXIS) for _ in flat), P(),
                    layout=layout)
            with _DISPATCH_LOCK:
                parts.append(fn(params, *flat,
                                _launch_meta=len(shard_list)))
        return parts

    def row_counts(self, field: str, view: str, filter_plan, holder,
                   index, shards) -> np.ndarray:
        return self.merge_counts(self.row_counts_async(
            field, view, filter_plan, holder, index, shards))

    # -- BSI aggregations (fragment.go:1111 sum, :1147 min/max) ------------

    def bsi_sum_async(self, field: str, view: str, filter_plan, holder,
                      index, shards) -> list:
        """Dispatch the per-slice popcounts; returns unblocked [2, depth+1]
        device matrices (one per shape group); combine via
        ``bsi.weighted_sum`` per part and add."""
        keys = self.batch_keys((field, view), filter_plan)
        slotted, params = (None, np.zeros(0, dtype=np.int32)) \
            if filter_plan is None else parametrize(filter_plan)
        params = jnp.asarray(params)
        parts = []
        for shard_list, placed, sig in self._stream_groups(
                keys, holder, index, shards):
            if sig[0] is None or _sig_rows(sig[0]) < bsi.OFFSET_ROW + 1:
                continue
            present = self._present(keys, placed, sig)
            pkeys = tuple(k for k, _, _ in present)
            pshapes = tuple(s for _, _, s in present)
            flat, layout = _flatten_present(present)
            key = self._plan_key("bsi_sum", slotted, pkeys, pshapes)
            fn = self._cache.get(key)
            if fn is None:
                fplan = slotted

                def per_shard(params_, *arrays, _layout=layout,
                              _k0=pkeys[0]):
                    frags = _unpack_frags(_layout, arrays)
                    frag = frags[_k0]
                    filt = None
                    if fplan is not None:
                        filt = eval_plan(fplan, frags, params_)
                    return bsi.sum_counts(frag, filt)   # [2, depth+1]

                def block_fn(params_, *arrays, _ps=per_shard,
                             _n=len(flat)):
                    counts = jnp.sum(jax.vmap(
                        _ps, in_axes=(None,) + (0,) * _n)(
                            params_, *arrays), axis=0)
                    return jax.lax.psum(counts, axis_name=SHARD_AXIS)

                fn = self._jit_shard_map(
                    key, block_fn,
                    (P(),) + tuple(P(SHARD_AXIS) for _ in flat), P(),
                    layout=layout)
            with _DISPATCH_LOCK:
                parts.append(fn(params, *flat,
                                _launch_meta=len(shard_list)))
        return parts

    def bsi_sum(self, field: str, view: str, filter_plan, holder,
                index, shards) -> tuple[int, int]:
        """(sum-of-base-values, non-null-count) over all shards."""
        total, count = 0, 0
        for p in self.bsi_sum_async(field, view, filter_plan, holder,
                                    index, shards):
            s, cnt = bsi.weighted_sum(np.asarray(p))
            total += s
            count += cnt
        return total, count

    def bsi_min_max(self, field: str, view: str, filter_plan, holder,
                    index, shards, want_max: bool):
        """Per-shard extremum bits gathered to host; returns a list of
        (value, count) per shard (padded shards yield count 0)."""
        keys = self.batch_keys((field, view), filter_plan)
        slotted, params = (None, np.zeros(0, dtype=np.int32)) \
            if filter_plan is None else parametrize(filter_plan)
        params = jnp.asarray(params)
        out = []
        for shard_list, placed, sig in self._stream_groups(
                keys, holder, index, shards):
            if sig[0] is None or _sig_rows(sig[0]) < bsi.OFFSET_ROW + 1:
                continue
            present = self._present(keys, placed, sig)
            pkeys = tuple(k for k, _, _ in present)
            pshapes = tuple(s for _, _, s in present)
            flat, layout = _flatten_present(present)
            key = self._plan_key("bsi_minmax", slotted, pkeys, pshapes,
                                 extra=(want_max,))
            fn = self._cache.get(key)
            if fn is None:
                fplan = slotted

                def per_shard(params_, *arrays, _layout=layout,
                              _k0=pkeys[0]):
                    frags = _unpack_frags(_layout, arrays)
                    frag = frags[_k0]
                    filt = None
                    if fplan is not None:
                        filt = eval_plan(fplan, frags, params_)
                    return bsi.min_max_bits(frag, filt, want_max=want_max)

                if self.multiprocess:
                    def block_fn(params_, *arrays, _ps=per_shard,
                                 _n=len(flat)):
                        outs = jax.vmap(
                            _ps, in_axes=(None,) + (0,) * _n)(
                                params_, *arrays)
                        return tuple(
                            jax.lax.all_gather(o, SHARD_AXIS, tiled=True)
                            for o in outs)

                    out_specs = (P(), P(), P())
                    check_vma = False
                else:
                    def block_fn(params_, *arrays, _ps=per_shard,
                                 _n=len(flat)):
                        return jax.vmap(
                            _ps, in_axes=(None,) + (0,) * _n)(
                                params_, *arrays)

                    out_specs = (P(SHARD_AXIS), P(SHARD_AXIS),
                                 P(SHARD_AXIS))
                    check_vma = True

                fn = self._jit_shard_map(
                    key, block_fn,
                    (P(),) + tuple(P(SHARD_AXIS) for _ in flat),
                    out_specs, check_vma=check_vma, layout=layout)
            with _DISPATCH_LOCK:
                outs = fn(params, *flat, _launch_meta=len(shard_list))
            bits, neg, cnt = (np.asarray(x) for x in outs)
            for i in range(len(shard_list)):
                out.append(bsi.reconstruct_min_max(
                    bits[i], int(neg[i]), int(cnt[i])))
        return out

    # -- batched variants: B same-shape calls, ONE executable invocation ---
    # A multi-call query's same-shape calls (e.g. 64 distinct Counts)
    # execute as one vmapped computation over a [B, P] params matrix —
    # collapsing B dispatch round trips into one.  This is the TPU-native
    # replacement for the reference's worker pool soaking up concurrent
    # queries (executor.go:80-110).

    def count_batch_async(self, slotted, params_mat, holder, index,
                          shards) -> list:
        """B counts that share one plan shape; parts are [B] vectors."""
        keys = plan_inputs(slotted)
        params = jnp.asarray(params_mat)               # [B, P]
        parts = []
        # no _stream_groups here: the callers (_run_batched_groups and
        # the dispatch batcher) own the slice schedule and pass
        # pre-scheduled shard slices — re-scheduling would re-walk the
        # holder per (group x chunk)
        for shard_list, placed, sig in self._placed_groups(
                keys, holder, index, shards):
            if all(s is None for s in sig):
                continue
            present = self._present(keys, placed, sig)
            pkeys = tuple(k for k, _, _ in present)
            pshapes = tuple(s for _, _, s in present)
            flat, layout = _flatten_present(present)
            key = self._plan_key("countB", slotted, pkeys, pshapes)
            fn = self._cache.get(key)
            if fn is None:
                def per_shard(params_, *arrays, _layout=layout):
                    frags = _unpack_frags(_layout, arrays)
                    segs = jax.vmap(
                        lambda p: eval_plan(slotted, frags, p))(params_)
                    return jnp.sum(
                        jax.lax.population_count(segs).astype(jnp.int32),
                        axis=-1)                       # [B]

                def block_fn(params_, *arrays, _ps=per_shard,
                             _n=len(flat)):
                    counts = jnp.sum(jax.vmap(
                        _ps, in_axes=(None,) + (0,) * _n)(
                            params_, *arrays), axis=0)
                    return jax.lax.psum(counts, axis_name=SHARD_AXIS)

                fn = self._jit_shard_map(
                    key, block_fn,
                    (P(),) + tuple(P(SHARD_AXIS) for _ in flat), P(),
                    layout=layout)
            with _DISPATCH_LOCK:
                parts.append(fn(params, *flat,
                                _launch_meta=len(shard_list)))
        return parts

    def row_counts_batch_async(self, field: str, view: str, slotted_filter,
                               params_mat, holder, index, shards) -> list:
        """B row-count passes sharing one filter shape; parts are
        [B, rows] matrices."""
        keys = self.batch_keys((field, view), slotted_filter)
        params = jnp.asarray(params_mat)
        parts = []
        # no _stream_groups here: the callers (_run_batched_groups and
        # the dispatch batcher) own the slice schedule and pass
        # pre-scheduled shard slices — re-scheduling would re-walk the
        # holder per (group x chunk)
        for shard_list, placed, sig in self._placed_groups(
                keys, holder, index, shards):
            if sig[0] is None:
                continue
            present = self._present(keys, placed, sig)
            pkeys = tuple(k for k, _, _ in present)
            pshapes = tuple(s for _, _, s in present)
            flat, layout = _flatten_present(present)
            key = self._plan_key("row_countsB", slotted_filter, pkeys,
                                 pshapes)
            fn = self._cache.get(key)
            if fn is None:
                def per_shard(params_, *arrays, _layout=layout,
                              _k0=pkeys[0], _fplan=slotted_filter):
                    frags = _unpack_frags(_layout, arrays)
                    frag = frags[_k0]                  # [rows, W]
                    if _fplan is None:
                        counts = jnp.sum(
                            jax.lax.population_count(frag).astype(jnp.int32),
                            axis=-1)                   # [rows]
                        return jnp.broadcast_to(
                            counts, (params_.shape[0],) + counts.shape)
                    masks = jax.vmap(
                        lambda p: eval_plan(_fplan, frags, p))(params_)
                    masked = frag[None, :, :] & masks[:, None, :]
                    return jnp.sum(
                        jax.lax.population_count(masked).astype(jnp.int32),
                        axis=-1)                       # [B, rows]

                def block_fn(params_, *arrays, _ps=per_shard,
                             _n=len(flat)):
                    counts = jnp.sum(jax.vmap(
                        _ps, in_axes=(None,) + (0,) * _n)(
                            params_, *arrays), axis=0)
                    return jax.lax.psum(counts, axis_name=SHARD_AXIS)

                fn = self._jit_shard_map(
                    key, block_fn,
                    (P(),) + tuple(P(SHARD_AXIS) for _ in flat), P(),
                    layout=layout)
            with _DISPATCH_LOCK:
                parts.append(fn(params, *flat,
                                _launch_meta=len(shard_list)))
        return parts

    def bsi_sum_batch_async(self, field: str, view: str, slotted_filter,
                            params_mat, holder, index, shards) -> list:
        """B BSI sums sharing one filter shape; parts are [B, 2, depth+1]."""
        keys = self.batch_keys((field, view), slotted_filter)
        params = jnp.asarray(params_mat)
        parts = []
        # no _stream_groups here: the callers (_run_batched_groups and
        # the dispatch batcher) own the slice schedule and pass
        # pre-scheduled shard slices — re-scheduling would re-walk the
        # holder per (group x chunk)
        for shard_list, placed, sig in self._placed_groups(
                keys, holder, index, shards):
            if sig[0] is None or _sig_rows(sig[0]) < bsi.OFFSET_ROW + 1:
                continue
            present = self._present(keys, placed, sig)
            pkeys = tuple(k for k, _, _ in present)
            pshapes = tuple(s for _, _, s in present)
            flat, layout = _flatten_present(present)
            key = self._plan_key("bsi_sumB", slotted_filter, pkeys, pshapes)
            fn = self._cache.get(key)
            if fn is None:
                def per_shard(params_, *arrays, _layout=layout,
                              _k0=pkeys[0], _fplan=slotted_filter):
                    frags = _unpack_frags(_layout, arrays)
                    frag = frags[_k0]
                    if _fplan is None:
                        counts = bsi.sum_counts(frag, None)
                        return jnp.broadcast_to(
                            counts, (params_.shape[0],) + counts.shape)

                    def one(p):
                        return bsi.sum_counts(frag, eval_plan(_fplan, frags,
                                                              p))

                    return jax.vmap(one)(params_)      # [B, 2, depth+1]

                def block_fn(params_, *arrays, _ps=per_shard,
                             _n=len(flat)):
                    counts = jnp.sum(jax.vmap(
                        _ps, in_axes=(None,) + (0,) * _n)(
                            params_, *arrays), axis=0)
                    return jax.lax.psum(counts, axis_name=SHARD_AXIS)

                fn = self._jit_shard_map(
                    key, block_fn,
                    (P(),) + tuple(P(SHARD_AXIS) for _ in flat), P(),
                    layout=layout)
            with _DISPATCH_LOCK:
                parts.append(fn(params, *flat,
                                _launch_meta=len(shard_list)))
        return parts

    # -- GroupBy inner loop (executor.go:1068 executeGroupBy) --------------

    # Max combos per dispatch: bounds the [S_local, chunk, rows] int32
    # intermediate (8 stacked shards x 256 combos x 1024 rows = 8 MB) so a
    # large odometer cannot OOM HBM; full chunks share one executable.
    GROUP_CHUNK = 256

    def group_counts_batch_async(self, last_key: tuple[str, str],
                                 prefix_keys: list[tuple[str, str]],
                                 combos: np.ndarray, filter_plan, holder,
                                 index, shards) -> list:
        """All C prefix combos of a GroupBy in a handful of executable
        invocations: ``combos`` is a [C, P] int32 matrix of prefix row ids.
        Returns [(lo, hi, parts)] where ``parts`` are [chunk, rows] count
        matrices covering combos[lo:hi] (rows beyond hi-lo are padding).
        The odometer's per-combo device round trips (executor.go:3058
        groupByIterator) collapse into a vmap over the combo axis, chunked
        to GROUP_CHUNK combos per dispatch to bound device memory."""
        combos = np.asarray(combos, dtype=np.int32)
        out = []
        for lo in range(0, combos.shape[0], self.GROUP_CHUNK):
            sub = combos[lo: lo + self.GROUP_CHUNK]
            out.append((lo, lo + sub.shape[0],
                        self._group_counts_chunk(
                            last_key, prefix_keys, sub, filter_plan,
                            holder, index, shards)))
        return out

    def _group_counts_chunk(self, last_key, prefix_keys, combos,
                            filter_plan, holder, index, shards) -> list:
        C = combos.shape[0]
        pad_c = 1
        while pad_c < C:
            pad_c *= 2
        if pad_c != C:
            combos = np.vstack(
                [combos, np.zeros((pad_c - C, combos.shape[1]), np.int32)])
        keys = [last_key]
        for k in prefix_keys:
            if k not in keys:
                keys.append(k)
        for k in self._filter_keys(filter_plan):
            if k not in keys:
                keys.append(k)
        rids = jnp.asarray(combos)
        slotted, params = (None, np.zeros(0, dtype=np.int32)) \
            if filter_plan is None else parametrize(filter_plan)
        params = jnp.asarray(params)
        parts = []
        for shard_list, placed, sig in self._stream_groups(
                keys, holder, index, shards):
            if sig[0] is None:
                continue
            key_to_sig = dict(zip(keys, sig))
            if any(key_to_sig[k] is None for k in prefix_keys):
                continue
            present = self._present(keys, placed, sig)
            pkeys = tuple(k for k, _, _ in present)
            pshapes = tuple(s for _, _, s in present)
            flat, layout = _flatten_present(present)
            key = self._plan_key("group_countsB", slotted, pkeys, pshapes,
                                 extra=(tuple(prefix_keys), pad_c))
            fn = self._cache.get(key)
            if fn is None:
                fplan = slotted
                pk_list = list(prefix_keys)

                def one_combo(rids_row, params_, frags, frag):
                    mask = None
                    for j, pk in enumerate(pk_list):
                        pfrag = frags[pk]
                        rid = rids_row[j]
                        if pfrag.shape[0] == 0:
                            seg = jnp.zeros(pfrag.shape[-1],
                                            dtype=pfrag.dtype)
                        else:
                            seg = jnp.where(
                                rid < pfrag.shape[0],
                                jax.lax.dynamic_index_in_dim(
                                    pfrag,
                                    jnp.minimum(rid, pfrag.shape[0] - 1),
                                    axis=0, keepdims=False),
                                jnp.zeros_like(pfrag[0]))
                        mask = seg if mask is None else mask & seg
                    if fplan is not None:
                        fseg = eval_plan(fplan, frags, params_)
                        mask = fseg if mask is None else mask & fseg
                    masked = frag if mask is None else frag & mask[None, :]
                    return jnp.sum(
                        jax.lax.population_count(masked).astype(jnp.int32),
                        axis=-1)                       # [rows]

                def per_shard(rids_, params_, *arrays, _layout=layout,
                              _k0=pkeys[0], _oc=one_combo):
                    frags = _unpack_frags(_layout, arrays)
                    frag = frags[_k0]                  # [rows, W]
                    return jax.vmap(
                        lambda r: _oc(r, params_, frags, frag))(
                            rids_)                     # [C, rows]

                def block_fn(rids_, params_, *arrays, _ps=per_shard,
                             _n=len(flat)):
                    counts = jnp.sum(jax.vmap(
                        _ps,
                        in_axes=(None, None) + (0,) * _n)(
                            rids_, params_, *arrays), axis=0)
                    return jax.lax.psum(counts, axis_name=SHARD_AXIS)

                fn = self._jit_shard_map(
                    key, block_fn,
                    (P(), P()) + tuple(P(SHARD_AXIS) for _ in flat), P(),
                    layout=layout)
            with _DISPATCH_LOCK:
                # (shards, C): the pow-2 combo padding (pad_c - C rows)
                # must count as padding waste, not actual work
                parts.append(fn(rids, params, *flat,
                                _launch_meta=(len(shard_list), C)))
        return parts


class _ShardSchedule:
    """Iterable of shard slices with prefetch + pinning.

    While the consumer stages and dispatches against slice k, a background
    uploader stages slice k+1 (host dense expansion + device placement off
    the critical path).  Both the in-use and the prefetched slices' budget
    entries are pinned so concurrent staging cannot evict them mid-use;
    pins release as each slice's dispatch completes (jax holds its own
    references to enqueued computations from then on)."""

    def __init__(self, mexec, holder, index, key_lists, slices):
        self.mexec = mexec
        self.holder = holder
        self.index = index
        self.key_lists = key_lists
        self.slices = slices

    @property
    def max_slice_len(self) -> int:
        return max((len(s) for s in self.slices), default=0)

    def _stage(self, shard_slice) -> list[tuple]:
        """Stage every key list's stack for one slice and pin the
        entries; returns the pinned budget keys (for the iterator to
        release after the slice's dispatch).  On a mid-stage failure
        (device OOM, fragment closed concurrently) every pin taken so
        far is released before re-raising — a leaked pin would shrink
        the effective budget for the process lifetime."""
        pinned = []
        try:
            for kl in self.key_lists:
                self.mexec._placed_groups(kl, self.holder, self.index,
                                          shard_slice)
                skey = self.mexec._pin_stack(kl, self.index, shard_slice)
                if skey is not None:
                    pinned.append(skey)
        except BaseException:
            for k in pinned:
                self.mexec._budget.unpin(k)
            raise
        return pinned

    def _slice_event(self, prof, i, sl, t0, up0, ev0):
        """One per-shard-slice profile stage: dispatch wall time plus the
        device-budget upload/evict deltas the slice drove — the
        streaming half of the EXPLAIN ANALYZE tree
        (docs/observability.md)."""
        budget = self.mexec._budget
        prof.event("device.slice", _time.perf_counter() - t0,
                   slice=i, shards=len(sl),
                   uploadBytes=budget.upload_bytes - up0,
                   evictions=budget.evictions - ev0)

    def __iter__(self):
        # Deadline + failpoint gate per slice: an expired query aborts
        # BETWEEN shard slices (check_current raises DeadlineExceeded;
        # the finally below releases pins, so partial device work is
        # freed, docs/robustness.md) instead of running to completion.
        prof = qprof.current()
        budget = self.mexec._budget
        if len(self.slices) <= 1:
            try:
                for sl in self.slices:
                    FAULTS.hit("mesh.slice", key=self.index)
                    check_current("mesh shard slice")
                    _devobs.set_slice(0, 1)
                    if prof is None:
                        yield sl
                    else:
                        t0, up0, ev0 = (_time.perf_counter(),
                                        budget.upload_bytes,
                                        budget.evictions)
                        yield sl
                        self._slice_event(prof, 0, sl, t0, up0, ev0)
            finally:
                _devobs.set_slice(None)
            return
        pool = self.mexec._uploader_pool()
        fut = None   # in-flight prefetch of the slice about to be served
        pins: list = []
        try:
            for i, sl in enumerate(self.slices):
                FAULTS.hit("mesh.slice", key=self.index)
                check_current("mesh shard slice")
                t0, up0, ev0 = (_time.perf_counter(), budget.upload_bytes,
                                budget.evictions)
                if fut is not None:
                    # prefetch-hit means the uploader finished BEFORE the
                    # consumer got here (checked via done() — result()
                    # blocks, so checking afterwards would report a hit
                    # even when streaming serialized on the upload) and
                    # the stacks are still token-valid
                    done = fut.done()
                    try:
                        pins.extend(fut.result())
                        budget.note_prefetch(done and all(
                            self.mexec._is_resident(kl, self.holder,
                                                    self.index, sl)
                            for kl in self.key_lists))
                    except (Exception, futures.CancelledError):
                        # CancelledError (a BaseException since 3.8):
                        # close() cancelling queued prefetches mid-query
                        # must degrade to inline staging, not abort
                        budget.note_prefetch(False)
                    fut = None
                # cold slices stage here; prefetched ones hit the cache
                pins.extend(self._stage(sl))
                if i + 1 < len(self.slices):
                    # the trace context crosses the uploader-pool
                    # boundary with the prefetch (orphan staging work
                    # would otherwise be untraceable)
                    fut = pool.submit(
                        GLOBAL_TRACER.task(self._stage,
                                           name="mesh.prefetch_slice"),
                        self.slices[i + 1])
                # launch-ledger slice position: dispatches between this
                # yield and the next run against slice i
                _devobs.set_slice(i, len(self.slices))
                yield sl
                # the consumer dispatched against this slice between the
                # yield and here — safe to let the budget rotate it out
                if prof is not None:
                    self._slice_event(prof, i, sl, t0, up0, ev0)
                for k in pins:
                    budget.unpin(k)
                pins = []
        finally:
            _devobs.set_slice(None)
            for k in pins:
                budget.unpin(k)
            if fut is not None:
                try:
                    for k in fut.result():
                        budget.unpin(k)
                # lint: allow(swallowed-exception) — unpin cleanup in a
                # finally; a failed prefetch already surfaces as a stage
                # miss (budget.prefetch_misses) and a re-upload
                except (Exception, futures.CancelledError):
                    pass

