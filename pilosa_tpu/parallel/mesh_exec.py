"""Multi-device shard execution over a jax Mesh.

The reference fans per-shard jobs to a goroutine pool and a star reduce
(executor.go:2455 mapReduce, :2482 coordinator-side reduce).  Here shards
with identical plan input shapes are STACKED into [S, rows, W] tensors,
sharded over a 1-d "shards" mesh axis, and the whole batch executes as one
XLA computation under shard_map: each device runs the vmapped plan on its
local shard block and cross-shard reductions (Count, per-row counts for
TopN) ride ICI collectives (psum) instead of host gather — the star reduce
becomes an all-reduce.

On a single device this degrades gracefully to one stacked call (still
better than per-shard dispatch given the ~100 ms tunnel round-trip floor).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..ops import bitset
from ..executor.plan import eval_plan, plan_inputs

SHARD_AXIS = "shards"


def default_mesh(devices=None) -> Mesh:
    devices = devices if devices is not None else jax.devices()
    return Mesh(np.array(devices), axis_names=(SHARD_AXIS,))


class MeshExecutor:
    """Executes resolved plans over stacked shard groups on a device mesh."""

    def __init__(self, mesh: Mesh | None = None):
        self.mesh = mesh or default_mesh()
        self.n_devices = self.mesh.devices.size
        # Fragment mirrors must live on the mesh's platform (e.g. a virtual
        # CPU mesh while the default backend is a TPU).  When the mesh IS on
        # the default platform we stage with target=None so the mesh path
        # and the per-shard executor share one cached upload per fragment
        # instead of holding two copies in device memory.
        stage = self.mesh.devices.flat[0]
        cfg_default = jax.config.jax_default_device
        default_platform = (cfg_default.platform if cfg_default is not None
                            else jax.devices()[0].platform)
        self.stage_device = None if stage.platform == default_platform \
            else stage
        self._cache: dict = {}

    # -- compiled executables ---------------------------------------------

    def _compiled(self, plan, input_keys, shapes, reducer):
        key = (repr(plan), tuple(input_keys), tuple(shapes), reducer,
               id(self.mesh))
        fn = self._cache.get(key)
        if fn is not None:
            return fn

        # input_keys here are only the PRESENT fragments; missing ones are
        # omitted from the arg list entirely (shard_map specs must map 1:1
        # to array args)
        def per_shard(*arrays):
            frags = dict(zip(input_keys, arrays))
            return eval_plan(plan, frags)

        vmapped = jax.vmap(per_shard)

        if reducer == "count":
            def block_fn(*arrays):
                segs = vmapped(*arrays)  # [S_local, W]
                local = jnp.sum(
                    jax.lax.population_count(segs).astype(jnp.int32))
                return jax.lax.psum(local, axis_name=SHARD_AXIS)

            out_specs = P()
        elif reducer == "row_counts":
            # per-(shard-row) popcounts of the first input fragment masked
            # by the plan result — TopN phase 1, reduced over shards on ICI
            def block_fn(*arrays):
                segs = vmapped(*arrays)            # [S_local, W]
                frag = arrays[0]                   # [S_local, rows, W]
                masked = frag & segs[:, None, :] if segs is not None else frag
                counts = jnp.sum(
                    jax.lax.population_count(masked).astype(jnp.int32),
                    axis=(0, 2))                   # [rows]
                return jax.lax.psum(counts, axis_name=SHARD_AXIS)

            out_specs = P()
        else:
            def block_fn(*arrays):
                return vmapped(*arrays)            # [S_local, W]

            out_specs = P(SHARD_AXIS)

        in_specs = tuple(P(SHARD_AXIS) for _ in shapes)
        fn = jax.jit(jax.shard_map(
            block_fn, mesh=self.mesh,
            in_specs=in_specs, out_specs=out_specs))
        self._cache[key] = fn
        return fn

    # -- shard grouping ----------------------------------------------------

    def _gather_inputs(self, plan, holder, index, shards):
        """Group shards by input-shape signature; returns
        [(shard_list, input_keys, stacked_arrays, shapes)]."""
        keys = plan_inputs(plan)
        groups: dict[tuple, list[tuple[int, list]]] = {}
        for shard in shards:
            arrays = []
            for field, view in keys:
                frag = holder.fragment(index, field, view, shard)
                arrays.append(
                    None if frag is None
                    else frag.device(self.stage_device))
            sig = tuple(None if a is None else a.shape for a in arrays)
            groups.setdefault(sig, []).append((shard, arrays))
        out = []
        for sig, members in groups.items():
            shard_list = [m[0] for m in members]
            stacked = []
            for i, shape in enumerate(sig):
                if shape is None:
                    stacked.append(None)
                else:
                    stacked.append([m[1][i] for m in members])
            out.append((shard_list, keys, stacked, sig))
        return out

    def _pad_and_place(self, arrays_list, shape, n: int):
        """Stack n member arrays, pad to a multiple of n_devices, and place
        sharded over the mesh axis."""
        pad = (-n) % self.n_devices
        mats = list(arrays_list)
        if pad:
            zero = jax.device_put(
                np.zeros(shape, dtype=np.uint32), self.stage_device)
            mats += [zero] * pad
        stacked = jnp.stack(mats)
        sharding = NamedSharding(self.mesh, P(SHARD_AXIS))
        return jax.device_put(stacked, sharding)

    # -- public entry points ----------------------------------------------

    def count(self, plan, holder, index, shards) -> int:
        total = 0
        for shard_list, keys, stacked, sig in self._gather_inputs(
                plan, holder, index, shards):
            if all(s is None for s in sig):
                continue  # no fragments -> plan evaluates to empty
            n = len(shard_list)
            present = [(k, a, s) for k, a, s in zip(keys, stacked, sig)
                       if s is not None]
            placed = [self._pad_and_place(a, s, n) for _, a, s in present]
            fn = self._compiled(plan, tuple(k for k, _, _ in present),
                                tuple(s for _, _, s in present), "count")
            total += int(fn(*placed))
        return total

    def segments(self, plan, holder, index, shards) -> dict[int, jax.Array]:
        from ..core import SHARD_WORDS

        out: dict[int, jax.Array] = {}
        for shard_list, keys, stacked, sig in self._gather_inputs(
                plan, holder, index, shards):
            if all(s is None for s in sig):
                zero = jnp.zeros(SHARD_WORDS, dtype=jnp.uint32)
                for shard in shard_list:
                    out[shard] = zero
                continue
            n = len(shard_list)
            present = [(k, a, s) for k, a, s in zip(keys, stacked, sig)
                       if s is not None]
            placed = [self._pad_and_place(a, s, n) for _, a, s in present]
            fn = self._compiled(plan, tuple(k for k, _, _ in present),
                                tuple(s for _, _, s in present), None)
            segs = fn(*placed)
            for i, shard in enumerate(shard_list):
                out[shard] = segs[i]
        return out
