"""Fleet metrics rollup: one node answers for the whole cluster
(docs/observability.md "Cluster plane").

PR 5/8 observability is strictly per-node: diagnosing a fleet-wide p99
regression or a mis-routing episode means ssh-ing to every node and
correlating ``/debug/vars`` by hand.  The :class:`FleetRollup` makes any
node (in practice the coordinator) aggregate its peers:

* ``GET /debug/cluster`` — per-node summaries (qps, p50/p99, HBM split,
  evictions, retraces, hedges, quarantines, ingest backlog) extracted
  from each peer's ``/debug/vars``, plus the local hot-shard table,
  overlay epoch, and a merged fleet event timeline;
* ``pilosa_tpu_cluster_*`` Prometheus gauges with ``node`` labels,
  appended to ``/metrics`` (own exposition, like the launch ledger's).

Fetch discipline: peer pulls ride the existing bounded
:class:`InternalClient` — per-peer circuit breakers apply (an open
breaker fails the pull instantly), fetches run CONCURRENTLY on a
dedicated pool with the cluster's probe timeout, and non-READY peers
are not fetched at all.  A failed or skipped pull serves the peer's
LAST summary stamped ``stale: true`` + ``staleS`` — a dead node can
never block a scrape, only age in it.  Results are TTL-cached
(``TTL_S``) so scrape storms collapse to one refresh.

The merged timeline pulls each peer's event journal with the
``/debug/events?since=<seq>`` cursor (utils/events.py), deduplicating
by (node, seq) — the fleet answer to "what state transitions happened
around that spike", with per-node attribution intact.
"""

from __future__ import annotations

import time
from collections import deque
from concurrent.futures import ThreadPoolExecutor

from ..utils.events import EVENTS
from ..utils.locks import make_lock

# display-only wall stamp (durations/ages come from monotonic pairs)
def _wall_stamp() -> float: return time.time()


def summarize_vars(v: dict) -> dict:
    """The per-node summary extracted from one /debug/vars snapshot —
    shared by the peer (wire) and local (in-process) paths so the
    rollup agrees with every node's own surface by construction."""
    counts = v.get("counts") or {}
    timings = v.get("timings") or {}
    hq = timings.get("http.query") or {}
    bud = v.get("deviceBudget") or {}
    dev = v.get("device") or {}
    comp = dev.get("compiles") or {}
    lau = dev.get("launches") or {}
    wq = v.get("wholeQuery") or {}
    ing = v.get("ingest") or {}
    adm = (v.get("admission") or {}).get("public") or {}
    cl = v.get("cluster") or {}
    quarantined = v.get("storage", {}).get("quarantined") or []
    # tenant isolation plane: the per-tenant qps/p99/shed/quota columns
    # each node publishes (docs/robustness.md "Tenant isolation")
    tenants = {}
    for name, row in (v.get("tenants") or {}).items():
        tenants[name] = {
            "qps": float(row.get("qps") or 0.0),
            "p99Ms": row.get("p99Ms"),
            "shed": int(row.get("shed") or 0),
            "hedgeDenied": int(row.get("hedgeDenied") or 0),
            "quotaEvicts": int(row.get("quotaEvicts") or 0),
        }
    return {
        "queries": int(hq.get("count") or 0),
        "p50Ms": round(hq["p50"] * 1e3, 3) if hq.get("p50") else None,
        "p99Ms": round(hq["p99"] * 1e3, 3) if hq.get("p99") else None,
        "hbmResidentBytes": int(bud.get("residentBytes") or 0),
        "hbmCompressedBytes": int(bud.get("compressedBytes") or 0),
        "hbmDenseBytes": int(bud.get("denseBytes") or 0),
        "hbmPinnedBytes": int(bud.get("pinnedBytes") or 0),
        "evictions": int(bud.get("evictions") or 0),
        "compiles": int(comp.get("compiles") or 0),
        "retraces": int(comp.get("retraces") or 0),
        "launches": int(lau.get("launches") or 0),
        "paddingWasteRatio": float(lau.get("paddingWasteRatio") or 0.0),
        "hedges": int(counts.get("cluster.hedges") or 0),
        "hedgeWins": int(counts.get("cluster.hedge_wins") or 0),
        "retryWaves": int(counts.get("cluster.retry_waves") or 0),
        "partialResults": int(counts.get("cluster.partial_results") or 0),
        "routingFallbacks": int(counts.get("routing.fallback") or 0),
        "wholeQueryFallbacks": int(wq.get("fallbacks") or 0),
        "quarantinedFragments": len(quarantined),
        "ingestBacklogBytes": int(ing.get("pendingBytes") or 0),
        "admissionInUse": int(adm.get("inUse") or 0),
        "admissionWaiting": int(adm.get("waiting") or 0),
        "overlayEpoch": int((cl.get("overlay") or {}).get("epoch") or 0),
        "tenants": tenants,
        # SLO engine (docs/observability.md "SLOs & alerting"): the
        # per-node alert state the fleet panel and the coordinator's
        # pilosa_tpu_cluster_active_alerts family render — stale peers
        # keep their last-known alert set, stamped stale like the rest
        "activeAlerts": len((v.get("alerts") or {}).get("active") or {}),
        "alertsFired": int((v.get("alerts") or {}).get("firedTotal")
                           or 0),
        "alertIds": sorted((v.get("alerts") or {}).get("active") or {}),
    }


class FleetRollup:
    """Owned by the Server when a cluster is configured; /debug/cluster
    and the /metrics cluster family both go through ``refresh()`` +
    ``snapshot()``."""

    TTL_S = 2.0            # scrape storms collapse to one refresh
    TIMELINE_MAX = 1024    # merged fleet events retained
    EVENTS_PER_PULL = 256  # per-peer events folded per refresh

    def __init__(self, cluster, local_vars_fn=None, stats=None):
        self.cluster = cluster
        self.local_vars_fn = local_vars_fn
        self.stats = stats
        self._lock = make_lock("rollup")
        # one refresh at a time; a caller losing the race serves the
        # cache the winner is about to replace (monotonic staleness,
        # never a thundering herd of peer fetches)
        self._refresh_serial = make_lock("rollup-refresh")
        self._pool = ThreadPoolExecutor(
            max_workers=max(2, len(cluster.nodes)),
            thread_name_prefix="ptpu-rollup")
        # nid -> {"summary", "wall", "mono", "stale", "error"}
        self._peers: dict[str, dict] = {}
        # nid -> (mono, queries) for qps deltas between refreshes
        self._prev_q: dict[str, tuple[float, int]] = {}
        self._qps: dict[str, float] = {}
        # per-PEER fetch cursor (highest seq pulled from that peer's
        # /debug/events) and per-EMITTER merge cursor (dedup by the
        # event's OWN node stamp — in-process multi-server tests share
        # one process-wide journal, so the same event can arrive via
        # several peers' pulls)
        self._cursor: dict[str, int] = {}
        self._merge_cursor: dict[str, int] = {}
        self._timeline: deque = deque(maxlen=self.TIMELINE_MAX)
        self._last_refresh: float | None = None
        self.refreshes = 0
        self.fetch_errors = 0

    def close(self):
        self._pool.shutdown(wait=False)

    # -- refresh -----------------------------------------------------------

    def _fetch_peer(self, node, timeout):
        """(vars, events, error) for one READY peer — runs on the
        rollup pool; breaker discipline applies inside the client."""
        client = self.cluster.client
        since = self._cursor.get(node.id, 0)
        try:
            v = client.debug_vars(node.host, timeout=timeout)
            ev = client.debug_events(node.host, since=since,
                                     timeout=timeout,
                                     limit=self.EVENTS_PER_PULL)
            return v, ev, None
        except Exception as e:
            return None, None, e

    def refresh(self, force: bool = False):
        """Refresh the per-peer cache if the TTL elapsed.  Never blocks
        on a dead node: non-READY peers are skipped outright, READY
        fetches run concurrently under the probe timeout, and failures
        leave the previous summary in place (stamped stale)."""
        now = time.monotonic()
        with self._lock:
            fresh = (not force and self._last_refresh is not None
                     and now - self._last_refresh < self.TTL_S)
        if fresh:
            return
        if not self._refresh_serial.acquire(blocking=False):
            return  # a concurrent refresh is filling the cache
        try:
            self._refresh_locked()
        finally:
            self._refresh_serial.release()

    def _refresh_locked(self):
        cluster = self.cluster
        timeout = cluster._probe_timeout()
        peers = cluster.peers()
        ready = [n for n in peers if n.state == "READY"
                 and not cluster.client.breaker_open(n.host)]
        # READY peers skipped because their breaker is open still age:
        # the docs' staleness contract is "a failed or SKIPPED pull
        # serves the last summary stamped stale" — without this, a
        # breaker-open peer's aging summary reads as fresh
        skipped = [n for n in peers
                   if n.state == "READY" and n not in ready]
        try:
            futs = [(n, self._pool.submit(self._fetch_peer, n, timeout))
                    for n in ready]
        except RuntimeError:  # pool shut down: close() raced a scrape
            futs = []
        local_summary = None
        if self.local_vars_fn is not None:
            try:
                local_summary = summarize_vars(self.local_vars_fn())
            except Exception:
                # the local surface failing must not fail the fleet view
                self.fetch_errors += 1
        local_events = EVENTS.since(self._cursor.get(cluster.node_id, 0),
                                    limit=self.EVENTS_PER_PULL)
        results = [(n, *f.result()) for n, f in futs]
        now = time.monotonic()
        with self._lock:
            self.refreshes += 1
            self._last_refresh = now
            for n in skipped:
                entry = self._peers.get(n.id)
                if entry is not None:
                    entry["stale"] = True
                    entry.setdefault("error", None)
                    entry["error"] = entry["error"] or "breaker open"
                else:
                    self._peers[n.id] = {
                        "summary": None, "wall": None, "mono": None,
                        "stale": True, "error": "breaker open"}
            if local_summary is not None:
                self._note_node(cluster.node_id, local_summary, now)
            for e in local_events:
                self._fold_event(cluster.node_id, e)
            for n, v, ev, err in results:
                if err is not None:
                    self.fetch_errors += 1
                    entry = self._peers.get(n.id)
                    if entry is not None:
                        entry["stale"] = True
                        entry["error"] = f"{type(err).__name__}: {err}"
                    else:
                        self._peers[n.id] = {
                            "summary": None, "wall": None, "mono": None,
                            "stale": True,
                            "error": f"{type(err).__name__}: {err}"}
                    continue
                self._note_node(n.id, summarize_vars(v), now)
                for e in (ev or {}).get("events", []):
                    self._fold_event(n.id, e)

    def _note_node(self, nid: str, summary: dict, now: float):
        prev = self._prev_q.get(nid)
        q = summary["queries"]
        if prev is not None and now > prev[0] and q >= prev[1]:
            self._qps[nid] = (q - prev[1]) / (now - prev[0])
        self._prev_q[nid] = (now, q)
        self._peers[nid] = {"summary": summary,
                            "wall": _wall_stamp(), "mono": now,
                            "stale": False, "error": None}

    def _fold_event(self, nid: str, e: dict):
        """Merge one node's journal entry into the fleet timeline.  The
        fetch cursor (per pulled-from peer) bounds the next pull; the
        merge cursor (per the event's own emitter stamp) makes re-pulls
        and shared-journal duplicates idempotent."""
        seq = int(e.get("seq", 0))
        if seq > self._cursor.get(nid, 0):
            self._cursor[nid] = seq
        emitter = e.get("node") or nid
        if seq <= self._merge_cursor.get(emitter, 0):
            return
        self._merge_cursor[emitter] = seq
        merged = dict(e)
        merged["node"] = emitter
        self._timeline.append(merged)

    # -- surfaces ----------------------------------------------------------

    def snapshot(self) -> dict:
        """GET /debug/cluster: per-node summaries (staleness-stamped),
        the merged fleet timeline (wall-ordered, newest last), and the
        coordinator-local overlay/balancer state."""
        cluster = self.cluster
        now = time.monotonic()
        with self._lock:
            nodes = {}
            for n in cluster.nodes:
                entry = self._peers.get(n.id)
                info = {"state": n.state, "host": n.host,
                        "qps": round(self._qps.get(n.id, 0.0), 2)}
                if entry is None or entry["summary"] is None:
                    info["stale"] = True
                    if entry is not None and entry.get("error"):
                        info["error"] = entry["error"]
                else:
                    info.update(entry["summary"])
                    stale = entry["stale"] or n.state != "READY"
                    info["stale"] = stale
                    if entry["mono"] is not None:
                        info["staleS"] = round(now - entry["mono"], 3)
                    if entry.get("error"):
                        info["error"] = entry["error"]
                nodes[n.id] = info
            # fleet-wide per-tenant rollup: qps/shed/hedge/quota summed
            # across nodes, p99 as the worst node's (a tenant's tail is
            # wherever it is slowest)
            fleet_tenants: dict[str, dict] = {}
            for info in nodes.values():
                for name, row in (info.get("tenants") or {}).items():
                    agg = fleet_tenants.setdefault(name, {
                        "qps": 0.0, "p99Ms": None, "shed": 0,
                        "hedgeDenied": 0, "quotaEvicts": 0})
                    agg["qps"] = round(agg["qps"] + row["qps"], 3)
                    agg["shed"] += row["shed"]
                    agg["hedgeDenied"] += row["hedgeDenied"]
                    agg["quotaEvicts"] += row["quotaEvicts"]
                    if row.get("p99Ms") is not None:
                        agg["p99Ms"] = max(agg["p99Ms"] or 0.0,
                                           row["p99Ms"])
            timeline = sorted(self._timeline,
                              key=lambda e: (e.get("wall", 0),
                                             e.get("seq", 0)))
            out = {
                "wall": _wall_stamp(),
                "ttlS": self.TTL_S,
                "refreshes": self.refreshes,
                "fetchErrors": self.fetch_errors,
                "coordinator": cluster.nodes[0].id,
                "overlayEpoch": cluster.overlay_epoch,
                "epoch": cluster.epoch,
                "nodes": nodes,
                "tenants": fleet_tenants,
                "timeline": timeline,
            }
        out["hotShards"] = cluster.balancer.snapshot()
        return out

    def prometheus_text(self) -> str:
        """``pilosa_tpu_cluster_*`` gauges with node labels — own
        exposition appended to /metrics (the launch-ledger pattern;
        cataloged in docs/observability.md "Cluster plane")."""
        gauges = (
            ("qps", "qps"), ("p99Ms", "p99_ms"),
            ("hbmResidentBytes", "hbm_resident_bytes"),
            ("hbmCompressedBytes", "hbm_compressed_bytes"),
            ("evictions", "evictions"),
            ("retraces", "retraces"),
            ("hedges", "hedges"), ("hedgeWins", "hedge_wins"),
            ("retryWaves", "retry_waves"),
            ("partialResults", "partial_results"),
            ("quarantinedFragments", "quarantined_fragments"),
            ("ingestBacklogBytes", "ingest_backlog_bytes"),
            ("overlayEpoch", "overlay_epoch"),
            ("activeAlerts", "active_alerts"),
            ("alertsFired", "alerts_fired_total"),
        )
        snap = self.snapshot()
        lines = []
        for field, metric in gauges:
            name = f"pilosa_tpu_cluster_{metric}"
            lines.append(f"# TYPE {name} gauge")
            for nid, info in sorted(snap["nodes"].items()):
                val = info.get("qps") if field == "qps" \
                    else info.get(field)
                if val is None:
                    continue
                lines.append(f'{name}{{node="{nid}"}} {val}')
        lines.append("# TYPE pilosa_tpu_cluster_stale gauge")
        for nid, info in sorted(snap["nodes"].items()):
            lines.append(f'pilosa_tpu_cluster_stale{{node="{nid}"}} '
                         f'{1 if info.get("stale") else 0}')
        return "\n".join(lines) + "\n"
