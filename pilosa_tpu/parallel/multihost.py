"""Multi-host execution: one engine spanning hosts over DCN.

Two composition modes cover the reference's multi-node story (SURVEY §5.8
— its HTTP+protobuf data plane and gossip membership):

1. **Cluster of single-host nodes** (parallel/cluster.py): each process
   owns a shard subset on its local devices; node-to-node traffic is the
   HTTP control plane.  This replaces the reference's NCCL-free
   scatter/gather star and is the default deployment.

2. **One multi-host mesh node**: all hosts join a single jax distributed
   runtime; the MeshExecutor's mesh spans every host's devices, and
   cross-shard reductions (psum) ride ICI within a slice and DCN across
   slices — XLA inserts and schedules the collectives.  A pilosa-tpu
   Server on the coordinator process then serves queries whose shard axis
   covers the global device set.  Use when one index's working set
   exceeds a host's HBM but the query rate does not require independent
   replicas.

This module wires mode 2: ``init_distributed`` brings up the jax
distributed runtime (the DCN rendezvous the reference's memberlist gossip
played for membership), and ``global_mesh`` builds the shard-axis mesh
over all processes' devices for ``Executor(mesh=...)``.

The driver-facing proof for this path is ``__graft_entry__.
dryrun_multichip``, which compiles and runs the full distributed query set
over an N-device mesh.
"""

from __future__ import annotations



def init_distributed(coordinator: str, num_processes: int,
                     process_id: int):
    """Join the jax distributed runtime (jax.distributed.initialize).

    ``coordinator``: "host:port" of process 0.  Must run before any
    device use in the process.  After it returns, ``jax.devices()`` spans
    every host and collectives cross DCN transparently."""
    import jax

    if num_processes < 1:
        raise ValueError("num_processes must be >= 1")
    if not 0 <= process_id < num_processes:
        raise ValueError(
            f"process_id {process_id} out of range [0, {num_processes})")
    jax.distributed.initialize(coordinator_address=coordinator,
                               num_processes=num_processes,
                               process_id=process_id)


def global_mesh():
    """A 1-d shard-axis Mesh over ALL processes' devices (the mesh the
    reference's cluster-wide shard ring corresponds to).  Pass to
    ``Executor(mesh=...)`` / ``MeshExecutor(mesh)``."""
    from .mesh_exec import default_mesh

    # jax.devices() is already global in a distributed runtime
    return default_mesh()


def process_shard_slice(n_shards: int) -> tuple[int, int]:
    """The contiguous shard range this process would own under an even
    split — the per-host partition for ``import_process_slice``."""
    import jax

    n = jax.process_count()
    i = jax.process_index()
    per = (n_shards + n - 1) // n
    return min(i * per, n_shards), min((i + 1) * per, n_shards)


def import_process_slice(field, rows, cols, n_shards: int,
                         max_row_id: int) -> tuple[int, int]:
    """Per-host import pipeline for multihost mode 2: this process keeps
    only ITS shard slice's bits host-side (the rest of the global array
    is supplied by the other processes' addressable device shards at
    staging time), while every process creates shape-matched empty
    fragments for remote shards so the stacked mesh groups — and thus
    the compiled SPMD executables — are identical on all processes.

    ``max_row_id``: the GLOBAL maximum row id across all hosts (row
    capacity grows in powers of two and is part of the executable's
    shape signature, so it must agree everywhere).  Returns the local
    (lo, hi) shard range."""
    import numpy as np

    from ..core import SHARD_WIDTH, VIEW_STANDARD

    rows = np.asarray(rows, dtype=np.int64)
    cols = np.asarray(cols, dtype=np.int64)
    lo, hi = process_shard_slice(n_shards)
    sel = (cols >= lo * SHARD_WIDTH) & (cols < hi * SHARD_WIDTH)
    field.import_bits(rows[sel], cols[sel])
    view = field._create_view_if_not_exists(VIEW_STANDARD)
    for s in range(n_shards):
        fr = view.create_fragment_if_not_exists(s)
        if fr.n_rows <= max_row_id:
            fr.set_row(max_row_id, None)  # grow capacity, no bits
    return lo, hi
