"""Hot-shard rebalancing (docs/cluster.md "Read routing & rebalancing";
ROADMAP item 5b).

Static jump-hash placement cannot react to load: a shard that turns hot
stays pinned to its ``replica_n`` owners forever.  This module detects
sustained per-shard load skew from the read fan-out's dispatch counters
(:class:`ShardLoadTracker`) and executes BOUNDED shard handoffs: the
coordinator tells an underloaded node to copy the hot shard's fragments
(reusing the existing resize-fetch machinery — the same
``/internal/fragment/data`` checkpoint copy a membership resize uses)
and then records the node as an EXTRA owner in the cluster's
placement-overlay table, broadcast epoch-gated like resize-complete so
every node routes — and fans writes out — consistently.

Overlay owners are real owners: writes fan to them, anti-entropy keeps
them converged, and the holder cleaner spares their fragments.  The
overlay therefore only ever widens a shard's replica set (hot-spot
splitting), never moves data away from a jump-hash owner — removing the
overlay (or running with ``balancer=off``, the default) restores the
static placement exactly.

The balancer thread runs on the COORDINATOR only (it owns the overlay
epoch and the broadcast, like resize); every node still tracks load so
/debug/vars shows per-shard heat anywhere.
"""

from __future__ import annotations

import time

from ..utils.locks import make_lock

# Floor on the per-window dispatch count before a shard can be "hot":
# skew over a handful of queries is noise, not load.
HOT_MIN_COUNT = 32
# Handoffs executed per balancer tick — rebalancing is deliberately slow
# and bounded; a tick that moved everything at once would thundering-herd
# the fragment fetches.
MAX_HANDOFFS_PER_TICK = 1
# Fragment copies ride one cluster-message POST, like resize fetches.
FETCH_TIMEOUT_S = 600.0


class ShardLoadTracker:
    """Windowed per-shard dispatch counters.

    Two rotating windows (current + previous): rates are computed over
    the PREVIOUS (complete) window so a half-filled current window never
    reads as a load drop.  Values are per-serving-node counters, so the
    same table answers both "which shard is hot" and "did more than one
    node serve it" (the replica-spread signal the routing tests
    assert)."""

    def __init__(self, window_s: float = 30.0):
        self.window_s = window_s
        self._lock = make_lock("shard-load")
        self._cur: dict[tuple[str, int], dict[str, int]] = {}
        self._prev: dict[tuple[str, int], dict[str, int]] = {}
        self._cur_start = time.monotonic()

    def _rotate_locked(self, now: float):
        if now - self._cur_start >= self.window_s:
            self._prev = self._cur
            self._cur = {}
            self._cur_start = now

    def note(self, index: str, shards, nid: str):
        """``nid`` was dispatched a read covering ``shards``."""
        now = time.monotonic()
        with self._lock:
            self._rotate_locked(now)
            for s in shards:
                by_node = self._cur.setdefault((index, int(s)), {})
                by_node[nid] = by_node.get(nid, 0) + 1

    def maybe_rotate(self):
        """Age the windows on the clock even when no traffic is noting
        dispatches: without this, counts from a past burst would keep a
        shard 'hot' forever on an idle cluster and the balancer would
        hand it off again every tick until every node owned it."""
        with self._lock:
            self._rotate_locked(time.monotonic())

    def rotate(self):
        """Force a window rotation (tests, so a decision never waits
        out a whole wall-clock window)."""
        with self._lock:
            self._prev = self._cur
            self._cur = {}
            self._cur_start = time.monotonic()

    def _counts_locked(self) -> dict[tuple[str, int], int]:
        out: dict[tuple[str, int], int] = {}
        for table in (self._prev, self._cur):
            for key, by_node in table.items():
                out[key] = out.get(key, 0) + sum(by_node.values())
        return out

    def node_counts(self) -> dict[str, int]:
        """Dispatches per serving node over both windows (the balancer's
        least-loaded-target signal)."""
        with self._lock:
            out: dict[str, int] = {}
            for table in (self._prev, self._cur):
                for by_node in table.values():
                    for nid, c in by_node.items():
                        out[nid] = out.get(nid, 0) + c
            return out

    def hot_shards(self, threshold: float,
                   min_count: int = HOT_MIN_COUNT
                   ) -> list[tuple[str, int, int]]:
        """(index, shard, count) for shards whose dispatch count over the
        tracked windows exceeds ``threshold`` x the mean across all
        active shards (and the absolute ``min_count`` floor), hottest
        first."""
        with self._lock:
            counts = self._counts_locked()
        if not counts:
            return []
        mean = sum(counts.values()) / len(counts)
        hot = [(idx, s, c) for (idx, s), c in counts.items()
               if c >= min_count and c >= threshold * mean]
        hot.sort(key=lambda t: -t[2])
        return hot

    def snapshot(self, top: int = 10) -> dict:
        """Hottest shards with their per-node serve split, for
        /debug/vars."""
        with self._lock:
            counts = self._counts_locked()
            merged: dict[tuple[str, int], dict[str, int]] = {}
            for table in (self._prev, self._cur):
                for key, by_node in table.items():
                    tgt = merged.setdefault(key, {})
                    for nid, c in by_node.items():
                        tgt[nid] = tgt.get(nid, 0) + c
        ranked = sorted(counts.items(), key=lambda kv: -kv[1])[:top]
        return {
            "windowS": self.window_s,
            "trackedShards": len(counts),
            "hottest": [{"index": idx, "shard": s, "count": c,
                         "nodes": merged.get((idx, s), {})}
                        for (idx, s), c in ranked],
        }


class HotShardBalancer:
    """Coordinator-side handoff engine over a :class:`ShardLoadTracker`.

    ``tick()`` is the whole algorithm — the background thread (started by
    ``Cluster.open`` when ``balancer=true``) just calls it on the
    ``balancer-interval`` cadence; tests call it directly."""

    def __init__(self, cluster, tracker: ShardLoadTracker,
                 threshold: float = 4.0, stats=None, logger=None,
                 min_count: int = HOT_MIN_COUNT):
        self.cluster = cluster
        self.tracker = tracker
        self.threshold = threshold
        self.min_count = min_count
        self.stats = stats
        self.logger = logger
        self.handoffs = 0
        self.errors = 0
        self.last_error: str | None = None

    def tick(self) -> int:
        """One balancing pass: find hot shards, widen the hottest one's
        replica set by one underloaded node.  Returns handoffs executed.
        Never raises — a failed handoff counts ``balancer.errors`` and
        the next tick retries."""
        cluster = self.cluster
        if not cluster.is_coordinator or cluster.state == "RESIZING":
            return 0
        # age the load windows by wall clock FIRST: an idle cluster's
        # stale burst counts must not read as sustained heat
        self.tracker.maybe_rotate()
        done = 0
        for index, shard, count in self.tracker.hot_shards(
                self.threshold, self.min_count):
            if done >= MAX_HANDOFFS_PER_TICK:
                break
            target = self._pick_target(index, shard)
            if target is None:
                continue
            try:
                self._handoff(index, shard, target)
            except Exception as e:
                self.errors += 1
                self.last_error = f"{index}/{shard} -> {target}: {e}"
                if self.stats is not None:
                    self.stats.count("balancer.errors")
                if self.logger is not None:
                    self.logger.error(
                        f"balancer handoff failed: {self.last_error}")
                continue
            done += 1
            self.handoffs += 1
            if self.stats is not None:
                self.stats.count("balancer.handoffs")
            if self.logger is not None:
                self.logger.info(
                    f"balancer: shard {index}/{shard} (count {count}) "
                    f"handed off to {target} "
                    f"(overlay epoch {cluster.overlay_epoch})")
        return done

    def _pick_target(self, index: str, shard: int) -> str | None:
        """Least-loaded READY node that is not already an owner."""
        cluster = self.cluster
        owners = set(cluster.shard_owner_nodes(index, shard))
        loads = self.tracker.node_counts()
        best, best_load = None, None
        for n in cluster.nodes:
            if n.id in owners or n.state != "READY":
                continue
            load = loads.get(n.id, 0)
            if best_load is None or load < best_load:
                best, best_load = n.id, load
        return best

    def _handoff(self, index: str, shard: int, target: str):
        """Copy the shard to ``target`` (resize-fetch reuse: full
        checkpoint fragment copies from a current owner), then publish it
        as an overlay owner.  The copy lands BEFORE the overlay broadcast
        so no node ever routes a read at a replica that lacks the data;
        a crash in between leaves an unused copy the holder cleaner
        GCs — never a data-less owner."""
        cluster = self.cluster
        owners = cluster.shard_owner_nodes(index, shard)
        sources = [o for o in owners
                   if o == cluster.node_id
                   or cluster.by_id[o].state == "READY"]
        if not sources:
            raise RuntimeError("no live source replica")
        src_host = cluster.by_id[sources[0]].host
        fetch_msg = {
            "type": "resize-fetch",
            "fetch": [{"index": index, "shard": shard,
                       "source": src_host}],
            "schema": cluster.holder.schema(),
        }
        if target == cluster.node_id:
            cluster.handle_message(fetch_msg)
        else:
            cluster.client.send_message(cluster.by_id[target].host,
                                        fetch_msg,
                                        timeout=FETCH_TIMEOUT_S)
        cluster.add_overlay(index, shard, target)

    def snapshot(self) -> dict:
        return {
            "handoffs": self.handoffs,
            "errors": self.errors,
            "lastError": self.last_error,
            "threshold": self.threshold,
            "load": self.tracker.snapshot(),
        }
