"""Whole-query pjit programs: ONE XLA computation per PQL request.

PAPER.md's stated design is that "PQL calls (Intersect/Union/TopN/
GroupBy/Count) compile to a single XLA computation per request" — the
pjit/PartitionSpec pattern of SNIPPETS.md [1][3].  The legacy executor
dispatches one shard_map executable per reducer stage per shape group,
with a Python hop between every PQL stage; this module compiles the
ENTIRE parsed request — every call, every shape group, the PR 7
container decode, and the cross-shard reductions — into one jitted
program over global mesh-sharded arrays (docs/whole-query.md).

Mechanics: the executor lowers a read query to a tuple of
``plan.ReduceNode``s (Count popcount-sums, TopN/Rows row-count
accumulations, BSI slice counts, Min/Max extremum scans, GroupBy combo
grids, raw segments) plus one params matrix per node.  ``run`` stacks
the request's fragment inputs with the SAME residency machinery the
legacy path uses — ``MeshExecutor._placed_groups`` with its stack
cache, device-budget accounting, compressed staging, and ingest
overlays all compose unchanged — and places them sharded over the
named ``shards`` mesh axis (``PartitionSpec(SHARD_AXIS)``); params ride
replicated (``P()``).  The whole program is ONE ``shard_map`` over
that axis: the body decodes compressed stacks once per shape group,
evaluates every node's per-shard contribution in one vmapped pass over
the device-local block, and reduces IN PROGRAM — local sums +
``lax.psum`` over the shard axis replace the per-shard ``segments()``
the legacy path assembled host-side.  (Manual partitioning on purpose:
auto-partitioned jit replicates the vmapped row-gathers — a 4096-wide
Count batch allocated a 279 GB gather temp — while shard_map pins the
per-device shapes the ``BATCH_TEMP_BYTES`` chunk budget assumes.)  One
launch per request — the launch ledger (utils/devobs.py) records it as
kind ``wholequery``.

Shapes the program cannot express raise ``WholeQueryUnsupported`` and
the executor reroutes to the legacy per-stage dispatch, counting
``wholequery.fallback`` (docs/whole-query.md has the fallback matrix):
multi-process meshes (per-process staging must stay deterministic),
over-budget working sets (the streaming slice planner owns those),
params batches beyond one dispatch chunk, and GroupBy grids beyond one
combo chunk.

Batching (docs/batching.md): concurrent requests whose programs share a
shape fuse in the dispatch batcher by concatenating each node's params
matrix along the batch axis — the batched parameter axis rides the same
compiled program, so the PR 4 fused-launch economics carry over
unchanged.
"""

from __future__ import annotations

import time as _time

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import NamedSharding, PartitionSpec as P

from ..core import CONTAINER_WORDS, SHARD_WORDS
from ..executor.plan import eval_plan, plan_inputs
from ..ops import bsi
from ..utils import devobs as _devobs
from ..utils import profile as qprof
from ..utils.deadline import check_current
from ..utils.faults import FAULTS
from .mesh_exec import _DISPATCH_LOCK, _SM_CHECK_KW, _flatten_present, \
    _shard_map, _sig_rows, _unpack_frags, SHARD_AXIS


class WholeQueryUnsupported(Exception):
    """A request (or runtime shape) the whole-query program cannot
    express.  The executor counts ``wholequery.fallback``, emits a
    structured log event naming the unsupported node, and reroutes to
    the legacy per-stage dispatch — never a silent slow path."""

    def __init__(self, node: str, detail: str = ""):
        super().__init__(f"{node}: {detail}" if detail else node)
        self.node = node
        self.detail = detail


# Node kinds that carry a genuine batch axis: programs made only of
# these can fuse across concurrent requests in the dispatch batcher
# (params concatenate along B).  bsi_minmax has no batch axis and
# group_counts' leading axis is the combo grid, so programs containing
# them launch un-fused.
_BATCH_KINDS = frozenset({"count", "segments", "row_counts", "bsi_sum"})


def node_keys(node, mesh) -> list[tuple[str, str]]:
    """Deterministic (field, view) key list one reducer node reads."""
    if node.kind in ("count", "segments"):
        return plan_inputs(node.plan)
    if node.kind == "group_counts":
        keys = [node.primary]
        for k in node.extra[:-1]:
            if k not in keys:
                keys.append(k)
        for k in (plan_inputs(node.plan) if node.plan is not None else []):
            if k not in keys:
                keys.append(k)
        return keys
    return mesh.batch_keys(node.primary, node.plan)


def program_keys(program, mesh) -> list[tuple[str, str]]:
    """Union of every node's keys, order-deterministic — the single
    stacked key list the whole request stages (and the shard schedule
    prefetches) once."""
    out: list[tuple[str, str]] = []
    for node in program:
        for k in node_keys(node, mesh):
            if k not in out:
                out.append(k)
    return out


def pad_pow2_rows(mat: np.ndarray, repeat: bool = True) -> np.ndarray:
    """Pad a params matrix's row count up to a power of two so arbitrary
    batch sizes reuse a bounded set of compiled programs (the batcher's
    convention).  ``repeat`` duplicates the last row (always in-range);
    otherwise zero rows (GroupBy combo grids, matching the legacy
    chunk padding)."""
    B = mat.shape[0]
    pad = 1 << max(0, B - 1).bit_length()
    if pad == B:
        return mat
    if repeat:
        return np.concatenate([mat, np.repeat(mat[-1:], pad - B, axis=0)])
    return np.concatenate(
        [mat, np.zeros((pad - B,) + mat.shape[1:], mat.dtype)])


def _mat_rows(mat) -> int:
    return mat[0].shape[0] if isinstance(mat, tuple) else mat.shape[0]


class WholeOut:
    """One whole-query launch's unfetched device outputs.

    ``parts[i]`` is node i's device arrays (unfetched, so the executor
    keeps its dispatch-all-then-fetch-once pipeline); ``meta[i]``
    carries the host-assembly facts the finalizers need (per-group
    shard lists, fragment-less shards, actual batch rows)."""

    __slots__ = ("parts", "meta", "sig", "compiled")

    def __init__(self, parts, meta, sig: str | None = None,
                 compiled: bool = False):
        self.parts = parts
        self.meta = meta
        # compiled program signature (devobs.sig_of of the executable
        # cache key — the SAME id the compile registry and launch ledger
        # record), surfaced on the request thread for the EXPLAIN plan
        # section; None for the no-live-groups empty launch
        self.sig = sig
        # True when THIS launch traced+compiled (a cold program); the
        # EXPLAIN plan section surfaces it as plan: warm|cold so a
        # post-deploy compile is visible per request (docs/warmup.md)
        self.compiled = compiled

    def slice_batch(self, program, node_lo: list[int], node_b: list[int]):
        """A fused launch's per-ticket view: slice every node's batch
        axis back out (batch-kind nodes only — fusibility is checked
        before tickets coalesce)."""
        parts, meta = [], []
        for ni, node in enumerate(program):
            lo, b = node_lo[ni], node_b[ni]
            m = dict(self.meta[ni])
            m["B"] = b
            if node.kind == "segments":
                parts.append([arr[:, lo:lo + b] for arr in self.parts[ni]])
            else:
                parts.append([arr[lo:lo + b] for arr in self.parts[ni]])
            meta.append(m)
        return WholeOut(parts, meta, self.sig, self.compiled)


class _InstrumentedWhole:
    """One compiled whole-query program plus its device-runtime
    telemetry — the wholequery analog of mesh_exec._InstrumentedExec:
    the traced body marks the compile registry (exact retrace
    detection), and every invocation lands in the launch ledger with
    the call site's actual-vs-padded shard and batch rows."""

    __slots__ = ("fn", "sig", "detail", "out_index")

    def __init__(self, fn, key, out_index):
        self.fn = fn
        self.sig = _devobs.sig_of(key)
        self.detail = repr(key[1])[:120]
        self.out_index = out_index

    def __call__(self, mats, *flat, _launch_meta=None):
        reg = _devobs.COMPILES
        reg.begin_call()
        t0 = _time.perf_counter()
        out = self.fn(mats, *flat)
        dt = _time.perf_counter() - t0
        compiled = reg.traced()
        if compiled:  # fingerprinting is only paid on compiles
            leaves = jax.tree_util.tree_leaves(mats)
            reg.note_call(self.sig, "wholequery", dt,
                          _devobs.fingerprint(list(leaves) + list(flat)),
                          detail=self.detail)
        m = _launch_meta or {}
        ctx = _devobs.launch_ctx() or {}
        rows = ctx.get("rows")
        if rows is None:
            rows = m.get("rows", 1)
        _devobs.LEDGER.record(
            sig=self.sig, kind="wholequery",
            shards=m.get("shards", 0),
            shards_padded=m.get("shards_padded", 0),
            batch_rows=rows, batch_rows_padded=m.get("rows_padded", 1),
            queue_s=ctx.get("queue_s", 0.0),
            tickets=ctx.get("tickets", 1),
            dispatch_s=dt, compiled=compiled,
            decode_bytes=m.get("decode_bytes", 0),
            slice_pos=_devobs.current_slice(),
            kernel_launches=m.get("kernel_launches", 0),
            kernel_tiles=m.get("kernel_tiles", 0))
        prof = qprof.current()
        if prof is not None:
            # rows/padding/decode tags feed the EXPLAIN launches section
            # (utils/explain.py), mirroring the ledger entry
            prof.event("device.launch", dt, kind="wholequery",
                       sig=self.sig, shards=m.get("shards", 0),
                       shardsPadded=m.get("shards_padded", 0),
                       batchRows=rows,
                       batchRowsPadded=m.get("rows_padded", 1),
                       decodeBytes=m.get("decode_bytes", 0),
                       compiled=compiled)
        return out


def _node_shard(node, mat, frags):
    """One reducer node's per-shard contribution, traced inside the
    vmapped per-shard pass (decode has already produced dense tiles in
    ``frags``).  Shapes mirror the legacy per-stage executables exactly
    — including int32 accumulation — so results stay byte-identical."""
    if node.kind in ("count", "segments"):
        segs = jax.vmap(lambda p: eval_plan(node.plan, frags, p))(mat)
        if node.kind == "segments":
            return segs                                    # [B, W]
        return jnp.sum(
            jax.lax.population_count(segs).astype(jnp.int32),
            axis=-1)                                       # [B]
    frag = frags[node.primary]
    if node.kind == "row_counts":
        if node.plan is None:
            counts = jnp.sum(
                jax.lax.population_count(frag).astype(jnp.int32), axis=-1)
            return jnp.broadcast_to(counts,
                                    (mat.shape[0],) + counts.shape)
        masks = jax.vmap(lambda p: eval_plan(node.plan, frags, p))(mat)
        masked = frag[None, :, :] & masks[:, None, :]
        return jnp.sum(
            jax.lax.population_count(masked).astype(jnp.int32),
            axis=-1)                                       # [B, rows]
    if node.kind == "bsi_sum":
        if node.plan is None:
            counts = bsi.sum_counts(frag, None)
            return jnp.broadcast_to(counts,
                                    (mat.shape[0],) + counts.shape)
        return jax.vmap(
            lambda p: bsi.sum_counts(frag, eval_plan(node.plan, frags,
                                                     p)))(
            mat)                                           # [B, 2, d+1]
    if node.kind == "bsi_minmax":
        filt = None
        if node.plan is not None:
            filt = eval_plan(node.plan, frags, mat[0])
        return bsi.min_max_bits(frag, filt,
                                want_max=node.extra[0] == "max")
    # group_counts: combos ride the leading axis of mat[0]
    rids, params = mat
    pk_list = node.extra[:-1]
    fseg = eval_plan(node.plan, frags, params) \
        if node.plan is not None else None

    def one_combo(rids_row):
        mask = None
        for j, pk in enumerate(pk_list):
            pfrag = frags[pk]
            rid = rids_row[j]
            if pfrag.shape[0] == 0:
                seg = jnp.zeros(pfrag.shape[-1], dtype=pfrag.dtype)
            else:
                seg = jnp.where(
                    rid < pfrag.shape[0],
                    jax.lax.dynamic_index_in_dim(
                        pfrag, jnp.minimum(rid, pfrag.shape[0] - 1),
                        axis=0, keepdims=False),
                    jnp.zeros_like(pfrag[0]))
            mask = seg if mask is None else mask & seg
        if fseg is not None:
            mask = fseg if mask is None else mask & fseg
        masked = frag if mask is None else frag & mask[None, :]
        return jnp.sum(
            jax.lax.population_count(masked).astype(jnp.int32),
            axis=-1)                                       # [rows]

    return jax.vmap(one_combo)(rids)                       # [C, rows]


class WholeQueryRunner:
    """Compiles + launches whole-query programs over a MeshExecutor's
    mesh, reusing its stacked-input staging (stack cache, device
    budget, compressed residency, ingest overlays) and executable
    cache verbatim."""

    def __init__(self, mesh):
        self.mesh = mesh

    # -- shape probes ------------------------------------------------------

    def program_keys(self, program):
        return program_keys(program, self.mesh)

    def fusible(self, program) -> bool:
        return all(n.kind in _BATCH_KINDS for n in program)

    def precheck(self, program, holder, index, shards):
        """Raise WholeQueryUnsupported for shapes the single-program
        path cannot take; returns the program's stacked key list."""
        mesh = self.mesh
        if mesh.multiprocess:
            raise WholeQueryUnsupported(
                "multiprocess-mesh",
                "per-process staging must stay deterministic")
        keys = self.program_keys(program)
        if keys and shards:
            sched = mesh.shard_schedule(holder, index, [keys], shards)
            if len(sched.slices) > 1:
                raise WholeQueryUnsupported(
                    "streamed-working-set",
                    f"{len(sched.slices)} shard slices")
        return keys

    @staticmethod
    def _participates(node, sig_map) -> bool:
        """Whether a shape group contributes to a node (mirrors the
        legacy per-stage skip conditions exactly)."""
        if node.kind in ("count", "segments"):
            return True
        s0 = sig_map.get(node.primary)
        if s0 is None:
            return False
        if node.kind in ("bsi_sum", "bsi_minmax") and \
                _sig_rows(s0) < bsi.OFFSET_ROW + 1:
            return False
        if node.kind == "group_counts":
            return all(sig_map.get(pk) is not None
                       for pk in node.extra[:-1])
        return True

    # -- execution ---------------------------------------------------------

    def run(self, program, mats, holder, index, shards) -> WholeOut:
        """Stage the request's inputs and launch the whole program as
        one device computation.  ``mats`` is one int32 params matrix
        per node ([B, P]; group_counts nodes carry (rids[C, Pk],
        params[Pf])).  Returns unfetched device parts per node."""
        mesh = self.mesh
        keys = self.precheck(program, holder, index, shards)
        FAULTS.hit("mesh.slice", key=index)
        check_current("whole-query dispatch")
        groups = mesh._placed_groups(keys, holder, index, list(shards)) \
            if keys and shards else []

        live = []           # (shard_list, sig_map, flat, layout, pk, ps)
        empty_shards: list[int] = []
        for shard_list, placed, sig in groups:
            if all(s is None for s in sig):
                empty_shards.extend(shard_list)
                continue
            present = mesh._present(keys, placed, sig)
            flat_g, layout_g = _flatten_present(present)
            live.append((shard_list, dict(zip(keys, sig)), flat_g,
                         layout_g, tuple(k for k, _, _ in present),
                         tuple(s for _, _, s in present)))

        pad_mats = []
        actual_b = []
        for node, mat in zip(program, mats):
            if node.kind == "group_counts":
                rids, params = mat
                actual_b.append(rids.shape[0])
                pad_mats.append((pad_pow2_rows(
                    np.asarray(rids, dtype=np.int32), repeat=False),
                    np.asarray(params, dtype=np.int32)))
            else:
                m = np.ascontiguousarray(mat, dtype=np.int32)
                actual_b.append(m.shape[0])
                pad_mats.append(pad_pow2_rows(m))
        pad_mats = tuple(pad_mats)

        # per-node schedule: which live groups contribute (static)
        sched = tuple(
            tuple(gi for gi, g in enumerate(live)
                  if self._participates(node, g[1]))
            for node in program)
        meta = self._node_meta(program, actual_b, live, sched,
                               empty_shards)
        if not live:
            return WholeOut([[] for _ in program], meta)  # no launch

        # The shard-bucket (stacked leading dim) is deliberately NOT in
        # the key: like every mesh executable, a bucket change re-traces
        # the cached program — the compile registry's retrace red flag
        # (PR 8 convention; everything the body reads is frozen static
        # structure, so the re-trace is correct by construction).
        buckets = tuple(g[2][0].shape[0] for g in live)
        key = ("wholequery", repr(program),
               tuple((g[4], g[5]) for g in live),
               tuple(jax.tree_util.tree_map(lambda a: a.shape,
                                            pad_mats)),
               mesh._exec_seq)
        with mesh._lock:
            fn = mesh._cache.get(key)
            if fn is None:
                fn = self._compile(key, program, live, sched, pad_mats)
                mesh._cache[key] = fn

        flat_all = [a for g in live for a in g[2]]
        from ..ops import kernels as _kernels
        decode_bytes = sum(
            bucket * sum(s[1] * SHARD_WORDS * 4
                         for _, n, s in g[3]
                         if n > 1 and _kernels.sig_backend(s) != "pallas")
            for bucket, g in zip(buckets, live))
        kernel_launches = sum(
            bucket * sum(1 for _, n, s in g[3]
                         if n > 1 and _kernels.sig_backend(s) == "pallas")
            for bucket, g in zip(buckets, live))
        kernel_tiles = sum(
            bucket * sum(s[1] * (SHARD_WORDS // CONTAINER_WORDS)
                         for _, n, s in g[3]
                         if n > 1 and _kernels.sig_backend(s) == "pallas")
            for bucket, g in zip(buckets, live))
        launch_meta = {
            "shards": sum(len(g[0]) for g in live),
            "shards_padded": sum(buckets),
            "rows": sum(actual_b),
            "rows_padded": sum(_mat_rows(m) for m in pad_mats),
            "decode_bytes": decode_bytes,
            "kernel_launches": kernel_launches,
            "kernel_tiles": kernel_tiles,
        }
        sharding = NamedSharding(mesh.mesh, P())
        mats_dev = jax.device_put(pad_mats, sharding)
        with _DISPATCH_LOCK:
            flat_out = fn(mats_dev, *flat_all, _launch_meta=launch_meta)
        parts = [[flat_out[j] for j in idxs] for idxs in fn.out_index]
        # tracing is synchronous on this thread (CompileRegistry's
        # thread-local protocol), so the flag read here is exactly
        # whether THIS launch compiled — even when run() executes on the
        # batcher's dispatcher thread for a fused launch
        return WholeOut(parts, meta, fn.sig, _devobs.COMPILES.traced())

    def _node_meta(self, program, actual_b, live, sched, empty_shards):
        meta = []
        for ni, node in enumerate(program):
            m = {"B": actual_b[ni]}
            if node.kind == "segments":
                m["groups"] = [live[gi][0] for gi in sched[ni]]
                m["empty"] = list(empty_shards)
            elif node.kind == "bsi_minmax":
                m["groups"] = [live[gi][0] for gi in sched[ni]]
            meta.append(m)
        return meta

    # -- compilation -------------------------------------------------------

    def _compile(self, key, program, live, sched, pad_mats):
        """Build + jit the program body.  Everything consulted inside
        the traced body is frozen static structure (program nodes,
        layouts, participation schedule, combine shapes) — the body
        takes only (mats, *stacked arrays)."""
        groups_static = tuple((g[3], len(g[2])) for g in live)
        sig_maps = tuple(g[1] for g in live)

        # per-node static combine targets (max rows / max BSI depth);
        # single-assignment so the traced body's closure cell can never
        # change under a re-trace (the PR 7 bug class)
        def _combine_info(ni, node):
            if node.kind in ("row_counts", "group_counts"):
                return {"rows": max(
                    (_sig_rows(sig_maps[gi][node.primary])
                     for gi in sched[ni]), default=0)}
            if node.kind == "bsi_sum":
                return {"depth": max(
                    (_sig_rows(sig_maps[gi][node.primary])
                     - bsi.OFFSET_ROW for gi in sched[ni]), default=0)}
            return {}

        combine = tuple(_combine_info(ni, node)
                        for ni, node in enumerate(program))

        def body(mats, *flat):
            # Inside shard_map: ``flat`` are the per-device LOCAL blocks
            # of the stacked arrays ([S_local, ...]); mats are
            # replicated.  Reductions sum locally and psum over the
            # named shard axis — the in-program collective that replaces
            # the legacy host-assembled per-shard reductions.
            per_group_raw: list[dict] = [dict() for _ in groups_static]
            i = 0
            for gi, (layout_g, n_g) in enumerate(groups_static):
                arrs = flat[i:i + n_g]
                i += n_g
                node_ids = tuple(
                    ni for ni in range(len(program)) if gi in sched[ni])
                if not node_ids:
                    continue

                def per_shard(*arrays, _layout=layout_g,
                              _nis=node_ids):
                    frags = _unpack_frags(_layout, arrays)
                    return tuple(
                        _node_shard(program[ni], mats[ni], frags)
                        for ni in _nis)

                outs_g = jax.vmap(per_shard)(*arrs)
                for slot, ni in enumerate(node_ids):
                    per_group_raw[gi][ni] = outs_g[slot]

            flat_outs: list = []
            for ni, node in enumerate(program):
                parts = [per_group_raw[gi][ni] for gi in sched[ni]]
                if node.kind == "segments":
                    flat_outs.extend(parts)   # [S_local, B, W] per group
                elif node.kind == "bsi_minmax":
                    for p in parts:                 # (bits, neg, cnt)
                        flat_outs.extend(p)
                elif not parts:
                    pass                            # no contributing group
                elif node.kind == "count":
                    total = parts[0].sum(axis=0)
                    for p in parts[1:]:
                        total = total + p.sum(axis=0)
                    flat_outs.append(
                        jax.lax.psum(total, axis_name=SHARD_AXIS))  # [B]
                elif node.kind == "bsi_sum":
                    D = combine[ni]["depth"]
                    B = mats[ni].shape[0]
                    acc = jnp.zeros((B, 2, D + 1), dtype=jnp.int32)
                    for p in parts:
                        s = p.sum(axis=0)           # [B, 2, d+1]
                        d = s.shape[-1] - 1
                        # magnitude counts and the trailing TOTAL column
                        # land separately: groups of different bit depth
                        # must not add a total into a magnitude slot
                        acc = acc.at[:, :, :d].add(s[:, :, :d])
                        acc = acc.at[:, :, D].add(s[:, :, d])
                    flat_outs.append(
                        jax.lax.psum(acc, axis_name=SHARD_AXIS))
                else:  # row_counts / group_counts
                    R = combine[ni]["rows"]
                    B = _mat_rows(mats[ni])
                    acc = jnp.zeros((B, R), dtype=jnp.int32)
                    for p in parts:
                        s = p.sum(axis=0)           # [B, rows_g]
                        acc = acc.at[:, :s.shape[1]].add(s)
                    flat_outs.append(
                        jax.lax.psum(acc, axis_name=SHARD_AXIS))
            return tuple(flat_outs)

        # flat-output index map + per-output PartitionSpec, computed
        # statically from the schedule (mirrors body's append order):
        # reduced outputs are replicated (psum), per-shard outputs keep
        # the shard axis
        out_index: list[list[int]] = []
        out_specs: list = []
        n_out = 0
        for ni, node in enumerate(program):
            if node.kind in ("segments", "bsi_minmax"):
                n_here = len(sched[ni]) * (3 if node.kind == "bsi_minmax"
                                           else 1)
                out_specs.extend([P(SHARD_AXIS)] * n_here)
            else:
                n_here = 1 if sched[ni] else 0
                out_specs.extend([P()] * n_here)
            out_index.append(list(range(n_out, n_out + n_here)))
            n_out += n_here

        def traced(mats, *flat):
            # runs ONLY while jax traces: an exact compile detector
            _devobs.COMPILES.mark_traced()
            return body(mats, *flat)

        n_flat_all = sum(n for _, n in groups_static)
        from ..ops import kernels as _kernels
        # shard_map's replication checker has no rule for pallas_call;
        # disable it only when a group actually decodes through the
        # Pallas backend (mesh_exec._jit_shard_map does the same)
        check = not any(
            n > 1 and _kernels.sig_backend(s) == "pallas"
            for layout_g, _ in groups_static for _, n, s in layout_g)
        fn = jax.jit(_shard_map(
            traced, mesh=self.mesh.mesh,
            in_specs=(P(),) + (P(SHARD_AXIS),) * n_flat_all,
            out_specs=tuple(out_specs),
            **{_SM_CHECK_KW: check}))
        return _InstrumentedWhole(fn, key, out_index)
