"""Cluster: static-membership distribution layer (reference cluster.go,
broadcast.go, http/client.go).

The reference runs a gossip-managed elastic cluster (memberlist, resize
jobs).  Per the TPU-native design (SURVEY §5.8) membership here is a
*static node list from config* — the mesh analog of a fixed TPU topology —
with a thin control plane over HTTP:

* shard -> node placement: FNV-1a partition + jump hash ring with ReplicaN
  successors (parallel/placement.py; cluster.go:871-959);
* query fan-out: shards grouped by owner, local shards on the local
  executor, remote groups POSTed as pinned single-call requests
  (executor.go:2455 mapReduce, :2414 remoteExec), with replica retry when
  a node is down (executor.go:2482-2514);
* write fan-out: Set/Clear go to every replica of the target shard
  (executor.go:2137-2166); Store/ClearRow to every node with its owned
  shard list; attr writes broadcast (executor.go:2207-2412);
* import regroup/forward: bits grouped by shard, each batch sent to every
  owner (api.go:920-1028);
* DDL broadcast: create/delete index/field POSTed to every peer
  (broadcast.go:30 SendSync, server.go:569 receiveMessage);
* failure detection: periodic /status probes; a node that fails a probe is
  marked DOWN and the cluster goes DEGRADED (cluster.go:1724
  confirmNodeDown; NORMAL<->DEGRADED cluster.go:571-583).

Reductions between nodes happen host-side on small results (counts,
ValCounts, pairs, compressed row segments); the heavy per-shard bitmap
work stays on each node's devices (its mesh executor / XLA plans).
"""

from __future__ import annotations

import base64
import http.client
import json
import os
import threading
import time
import zlib
from concurrent.futures import FIRST_COMPLETED, ThreadPoolExecutor
from concurrent.futures import wait as futures_wait
from typing import Any

import numpy as np

from ..core import SHARD_WIDTH, SHARD_WORDS
from ..executor.executor import TOPN_EXTRAS
from ..executor.results import (
    GroupCount, FieldRow, Pair, RowIdentifiers, RowResult, ValCount,
    merge_pairs, sort_pairs,
)
from ..pql import Call, Query, parse
from ..pql.wire import call_from_wire, call_to_wire
from ..utils import degraded
from ..utils import events
from ..utils import explain as qexplain
from ..utils import profile as qprof
from ..utils import tenant as qtenant
from ..utils.deadline import DEADLINE_HEADER, current as current_ctx
from ..utils.faults import FAULTS
from ..utils.locks import make_lock, make_rlock
from ..utils.tracing import GLOBAL_TRACER, PROBE_HEADER, TRACE_HEADER
from . import qwire
from .placement import Placement

NODE_READY = "READY"
NODE_DOWN = "DOWN"
# Alive but replaying its warmup corpus (docs/warmup.md): probes fold a
# peer's advertised warming phase here, so every `state == NODE_READY`
# gate (read routing, AE, broadcast, repair donors) automatically keeps
# traffic off a cold process.  Warming is NOT counted by _update_state —
# a warming peer never flips the cluster DEGRADED.
NODE_WARMING = "WARMING"


def _wall_stamp() -> float: return time.time()  # display-only wall clock
# (anti-entropy last-error/last-success stamps shown to operators; every
# DURATION in this module still comes from perf_counter pairs — see the
# scripts/check.sh timing lint, which excludes this helper by name)

STATE_STARTING = "STARTING"
STATE_NORMAL = "NORMAL"
STATE_DEGRADED = "DEGRADED"
STATE_RESIZING = "RESIZING"


class ClusterError(RuntimeError):
    pass


class IngestBackpressure(ClusterError):
    """A forwarded ingest batch was refused 503 by the shard owner (its
    group-commit backlog is over high-water).  The coordinator maps this
    back to its own 503 + Retry-After so the producer backs off the
    whole (idempotent) stream — backpressure propagates end-to-end
    instead of queueing invisibly (docs/ingest.md)."""


class CircuitOpenError(ClusterError):
    """Fail-fast rejection: the target peer's circuit breaker is open
    (N consecutive transport failures).  A ClusterError subclass so
    callers that only know ClusterError still handle it, but DISTINCT so
    the fan-out treats it like a transport failure (exclude + replica
    retry + mark DOWN) rather than an application error from a live
    peer."""


# -- result wire codec ------------------------------------------------------
# (the reference's protobuf QueryResponse, encoding/proto/proto.go; JSON +
# compressed raw segments here)

def _seg_to_wire(seg) -> str:
    words = np.asarray(seg, dtype=np.uint32)
    return base64.b64encode(zlib.compress(words.tobytes(), 1)).decode()


def _seg_from_wire(s: str) -> np.ndarray:
    raw = zlib.decompress(base64.b64decode(s))
    words = np.frombuffer(raw, dtype=np.uint32)
    if words.size != SHARD_WORDS:
        raise ClusterError(f"bad segment size {words.size}")
    return words


def result_to_wire(r) -> dict:
    if isinstance(r, RowResult):
        out = {"t": "row", "segments": {
            str(s): _seg_to_wire(seg) for s, seg in r.segments.items()}}
        if r.attrs:
            out["attrs"] = r.attrs
        return out
    if isinstance(r, ValCount):
        return {"t": "valcount", "val": r.val, "count": r.count}
    if isinstance(r, RowIdentifiers):
        return {"t": "rowids", "rows": r.rows, "keys": r.keys}
    if isinstance(r, list) and (not r or isinstance(r[0], Pair)):
        return {"t": "pairs",
                "pairs": [[p.id, p.count, p.key] for p in r]}
    if isinstance(r, list) and r and isinstance(r[0], GroupCount):
        return {"t": "groups", "groups": [
            {"group": [[fr.field, fr.row_id, fr.row_key] for fr in g.group],
             "count": g.count} for g in r]}
    return {"t": "raw", "v": r}


def result_from_wire(d: dict):
    t = d.get("t")
    if t == "row":
        return RowResult({int(s): _seg_from_wire(w)
                          for s, w in d["segments"].items()},
                         attrs=d.get("attrs"))
    if t == "valcount":
        return ValCount(d["val"], d["count"])
    if t == "rowids":
        return RowIdentifiers(rows=d["rows"], keys=d.get("keys") or [])
    if t == "pairs":
        return [Pair(i, c, k) for i, c, k in d["pairs"]]
    if t == "groups":
        return [GroupCount([FieldRow(f, ri, rk) for f, ri, rk in g["group"]],
                           g["count"]) for g in d["groups"]]
    return d.get("v")


# -- internal RPC client ----------------------------------------------------

class _Breaker:
    """Per-peer circuit breaker state (closed -> open -> half-open)."""

    __slots__ = ("fails", "state", "opened_at", "trial_inflight",
                 "opened_total", "fast_fails", "half_open_emitted")

    def __init__(self):
        self.fails = 0
        self.state = "closed"
        self.opened_at = 0.0
        self.trial_inflight = False
        self.opened_total = 0
        self.fast_fails = 0
        # breaker.half_open journals once per OPEN episode, not once per
        # admitted trial: probes are always admitted as trials, so a
        # dead peer would otherwise emit every health interval and flood
        # the bounded event ring for the whole outage
        self.half_open_emitted = False


class InternalClient:
    """Node-to-node HTTP(S) RPC (reference http/client.go:69
    InternalClient).  Hosts may carry an ``https://`` prefix; mutual-TLS
    client credentials come from ``configure_tls``.

    Every request runs through a PER-PEER circuit breaker:
    ``breaker_threshold`` consecutive TRANSPORT failures (timeouts,
    refused/reset connections — HTTP error statuses are a live peer and
    do not count) open the circuit, and further requests fail fast with
    ``CircuitOpenError`` instead of each burning a full socket timeout
    against a dead node.  After ``breaker_cooldown`` seconds ONE trial
    request is let through (half-open); success closes the circuit,
    failure re-arms the cooldown.  ``Cluster.probe_peers`` runs on the
    health cadence and its /status probes double as the half-open
    trials, so breaker state and NODE_DOWN converge on the same answer
    (cluster.go:1724 confirmNodeDown).  ``breaker_threshold <= 0``
    disables breaking entirely."""

    # Pooled connections idle longer than this are proactively replaced:
    # servers close idle keep-alives after 120 s (handler timeout), and a
    # connection the server already FIN'd often fails only at RESPONSE
    # time — where POSTs must not retry (the peer may have executed the
    # request).  Never reusing a socket old enough to be at risk keeps
    # the narrow retry policy sound.
    POOL_IDLE_MAX = 60.0

    def __init__(self, timeout: float = 30.0, breaker_threshold: int = 5,
                 breaker_cooldown: float = 5.0, stats=None,
                 wire_mode: str = qwire.WIRE_BIN1):
        self.timeout = timeout
        self.breaker_threshold = breaker_threshold
        self.breaker_cooldown = breaker_cooldown
        self.stats = stats
        # Internal query wire preference (docs/cluster.md "Internal query
        # wire"): "bin1" speaks the PTPUQRY1 framed binary transport to
        # peers that advertise it (or whose capability is still unknown —
        # optimistic, pre-first-probe) and downgrades per-peer to the
        # verbatim JSON path on refusal; "json" restores JSON exactly.
        self.wire_mode = wire_mode
        # host -> capability learned from its /status `wire` list; absent
        # means unknown (optimistically binary).  Plain dicts mutated
        # with single GIL-atomic ops, like _host_gen below.
        self._peer_wire: dict[str, str] = {}
        # host -> True after a 415/400 refusal of a binary POST; cleared
        # when the peer's /status re-advertises bin1 (rolling-upgrade
        # recovery — a restarted peer that now speaks binary gets it
        # back within one health interval)
        self._wire_down: dict[str, bool] = {}
        self._ssl_ctx = None
        # per-thread keep-alive connections (the server speaks HTTP/1.1):
        # a cluster fan-out must not pay a TCP handshake per sub-query
        self._local = threading.local()
        # every pooled connection also registers here so close() can
        # release sockets owned by other threads' pools
        self._all_conns: set = set()
        self._conns_lock = make_lock("client-conns")
        self._breakers: dict[str, _Breaker] = {}
        self._breaker_lock = make_lock("breaker")
        # per-host pool generation (see note_recovered); conns stamp the
        # generation at creation and are lazily discarded on mismatch
        self._host_gen: dict[str, int] = {}

    def note_recovered(self, host: str):
        """A peer that was DOWN is reachable again: every pooled
        connection to it predates the outage and points at a dead (or
        restarted) process.  Reusing one is worse than useless — the
        send can land in the severed socket's kernel buffer and fail
        only at getresponse(), exactly where non-idempotent POSTs must
        NOT be retried, turning the peer's recovery into spurious write
        failures.  Bumping the host's pool generation makes every
        thread lazily discard its stale conn and dial fresh (GIL-atomic
        int bump; racing requests see either generation, both safe)."""
        self._host_gen[host] = self._host_gen.get(host, 0) + 1

    # -- internal query wire negotiation -----------------------------------

    def note_peer_wire(self, host: str, caps):
        """Fold a peer's advertised wire capability (its /status ``wire``
        list) into the negotiation state.  A peer advertising bin1 clears
        any earlier downgrade — the rolling-upgrade recovery path (a peer
        that persists in refusing binary despite advertising it just
        re-downgrades within its next RPC).  No ``wire`` key (an older
        peer) reads as JSON-only."""
        bin1 = isinstance(caps, (list, tuple)) and qwire.WIRE_BIN1 in caps
        self._peer_wire[host] = qwire.WIRE_BIN1 if bin1 else qwire.WIRE_JSON
        if bin1:
            self._wire_down.pop(host, None)

    def peer_wire_mode(self, host: str) -> str:
        """The wire this client would speak to ``host`` right now:
        binary when the client prefers it, the peer has not refused it,
        and the peer's advertised capability is bin1 — or still UNKNOWN
        (optimistic pre-probe: a refusal costs one downgraded retry,
        while pessimism would leave the first health interval's whole
        fan-out on JSON)."""
        if self.wire_mode != qwire.WIRE_BIN1 or self._wire_down.get(host):
            return qwire.WIRE_JSON
        if self._peer_wire.get(host, qwire.WIRE_BIN1) != qwire.WIRE_BIN1:
            return qwire.WIRE_JSON
        return qwire.WIRE_BIN1

    def _wire_downgrade(self, host: str, status: int):
        """A peer refused a binary POST (415 from a new peer pinned to
        internal-wire=json; 400 from an old peer that read PTPUQRY1 as a
        broken JSON body): latch this host to the JSON wire and journal
        the downgrade.  A genuine application-level 400 on the binary
        path trips this too — the cost is one spurious JSON retry that
        fails with the same error, and the next /status probe clears the
        latch if the peer advertises bin1."""
        self._wire_down[host] = True
        if self.stats is not None:
            self.stats.count("cluster.wire_fallback")
        events.emit("wire.downgrade", host=host, status=status)

    # -- circuit breaker ---------------------------------------------------

    def _breaker(self, host: str) -> _Breaker:
        b = self._breakers.get(host)
        if b is None:
            # insert under the lock: breaker_snapshot iterates the dict
            # under it, and an unlocked insert resizing the dict mid-
            # iteration would 500 the /debug/vars endpoint
            with self._breaker_lock:
                b = self._breakers.setdefault(host, _Breaker())
        return b

    def _breaker_allow(self, host: str, trial: bool = False):
        """Admit the request or raise CircuitOpenError.  When the circuit
        is open and the cooldown has elapsed, admit exactly ONE trial
        (half-open) — concurrent callers keep failing fast until the
        trial resolves.  ``trial=True`` (health probes) is ALWAYS
        admitted as the half-open trial regardless of cooldown: probes
        are the designated recovery path, and a dead node's own failed
        probes re-arm the cooldown every cycle — gating the probe on it
        would let the breaker latch a RECOVERED node DOWN forever."""
        if self.breaker_threshold <= 0:
            return
        b = self._breaker(host)
        admitted = emit_half_open = False
        with self._breaker_lock:
            if b.state == "closed":
                return
            now = time.monotonic()
            if trial or (now - b.opened_at >= self.breaker_cooldown
                         and not b.trial_inflight):
                b.trial_inflight = True  # half-open trial
                admitted = True
                emit_half_open = not b.half_open_emitted
                b.half_open_emitted = True
            else:
                b.fast_fails += 1
                if self.stats is not None:
                    self.stats.count("breaker.fail_fast")
        if admitted:
            if emit_half_open:
                # journaled OUTSIDE the breaker lock (events is a leaf
                # lock; transitions are rare, never the fail-fast hot
                # path) and once per open EPISODE — probes are always
                # admitted as trials, so per-trial emission would flood
                # the ring for a whole outage
                events.emit("breaker.half_open", host=host)
            return
        raise CircuitOpenError(
            f"circuit open for {host} ({b.fails} consecutive failures); "
            f"failing fast")

    def _breaker_success(self, host: str):
        if self.breaker_threshold <= 0:
            return
        b = self._breaker(host)
        # lock-free fast path for the overwhelmingly common steady state:
        # every fan-out RPC success would otherwise serialize on the one
        # process-wide breaker lock just to rewrite values it already
        # has.  Racing a concurrent failure here is benign — both fields
        # only move toward this state on success, and a missed reset
        # costs at most one extra failure toward the threshold.
        if b.state == "closed" and b.fails == 0:
            return
        with self._breaker_lock:
            was_open = b.state == "open"
            b.fails = 0
            b.trial_inflight = False
            b.half_open_emitted = False
            b.state = "closed"
        if was_open:
            events.emit("breaker.close", host=host)

    def _breaker_failure(self, host: str):
        if self.breaker_threshold <= 0:
            return
        b = self._breaker(host)
        opened = False
        with self._breaker_lock:
            b.trial_inflight = False
            b.fails += 1
            now = time.monotonic()
            if b.state == "open":
                b.opened_at = now  # failed trial re-arms the cooldown
            elif b.fails >= self.breaker_threshold:
                b.state = "open"
                b.opened_at = now
                b.opened_total += 1
                b.half_open_emitted = False
                opened = True
                if self.stats is not None:
                    self.stats.count("breaker.opened")
        if opened:
            events.emit("breaker.open", host=host, fails=b.fails)

    def breaker_snapshot(self) -> dict:
        """Per-peer breaker state for /debug/vars."""
        with self._breaker_lock:
            return {host: {"state": b.state, "consecutiveFails": b.fails,
                           "openedTotal": b.opened_total,
                           "fastFails": b.fast_fails}
                    for host, b in self._breakers.items()}

    def breaker_open(self, host: str) -> bool:
        """Is ``host``'s circuit currently open?  The read router skips
        such peers BEFORE dispatch (routing.breaker_skip) instead of
        letting each fan-out burn a CircuitOpenError round through the
        retry machinery.  Lock-free read: a racing transition costs one
        query a suboptimal (but correct) replica choice."""
        if self.breaker_threshold <= 0:
            return False
        b = self._breakers.get(host)
        return b is not None and b.state == "open"

    def close(self):
        with self._conns_lock:
            conns, self._all_conns = self._all_conns, set()
        for c in conns:
            try:
                c.close()
            # lint: allow(swallowed-exception) — client shutdown: the
            # socket may already be dead, and there is nothing to do
            except Exception:
                pass

    def configure_tls(self, cert: str, key: str, ca: str | None,
                      skip_verify: bool = False):
        """Client credentials for an https cluster (server/server.go
        GetTLSConfig; tls-skip-verify for self-signed deployments)."""
        import ssl
        ctx = ssl.create_default_context(
            cafile=ca if ca else None)
        ctx.load_cert_chain(cert, key)
        if skip_verify:
            ctx.check_hostname = False
            ctx.verify_mode = ssl.CERT_NONE
        self._ssl_ctx = ctx

    def _new_conn(self, host: str, timeout: float):
        https = host.startswith("https://")
        hostport = host.removeprefix("https://").removeprefix("http://")
        h, _, p = hostport.rpartition(":")
        if https:
            import ssl
            # no configured client context -> default VERIFIED context
            # (never silently skip verification; skip-verify is an
            # explicit configure_tls option)
            return http.client.HTTPSConnection(
                h or "localhost", int(p), timeout=timeout,
                context=self._ssl_ctx or ssl.create_default_context())
        return http.client.HTTPConnection(h or "localhost", int(p),
                                          timeout=timeout)

    def _request(self, host: str, method: str, path: str,
                 body: bytes | None = None,
                 ctype: str = "application/json",
                 timeout: float | None = None,
                 headers_extra: dict | None = None,
                 breaker_trial: bool = False) -> tuple[int, bytes]:
        """Breaker-gated request: open circuit -> CircuitOpenError fast;
        transport failures (OSError/HTTPException, including injected
        faults) count toward opening it, HTTP error statuses do not.
        ``breaker_trial``: health probes — always admitted (see
        _breaker_allow)."""
        self._breaker_allow(host, trial=breaker_trial)
        try:
            out = self._request_inner(host, method, path, body, ctype,
                                      timeout, headers_extra)
        except (OSError, http.client.HTTPException):
            self._breaker_failure(host)
            raise
        self._breaker_success(host)
        return out

    def _request_inner(self, host: str, method: str, path: str,
                       body: bytes | None = None,
                       ctype: str = "application/json",
                       timeout: float | None = None,
                       headers_extra: dict | None = None
                       ) -> tuple[int, bytes]:
        FAULTS.hit("client.request", key=f"{host} {path}")
        timeout = timeout or self.timeout
        conns = getattr(self._local, "conns", None)
        if conns is None:
            conns = self._local.conns = {}
        headers = {"Content-Type": ctype,
                   "Content-Length": str(len(body or b""))}
        # Trace propagation (http/client.go:1043 inject): every outbound
        # hop carries trace_id:parent_span_id when a trace is active, so
        # remote spans parent correctly under the calling span.  Probes
        # run on the probe pool with no active trace — no header.
        trace_hdr = GLOBAL_TRACER.inject()
        if trace_hdr is not None:
            headers[TRACE_HEADER] = trace_hdr
        # Tenant propagation (docs/robustness.md "Tenant isolation"):
        # only an EXPLICIT token forwards — a derived identity is
        # re-derived from the index on the peer, same answer, no header.
        tenant_hdr = qtenant.header_value()
        if tenant_hdr is not None:
            headers[qtenant.TENANT_HEADER] = tenant_hdr
        if headers_extra:
            headers.update(headers_extra)

        def drop(conn):
            conn.close()
            conns.pop(host, None)
            with self._conns_lock:
                self._all_conns.discard(conn)

        # One reconnect retry, ONLY when a POOLED connection fails during
        # SEND — the stale-keep-alive case, where the request provably
        # never reached the peer.  A fresh-connection failure must not
        # retry (it would double every timeout against a dead node), and
        # a response-phase failure must not retry (the peer may have
        # executed a non-idempotent request already).
        host_gen = self._host_gen.get(host, 0)
        for attempt in (0, 1):
            conn = conns.get(host)
            # a conn pooled before the peer's last recovery points at the
            # DEAD pre-restart process (see note_recovered): discard it
            # rather than risk a response-phase failure on a POST
            if conn is not None and \
                    getattr(conn, "_ptpu_gen", 0) != host_gen:
                drop(conn)
                conn = None
            # a pooled entry whose socket is gone (client.close() raced a
            # fan-out thread) is NOT a live keep-alive: replace it so it
            # re-registers and gets fresh-connection (no-retry) semantics
            if conn is not None and conn.sock is not None and \
                    time.monotonic() - getattr(
                        conn, "_ptpu_last_use",
                        time.monotonic()) > self.POOL_IDLE_MAX:
                drop(conn)
                conn = None
            reused = conn is not None and conn.sock is not None
            if conn is None or conn.sock is None:
                if conn is not None:
                    drop(conn)
                conn = conns[host] = self._new_conn(host, timeout)
                conn._ptpu_gen = host_gen
                with self._conns_lock:
                    self._all_conns.add(conn)
            if conn.sock is not None:
                conn.sock.settimeout(timeout)
            try:
                conn.request(method, path, body=body, headers=headers)
            except (OSError, http.client.HTTPException):
                drop(conn)
                if reused and attempt == 0:
                    continue
                raise
            try:
                resp = conn.getresponse()
                data = resp.read()
            except (OSError, http.client.HTTPException):
                drop(conn)
                # a FIN'd keep-alive often fails only here (the send
                # lands in the kernel buffer); GETs are idempotent, so
                # they get the reconnect retry — POSTs may have executed
                # on the peer and must not resend
                if reused and attempt == 0 and method == "GET":
                    continue
                raise
            if resp.will_close:
                drop(conn)
            else:
                conn._ptpu_last_use = time.monotonic()
            return resp.status, data

    def _json(self, host, method, path, obj=None, timeout=None,
              headers=None, breaker_trial=False):
        body = None if obj is None else json.dumps(obj).encode()
        status, data = self._request(host, method, path, body,
                                     timeout=timeout, headers_extra=headers,
                                     breaker_trial=breaker_trial)
        if status >= 400:
            raise self._http_error(host, path, status, data)
        return json.loads(data) if data else {}

    @staticmethod
    def _http_error(host, path, status, data) -> ClusterError:
        try:
            msg = json.loads(data).get("error", data.decode())
        # lint: allow(swallowed-exception) — error-body decode
        # fallback; the ClusterError below carries the raw body
        except Exception:
            msg = data.decode(errors="replace")
        return ClusterError(f"{host} {path}: {status} {msg}")

    # -- RPCs --------------------------------------------------------------

    def status(self, host: str, timeout: float | None = None,
               probe: bool = False) -> dict:
        """``probe=True``: this is a health probe — it rides through an
        open breaker as the half-open trial (the designated recovery
        path; see _breaker_allow), and is TAGGED on the wire so the
        peer excludes it from latency histograms and the slow-query log
        (background traffic must not pollute p99)."""
        headers = {PROBE_HEADER: "1"} if probe else None
        return self._json(host, "GET", "/status", timeout=timeout,
                          headers=headers, breaker_trial=probe)

    def debug_vars(self, host: str, timeout: float | None = None) -> dict:
        """One peer's /debug/vars snapshot — the fleet rollup's pull
        (parallel/rollup.py).  Probe-tagged on the wire (background
        traffic) and subject to the breaker like any other RPC, but NOT
        a breaker trial: the rollup must never be the thing that closes
        a breaker the probes haven't vetted."""
        return self._json(host, "GET", "/debug/vars", timeout=timeout,
                          headers={PROBE_HEADER: "1"})

    def debug_events(self, host: str, since: int = 0,
                     timeout: float | None = None,
                     limit: int | None = None) -> dict:
        """One peer's event journal after ``since`` (the /debug/events
        cursor contract, utils/events.py)."""
        path = f"/debug/events?since={int(since)}"
        if limit is not None:
            path += f"&limit={int(limit)}"
        return self._json(host, "GET", path, timeout=timeout,
                          headers={PROBE_HEADER: "1"})

    @staticmethod
    def _deadline_extras(deadline_s, base_timeout):
        """(headers, timeout) for a deadline-carrying hop: the header
        ships the coordinator's REMAINING budget so the remote inherits
        it, and the socket timeout is clamped just above that budget so
        a hung peer costs ~the budget, not the full default timeout (a
        small grace lets the remote's own 504 arrive instead of being
        cut off mid-response)."""
        if deadline_s is None:
            return None, None
        deadline_s = max(deadline_s, 0.001)
        headers = {DEADLINE_HEADER: f"{deadline_s:.6f}"}
        timeout = min(base_timeout,
                      deadline_s + max(0.05, 0.5 * deadline_s))
        return headers, timeout

    def query_call(self, host: str, index: str, call: Call,
                   shards: list[int] | None) -> Any:
        """(http/client.go:268 QueryNode — pinned single-call query)"""
        out = self._json(host, "POST", f"/internal/query/{index}", {
            "call": call_to_wire(call),
            "shards": shards,
        })
        return result_from_wire(out["result"])

    def query_calls(self, host: str, index: str, calls: list[Call],
                    shards: list[int] | None,
                    deadline_s: float | None = None
                    ) -> tuple[list[Any], float]:
        """Pinned MULTI-call query: the peer executes the whole batch as
        one device wave (its executor's grouped/prepared path) instead of
        one dispatch per call.  Returns (results, peer_exec_seconds) so
        the coordinator can attribute wire vs device time.

        ``deadline_s``: the coordinator's remaining deadline budget —
        shipped in the X-Pilosa-Tpu-Deadline header (the remote inherits
        it) and used to clamp the socket timeout.

        The third return element is the peer's fragment-generation
        summary for the index (piggybacked so the coordinator can key
        cross-node result-cache entries; cache/results.py).  4th: the
        peer's quarantined-fragment count — the coordinator folds it
        into the response's degraded flag (utils/degraded.py).  5th: the
        peer's admission-queue depth, piggybacked for the read router's
        load scores (parallel/routing.py — the same piggyback pattern
        as gens).

        Rides the PTPUQRY1 binary wire when negotiation allows
        (peer_wire_mode) and falls back to the verbatim JSON envelope on
        refusal — same results, same piggybacks, byte-identical merged
        answers either way (docs/cluster.md "Internal query wire")."""
        headers, timeout = self._deadline_extras(deadline_s, self.timeout)
        path = f"/internal/query/{index}"
        calls_wire = [call_to_wire(c) for c in calls]
        if self.peer_wire_mode(host) == qwire.WIRE_BIN1:
            body = qwire.encode_request(calls_wire, shards)
            status, data = self._request(
                host, "POST", path, body, ctype=qwire.CONTENT_TYPE,
                timeout=timeout, headers_extra=headers)
            if status < 400:
                try:
                    results, trailer, nframes = qwire.decode_response(data)
                except qwire.FrameError as e:
                    raise ClusterError(
                        f"{host} {path}: bad binary response: {e}")
                if self.stats is not None:
                    # request frames (calls + shards) count too: the
                    # bench's bytes/query split wants BOTH directions
                    self.stats.count("cluster.wire_bytes_tx", len(body))
                    self.stats.count("cluster.wire_bytes_rx", len(data))
                    self.stats.count("cluster.wire_frames", nframes + 2)
                GLOBAL_TRACER.adopt(trailer.get("spans"))
                return (results, float(trailer.get("execS", 0.0)),
                        trailer.get("gens"),
                        int(trailer.get("quarantined", 0)),
                        trailer.get("load"))
            if status not in (415, 400):
                raise self._http_error(host, path, status, data)
            # 415: a bin1-capable peer pinned to internal-wire=json.
            # 400: an old peer that read the frames as broken JSON.
            # Either way: latch this host to JSON and retry the SAME
            # request on the JSON wire — safe because every call through
            # here is an idempotent internal read (writes fan out on
            # their own paths and never ride query_calls).
            self._wire_downgrade(host, status)
        body = json.dumps({"calls": calls_wire,
                           "shards": shards}).encode()
        status, data = self._request(host, "POST", path, body,
                                     timeout=timeout, headers_extra=headers)
        if status >= 400:
            raise self._http_error(host, path, status, data)
        if self.stats is not None:
            # counted on the JSON leg too, so bin1-vs-json bytes/query
            # compare from the same counters (docs/observability.md)
            self.stats.count("cluster.wire_bytes_tx", len(body))
            self.stats.count("cluster.wire_bytes_rx", len(data))
        out = json.loads(data) if data else {}
        # remote span summaries piggyback on the response (like the gen
        # summaries): fold them into the local ring so /debug/traces on
        # the coordinator renders the whole cluster tree
        GLOBAL_TRACER.adopt(out.get("spans"))
        return ([result_from_wire(r) for r in out["results"]],
                float(out.get("execS", 0.0)), out.get("gens"),
                int(out.get("quarantined", 0)), out.get("load"))

    def send_message(self, host: str, msg: dict,
                     timeout: float | None = None):
        """(broadcast.go SendTo -> POST /internal/cluster/message).
        ``timeout`` overrides the default 30 s for long-running messages
        (a resize-fetch copies whole fragment sets inside one POST)."""
        self._json(host, "POST", "/internal/cluster/message", msg,
                   timeout=timeout)

    def import_local(self, host: str, index: str, field: str, payload: dict):
        """Forward a pre-grouped import batch to a shard owner
        (http/client.go Import; applied locally, never re-forwarded)."""
        self._json(host, "POST",
                   f"/internal/import/{index}/{field}", payload)

    def ingest_frames(self, host: str, index: str, field: str,
                      body: bytes, timeout: float | None = None) -> dict:
        """Forward routed ingest frames to a shard owner as a binary
        stream (docs/ingest.md): ``body`` is magic + frames, exactly the
        public wire format.  Returns after the OWNER's group commit
        acked; a 503 surfaces as IngestBackpressure so the coordinator
        can push back to its own producer."""
        status, data = self._request(
            host, "POST", f"/internal/ingest/{index}/{field}", body,
            ctype="application/octet-stream", timeout=timeout)
        if status == 503:
            raise IngestBackpressure(
                f"{host}: ingest backlog over high-water")
        if status >= 400:
            try:
                msg = json.loads(data).get("error", data.decode())
            # lint: allow(swallowed-exception) — error-body decode
            # fallback; the ClusterError below carries the raw body
            except Exception:
                msg = data.decode(errors="replace")
            raise ClusterError(f"{host} ingest: {status} {msg}")
        return json.loads(data) if data else {}

    def import_roaring_binary(self, host: str, index: str, field: str,
                              shard: int, view: str, data: bytes,
                              clear: bool):
        """Forward one view's roaring blob raw — the node-to-node half
        of killing the 4/3 base64-in-JSON blowup on roaring imports."""
        status, resp = self._request(
            host, "POST",
            f"/internal/import-roaring/{index}/{field}/{shard}"
            f"?view={view}&clear={'true' if clear else 'false'}",
            data, ctype="application/octet-stream")
        if status >= 400:
            try:
                msg = json.loads(resp).get("error", resp.decode())
            # lint: allow(swallowed-exception) — error-body decode
            # fallback; the ClusterError below carries the raw body
            except Exception:
                msg = resp.decode(errors="replace")
            raise ClusterError(
                f"{host} import-roaring: {status} {msg}")

    def available_shards(self, host: str, index: str,
                         timeout: float | None = None) -> list[int]:
        out = self._json(host, "GET", f"/internal/index/{index}/shards",
                         timeout=timeout)
        return out.get("shards", [])

    def fragment_blocks(self, host: str, index: str, field: str, view: str,
                        shard: int) -> tuple[dict[int, str], bool]:
        """(block checksums, peer-quarantined flag).  A quarantined
        peer's empty block map must NOT enter merge consensus — its
        emptiness is corruption fallout, not a legitimate clear."""
        out = self._json(
            host, "GET",
            f"/internal/fragment/blocks?index={index}&field={field}"
            f"&view={view}&shard={shard}")
        return ({int(k): v for k, v in out.get("blocks", {}).items()},
                bool(out.get("quarantined", False)))

    def block_data(self, host: str, index: str, field: str, view: str,
                   shard: int, block: int) -> tuple[np.ndarray, np.ndarray]:
        out = self._json(
            host, "GET",
            f"/internal/fragment/block/data?index={index}&field={field}"
            f"&view={view}&shard={shard}&block={block}")
        return (np.asarray(out["rows"], dtype=np.int64),
                np.asarray(out["cols"], dtype=np.int64))

    def block_repair(self, host: str, index: str, field: str, view: str,
                     shard: int, sets, clears):
        """Push a merge-consensus diff to a peer (the reference's
        syncBlock remote Import/Import-clear calls, fragment.go:2995-3031).
        ``sets``/``clears`` are (rows, cols) pairs, shard-local."""
        self._json(host, "POST", "/internal/fragment/block/repair", {
            "index": index, "field": field, "view": view, "shard": shard,
            "setRows": sets[0].tolist(), "setCols": sets[1].tolist(),
            "clearRows": clears[0].tolist(),
            "clearCols": clears[1].tolist(),
        })

    def attr_diff(self, host: str, index: str, field: str | None,
                  blocks_hex: dict) -> dict[int, dict]:
        """Fetch the peer's attrs for blocks whose checksum differs from
        ours (holder.go:1002 syncIndex ColumnAttrDiff/RowAttrDiff)."""
        out = self._json(host, "POST", "/internal/attr/diff", {
            "index": index, "field": field, "blocks": blocks_hex})
        return {int(k): v for k, v in out.get("attrs", {}).items()}

    def fragment_list(self, host: str, index: str,
                      shard: int) -> list[tuple[str, str]]:
        """(field, view) fragments a node holds for (index, shard) — the
        discovery step of a resize fetch."""
        out = self._json(host, "GET",
                         f"/internal/fragment/list?index={index}"
                         f"&shard={shard}")
        return [(f, v) for f, v in out.get("fragments", [])]

    def fragment_data(self, host: str, index: str, field: str, view: str,
                      shard: int) -> bytes:
        """Whole-fragment fetch as a pilosa-roaring blob
        (http/client.go:742 RetrieveShardFromURI)."""
        status, data = self._request(
            host, "GET",
            f"/internal/fragment/data?index={index}&field={field}"
            f"&view={view}&shard={shard}")
        if status >= 400:
            raise ClusterError(f"fragment data fetch failed: {status}")
        return data

    def fragment_fetch(self, host: str, index: str, field: str, view: str,
                       shard: int) -> bytes:
        """Whole-fragment fetch as CHECKSUMMED native snapshot bytes
        (quarantine repair; docs/robustness.md).  The caller verifies the
        embedded CRCs on receipt (Fragment.restore_snapshot_bytes) — a
        flip in flight or on the peer's side must not launder itself into
        a 'repaired' fragment."""
        status, data = self._request(
            host, "GET",
            f"/internal/fragment/fetch?index={index}&field={field}"
            f"&view={view}&shard={shard}")
        if status >= 400:
            raise ClusterError(f"fragment fetch failed: {status}")
        return data


class RemoteTranslateStore:
    """Key translation routed to the coordinator with a read-through cache
    — the static-cluster replacement for the reference's primary-writes +
    streamed-replication scheme (translate.go:35, holder.go:812)."""

    def __init__(self, client: InternalClient, host: str, index: str,
                 field: str | None):
        self.client = client
        self.host = host
        self.index = index
        self.field = field
        self._k2i: dict[str, int] = {}
        self._i2k: dict[int, str] = {}
        self._sync_after = 0  # contiguous replication watermark
        self._lock = make_rlock("remote-translate")

    def _path(self) -> str:
        p = f"/internal/translate/{self.index}"
        return p + (f"/{self.field}" if self.field else "")

    # entries per catch-up page (bounds coordinator lock hold + response
    # size; the loop below drains all pages)
    SYNC_PAGE = 50_000

    def sync_entries(self) -> int:
        """Streaming replication catch-up (holder.go:812
        holderTranslateStoreReplicator): page entries after our CONTIGUOUS
        replication watermark from the coordinator, so reads on this
        replica stop paying a coordinator round trip for keys written
        since the last pass.  The watermark is separate from the lookup
        cache — a read-through hit on a high id must not make replication
        skip everything below it.  Driven from the anti-entropy loop."""
        total = 0
        while True:
            out = self.client._json(
                self.host, "POST", self._path(),
                {"after": self._sync_after, "limit": self.SYNC_PAGE})
            entries = out.get("entries", [])
            if entries:
                with self._lock:
                    for kid, key in entries:
                        self._k2i[key] = kid
                        self._i2k[kid] = key
                self._sync_after = max(self._sync_after,
                                       max(kid for kid, _ in entries))
                total += len(entries)
            if len(entries) < self.SYNC_PAGE:
                return total

    def translate_key(self, key: str) -> int:
        with self._lock:
            kid = self._k2i.get(key)
        if kid is not None:
            return kid
        out = self.client._json(self.host, "POST", self._path(),
                                {"keys": [key]})
        kid = out["ids"][0]
        with self._lock:
            self._k2i[key] = kid
            self._i2k[kid] = key
        return kid

    def translate_keys(self, keys) -> list[int]:
        """One POST for the whole uncached set (the endpoint accepts lists;
        the per-key loop was the r2 advisor's last open finding — N keyed
        columns cost N coordinator round trips)."""
        keys = list(keys)
        with self._lock:
            missing = sorted({k for k in keys if k not in self._k2i})
        if missing:
            out = self.client._json(self.host, "POST", self._path(),
                                    {"keys": missing})
            with self._lock:
                for k, kid in zip(missing, out["ids"]):
                    self._k2i[k] = kid
                    self._i2k[kid] = k
        with self._lock:
            return [self._k2i[k] for k in keys]

    def translate_id(self, kid: int) -> str | None:
        with self._lock:
            key = self._i2k.get(kid)
        if key is not None:
            return key
        out = self.client._json(self.host, "POST", self._path(),
                                {"ids": [kid]})
        key = out["keys"][0]
        if key is not None:
            with self._lock:
                self._k2i[key] = kid
                self._i2k[kid] = key
        return key

    def translate_ids(self, ids) -> list[str | None]:
        """One POST for the whole uncached set (see translate_keys)."""
        ids = list(ids)
        with self._lock:
            missing = sorted({i for i in ids if i not in self._i2k})
        if missing:
            out = self.client._json(self.host, "POST", self._path(),
                                    {"ids": missing})
            with self._lock:
                for kid, key in zip(missing, out["keys"]):
                    if key is not None:
                        self._k2i[key] = kid
                        self._i2k[kid] = key
        with self._lock:
            return [self._i2k.get(i) for i in ids]

    def find_key(self, key: str) -> int | None:
        with self._lock:
            return self._k2i.get(key)

    def close(self):
        pass


# -- node & cluster ---------------------------------------------------------

class Node:
    def __init__(self, node_id: str, host: str):
        self.id = node_id
        self.host = host
        self.state = NODE_READY
        # consecutive probe failures (health-down-threshold gate)
        self.probe_fails = 0

    def to_dict(self, coordinator_id: str) -> dict:
        return {"id": self.id, "uri": self.host,
                "isCoordinator": self.id == coordinator_id,
                "state": self.state}


class Cluster:
    """Static-membership cluster (the module server.py:103 wires in).

    ``hosts`` is the ordered node list from config; node ids are
    "node0".."nodeN-1" by position and ``node_id`` selects which entry is
    this process (matching the reference's URI-identity with explicit
    names).  Node 0 is the coordinator (primary for DDL broadcast).
    """

    def __init__(self, node_id: str, hosts: list[str], replica_n: int = 1,
                 holder=None, hasher=None, health_interval: float = 5.0,
                 health_down_threshold: int = 2,
                 breaker_threshold: int = 5, stats=None,
                 read_routing: str = "loaded",
                 residency_routing: bool = True,
                 balancer: bool = False,
                 balancer_interval: float = 30.0,
                 hot_shard_threshold: float = 4.0,
                 hedge_reads: bool = True,
                 hedge_delay_ms: float = 0.0,
                 internal_wire: str = qwire.WIRE_BIN1,
                 tenant_hedge_budget: float = 0.0):
        if internal_wire not in (qwire.WIRE_JSON, qwire.WIRE_BIN1):
            raise ClusterError(
                f"internal_wire must be one of "
                f"{[qwire.WIRE_JSON, qwire.WIRE_BIN1]}, "
                f"got {internal_wire!r}")
        # Internal query wire (docs/cluster.md "Internal query wire"):
        # governs BOTH directions — what this node's client speaks to
        # peers (subject to per-peer negotiation) and what its handler
        # accepts (415 on binary POSTs when pinned to "json").
        self.internal_wire = internal_wire
        self.nodes = [Node(f"node{i}", h) for i, h in enumerate(hosts)]
        self.by_id = {n.id: n for n in self.nodes}
        if node_id not in self.by_id:
            raise ClusterError(
                f"node_id {node_id!r} not in cluster hosts (expected one of "
                f"{sorted(self.by_id)})")
        self.node_id = node_id
        self.holder = holder
        self.replica_n = replica_n
        self.placement = Placement([n.id for n in self.nodes],
                                   replica_n=replica_n, hasher=hasher)
        # soft probe failures (timeouts, resets) needed before NODE_DOWN;
        # a refused connection (nothing listening) flips immediately —
        # see _note_probe_failure
        self.health_down_threshold = max(1, health_down_threshold)
        # breaker half-open trials ride the health cadence, so breaker
        # state and probe-driven NODE_DOWN converge on the same answer
        self.client = InternalClient(
            breaker_threshold=breaker_threshold,
            breaker_cooldown=max(health_interval, 1.0)
            if health_interval > 0 else 5.0,
            stats=stats, wire_mode=internal_wire)
        self.api = None
        self.state = STATE_STARTING
        self.health_interval = health_interval
        self._closing = threading.Event()
        self._health_thread = None
        self._resize_lock = make_lock("resize-job")
        # membership epoch: bumped by every completed resize, persisted in
        # .topology, carried on resize-complete messages so retries are
        # idempotent and stale nodes are detectable by probe
        self.epoch = 0
        # seconds before post-resize fragment GC (0 = inline); covers the
        # window where nodes adopt the new membership at different times
        # while reads keep serving
        self.cleaner_grace = 5.0
        # per-index remote shard availability, folded from every
        # successful peer poll (the in-memory analog of field.go:263's
        # gossiped available-shard bitmaps).  A DOWN peer's shards stay
        # visible here, so a query over them FAILS loudly instead of
        # silently shrinking to the live nodes' data.  Related but not
        # redundant: Field.remote_available_shards records per-FIELD
        # knowledge learned at import fan-out time; this map records
        # per-INDEX knowledge learned from peer polls (the poll API is
        # index-level).  Both feed the query scope; shards leave this
        # map via forget_index_shards and resize data-loss pruning.
        # Mutated from concurrent query threads (peer polls) AND cluster
        # messages; _shards_lock (a leaf lock, never held across I/O or
        # another lock) guards every access instead of leaning on GIL
        # atomicity of single set ops (r5 advisor).
        self._remote_shards: dict[str, set[int]] = {}
        self._shards_lock = make_lock("cluster-shards")
        # Per-(index, peer) data-version registry for the coordinator-
        # scope result cache (cache/results.py): bumped whenever this
        # node forwards a write/import/repair to the peer, and whenever a
        # piggybacked gen summary (on /internal/query responses and
        # /status probes) differs from the last one seen.  Cache keys
        # embed the versions, so a bump structurally invalidates every
        # entry that depended on that peer's data.  _gen_lock is a leaf
        # lock (never held across I/O).
        self._peer_data_ver: dict[tuple[str, str], int] = {}
        self._peer_gen_seen: dict[tuple[str, str], tuple] = {}
        self._gen_lock = make_lock("peer-gen")
        # Anti-entropy observability (docs/robustness.md): failures as
        # DATA, not just a log line — counters ride self.stats
        # (antientropy.errors / antientropy.repairs), and the last
        # error/success land here for /debug/vars.  _ae_lock is a leaf
        # lock.
        self.stats = stats
        self._ae_lock = make_lock("anti-entropy")
        self._ae_last_error: str | None = None
        self._ae_last_error_ts: float | None = None
        self._ae_last_success_ts: float | None = None
        # Elastic serving (docs/cluster.md "Read routing & rebalancing"):
        # placement-overlay table — (index, shard) -> EXTRA owner ids the
        # balancer appended beyond the jump-hash owners.  Epoch-gated and
        # broadcast like resize-complete so all nodes route (and fan
        # writes) consistently; persisted with the topology.  _overlay_lock
        # is a leaf lock (never held across I/O or another lock).
        self._overlay: dict[tuple[str, int], list[str]] = {}
        self.overlay_epoch = 0
        self._overlay_lock = make_lock("placement-overlay")
        from .balancer import HotShardBalancer, ShardLoadTracker
        from .routing import ReadRouter
        self.router = ReadRouter(self, policy=read_routing,
                                 residency_routing=residency_routing,
                                 stats=stats)
        self.load_tracker = ShardLoadTracker(
            window_s=max(balancer_interval, 1.0))
        self.balancer_on = bool(balancer)
        self.balancer_interval = balancer_interval
        self.balancer = HotShardBalancer(
            self, self.load_tracker, threshold=hot_shard_threshold,
            stats=stats)
        # Tail-tolerant fan-out (docs/robustness.md "Tail-tolerant
        # fan-out"): hedged reads fire a speculative duplicate of a
        # straggling shard-group RPC at the next-best replica; safe
        # because every call through _fan_out_multi is an idempotent
        # internal read (writes fan out through their own replica-
        # synchronous paths and are NEVER hedged).  hedge_delay_ms = 0
        # derives the delay from the router's EWMA RTT.
        self.hedge_reads = bool(hedge_reads)
        self.hedge_delay_ms = float(hedge_delay_ms)
        # Per-tenant hedge token budget (docs/robustness.md "Tenant
        # isolation"): each speculative duplicate draws a token from the
        # requesting tenant's bucket; an exhausted bucket reads unhedged
        # (counted, never an error).  0 (the bare-Cluster default)
        # disables the budget entirely.
        self.hedge_budget = qtenant.HedgeBudget(rate=tenant_hedge_budget)
        # structured-event sink (cluster.fanout_failed); the Server
        # wires its logger in, standalone clusters stay silent
        self.logger = None
        # residency-summary TTL cache (walking every fragment per /status
        # probe would make probes O(fragments); 2s staleness is far under
        # RESIDENCY_TTL_S)
        self._residency_cache: tuple[float, dict] | None = None
        # set by Server.register_internal_routes: the admission pools the
        # load piggyback reports (None standalone — zero-load answers)
        self._server = None
        self._load_topology()
        self._pool = ThreadPoolExecutor(
            max_workers=max(4, 2 * len(self.nodes)))
        # DEDICATED probe pool: health probes must never queue behind
        # query fan-out RPCs blocked on a hung peer's socket timeout in
        # the shared pool — that would delay NODE_DOWN detection (and
        # the breaker's half-open trial) by exactly the latency the
        # probes exist to bound
        self._probe_pool = ThreadPoolExecutor(
            max_workers=max(2, len(self.nodes)),
            thread_name_prefix="ptpu-probe")
        # One probe pass at a time: the health thread and an explicit
        # probe_peers() call must not interleave, or a pass that gathered
        # its results while a peer was still dead could apply a stale
        # DOWN after a newer pass already marked the recovered peer READY
        self._probe_serial = make_lock("probe-serial")

    # -- lifecycle ---------------------------------------------------------

    def open(self, api):
        self.api = api
        self.state = STATE_NORMAL
        if self.is_coordinator:
            self._recover_resize_job()
        if self.health_interval > 0:
            self._health_thread = threading.Thread(
                target=self._monitor_health, daemon=True)
            self._health_thread.start()
        if self.balancer_on and self.is_coordinator \
                and self.balancer_interval > 0:
            t = threading.Thread(target=self._monitor_balancer,
                                 daemon=True)
            t.start()

    def _monitor_balancer(self):
        """Hot-shard rebalancing cadence (coordinator only; the tick
        itself never raises — failed handoffs count balancer.errors)."""
        while not self._closing.wait(self.balancer_interval):
            self.balancer.tick()

    def close(self):
        self._closing.set()
        self._pool.shutdown(wait=False)
        self._probe_pool.shutdown(wait=False)
        self.client.close()

    @property
    def local(self) -> Node:
        return self.by_id[self.node_id]

    def peers(self) -> list[Node]:
        return [n for n in self.nodes if n.id != self.node_id]

    @property
    def is_coordinator(self) -> bool:
        return self.node_id == self.nodes[0].id

    def remote_translate_factory(self, path, index, field):
        """translate_factory for non-coordinator nodes: route key
        translation to the coordinator (see RemoteTranslateStore)."""
        return RemoteTranslateStore(self.client, self.nodes[0].host,
                                    index, field)

    # -- failure detection (cluster.go:1724 confirmNodeDown) ---------------

    def _monitor_health(self):
        while not self._closing.wait(self.health_interval):
            self.probe_peers()

    # floor for the per-probe timeout so tiny health intervals (tests)
    # don't flap probes on scheduler jitter
    PROBE_TIMEOUT_MIN = 2.0

    def _probe_timeout(self) -> float:
        if self.health_interval <= 0:
            return self.client.timeout
        return min(self.client.timeout,
                   max(2 * self.health_interval, self.PROBE_TIMEOUT_MIN))

    def _probe_status(self, node, timeout):
        try:
            return self.client.status(node.host, timeout=timeout,
                                      probe=True), None
        except Exception as e:
            return None, e

    def _note_probe_failure(self, n: Node, err: Exception):
        """One probe miss is not death (cluster.go:1724 confirmNodeDown):
        soft failures (timeouts, resets) need health_down_threshold
        CONSECUTIVE misses before NODE_DOWN so a transient hiccup can't
        flip the cluster DEGRADED.  A DEFINITE failure — connection
        refused, i.e. nothing is listening — flips immediately, and an
        already-DOWN node stays down.  (Probes bypass an open breaker as
        its half-open trial, so CircuitOpenError never reaches here.)"""
        n.probe_fails += 1
        if isinstance(err, ConnectionRefusedError) \
                or n.state == NODE_DOWN \
                or n.probe_fails >= self.health_down_threshold:
            if n.state != NODE_DOWN:
                events.emit("node.down", peer=n.id,
                            reason=f"{type(err).__name__}: {err}"[:160])
            n.state = NODE_DOWN

    def probe_peers(self):
        # One pass at a time (see _probe_serial): a pass's gathered
        # results must be applied before the next pass starts, or a
        # stale failure could overwrite a newer recovery.
        with self._probe_serial:
            self._probe_peers_serialized()

    def _probe_peers_serialized(self):
        # Probe CONCURRENTLY over the dedicated pool: one hung peer must
        # cost one probe timeout of wall clock, not serialize the whole
        # loop behind its full socket timeout (r6 issue).  State is
        # applied sequentially below once every future resolves.
        peers = self.peers()
        timeout = self._probe_timeout()
        try:
            futs = [(n, self._probe_pool.submit(self._probe_status, n,
                                                timeout))
                    for n in peers]
        except RuntimeError:
            return  # pool shut down: close() raced the health thread
        for n, fut in futs:
            st, err = fut.result()
            was_down = n.state == NODE_DOWN
            if st is None:
                self._note_probe_failure(n, err)
                continue
            n.probe_fails = 0
            # a peer replaying its warmup corpus advertises warming on
            # /status; treat it as alive-but-not-READY so routing and
            # repair skip it until its replay finishes (docs/warmup.md)
            prev = n.state
            n.state = NODE_WARMING if st.get("warming") else NODE_READY
            if prev != NODE_READY and n.state == NODE_READY:
                # node.up marks ENTERING SERVICE: a restarted peer that
                # comes back warming emits it when the warmup finishes,
                # not when its socket first answers
                events.emit("node.up", peer=n.id)
            # fold the probe's piggybacked gen summaries into the result-
            # cache registry: writes that entered the cluster through
            # OTHER nodes (never crossing this coordinator) stop matching
            # cached entries within one health interval
            for iname, summary in (st.get("dataGens") or {}).items():
                self.note_peer_gens(iname, n.id, tuple(summary))
            # fold the peer's load + residency summary into the read
            # router (parallel/routing.py): the probe cadence keeps tier
            # preferences fresh even for peers the fan-out never hits
            self.router.note_status(n.id, st)
            # fold the peer's advertised wire capability (clears a stale
            # per-peer JSON downgrade once the peer speaks bin1 again —
            # the rolling-upgrade recovery path)
            self.client.note_peer_wire(n.host, st.get("wire"))
            if was_down:
                # every pooled connection to the peer predates its
                # outage/restart — invalidate them BEFORE any traffic
                # (writes included) re-targets the node, or a stale
                # keep-alive's response-phase failure turns recovery
                # into spurious non-retryable POST errors
                self.client.note_recovered(n.host)
            peer_overlay = st.get("overlayEpoch")
            if (self.is_coordinator and peer_overlay is not None
                    and peer_overlay < self.overlay_epoch):
                # straggler on an older placement overlay (missed the
                # broadcast, or restarted with wiped state): re-push the
                # full table, epoch-gated like resize-complete
                try:
                    self.client.send_message(n.host, {
                        "type": "placement-overlay",
                        "overlay": self._overlay_wire(),
                        "epoch": self.overlay_epoch})
                # lint: allow(swallowed-exception) — DOWN is the
                # handling: probe reconciliation re-pushes next pass
                except Exception:
                    n.state = NODE_DOWN
                    continue
            peer_epoch = st.get("epoch")
            if (self.is_coordinator and peer_epoch is not None
                    and peer_epoch < self.epoch):
                # straggler on an older membership (missed a
                # resize-complete): re-push the current one, epoch-gated
                try:
                    self.client.send_message(n.host, {
                        "type": "resize-complete",
                        "membership": self._membership(),
                        "replicaN": self.replica_n,
                        "epoch": self.epoch})
                # lint: allow(swallowed-exception) — DOWN is the
                # handling: probe reconciliation re-pushes next pass
                except Exception:
                    n.state = NODE_DOWN
                    continue
            if (not self.is_coordinator and n.id == self.nodes[0].id
                    and self.state == STATE_RESIZING
                    and st.get("state") != STATE_RESIZING):
                coord_members = {d.get("id") for d in st.get("nodes", [])}
                if self.node_id not in coord_members and peer_epoch:
                    # that resize REMOVED us and its revert notification
                    # never arrived: adopt the single-node view ourselves
                    self._apply_resize_complete({
                        "membership": st.get("nodes", []),
                        "replicaN": 1, "epoch": peer_epoch})
                elif peer_epoch is None or peer_epoch <= self.epoch:
                    # the resize that latched us RESIZING died with its
                    # coordinator (no job record survived); unlatch
                    self.state = STATE_NORMAL
            if was_down:
                # Schema catch-up: a node that was DOWN during a DDL
                # broadcast missed it permanently (broadcast skips DOWN
                # peers), so on recovery push the full schema (the
                # reference replays state via ClusterStatus on rejoin,
                # cluster.go:1301 mergeClusterStatus/applySchema).
                try:
                    self.client.send_message(n.host, {
                        "type": "apply-schema",
                        "schema": self.holder.schema(),
                    })
                # lint: allow(swallowed-exception) — DOWN is the
                # handling: the next recovery probe retries catch-up
                except Exception:
                    n.state = NODE_DOWN
        # an outstanding resize job whose members are all current resolves
        job = self._load_resize_job()
        if (job is not None and self.is_coordinator
                and job.get("epoch", 0) <= self.epoch
                and all(n.state == NODE_READY for n in self.peers())):
            self._clear_resize_job()
        self._update_state()

    def _update_state(self):
        if self.state in (STATE_STARTING, STATE_RESIZING):
            return
        down = any(n.state == NODE_DOWN for n in self.nodes)
        self.state = STATE_DEGRADED if down else STATE_NORMAL

    def set_local_warming(self, warming: bool):
        """Flip the LOCAL node's advertised state between WARMING and
        READY (docs/warmup.md).  The Server calls this around the AOT
        warmup replay; peers additionally fold the /status ``warming``
        flag on their probe cadence, so both the local node_statuses
        and the fleet's routers see the phase."""
        n = self.by_id.get(self.node_id)
        if n is not None and n.state != NODE_DOWN:
            n.state = NODE_WARMING if warming else NODE_READY

    def _mark_down(self, node_id: str):
        n = self.by_id.get(node_id)
        if n is not None:
            if n.state != NODE_DOWN:
                events.emit("node.down", peer=node_id,
                            reason="marked down by fan-out/broadcast")
            n.state = NODE_DOWN
            self._update_state()

    # -- info --------------------------------------------------------------

    def node_statuses(self) -> list[dict]:
        coord = self.nodes[0].id
        return [n.to_dict(coord) for n in self.nodes]

    def shard_nodes_info(self, index: str, shard: int) -> list[dict]:
        return [{"id": nid, "uri": self.by_id[nid].host}
                for nid in self.shard_owner_nodes(index, shard)]

    # -- placement overlay (docs/cluster.md "Read routing & rebalancing") --

    def shard_owner_nodes(self, index: str, shard: int) -> list[str]:
        """Effective owners of a shard: the jump-hash placement owners
        PLUS any overlay owners the balancer appended (hot-spot
        splitting).  Every ownership decision — read routing, write
        fan-out, import grouping, anti-entropy, the holder cleaner —
        consults this, so an overlay owner is a full replica, not a
        read-only cache.  With an empty overlay (balancer off, the
        default) this is exactly ``placement.shard_nodes``."""
        owners = self.placement.shard_nodes(index, shard)
        with self._overlay_lock:
            extras = self._overlay.get((index, shard))
            if not extras:
                return owners
            return owners + [nid for nid in extras
                             if nid in self.by_id and nid not in owners]

    def owns_shard(self, node_id: str, index: str, shard: int) -> bool:
        return node_id in self.shard_owner_nodes(index, shard)

    def owned_shards(self, node_id: str, index: str, shards) -> list[int]:
        """Overlay-aware ``placement.owned_shards``: shards (including
        replicas and overlay extras) the node holds."""
        return [s for s in shards
                if node_id in self.shard_owner_nodes(index, s)]

    def overlay_snapshot(self) -> dict:
        with self._overlay_lock:
            return {"epoch": self.overlay_epoch,
                    "entries": [{"index": i, "shard": s, "extra": list(e)}
                                for (i, s), e in
                                sorted(self._overlay.items())]}

    def _overlay_wire(self) -> list:
        with self._overlay_lock:
            return [[i, s, list(e)] for (i, s), e in
                    sorted(self._overlay.items())]

    def add_overlay(self, index: str, shard: int, node_id: str) -> bool:
        """Coordinator: append an overlay owner for a shard, bump the
        overlay epoch, persist, and broadcast the FULL table (like
        resize-complete — receivers apply epoch-gated, stragglers get
        probe re-pushes).  The caller (the balancer) has already copied
        the shard's fragments to the node."""
        if node_id not in self.by_id:
            raise ClusterError(f"unknown overlay node {node_id!r}")
        with self._overlay_lock:
            if node_id in self.placement.shard_nodes(index, shard):
                return False
            extras = self._overlay.setdefault((index, shard), [])
            if node_id in extras:
                return False
            extras.append(node_id)
            self.overlay_epoch += 1
        events.emit("overlay.handoff", index=index, shard=shard,
                    to=node_id, epoch=self.overlay_epoch)
        self._save_topology()
        self.broadcast_overlay()
        return True

    def broadcast_overlay(self):
        """Push the overlay table to every READY peer; failures mark the
        peer DOWN and probe reconciliation re-pushes (the peer's /status
        carries its overlayEpoch)."""
        msg = {"type": "placement-overlay",
               "overlay": self._overlay_wire(),
               "epoch": self.overlay_epoch}
        for n in self.peers():
            if n.state != NODE_READY:
                continue
            try:
                self.client.send_message(n.host, msg)
            except Exception:
                # DOWN is the handling: the probe's overlay-epoch
                # reconciliation re-pushes the table next pass
                self._mark_down(n.id)

    def _apply_overlay(self, msg: dict):
        """Receive a placement-overlay broadcast: epoch-gated full-table
        replace (an older or duplicate push is an idempotent no-op ack,
        exactly like resize-complete), persisted so a restart keeps
        routing consistently."""
        epoch = int(msg.get("epoch", 0))
        with self._overlay_lock:
            if epoch <= self.overlay_epoch:
                return
            self._overlay = {
                (i, int(s)): [nid for nid in extras if nid in self.by_id]
                for i, s, extras in msg.get("overlay", [])}
            self.overlay_epoch = epoch
        self._save_topology()

    # -- residency tiers + load (status/query piggybacks) ------------------

    # shards listed per tier per index in a residency summary; beyond it
    # the summary truncates (the router treats unlisted as disk-only,
    # which only costs a preference, never correctness)
    RESIDENCY_MAX_SHARDS = 2048
    RESIDENCY_CACHE_TTL = 2.0
    # One query firing this many speculative duplicates is a hedge storm
    # (journaled once per query in the event timeline): the cluster is
    # tail-degrading broadly, not routing around one slow peer.
    HEDGE_STORM_MIN = 4

    def residency_summary(self) -> dict:
        """Per-index shard residency tiers this node can serve from:
        ``hbm`` (device mirror or a mesh stack holds the shard — answers
        without an upload), ``host`` (dense stage / packed stream cached
        — answers without re-expansion), everything else disk-only.
        Advertised on /status probes; the router prefers replicas that
        hold the queried shards high (docs/cluster.md).  TTL-cached:
        probes and fan-outs must not walk every fragment each time.
        Reads fragment attributes without their locks — a torn read
        costs one probe interval of preference, never correctness."""
        now = time.monotonic()
        cached = self._residency_cache
        if cached is not None and now - cached[0] < self.RESIDENCY_CACHE_TTL:
            return cached[1]
        hbm: dict[str, set[int]] = {}
        host: dict[str, set[int]] = {}
        api = self.api
        mesh = getattr(getattr(api, "executor", None), "mesh_exec", None) \
            if api is not None else None
        if mesh is not None:
            with mesh._sc_lock:
                stack_keys = list(mesh._stack_cache.keys())
            for iname, _keys, shards in stack_keys:
                hbm.setdefault(iname, set()).update(int(s) for s in shards)
        if self.holder is not None:
            for iname, _f, _v, shard, frag in self.holder.iter_fragments():
                if frag._mirrors:
                    hbm.setdefault(iname, set()).add(shard)
                elif frag._stage is not None or frag._packed is not None:
                    host.setdefault(iname, set()).add(shard)
        out = {}
        cap = self.RESIDENCY_MAX_SHARDS
        for iname in set(hbm) | set(host):
            h = sorted(hbm.get(iname, set()))
            st = sorted(host.get(iname, set()) - hbm.get(iname, set()))
            entry = {"hbm": h[:cap], "host": st[:cap]}
            if len(h) > cap or len(st) > cap:
                entry["truncated"] = True
            out[iname] = entry
        self._residency_cache = (now, out)
        return out

    def local_load(self) -> dict:
        """This node's admission depth, piggybacked on /status and
        /internal/query responses for the router's load scores."""
        srv = self._server
        if srv is None:
            return {"inFlight": 0, "queued": 0}
        a = srv.admission.snapshot()
        b = srv.admission_internal.snapshot()
        return {"inFlight": a["inUse"] + b["inUse"],
                "queued": a["waiting"] + b["waiting"]}

    def wire_capabilities(self) -> list[str]:
        """The internal-query wire formats this node's handler accepts,
        advertised on /status for peer negotiation (docs/cluster.md
        "Internal query wire").  JSON is always accepted; bin1 only when
        the internal-wire knob allows it."""
        caps = [qwire.WIRE_JSON]
        if self.internal_wire == qwire.WIRE_BIN1:
            caps.append(qwire.WIRE_BIN1)
        return caps

    # -- peer data-version registry (result-cache keying) ------------------

    def note_peer_write(self, index: str, node_ids):
        """A write/import/repair was forwarded to these peers: their data
        (from our point of view) changed — bump their versions so cached
        cross-node results stop matching."""
        with self._gen_lock:
            for nid in node_ids:
                if nid == self.node_id:
                    continue
                self._peer_data_ver[(index, nid)] = \
                    self._peer_data_ver.get((index, nid), 0) + 1

    def note_peer_gens(self, index: str, nid: str, summary):
        """Fold a piggybacked gen summary (from an /internal/query
        response or a /status probe) into the registry; cache keys embed
        the last-seen summary, so a changed one stops every dependent
        entry from matching."""
        if summary is None:
            return
        with self._gen_lock:
            self._peer_gen_seen[(index, nid)] = tuple(summary)

    def _peer_seen_vector(self, index: str) -> tuple:
        """Last-seen per-peer gen summaries.  At FILL time this reflects
        the fan-out's own responses — i.e. it describes exactly the data
        the results were computed from."""
        with self._gen_lock:
            return tuple((n.id, self._peer_gen_seen.get((index, n.id)))
                         for n in self.nodes if n.id != self.node_id)

    def _peer_write_vector(self, index: str) -> tuple:
        with self._gen_lock:
            return tuple((n.id, self._peer_data_ver.get((index, n.id), 0))
                         for n in self.nodes if n.id != self.node_id)

    # -- shard discovery ---------------------------------------------------

    def forget_index_shards(self, index: str):
        """Drop remembered remote shard availability for a deleted
        index (both deletion paths — local API and cluster message —
        funnel here).  Overlay entries for the index go with it, WITH an
        epoch bump when any existed: every live node applies the same
        delete so they bump in lockstep, and a node that was DOWN (stale
        entries, stale epoch) is then behind the coordinator and gets
        the probe's overlay re-push — without the bump its stale entries
        would be unrepairable, and a recreated index would route reads
        at a phantom overlay owner."""
        with self._shards_lock:
            self._remote_shards.pop(index, None)
        with self._overlay_lock:
            dropped = [k for k in self._overlay if k[0] == index]
            for key in dropped:
                del self._overlay[key]
            if dropped:
                self.overlay_epoch += 1

    def _available_shards(self, index: str,
                          mark_down: bool = True,
                          on_error=None,
                          patient: bool = False) -> list[int]:
        """Union of local + peer available shards.  The reference gossips
        per-field available-shard bitmaps (field.go:263); with static
        membership we ask peers directly and fold the answer into
        remote-known shards so it converges without re-asking.
        ``mark_down=False`` for read-only informational callers (e.g.
        /internal/shards/max): a transient peer timeout there must not
        flip the cluster DEGRADED.  ``on_error``: optional
        ``(node_id, exc)`` callback — the anti-entropy pass surfaces
        these swallowed failures as DATA (a peer poll failing here used
        to mark the node DOWN, which silently empties every later peer
        loop in the pass; without the callback the whole pass would look
        like a clean no-op success).

        A poll failure routes through the PROBER's consecutive-miss
        accounting (_note_probe_failure) rather than marking the peer
        DOWN outright: one transient discovery timeout used to flip a
        READY node DOWN and silently shrink every later fan-out wave,
        bypassing the health-down-threshold discipline every other
        failure path honors.  A successful poll clears the miss streak
        exactly like a successful probe.

        ``patient=True`` disables the hedge-derived straggler grace:
        anti-entropy and resize need the COMPLETE answer (a shard
        missing from the remembered map would be silently skipped by a
        sync pass, or omitted from a resize's fetch lists — a one-shot
        data-placement gap), so they wait out slow polls; only the
        query path trades completeness for bounded discovery time."""
        idx = self.holder.index(index)
        shards = set(idx.available_shards()) if idx is not None else set()
        peers = [n for n in self.peers() if n.state == NODE_READY]
        # Polls run CONCURRENTLY with a bounded, deadline-clamped
        # timeout: this discovery step precedes every coordinator
        # fan-out, so a straggling peer must cost ONE bounded poll of
        # wall clock — not a serial sweep of default socket timeouts
        # (the tail-at-scale hole one layer above the fan-out itself).
        # task() re-installs the request's trace context so the poll's
        # outbound hop still carries the trace header.
        if peers:
            timeout = self._probe_timeout()
            ctx = current_ctx()
            if ctx is not None:
                rem = ctx.remaining()
                if rem is not None:
                    timeout = max(min(timeout, rem + 0.05), 0.05)
            try:
                futs = [(n, self._pool.submit(
                    GLOBAL_TRACER.task(self.client.available_shards),
                    n.host, index, timeout)) for n in peers]
            except RuntimeError:
                futs = []  # pool shut down: close() raced this query
            # Straggler grace: wait up to the hedge delay, then stop
            # BLOCKING on slow polls — the remembered map serves the
            # query (exactly the long-standing poll-FAILURE semantic,
            # reached in bounded time), and the abandoned poll still
            # completes in the background, folding its answer into the
            # map for the next query.  Writes this coordinator acked
            # are never at risk: forwarding already recorded their
            # shards in the per-field remote sets at ack time.  With
            # hedging off (or a cold EWMA), polls stay fully patient.
            grace = self.router.hedge_delay(
                max(self.hedge_delay_ms, 0.0) / 1e3) \
                if not patient and self.hedge_reads and futs else None
            pending = {fut: n for n, fut in futs}
            if pending:
                done, _slow = futures_wait(set(pending), timeout=grace)
                for fut in list(pending):
                    if fut in done:
                        self._fold_poll(index, pending.pop(fut), fut,
                                        mark_down, on_error)
                for fut, n in pending.items():
                    fut.add_done_callback(
                        self._poll_finalizer(index, n, mark_down,
                                             on_error))
        # include every shard ever reported by a peer: a DOWN owner's
        # shards must stay in the query's scope so the fan-out surfaces
        # the failure instead of silently returning partial results
        with self._shards_lock:
            shards |= self._remote_shards.get(index, set())
        return sorted(shards)

    def _fold_poll(self, index: str, n: Node, fut, mark_down: bool,
                   on_error):
        """Fold one completed available-shards poll into the remembered
        map + the prober's miss accounting (shared by the in-grace and
        background-completion paths)."""
        try:
            got = fut.result()
        except Exception as e:
            if on_error is not None:
                on_error(n.id, e)
            if mark_down:
                self._note_probe_failure(n, e)
                self._update_state()
            return
        if n.state == NODE_READY:
            n.probe_fails = 0
        with self._shards_lock:
            self._remote_shards.setdefault(index, set()).update(got)

    def _poll_finalizer(self, index: str, n: Node, mark_down: bool,
                        on_error):
        """Done-callback for a poll its query stopped waiting on (the
        straggler grace elapsed): the late answer still converges the
        remembered map, and a real failure still counts its miss."""
        def _done(fut):
            self._fold_poll(index, n, fut, mark_down, on_error)
        return _done

    # -- query fan-out (executor.go:2455 mapReduce) ------------------------

    def execute(self, index: str, query, shards=None,
                ctx=None) -> list[Any]:
        """``ctx``: optional QueryContext (utils/deadline.py); installed
        as the current context for the whole fan-out so remotes inherit
        the remaining budget and retry waves abort once it expires."""
        from ..utils.deadline import activate
        if ctx is None:
            ctx = current_ctx()
        with activate(ctx):
            return self._execute_ctx(index, query, shards)

    def _execute_ctx(self, index: str, query, shards) -> list[Any]:
        if isinstance(query, str):
            with qprof.stage("parse"):
                query = parse(query)
        if self.holder.index(index) is None:
            from ..api import NotFoundError
            raise NotFoundError(f"index not found: {index}")
        # Reject writes while RESIZING BEFORE translation: a create-on-
        # miss key lookup for a rejected write must not durably mutate
        # the replicated translate stores mid-resize.
        if self.state == STATE_RESIZING:
            writes = sorted({name for c in query.calls
                             for name in self._write_names(c)})
            if writes:
                from ..api import DisallowedError
                raise DisallowedError(
                    f"write calls {writes} are blocked while the cluster "
                    f"is resizing (reads keep serving)")
        # key translation happens ONCE at the coordinating node; fanned-out
        # internal calls carry ids only (executor.go:147 skips
        # translateCalls when opt.Remote)
        translator = self.api.executor.translator
        with qprof.stage("translate"):
            query = translator.translate_query(index, query)
        if shards is None:
            shards = self._available_shards(index)
        # Coordinator-scope result cache: keyed on the NORMALIZED plan
        # repr (post-translation), the shard set, the local fragment
        # generation vector, and the per-peer data versions (see
        # note_peer_write/note_peer_gens) — so local mutations, forwarded
        # writes, and peer-reported gen changes all structurally
        # invalidate (cache/results.py).
        qkey = local_part = None
        cache = self.api.executor.result_cache
        if cache is not None and cache.limit_bytes > 0:
            from ..core import attr_epoch, schema_epoch
            from ..cache.results import gen_vector, query_is_readonly
            if query_is_readonly(query):
                qkey = ("cluster", index, repr(query), tuple(shards))
                # local gens/epochs and the per-peer WRITE versions are
                # captured here and reused verbatim at fill time: a write
                # landing during the fan-out must key the entry to the
                # PRE-write state (so it never matches again), not be
                # masked by a post-write re-read of the counters
                local_part = (gen_vector(self.holder, index),
                              schema_epoch(), attr_epoch(),
                              self._peer_write_vector(index))
                with qprof.stage("resultcache.lookup") as pnode:
                    out = cache.lookup(
                        qkey + local_part
                        + (self._peer_seen_vector(index),))
                    if pnode is not None:
                        pnode.tags["outcome"] = \
                            "hit" if out is not None else "miss"
                        pnode.tags["scope"] = "cluster"
                qexplain.note("caches", {
                    "cache": "result", "scope": "cluster",
                    "outcome": "hit" if out is not None else "miss",
                    "key": {"index": index, "shards": len(shards),
                            "genVector": hash(local_part[0]) & 0xFFFFFFFF,
                            "peerWriteVector": hash(local_part[3])
                            & 0xFFFFFFFF}})
                if out is not None:
                    return out
        if len(query.calls) > 1 and \
                all(self._batchable_read(c) for c in query.calls):
            results = self._execute_calls_batched(index, query.calls,
                                                  shards)
        else:
            from ..utils.deadline import check_current
            results = []
            for c in query.calls:
                check_current("cluster call dispatch")
                results.append(self._execute_call(index, c, shards))
        if translator.needs_translation(index):
            results = translator.translate_results(index, query.calls,
                                                   results)
        if qkey is not None and not degraded.is_degraded():
            # Fill key = lookup-time local state + the peer gen summaries
            # AS OBSERVED by this fan-out's responses.  Only the seen
            # vector is re-read: the responses describe exactly the data
            # the results came from (so the first warm repeat hits),
            # while everything captured at lookup time guarantees a
            # concurrent write's invalidation can never be overwritten.
            # A DEGRADED answer — shards lost under partialResults OR
            # quarantined fragments answering empty — is never cached: a
            # later healthy repeat must recompute, not serve the
            # degraded result (is_partial alone would memoize the
            # quarantined case).
            cache.fill(qkey, qkey + local_part +
                       (self._peer_seen_vector(index),), results,
                       tenant=qtenant.current_or_none())
        return results

    @classmethod
    def _write_names(cls, c: Call):
        """Write-call names inside ``c``, looking through Options
        wrappers (Options(Set(...)) must not slip past the resize write
        block)."""
        from ..executor.executor import WRITE_CALLS
        if c.name in WRITE_CALLS:
            yield c.name
        elif c.name == "Options":
            for ch in c.children:
                yield from cls._write_names(ch)

    def _batchable_read(self, c: Call) -> bool:
        """Calls whose cluster fan-out can ride one multi-call POST per
        node (plus one shared second phase for bounded TopN).  Writes
        must keep execution order, Options can override shards per call,
        and TopN extras need the coordinator's global finalize — those
        stay on the per-call path."""
        from ..executor.executor import WRITE_CALLS
        if c.name in WRITE_CALLS or c.name == "Options":
            return False
        if c.name == "TopN" and any(k in c.args for k in TOPN_EXTRAS):
            return False
        return True

    def _execute_calls_batched(self, index: str, calls, shards):
        """Fan a multi-call read query out as ONE pinned POST per owner
        node — each node answers the whole batch in one device wave via
        its executor's grouped/prepared path — plus one shared second
        wave finishing every bounded TopN.  The r4 distributed bench paid
        one dispatch round trip per call per phase (a 16-call batch = 32
        sequential device RTTs per node); this is the same reduce
        semantics (executor.go:2455 mapReduce, :879 TopN two-phase) at
        two RTTs per batch."""
        stats = self.api.stats
        two_phase: set[int] = set()
        phase1: list[Call] = []
        for i, c in enumerate(calls):
            if c.name == "TopN" and "n" in c.args:
                if c.args.get("n") and "ids" not in c.args and \
                        len(self.nodes) > 1:
                    two_phase.add(i)
                    phase1.append(self._topn_phase1_call(c))
                else:
                    # exact path: n applies at reduce, nodes must not
                    # truncate rows whose count only wins globally
                    p = c.clone()
                    del p.args["n"]
                    phase1.append(p)
            else:
                phase1.append(c)
        grouped = self._fan_out_multi(index, phase1, shards)
        results: list[Any] = [None] * len(calls)
        phase2: list[tuple[int, Call]] = []
        with stats.timer("cluster.multi.reduce"), qprof.stage("reduce"):
            for i, c in enumerate(calls):
                if i in two_phase:
                    cands = sorted({p.id for r in grouped[i] for p in r})
                    if not cands:
                        results[i] = []
                        continue
                    phase2.append((i, self._topn_phase2_call(c, cands)))
                else:
                    results[i] = self._reduce(index, c, grouped[i])
        if phase2:
            r2 = self._fan_out_multi(index, [p for _, p in phase2],
                                     shards)
            with stats.timer("cluster.multi.reduce"), \
                    qprof.stage("reduce"):
                for (i, _p2), rr in zip(phase2, r2):
                    results[i] = self._topn_finalize(calls[i], rr)
        return results

    def _fan_out_multi(self, index: str, calls: list[Call],
                       shards: list[int]) -> list[list[Any]]:
        """Fan one pinned multi-call query to shard owners, tail-
        tolerantly (docs/robustness.md "Tail-tolerant fan-out"); returns
        per-call lists of group results.

        Responses are consumed AS THEY COMPLETE: a failed owner's shards
        re-dispatch to a replica immediately, while other peers are
        still in flight, instead of after the whole wave drains.  A
        straggling-but-alive peer gets a HEDGE — after its hedge delay
        (hedge-delay-ms, or EWMA-derived; parallel/routing.py) the same
        call set speculatively duplicates to the next-best replica and
        the first answer wins, the loser is ignored.  Safe because every
        call through this path is an idempotent internal read — writes
        fan out through their own replica-synchronous paths and are
        never hedged.  Shards whose every replica is exhausted either
        fail the query loudly (with a per-node attempt log on the error
        and a ``cluster.fanout_failed`` event) or, when the request
        opted into partial results (utils/degraded.py), degrade to a
        partial answer that names exactly the missing shards.

        Per-node wire overhead (POST elapsed minus the peer's reported
        execution time) and peer execution time feed /debug/vars for the
        distributed latency breakdown."""
        stats = self.api.stats
        out: list[list[Any]] = [[] for _ in calls]
        q = Query(list(calls))
        if not shards:
            for i, r in enumerate(self.api.executor.execute(
                    index, q, [], translate=False)):
                out[i].append(r)
            return out
        ctx = current_ctx()
        # a shard group may be re-dispatched at most this many times —
        # the same bound the old whole-wave retry loop enforced
        max_wave = len(self.nodes) + 1
        hedge_enabled = self.hedge_reads and len(self.nodes) > 1
        hedge_fixed_s = max(self.hedge_delay_ms, 0.0) / 1e3
        exclude: set[str] = set()
        remaining: set[int] = {int(s) for s in shards}
        failed_nodes: set[str] = set()
        attempts: list[dict] = []  # per-node attempt log (error surface)
        last_err: Exception | None = None
        partial_counted = False
        hedges_fired = 0  # this query's speculative duplicates
        # one in-flight dispatch per future.  First-answer-wins is
        # per-SHARD-SET with all-or-nothing acceptance: a flight's
        # results are per-group AGGREGATES (a Count over its whole
        # shard list) and can never be split, so a completed flight is
        # accepted only when EVERY one of its shards is still
        # unanswered; otherwise it is discarded whole and any leftover
        # shards nothing else covers re-dispatch.  `cover` counts the
        # in-flight flights per shard so a failure only re-dispatches
        # shards no surviving twin still covers.
        inflight: dict[Any, dict] = {}  # future -> flight dict
        cover: dict[int, int] = {}

        def submit(nid: str, nshards: list[int], wave: int,
                   hedge: bool = False):
            for s in nshards:
                cover[s] = cover.get(s, 0) + 1
            # remotes inherit the coordinator's REMAINING budget (wire
            # header + clamped socket timeout), recomputed per dispatch
            # so retries and hedges inherit the shrunken budget
            deadline_s = ctx.remaining() if ctx is not None else None
            # deadline rides as an extra arg ONLY when a budget is set,
            # so the un-budgeted call convention stays stable
            args = (self.by_id[nid].host, index, calls, list(nshards))
            if deadline_s is not None:
                args += (deadline_s,)
            # router feed: coordinator-observed in-flight depth and the
            # per-shard load counters the balancer watches
            self.router.note_dispatch(nid, len(nshards))
            self.load_tracker.note(index, nshards, nid)
            qexplain.note("dispatch", {
                "node": nid, "shards": [int(s) for s in nshards[:64]],
                "wave": wave, "hedge": hedge})

            # the router's RTT sample is timed INSIDE the pool worker:
            # the consumption loop's elapsed also counts local execution
            # and other peers' result waits, which would systematically
            # inflate remote scores vs local
            def timed_rpc(*a, _fn=self.client.query_calls):
                t = time.perf_counter()
                return _fn(*a), time.perf_counter() - t

            hedge_at = None
            if hedge_enabled and not hedge:
                d = self.router.hedge_delay(hedge_fixed_s)
                if d is not None:
                    hedge_at = time.perf_counter() + d
            span_tags = {"host": self.by_id[nid].host}
            if hedge:
                span_tags["hedge"] = True
            # task(): the pool worker re-installs this thread's trace
            # context and runs the RPC under a per-peer client span —
            # the injected header then carries that span's id, so the
            # remote's spans parent under it (docs/observability.md)
            fut = self._pool.submit(
                GLOBAL_TRACER.task(timed_rpc,
                                   name=f"cluster.rpc {nid}",
                                   **span_tags),
                *args)
            inflight[fut] = {"nid": nid,
                             "shards": tuple(int(s) for s in nshards),
                             "wave": wave, "hedge": hedge,
                             "hedged": False,
                             "t0": time.perf_counter(),
                             "hedge_at": hedge_at}

        def run_local(nshards: list[int], wave: int):
            self.router.note_dispatch(self.node_id, len(nshards))
            self.load_tracker.note(index, nshards, self.node_id)
            qexplain.note("dispatch", {
                "node": self.node_id,
                "shards": [int(s) for s in nshards[:64]],
                "wave": wave, "local": True})
            t_local = time.perf_counter()
            try:
                with stats.timer("cluster.multi.local_exec"), \
                        qprof.stage("local_exec"):
                    for i, r in enumerate(self.api.executor.execute(
                            index, q, list(nshards), translate=False)):
                        out[i].append(r)
            finally:
                self.router.note_done(
                    self.node_id, time.perf_counter() - t_local)
            remaining.difference_update(int(s) for s in nshards)

        def unservable(shard_set: set[int], exhausted: bool):
            """Every replica of these shards is gone: degrade to a
            partial answer when the request opted in, else raise with
            the per-node attempt log attached."""
            nonlocal partial_counted
            if ctx is not None:
                ctx.check("cluster fan-out")  # expired -> 504, not 500
            if degraded.partial_allowed():
                degraded.note_missing(index, shard_set, failed_nodes)
                if not partial_counted:
                    stats.count("cluster.partial_results")
                    partial_counted = True
                self._fanout_event(index, shard_set, attempts,
                                   partial=True)
                remaining.difference_update(shard_set)
                return
            self._fanout_event(index, shard_set, attempts, partial=False)
            base = "query retries exhausted" if exhausted else \
                (f"no replicas available for shards "
                 f"{sorted(shard_set)} of {index!r}")
            err = ClusterError(base + self._attempts_suffix(attempts))
            err.attempts = list(attempts)
            raise err from last_err

        def dispatch_shards(shard_set: set[int], wave: int):
            if wave >= max_wave:
                unservable(shard_set, exhausted=True)
                return
            if wave > 0:
                stats.count("cluster.retry_waves")
            nonlocal last_err
            try:
                groups = self._group_shards(index, sorted(shard_set),
                                            exclude)
            except ClusterError as e:
                # re-admit owners that failed with an APPLICATION error
                # (they responded — still READY): one failure is not
                # death, so they get another pass.  Transport-failed
                # owners were marked DOWN and stay excluded — a dead or
                # partitioned sole owner must fail after ONE timeout,
                # not len(nodes)+1 of them.
                readmit = {nid for nid in exclude
                           if self.by_id[nid].state == NODE_READY}
                if not readmit:
                    last_err = e
                    unservable(shard_set, exhausted=False)
                    return
                exclude.difference_update(readmit)
                try:
                    groups = self._group_shards(index, sorted(shard_set),
                                                exclude)
                except ClusterError as e2:
                    last_err = e2
                    unservable(shard_set, exhausted=False)
                    return
            local_shards = groups.pop(self.node_id, None)
            for nid, nshards in groups.items():
                submit(nid, nshards, wave)
            if local_shards is not None:
                run_local(local_shards, wave)

        def record_failure(fl: dict, e: Exception, down: bool):
            nonlocal last_err
            last_err = e
            attempts.append({"node": fl["nid"], "wave": fl["wave"],
                             "hedge": fl["hedge"],
                             "shards": len(fl["shards"]),
                             "error": f"{type(e).__name__}: {e}"})
            failed_nodes.add(fl["nid"])
            self.router.note_done(fl["nid"], None, ok=False)
            if down:
                self._mark_down(fl["nid"])
            exclude.add(fl["nid"])
            # re-dispatch only the shards no surviving twin (hedge or
            # primary) still covers — a still-flying duplicate gets to
            # answer before another retry burns a wave
            retry = {s for s in fl["shards"]
                     if s in remaining and cover.get(s, 0) == 0}
            if retry:
                dispatch_shards(retry, fl["wave"] + 1)

        def accept(fl: dict, res, exec_s, peer_gens, peer_quarantined,
                   peer_load, rtt):
            self.router.note_done(fl["nid"], rtt)
            self.router.note_query_load(fl["nid"], peer_load)
            unanswered = [s for s in fl["shards"] if s in remaining]
            if len(unanswered) != len(fl["shards"]):
                # a racing flight (hedge winner / replica retry) already
                # answered part of this group.  The group's results are
                # aggregates over its WHOLE shard list — they cannot be
                # split — so discard them entirely, and re-dispatch any
                # leftover shards nothing else still covers (rare: only
                # a lost race can produce leftovers, so progress was
                # made elsewhere and this terminates)
                leftover = {s for s in unanswered
                            if cover.get(s, 0) == 0}
                if leftover:
                    dispatch_shards(leftover, fl["wave"])
                return
            if fl["hedge"]:
                stats.count("cluster.hedge_wins")
                self.router.note_hedge_win(fl["nid"])
                qexplain.note("hedges", {"outcome": "won",
                                         "node": fl["nid"],
                                         "shards": len(fl["shards"])})
            if peer_quarantined:
                # peer answered with quarantined fragments serving
                # empty: surface it on THIS response (consumed on the
                # request thread, where the handler's degraded
                # collector is active)
                degraded.note(peer_quarantined)
            elapsed = time.perf_counter() - fl["t0"]
            stats.timing("cluster.multi.peer_exec", exec_s)
            stats.timing("cluster.multi.wire_overhead",
                         max(elapsed - exec_s, 0.0))
            # per-peer fan-out RTT in the profile tree: total round
            # trip, the peer's own execution time, and the wire/
            # serialization overhead between them
            qprof.event(f"peer.{fl['nid']}", elapsed,
                        shards=len(fl["shards"]),
                        peerExecS=round(exec_s, 6),
                        wireS=round(max(elapsed - exec_s, 0.0), 6))
            self.note_peer_gens(index, fl["nid"], peer_gens)
            for i, r in enumerate(res):
                out[i].append(r)
            remaining.difference_update(fl["shards"])

        try:
            # the initial dispatch runs INSIDE the finalizer scope: if
            # local execution (or a mid-submit pool shutdown) raises
            # while remote RPCs are already flying, their router
            # in-flight depth must still unwind via the done-callbacks
            dispatch_shards(remaining.copy(), 0)
            # run until every shard is answered or abandoned — NOT until
            # every future drains: once a hedge (or a replica retry) has
            # answered a group, its loser must not hold the query open
            while remaining:
                if not inflight:
                    # unanswered shards with nothing flying: fail or
                    # degrade (clears `remaining` either way)
                    unservable(remaining.copy(), exhausted=True)
                    continue
                if ctx is not None:
                    ctx.check("cluster fan-out")
                # wake for whichever comes first: a completion, the
                # next hedge deadline, or the query deadline
                timeout = None
                if hedge_enabled:
                    now = time.perf_counter()
                    due = [fl["hedge_at"] - now
                           for fl in inflight.values()
                           if fl["hedge_at"] is not None
                           and not fl["hedge"] and not fl["hedged"]]
                    if due:
                        timeout = max(0.0, min(due))
                if ctx is not None:
                    rem = ctx.remaining()
                    if rem is not None:
                        rem = max(rem, 0.001)
                        timeout = rem if timeout is None \
                            else min(timeout, rem)
                done, _still = futures_wait(set(inflight),
                                            timeout=timeout,
                                            return_when=FIRST_COMPLETED)
                for fut in done:
                    fl = inflight.pop(fut)
                    for s in fl["shards"]:
                        cover[s] = cover.get(s, 1) - 1
                    try:
                        ((res, exec_s, peer_gens, peer_quarantined,
                          peer_load), rtt) = fut.result()
                    except CircuitOpenError as e:
                        # fail-fast: the peer's breaker is open (N
                        # consecutive transport failures) — treat like
                        # a dead node, not an application error from a
                        # live one.  (The router pre-skips open
                        # breakers, so this only fires when EVERY
                        # candidate was open or the breaker opened
                        # mid-flight.)
                        record_failure(fl, e, down=True)
                    except ClusterError as e:
                        # the peer RESPONDED (HTTP error): it is alive,
                        # so an application-level failure must not
                        # poison membership — just retry these shards
                        # on a replica
                        record_failure(fl, e, down=False)
                    except Exception as e:
                        record_failure(fl, e, down=True)
                    else:
                        accept(fl, res, exec_s, peer_gens,
                               peer_quarantined, peer_load, rtt)
                if hedge_enabled and remaining and inflight:
                    now = time.perf_counter()
                    for fl in list(inflight.values()):
                        if (fl["hedge"] or fl["hedged"]
                                or fl["hedge_at"] is None
                                or now < fl["hedge_at"]):
                            continue
                        fl["hedged"] = True  # at most one hedge round
                        hedge_shards = [s for s in fl["shards"]
                                        if s in remaining]
                        if not hedge_shards:
                            continue
                        # Per-tenant hedge budget (docs/robustness.md
                        # "Tenant isolation"): each hedge round draws a
                        # token from the requesting tenant's bucket; an
                        # exhausted bucket keeps the read UNHEDGED —
                        # counted and visible, never an error — so one
                        # tenant's straggler storm cannot amplify its
                        # own load onto the fleet.
                        hedge_tenant = qtenant.current()
                        if not self.hedge_budget.try_take(hedge_tenant):
                            stats.count("cluster.hedge_budget_denied")
                            stats.count(
                                f"tenant.{hedge_tenant}.hedge_denied")
                            qtenant.REGISTRY.note_hedge_denied(
                                hedge_tenant)
                            qexplain.note("hedges", {
                                "outcome": "budget_denied",
                                "tenant": hedge_tenant,
                                "insteadOf": fl["nid"],
                                "shards": len(hedge_shards)})
                            continue
                        excl = exclude | {fl["nid"]}
                        # cheapest shape first: ONE replica owning the
                        # whole group duplicates it in a single RPC;
                        # otherwise split by the router's own grouping
                        # so every shard still gets a speculative
                        # second chance (jump-hash rarely gives a big
                        # group one common alternate owner)
                        target = self.router.hedge_candidate(
                            index, hedge_shards, excl)
                        if target is not None:
                            groups = {target: list(hedge_shards)}
                        else:
                            try:
                                groups = self._group_shards(
                                    index, sorted(hedge_shards), excl)
                            except ClusterError:
                                continue  # nobody can hedge this group
                            # hedges go to REMOTE replicas only: local
                            # execution is not a network-straggler
                            # path, and running it inline here would
                            # stall consumption of completed responses
                            groups.pop(self.node_id, None)
                        for nid, nshards in groups.items():
                            stats.count("cluster.hedges")
                            self.router.note_hedge(nid)
                            qexplain.note("hedges", {
                                "outcome": "fired", "node": nid,
                                "insteadOf": fl["nid"],
                                "shards": len(nshards)})
                            hedges_fired += 1
                            if hedges_fired == self.HEDGE_STORM_MIN:
                                # one query speculating this widely is a
                                # tail-latency incident, not routine
                                # hedging — journal it once per query
                                events.emit("cluster.hedge_storm",
                                            index=index,
                                            hedges=hedges_fired)
                            submit(nid, nshards, fl["wave"],
                                   hedge=True)
        finally:
            # abandoned flights (hedge-race losers, RPCs still flying
            # when the query finished/raised/expired): finalize their
            # router bookkeeping off-thread — the in-flight depth must
            # unwind, and a straggler's TRUE RTT still feeds its EWMA
            # (how the router learns the peer is slow)
            for fut, fl in list(inflight.items()):
                fut.add_done_callback(self._flight_finalizer(fl))
        return out

    def _flight_finalizer(self, fl: dict):
        """Done-callback for a fan-out flight its query abandoned (a
        hedge race loser, or any RPC still in flight when the query
        completed, raised, or hit its deadline).  Runs on the pool
        worker: only router bookkeeping — never the query's own state,
        which may already be serialized and gone."""
        def _done(fut):
            try:
                ((_res, _exec_s, _gens, _quar, load),
                 rtt) = fut.result()
            except Exception:
                # the query already finished without this flight; the
                # router's error counter (note_done ok=False) is the
                # only consumer of the outcome
                self.router.note_done(fl["nid"], None, ok=False)
            else:
                self.router.note_done(fl["nid"], rtt)
                self.router.note_query_load(fl["nid"], load)
        return _done

    @staticmethod
    def _format_attempt(a: dict) -> str:
        """One attempt-log entry as 'node waveN [hedge]: error' — the
        shared format of the error suffix and the structured event."""
        return (f"{a['node']} wave{a['wave']}"
                + (" hedge" if a["hedge"] else "")
                + f": {a['error']}")

    @staticmethod
    def _attempts_suffix(attempts: list[dict]) -> str:
        """Human-readable per-node attempt trail for fan-out errors —
        'which node failed how, in which wave' used to be discarded."""
        if not attempts:
            return ""
        tail = attempts[-8:]
        parts = [Cluster._format_attempt(a) for a in tail]
        more = f" (+{len(attempts) - len(tail)} earlier)" \
            if len(attempts) > len(tail) else ""
        return " [attempts: " + "; ".join(parts) + more + "]"

    def _fanout_event(self, index: str, shard_set, attempts: list[dict],
                      partial: bool):
        """Structured ``cluster.fanout_failed`` event: the per-node
        failure detail that used to vanish into a bare ClusterError."""
        if self.stats is not None:
            self.stats.count("cluster.fanout_failed")
        logger = self.logger
        if logger is None:
            return
        try:
            logger.event(
                "cluster.fanout_failed", index=index,
                shards=sorted(int(s) for s in shard_set)[:64],
                partial=partial,
                attempts="; ".join(
                    self._format_attempt(a) for a in attempts[-8:]))
        # lint: allow(swallowed-exception) — telemetry must never fail
        # the query path (the PR 8 retrace-sink lesson); the error
        # itself still raises/degrades through the caller
        except Exception:
            pass

    def _execute_call(self, index: str, c: Call, shards: list[int]):
        if c.name in ("Set", "Clear"):
            return self._execute_col_write(index, c)
        if c.name in ("Store", "ClearRow"):
            return self._execute_all_nodes_write(index, c, shards)
        if c.name in ("SetRowAttrs", "SetColumnAttrs"):
            return self._execute_attr_write(index, c)
        if c.name == "Options":
            return self._execute_options(index, c, shards)
        return self._execute_read(index, c, shards)

    def _execute_options(self, index: str, c: Call, shards: list[int]):
        """Unwrap Options at the coordinator: fan out the CHILD call (so
        per-call reduce semantics — Count sum, ValCount add, TopN
        n-stripping — apply to the real call, not the wrapper) and shape
        the merged result here (executor.go:340-403; attr stores are
        replicated on every node)."""
        from ..executor.executor import Executor

        if len(c.children) != 1:
            raise ClusterError("Options() requires exactly one child")
        if "shards" in c.args:
            if not isinstance(c.args["shards"], list):
                raise ClusterError("Options() shards must be a list")
            shards = [int(s) for s in c.args["shards"]]
        exclude_columns = Executor._options_bool(c, "excludeColumns")
        column_attrs = Executor._options_bool(c, "columnAttrs")
        exclude_row_attrs = Executor._options_bool(c, "excludeRowAttrs")
        result = self._execute_call(index, c.children[0], shards)
        if isinstance(result, RowResult):
            if exclude_columns:
                result.segments = {}
            if column_attrs:
                Executor.attach_column_attrs(self.holder, index, result)
            if exclude_row_attrs:
                result.attrs = {}
        return result

    def _local_exec(self, index: str, c: Call, shards: list[int]):
        return self.api.executor.execute(index, Query([c]), shards,
                                         translate=False)[0]

    def _ready_owner_order(self, index: str, shard: int) -> list[str]:
        owners = self.shard_owner_nodes(index, shard)
        ready = [o for o in owners if self.by_id[o].state == NODE_READY]
        return ready or owners

    def _group_shards(self, index: str,
                      shards: list[int],
                      exclude: set[str] = frozenset()) -> dict[str, list]:
        """shard -> executor node, chosen by the read router
        (parallel/routing.py): ``read-routing=primary`` reproduces the
        legacy grouping — self if it owns the shard, else the first
        READY owner (executor.go:2435 shardsByNode) — while
        ``round-robin``/``loaded`` spread reads across replicas."""
        return self.router.group_shards(index, shards, exclude)

    def _execute_topn_extras(self, index: str, c: Call, shards: list[int]):
        """TopN with tanimoto/attr filtering, finalized GLOBALLY at the
        coordinator: per-node tanimoto on node-local counts would keep or
        drop different rows than a single node holding all the data.  Fans
        out raw filtered counts (plus, for tanimoto, the unfiltered counts
        and the source-row count), then applies Executor._topn_finalize on
        the merged totals (fragment.go:1704 semantics, exact)."""
        from ..executor.executor import Executor, topn_extras

        tan_thresh, attr_name, attr_values = topn_extras(c)
        base = c.clone()
        for k in TOPN_EXTRAS + ("n",):
            base.args.pop(k, None)
        pairs = self._execute_read(index, base, shards)
        row_tot = np.zeros(0, dtype=np.int64)
        src = 0
        if tan_thresh:
            unfiltered = base.clone()
            unfiltered.children = []
            pairs_u = self._execute_read(index, unfiltered, shards)
            src = self._execute_read(
                index, Call("Count", children=[c.children[0].clone()]),
                shards)
            for p in pairs_u:
                if p.id >= row_tot.size:
                    grown = np.zeros(p.id + 1, dtype=np.int64)
                    grown[: row_tot.size] = row_tot
                    row_tot = grown
                row_tot[p.id] = p.count
        size = 1 + max((p.id for p in pairs), default=0)
        counts = np.zeros(size, dtype=np.int64)
        for p in pairs:
            counts[p.id] = p.count
        n, _ = c.uint_arg("n")
        field_name, _ = c.string_arg("_field")
        field = self.holder.field(index, field_name)
        return Executor._topn_finalize(
            counts, row_tot, src, c.args.get("ids"), n, tan_thresh,
            attr_name, attr_values, field)

    @staticmethod
    def _topn_phase1_call(c: Call) -> Call:
        """Phase-1 candidate call: per-node top list with 4x slack
        (executor.go:879-899).  APPROXIMATE like the reference's
        cache-based phase 1: a row can rank below every node's candidate
        cutoff yet sum into the global top k; the slack makes that
        require a pathologically skewed distribution, and the counts
        reported for returned rows are always exact (phase 2)."""
        n, _ = c.uint_arg("n")
        phase1 = c.clone()
        phase1.args["n"] = max(4 * n, n + 16)
        return phase1

    @staticmethod
    def _topn_phase2_call(c: Call, candidates: list[int]) -> Call:
        """Phase-2 exact-recount call over the candidate union."""
        phase2 = c.clone()
        del phase2.args["n"]
        phase2.args["ids"] = candidates
        return phase2

    @staticmethod
    def _topn_finalize(c: Call, group_results) -> list:
        """Merge phase-2 per-group pairs and apply the original n."""
        n, _ = c.uint_arg("n")
        merged = merge_pairs(group_results)
        return sort_pairs([p for p in merged if p.count > 0], n or None)

    def _execute_topn_two_phase(self, index: str, c: Call,
                                shards: list[int]):
        """TopN(n=k) across nodes in two bounded phases: phase 1 fans
        out a per-node candidate top list — each node ships O(k) pairs,
        not every nonzero row — and phase 2 re-fetches exact global
        counts for the union of candidate ids (see _topn_phase1_call)."""
        results = []
        for r in self._fan_out_read(index, self._topn_phase1_call(c),
                                    shards):
            results.extend(r)
        candidates = sorted({p.id for p in results})
        if not candidates:
            return []
        return self._topn_finalize(c, self._fan_out_read(
            index, self._topn_phase2_call(c, candidates), shards))

    def _execute_read(self, index: str, c: Call, shards: list[int]):
        send = c
        if c.name == "TopN" and \
                any(k in c.args for k in TOPN_EXTRAS):
            return self._execute_topn_extras(index, c, shards)
        if c.name == "TopN" and "n" in c.args:
            if c.args.get("n") and "ids" not in c.args \
                    and len(self.nodes) > 1:
                # bounded two-phase protocol; n=0 (unlimited), explicit
                # ids, and single-node clusters take the exact path below
                return self._execute_topn_two_phase(index, c, shards)
            # exact path: strip the limit so no node truncates rows whose
            # global count only wins across nodes; n applies at reduce
            send = c.clone()
            del send.args["n"]
        return self._reduce(index, c,
                            self._fan_out_read(index, send, shards))

    def _fan_out_read(self, index: str, send: Call,
                      shards: list[int]) -> list[Any]:
        """Fan a pinned read call out to shard owners with replica retry;
        returns the per-group raw results (executor.go:2455 mapReduce).
        The single-call case of ``_fan_out_multi`` — one retry/owner-
        grouping machinery, not two."""
        return self._fan_out_multi(index, [send], shards)[0]

    # -- writes ------------------------------------------------------------

    def _require_ready(self, node_ids, what: str):
        """Writes need every replica reachable: silently skipping a DOWN
        owner would lose the write on that replica (and union-only
        anti-entropy could later resurrect cleared bits from it).  The
        reference likewise surfaces replica-write failures
        (executor.go:2156-2166 remoteExec error propagation)."""
        down = [nid for nid in node_ids
                if nid != self.node_id
                and self.by_id[nid].state != NODE_READY]
        if down:
            raise ClusterError(
                f"cannot {what}: replica node(s) {down} unavailable")

    def _execute_col_write(self, index: str, c: Call):
        """Set/Clear: fan to every replica of the column's shard
        (executor.go:2137-2166)."""
        col = c.args.get("_col")
        if not isinstance(col, int) or isinstance(col, bool):
            return self._local_exec(index, c, [])
        shard = col // SHARD_WIDTH
        owners = self.shard_owner_nodes(index, shard)
        self._require_ready(owners, f"write shard {shard} of {index!r}")
        self.note_peer_write(index, owners)
        futures = []
        for nid in owners:
            if nid != self.node_id:
                futures.append(self._pool.submit(
                    GLOBAL_TRACER.task(self.client.query_call),
                    self.by_id[nid].host, index, c, [shard]))
        result = self._local_exec(index, c, [shard]) \
            if self.node_id in owners else None
        remote = None
        for f in futures:
            remote = f.result()  # raise on replica-write failure
        return result if result is not None else remote

    def _execute_all_nodes_write(self, index: str, c: Call,
                                 shards: list[int]):
        """Store/ClearRow touch every owned fragment on every node."""
        involved = [n.id for n in self.nodes
                    if self.owned_shards(n.id, index, shards)]
        self._require_ready(involved, f"{c.name} on {index!r}")
        self.note_peer_write(index, involved)
        changed = False
        futures = []
        for n in self.nodes:
            owned = self.owned_shards(n.id, index, shards)
            if not owned or n.id == self.node_id:
                continue
            futures.append(self._pool.submit(
                GLOBAL_TRACER.task(self.client.query_call),
                n.host, index, c, owned))
        local_owned = self.owned_shards(self.node_id, index, shards)
        if local_owned:
            changed = bool(self._local_exec(index, c, local_owned))
        for f in futures:
            changed = bool(f.result()) or changed
        return changed

    def _execute_attr_write(self, index: str, c: Call):
        """Attr stores are replicated on every node (executor.go:2207
        SetRowAttrs local write + broadcast).  Requires every node READY —
        a DOWN peer silently skipped would diverge permanently since DDL
        replay doesn't carry attrs; anti-entropy attr sync repairs the
        divergence a mid-fan-out failure can still leave."""
        self._require_ready([n.id for n in self.nodes],
                            f"{c.name} on {index!r}")
        self.note_peer_write(index, [n.id for n in self.peers()])
        # local write FIRST: if it fails, no peer has diverged yet
        out = self._local_exec(index, c, [])
        futures = [self._pool.submit(
            GLOBAL_TRACER.task(self.client.query_call), n.host, index,
            c, [])
            for n in self.peers()]
        errors = []
        for f in futures:
            try:
                f.result()
            except Exception as e:
                errors.append(str(e))
        if errors:
            raise ClusterError(
                "attr write incomplete (anti-entropy will repair): "
                + "; ".join(errors))
        return out

    # -- reduce (executor.go:2482 reduce fns per call type) ----------------

    def _reduce(self, index: str, c: Call, results: list[Any]):
        results = [r for r in results if r is not None]
        if not results:
            return None
        name = c.name
        first = results[0]
        if name == "Count":
            return sum(int(r) for r in results)
        if isinstance(first, RowResult):
            segments = {}
            attrs = {}
            for r in results:
                segments.update(r.segments)
                attrs = attrs or r.attrs  # row attrs replicated per node
            return RowResult(segments, attrs=attrs or None)
        if isinstance(first, ValCount):
            acc = first
            for r in results[1:]:
                if name == "Sum":
                    acc = acc.add(r)
                elif name in ("Min", "MinRow"):
                    acc = acc.smaller(r)
                else:
                    acc = acc.larger(r)
            return acc
        if name == "TopN":
            n, _ = c.uint_arg("n")
            pairs = merge_pairs(results)
            return sort_pairs([p for p in pairs if p.count > 0], n or None)
        if isinstance(first, RowIdentifiers):
            rows = sorted(set().union(*[set(r.rows) for r in results]))
            limit = c.args.get("limit")
            if limit is not None:
                rows = rows[:limit]
            return RowIdentifiers(rows=rows)
        if name == "GroupBy":
            return self._reduce_group_by(c, results)
        return first

    @staticmethod
    def _reduce_group_by(c: Call, results: list[list[GroupCount]]):
        """(executor.go:1195 mergeGroupCounts)"""
        acc: dict[tuple, GroupCount] = {}
        for node_groups in results:
            for g in node_groups:
                key = tuple((fr.field, fr.row_id) for fr in g.group)
                if key in acc:
                    acc[key] = GroupCount(g.group, acc[key].count + g.count)
                else:
                    acc[key] = g
        out = sorted(acc.values(), key=lambda g: tuple(
            (fr.field, fr.row_id) for fr in g.group))
        limit = c.args.get("limit")
        return out[:limit] if limit is not None else out

    # -- DDL broadcast (broadcast.go:30, server.go:569 receiveMessage) -----

    def broadcast(self, msg: dict):
        """Send a cluster message to every READY peer, synchronously."""
        errors = []
        for n in self.peers():
            if n.state != NODE_READY:
                continue
            try:
                self.client.send_message(n.host, msg)
            except Exception as e:
                # Mark DOWN so the next successful probe triggers the
                # apply-schema catch-up; a peer that missed a DDL broadcast
                # while staying READY would diverge permanently.
                self._mark_down(n.id)
                errors.append(f"{n.id}: {e}")
        if errors:
            raise ClusterError("broadcast failed: " + "; ".join(errors))

    def handle_message(self, msg: dict):
        """Apply a received cluster message locally (server.go:569)."""
        t = msg.get("type")
        holder = self.holder
        if t == "create-index":
            holder.create_index_if_not_exists(
                msg["index"], keys=msg.get("keys", False),
                track_existence=msg.get("trackExistence", True))
        elif t == "delete-index":
            self.forget_index_shards(msg["index"])
            try:
                holder.delete_index(msg["index"])
            except ValueError:
                pass
        elif t == "create-field":
            from ..storage import FieldOptions
            idx = holder.index(msg["index"])
            if idx is None:
                # can happen if this node missed the create-index while
                # down; the field implies the index
                idx = holder.create_index_if_not_exists(msg["index"])
            # lenient: applying a peer's schema must never crash this
            # node — the coordinator already validated user input
            idx.create_field_if_not_exists(
                msg["field"], FieldOptions.from_dict(
                    msg.get("options", {}), lenient=True))
        elif t == "apply-schema":
            from ..storage import FieldOptions
            for idx_def in msg.get("schema", []):
                opts = idx_def.get("options", {})
                idx = holder.create_index_if_not_exists(
                    idx_def["name"], keys=opts.get("keys", False),
                    track_existence=opts.get("trackExistence", True))
                for fdef in idx_def.get("fields", []):
                    idx.create_field_if_not_exists(
                        fdef["name"],
                        FieldOptions.from_dict(fdef.get("options", {}),
                                               lenient=True))
        elif t == "delete-field":
            idx = holder.index(msg["index"])
            if idx is not None:
                try:
                    idx.delete_field(msg["field"])
                except ValueError:
                    pass
        elif t == "set-state":
            # coordinator-driven state transition (resize begin/abort —
            # cluster.go:1116 setStateAndBroadcast)
            self.state = msg["state"]
            self._update_state()
        elif t == "resize-fetch":
            self._apply_resize_fetch(msg)
        elif t == "resize-complete":
            self._apply_resize_complete(msg)
        elif t == "placement-overlay":
            self._apply_overlay(msg)
        else:
            raise ClusterError(f"unknown cluster message type {t!r}")

    # -- import forwarding (api.go:920-1028) -------------------------------

    def _forward_grouped(self, index: str, field: str, cols: np.ndarray,
                         payload_fn):
        """Shared import fan-out: group bits by shard, build one payload
        per owner node via ``payload_fn(selection_mask)``, apply locally /
        POST remotely in parallel (api.go:963-996 importsByNode)."""
        shards = cols // SHARD_WIDTH
        by_node: dict[str, list[int]] = {}
        for s in np.unique(shards):
            owners = self.shard_owner_nodes(index, int(s))
            self._require_ready(owners, f"import shard {int(s)}")
            for nid in owners:
                by_node.setdefault(nid, []).append(int(s))
        idx = self.holder.index(index)
        # forwarded imports mutate the owners' data: invalidate cached
        # cross-node results that depended on them
        self.note_peer_write(index, by_node)
        futures = []
        local_payload = None
        for nid, nshards in by_node.items():
            payload = payload_fn(np.isin(shards, nshards))
            if nid == self.node_id:
                local_payload = payload
                continue
            futures.append(self._pool.submit(
                GLOBAL_TRACER.task(self.client.import_local),
                self.by_id[nid].host, index, field, payload))
            if idx is not None:
                f = idx.field(field)
                if f is not None:
                    f.remote_available_shards.update(
                        s for s in nshards
                        if not self.owns_shard(self.node_id, index, s))
        if local_payload is not None:
            self.api.apply_import_local(index, field, local_payload)
        for fut in futures:
            fut.result()  # propagate owner-import failures

    def import_bits(self, index: str, field: str, rows: np.ndarray,
                    cols: np.ndarray, timestamps=None, clear: bool = False):
        """Group bits by shard, send each shard batch to every owner."""
        self._forward_grouped(index, field, cols, lambda sel: {
            "rowIDs": rows[sel].tolist(),
            "columnIDs": cols[sel].tolist(),
            "timestamps": ([timestamps[i] for i in np.nonzero(sel)[0]]
                           if timestamps else None),
            "clear": clear,
        })

    def import_values(self, index: str, field: str, cols: np.ndarray,
                      vals: np.ndarray, clear: bool = False):
        self._forward_grouped(index, field, cols, lambda sel: {
            "columnIDs": cols[sel].tolist(),
            "values": vals[sel].tolist() if not clear else None,
            "clear": clear,
        })

    def import_roaring(self, index: str, field: str, shard: int,
                       views: dict[str, bytes], clear: bool):
        """Forward a pre-serialized roaring import to each shard owner.
        Single-view imports (the overwhelmingly common shape) ship RAW
        over /internal/import-roaring — no base64, no JSON envelope;
        multi-view imports keep the legacy JSON forward."""
        self.note_peer_write(index, self.shard_owner_nodes(index, shard))
        for nid in self.shard_owner_nodes(index, shard):
            if nid == self.node_id:
                self.api.apply_import_roaring_local(index, field, shard,
                                                    views, clear)
            elif len(views) == 1:
                (view, data), = views.items()
                self.client.import_roaring_binary(
                    self.by_id[nid].host, index, field, shard,
                    view or "standard", data, clear)
            else:
                payload = {
                    "shard": shard,
                    "clear": clear,
                    "views": {k: base64.b64encode(v).decode()
                              for k, v in views.items()},
                }
                self.client.import_local(self.by_id[nid].host, index, field,
                                         payload)

    # -- anti-entropy (holder.go:909 holderSyncer; fleshed out with the
    # block-merge protocol in storage/fragment blocks/block_data) ----------

    def _note_ae_error(self, context: str, exc: BaseException):
        """Anti-entropy failure as DATA (docs/robustness.md): counter +
        last-error surface, whether or not the pass continues."""
        if self.stats is not None:
            self.stats.count("antientropy.errors")
        with self._ae_lock:
            self._ae_last_error = f"{context}: {exc}"
            self._ae_last_error_ts = _wall_stamp()

    def _note_ae_success(self):
        if self.stats is not None:
            self.stats.count("antientropy.runs")
        with self._ae_lock:
            self._ae_last_success_ts = _wall_stamp()

    def ae_snapshot(self) -> dict:
        """Anti-entropy health for /debug/vars (counters live in the
        stats counts; this carries the last-error/last-success surface)."""
        with self._ae_lock:
            return {
                "lastError": self._ae_last_error,
                "lastErrorTs": self._ae_last_error_ts,
                "lastSuccessTs": self._ae_last_success_ts,
            }

    def sync_holder(self):
        """Anti-entropy pass (holder.go:938 SyncHolder): first heal any
        QUARANTINED local fragments wholesale from a healthy replica
        (repair_quarantined), then for every owned fragment, compare
        100-row block checksums with replicas and run the union-MAJORITY
        merge — consensus-set bits are added, consensus-clear bits are
        CLEARED (no resurrection), and peers whose value disagrees with
        consensus get repairs PUSHED to them (fragment.go:1875 mergeBlock
        + :2941 syncFragment).  Attr stores sync by block diff
        (holder.go:1002-1096).  Also re-runs the holder cleaner: post-
        resize fragment GC is deferred (see _apply_resize_complete), and
        the AE cadence is its periodic backstop (holder.go:1131)."""
        from ..storage.roaring_io import unpack_roaring

        try:
            self.repair_quarantined()
            if self.state != STATE_RESIZING:
                self._holder_cleaner()
            holder = self.holder
            for index_name, idx in list(holder.indexes.items()):
                shards = self._available_shards(
                    index_name, patient=True,
                    on_error=lambda nid, e, i=index_name: self._note_ae_error(
                        f"shard poll for {i} from {nid}", e))
                for fname, f in list(idx.fields.items()):
                    for s in shards:
                        owners = self.shard_owner_nodes(index_name, s)
                        if self.node_id not in owners:
                            continue
                        for vname in list(f.views) or ["standard"]:
                            self._sync_fragment(index_name, fname, vname, s,
                                                owners, unpack_roaring)
            self._sync_attrs()
            self._sync_translate_entries()
        except Exception as e:
            self._note_ae_error("sync_holder", e)
            raise
        self._note_ae_success()

    # -- quarantine repair (docs/robustness.md "Replica repair") -----------

    def repair_quarantined(self) -> int:
        """Re-fetch every quarantined local fragment wholesale from a
        healthy replica: checksummed snapshot bytes over
        /internal/fragment/fetch, CRC-verified on receipt, atomically
        swapped in via the durable-replace path, generation bumped (so
        result caches keyed on the gen vector invalidate).  Returns the
        number repaired; failures count antientropy.errors and are
        retried next pass."""
        repaired = 0
        if self.holder is None:
            return 0
        for iname, fname, vname, shard, frag in \
                list(self.holder.iter_fragments()):
            if frag.quarantined is None:
                continue
            owners = self.shard_owner_nodes(iname, shard)
            for nid, host in self._ready_peer_hosts(owners):
                try:
                    blob = self.client.fragment_fetch(
                        host, iname, fname, vname, shard)
                    frag.restore_snapshot_bytes(blob)
                except Exception as e:
                    # unreachable peer, peer also quarantined (409), or
                    # corrupt bytes in flight (CRC mismatch on receipt)
                    self._note_ae_error(
                        f"repair {iname}/{fname}/{vname}/{shard} "
                        f"from {nid}", e)
                    continue
                repaired += 1
                if self.stats is not None:
                    self.stats.count("antientropy.repairs")
                events.emit("storage.repair", index=iname, field=fname,
                            view=vname, shard=shard, source=nid)
                break
        return repaired

    def _sync_translate_entries(self):
        """Replica key-table catch-up: pull new translate entries from the
        coordinator for every keyed index/field (the streaming replication
        of holder.go:812, batched onto the anti-entropy cadence)."""
        if self.nodes[0].state != NODE_READY:
            return  # coordinator down: don't stall the anti-entropy
            #         thread on per-store timeouts (repair must continue)
        for idx in list(self.holder.indexes.values()):
            stores = []
            if idx.keys:
                stores.append(idx.translate_store())
            for f in list(idx.fields.values()):
                if f.options.keys:
                    stores.append(f.translate_store())
            for ts in stores:
                if isinstance(ts, RemoteTranslateStore):
                    try:
                        ts.sync_entries()
                    except Exception as e:
                        self._note_ae_error("translate sync", e)
                        # next pass retries

    def _ready_peer_hosts(self, node_ids) -> list[tuple[str, str]]:
        return [(nid, self.by_id[nid].host) for nid in node_ids
                if nid != self.node_id
                and self.by_id[nid].state == NODE_READY]

    def _sync_fragment(self, index: str, field: str, view: str, shard: int,
                       owners: list[str], unpack_roaring):
        local = self.holder.fragment(index, field, view, shard)
        if local is not None and local.quarantined is not None:
            # repair_quarantined (start of this pass) couldn't heal it
            # yet: its empty store must not feed the consensus merge —
            # that would CLEAR healthy replicas with corruption fallout
            return
        # hex digests to match the wire encoding of fragment_blocks
        local_blocks = {b: ck.hex() for b, ck in local.blocks().items()} \
            if local is not None else {}
        peers = []
        remote_blocks = {}
        for nid, host in self._ready_peer_hosts(owners):
            try:
                blocks, peer_quarantined = self.client.fragment_blocks(
                    host, index, field, view, shard)
            except Exception as e:
                self._note_ae_error(
                    f"blocks {index}/{field}/{view}/{shard} from {nid}", e)
                continue
            if peer_quarantined:
                # same rule for peers: a quarantined replica is excluded
                # from consensus entirely (its own repair pass heals it)
                continue
            remote_blocks[nid] = blocks
            peers.append((nid, host))
        if not peers:
            return
        if local is None and any(remote_blocks.values()):
            # fragment absent entirely -> bootstrap whole-fragment copy
            # (fragment.go:2876); the merge below reconciles the rest.
            # An EXISTING-but-empty fragment must NOT take this path: its
            # emptiness may be a legitimate majority clear, and a full
            # copy would resurrect bits the merge just removed.
            for nid, host in peers:
                if not remote_blocks[nid]:
                    continue
                try:
                    blob = self.client.fragment_data(
                        host, index, field, view, shard)
                except Exception as e:
                    self._note_ae_error(
                        f"fragment_data {index}/{field}/{view}/{shard} "
                        f"from {nid}", e)
                    continue
                rows, cols = unpack_roaring(blob, self.holder.max_row_id)
                idx = self.holder.index(index)
                frag = idx.field(field)._create_view_if_not_exists(view) \
                    .create_fragment_if_not_exists(shard)
                frag.bulk_import(rows, cols)
                local = frag
                local_blocks = {b: ck.hex()
                                for b, ck in local.blocks().items()}
                break
        diff_blocks: set[int] = set()
        for nid, rb in remote_blocks.items():
            for b, ck in rb.items():
                if local_blocks.get(b) != ck:
                    diff_blocks.add(b)
            for b, ck in local_blocks.items():
                if rb.get(b) != ck:
                    diff_blocks.add(b)
        for b in sorted(diff_blocks):
            self._merge_block(index, field, view, shard, b, local, peers)

    def _merge_block(self, index: str, field: str, view: str, shard: int,
                     block: int, local, peers):
        """mergeBlock (fragment.go:1875): majority consensus per (row,col)
        pair across local + reachable replicas; even split -> set.  Applies
        the local diff and pushes each peer's diff to it."""
        flats = []   # per holder: sorted flat pair encodings
        got_peers = []
        if local is not None:
            rows, cols = local.block_data(block)
            flats.append(rows * SHARD_WIDTH + cols)
        else:
            flats.append(np.zeros(0, dtype=np.int64))
        for nid, host in peers:
            try:
                rows, cols = self.client.block_data(
                    host, index, field, view, shard, block)
            except Exception as e:
                self._note_ae_error(
                    f"block_data {index}/{field}/{view}/{shard}"
                    f"#{block} from {nid}", e)
                continue
            flats.append(rows * SHARD_WIDTH + cols)
            got_peers.append((nid, host))
        if not got_peers:
            return
        n = 1 + len(got_peers)
        majority = (n + 1) // 2
        universe, counts = np.unique(np.concatenate(flats),
                                     return_counts=True)
        consensus_set = universe[counts >= majority]
        consensus_clear = universe[counts < majority]

        def decode(flat):
            return flat // SHARD_WIDTH, flat % SHARD_WIDTH

        # local diff
        sets = np.setdiff1d(consensus_set, flats[0], assume_unique=True)
        clears = np.intersect1d(consensus_clear, flats[0],
                                assume_unique=True)
        if sets.size or clears.size:
            idx = self.holder.index(index)
            frag = idx.field(field)._create_view_if_not_exists(view) \
                .create_fragment_if_not_exists(shard)
            if sets.size:
                frag.bulk_import(*decode(sets))
            if clears.size:
                frag.bulk_import(*decode(clears), clear=True)
        # push diffs to disagreeing peers (fragment.go:2995 syncBlock)
        for (nid, host), flat in zip(got_peers, flats[1:]):
            p_sets = np.setdiff1d(consensus_set, flat, assume_unique=True)
            p_clears = np.intersect1d(consensus_clear, flat,
                                      assume_unique=True)
            if not (p_sets.size or p_clears.size):
                continue
            try:
                self.client.block_repair(
                    host, index, field, view, shard,
                    decode(p_sets), decode(p_clears))
                self.note_peer_write(index, [nid])
            except Exception as e:
                # peer repair is best-effort; next pass retries
                self._note_ae_error(
                    f"block_repair {index}/{field}/{view}/{shard}"
                    f"#{block} to {nid}", e)
                continue

    # -- attr anti-entropy (holder.go:1002-1096 syncIndex/syncField) -------

    def _sync_attrs(self):
        holder = self.holder
        for index_name, idx in list(holder.indexes.items()):
            self._sync_attr_store(index_name, None, idx.column_attrs)
            for fname, f in list(idx.fields.items()):
                self._sync_attr_store(index_name, fname, f.row_attrs)

    def _sync_attr_store(self, index: str, field: str | None, store):
        """Pull peers' attrs for blocks whose checksum differs and merge
        them in (the reference's pull-per-node scheme: each node's own
        sync pass converges it toward its peers)."""
        local_blocks = {str(b): ck.hex() for b, ck in store.blocks().items()}
        for nid, host in self._ready_peer_hosts([n.id for n in self.nodes]):
            try:
                attrs = self.client.attr_diff(host, index, field,
                                              local_blocks)
            except Exception as e:
                self._note_ae_error(
                    f"attr_diff {index}/{field or 'columns'} from {nid}", e)
                continue
            if attrs:
                store.set_bulk_attrs(attrs)

    # -- elasticity: checkpoint resharding (cluster.go:1196-1561) ----------
    #
    # The reference resizes live via coordinator-computed ResizeInstructions
    # driven by gossip membership events.  The TPU-native design (SURVEY
    # §5.8) reshapes a STATIC membership instead: an operator request tells
    # the coordinator the new node list; the coordinator drives a
    # two-phase protocol over plain HTTP:
    #   phase 1 "resize-fetch":    every surviving node copies the
    #       fragments it will own under the NEW placement but lacks,
    #       sourced from a current owner (full-fragment checkpoint copy via
    #       /internal/fragment/data — fragment.go:1297
    #       followResizeInstruction's RetrieveShardFromURI).  Old placement
    #       stays live for queries throughout.
    #   phase 2 "resize-complete": every node atomically adopts the new
    #       membership/placement and garbage-collects fragments it no
    #       longer owns (holder.go:1131 holderCleaner).
    # No node drops data before every node has fetched, so a crash mid-
    # resize leaves a superset of the needed data and the operation can be
    # retried.

    def _membership(self) -> list[dict]:
        return [{"id": n.id, "uri": n.host} for n in self.nodes]

    # -- topology persistence (cluster.go:1580-1692 Topology,
    #    considerTopology) -------------------------------------------------

    def _topology_path(self) -> str | None:
        base = getattr(self.holder, "path", None) if self.holder else None
        return os.path.join(base, ".topology") if base else None

    def _resize_job_path(self) -> str | None:
        base = getattr(self.holder, "path", None) if self.holder else None
        return os.path.join(base, ".resize_job") if base else None

    def _load_topology(self):
        """Adopt persisted membership over the config host list (the
        reference reconciles its .topology protobuf the same way at
        startup; a restart after a live resize must not silently revert
        to the config file and split-brain the cluster)."""
        path = self._topology_path()
        if path is None or not os.path.exists(path):
            return
        with open(path) as f:
            data = json.load(f)
        membership = data.get("membership") or []
        if not membership:
            return
        if self.node_id not in {m["id"] for m in membership}:
            # the considerTopology mismatch case: disk says this node is
            # not a member — refuse to start rather than serve a placement
            # the rest of the cluster doesn't share (operator removes
            # .topology to deliberately re-seed from config)
            raise ClusterError(
                f"node {self.node_id!r} is not in the persisted topology "
                f"{path} (members: {[m['id'] for m in membership]}); "
                f"remove the file to re-seed membership from config")
        self.nodes = [Node(m["id"], m["uri"]) for m in membership]
        self.by_id = {n.id: n for n in self.nodes}
        self.replica_n = int(data.get("replicaN", self.replica_n))
        self.epoch = int(data.get("epoch", 0))
        self.placement = Placement([n.id for n in self.nodes],
                                   replica_n=self.replica_n,
                                   hasher=self.placement.hasher)
        # placement overlay rides the topology file: a restarted overlay
        # owner must keep serving (and receiving writes for) its extra
        # shards; a node restarted with wiped state converges via the
        # probe's overlay-epoch re-push instead
        self.overlay_epoch = int(data.get("overlayEpoch", 0))
        self._overlay = {
            (i, int(s)): [nid for nid in extras if nid in self.by_id]
            for i, s, extras in data.get("overlay", [])}

    def _save_topology(self):
        from ..utils.durable import durable_replace, fsync_file
        path = self._topology_path()
        if path is None:
            return
        os.makedirs(os.path.dirname(path), exist_ok=True)
        tmp = path + ".tmp"
        with open(tmp, "w") as f:
            json.dump({"epoch": self.epoch, "replicaN": self.replica_n,
                       "membership": self._membership(),
                       "overlayEpoch": self.overlay_epoch,
                       "overlay": self._overlay_wire()}, f)
            # a crash must not leave a node on the PRE-resize membership
            # after it acked the new one (split-brain on restart)
            fsync_file(f)
        durable_replace(tmp, path)

    # -- resize job record (cluster.go:1413-1441 resizeJob): persisted on
    #    the coordinator between phase 1 and 2 so a crash mid-completion
    #    can be re-driven instead of diverging ---------------------------

    def _save_resize_job(self, job: dict):
        from ..utils.durable import durable_replace, fsync_file
        path = self._resize_job_path()
        if path is None:
            return
        os.makedirs(os.path.dirname(path), exist_ok=True)
        tmp = path + ".tmp"
        with open(tmp, "w") as f:
            json.dump(job, f)
            # this record is the crash-recovery source of truth between
            # resize phases 1 and 2 — it must be durable BEFORE any node
            # adopts the new membership, or a power loss leaves a
            # partially-applied resize that can never reconverge
            fsync_file(f)
        durable_replace(tmp, path)

    def _load_resize_job(self) -> dict | None:
        path = self._resize_job_path()
        if path is None or not os.path.exists(path):
            return None
        try:
            with open(path) as f:
                return json.load(f)
        except Exception as e:
            # a corrupt/torn job record reads as "no resize in flight" —
            # that must be visible, not a silent shrug, because the
            # interrupted resize's revert pushes will never happen
            self._note_ae_error(f"resize-job load {path}", e)
            return None

    def _clear_resize_job(self):
        path = self._resize_job_path()
        if path is not None and os.path.exists(path):
            try:
                os.remove(path)
            except OSError:
                pass

    def _recover_resize_job(self):
        """Coordinator startup: an on-disk job means a crash happened
        after phase 1 (data fetched) but before every member acked
        resize-complete.  Completion is the only safe direction — fetched
        data is a superset, while reverting would need an inverse copy —
        so re-drive phase 2 idempotently (epoch-gated on receivers)."""
        job = self._load_resize_job()
        if job is None:
            return
        epoch = job.get("epoch", self.epoch + 1)
        done_msg = {"type": "resize-complete",
                    "membership": job["membership"],
                    "replicaN": job.get("replicaN", self.replica_n),
                    "lostShards": job.get("lostShards", {}),
                    "epoch": epoch}
        ok = True
        # short per-send timeout: this runs inside Server.open(), and an
        # unreachable member must not stall startup for the default 30s
        # each — probe reconciliation re-pushes on the health cadence
        for m in job["membership"]:
            if m["id"] == self.node_id:
                continue
            try:
                self.client.send_message(m["uri"], done_msg, timeout=5.0)
            # lint: allow(swallowed-exception) — ok=False keeps the job
            # record; probe reconciliation keeps pushing
            except Exception:
                ok = False
        self.handle_message(done_msg)
        # nodes the interrupted resize was removing still need their
        # single-node revert, or they stay latched RESIZING forever (the
        # probe safety net in probe_peers also covers this)
        for m in job.get("removed", []):
            try:
                self.client.send_message(m["uri"], {
                    "type": "resize-complete",
                    "membership": [m], "replicaN": 1, "epoch": epoch},
                    timeout=5.0)
            # lint: allow(swallowed-exception) — ok=False keeps the job
            # record; the probe safety net re-pushes the revert
            except Exception:
                ok = False
        if ok:
            self._clear_resize_job()

    def resize_add_node(self, node_id: str, host: str):
        """(api.go:1226-ish AddNode analog; coordinator only)"""
        if not self.is_coordinator:
            raise ClusterError("resize must be requested on the coordinator")
        if node_id in self.by_id:
            raise ClusterError(f"node {node_id!r} already in cluster")
        new = self._membership() + [{"id": node_id, "uri": host}]
        self._run_resize(new)

    def resize_remove_node(self, node_id: str):
        """(api.go:1226 RemoveNode; coordinator only)"""
        if not self.is_coordinator:
            raise ClusterError("resize must be requested on the coordinator")
        if node_id == self.node_id:
            raise ClusterError("cannot remove the coordinator")
        if node_id not in self.by_id:
            raise ClusterError(f"unknown node {node_id!r}")
        new = [m for m in self._membership() if m["id"] != node_id]
        self._run_resize(new)

    # resize-fetch can copy whole fragment sets inside one message POST
    RESIZE_FETCH_TIMEOUT = 600.0

    def _run_resize(self, new_membership: list[dict]):
        if not self._resize_lock.acquire(blocking=False):
            raise ClusterError("a resize is already in progress")
        try:
            if self.state not in (STATE_NORMAL, STATE_DEGRADED):
                raise ClusterError(
                    f"cannot resize in state {self.state}")
            self._run_resize_locked(new_membership)
        finally:
            self._resize_lock.release()

    def _run_resize_locked(self, new_membership: list[dict]):
        old_placement = self.placement
        new_ids = [m["id"] for m in new_membership]
        new_placement = Placement(new_ids, replica_n=self.replica_n,
                                  hasher=self.placement.hasher)
        hosts = {m["id"]: m["uri"] for m in new_membership}
        removed = [n for n in self.nodes if n.id not in hosts]
        # every participant (old members + joiners) blocks writes while
        # fragments are in flight; an aborted resize restores NORMAL below
        participants = {n.id: n.host for n in self.nodes}
        participants.update(hosts)
        # latch our own state FIRST: a peer probing mid-notify must see
        # the coordinator RESIZING, or its stale-latch safety valve
        # (probe_peers) would unlatch it during phase-1 fetch and let
        # writes land on fragments already copied away (r5 review)
        self.state = STATE_RESIZING
        for nid, host in participants.items():
            if nid != self.node_id:
                try:
                    self.client.send_message(
                        host, {"type": "set-state",
                               "state": STATE_RESIZING})
                # lint: allow(swallowed-exception) — DOWN old member;
                # fetch sources skip it anyway
                except Exception:
                    pass
        completed = False
        try:
            # per-node fetch lists: (index, shard) pairs the node will own
            # under the new placement but does not own now, with a current
            # owner as source (cluster.go:784 fragSources)
            fetches: dict[str, list[dict]] = {nid: [] for nid in new_ids}
            removed_ids = {n.id for n in removed}
            lost: dict[str, set[int]] = {}
            for index_name in list(self.holder.indexes):
                for s in self._available_shards(index_name, patient=True):
                    old_owners = old_placement.shard_nodes(index_name, s)
                    ready_sources = [
                        o for o in old_owners
                        if o == self.node_id
                        or self.by_id[o].state == NODE_READY]
                    if not ready_sources:
                        if all(o in removed_ids for o in old_owners):
                            # every replica lives only on unreachable
                            # nodes the operator is explicitly removing:
                            # accept the data loss and forget the shard
                            # (otherwise a dead ReplicaN=1 node could
                            # never be removed — the resize would abort
                            # on it forever)
                            lost.setdefault(index_name, set()).add(s)
                            continue
                        raise ClusterError(
                            f"no live source for shard {s} of "
                            f"{index_name!r}")
                    src_host = self.by_id[ready_sources[0]].host
                    for nid in new_placement.shard_nodes(index_name, s):
                        if nid not in old_owners:
                            fetches[nid].append({
                                "index": index_name, "shard": s,
                                "source": src_host})
            schema = self.holder.schema()
            # phase 1: everyone fetches (parallel, all must succeed)
            futs = []
            for nid in new_ids:
                msg = {"type": "resize-fetch", "fetch": fetches[nid],
                       "schema": schema}
                if nid == self.node_id:
                    self.handle_message(msg)
                else:
                    futs.append(self._pool.submit(
                        self.client.send_message, hosts[nid], msg,
                        self.RESIZE_FETCH_TIMEOUT))
            for f in futs:
                f.result()  # any fetch failure aborts before data loss
            # Point of no return: persist the job record BEFORE any node
            # adopts the new membership (cluster.go:1413 resizeJob).  From
            # here the resize only moves forward — fetched data is a
            # superset, so completion is always safe, while a partial
            # completion with no record could never reconverge.
            new_epoch = self.epoch + 1
            # data-loss shards ride the resize-complete broadcast so EVERY
            # node prunes them from its availability maps — coordinator-
            # only pruning let peer polls re-propagate forgotten shards
            # back into query scope forever (r5 advisor)
            lost_wire = {idx: sorted(s) for idx, s in lost.items()}
            self._save_resize_job({
                "epoch": new_epoch, "membership": new_membership,
                "replicaN": self.replica_n,
                "lostShards": lost_wire,
                "removed": [{"id": n.id, "uri": n.host} for n in removed]})
            completed = True  # phase-1 abort path no longer applies
            # phase 2: peers adopt FIRST, with retries; the coordinator
            # adopts only after every peer acked (r4 advisor: adopting
            # locally before peer acks made a failed peer permanently
            # diverge, and the retry raised 'already in cluster').
            done_msg = {"type": "resize-complete",
                        "membership": new_membership,
                        "replicaN": self.replica_n,
                        "lostShards": lost_wire,
                        "epoch": new_epoch}
            unacked = {nid for nid in new_ids if nid != self.node_id}
            for _ in range(3):
                for nid in sorted(unacked):
                    try:
                        self.client.send_message(hosts[nid], done_msg)
                        unacked.discard(nid)
                    # lint: allow(swallowed-exception) — stragglers stay
                    # in `unacked` and are marked DOWN below; the epoch-
                    # gated re-push loop owns convergence
                    except Exception:
                        pass
                if not unacked:
                    break
                time.sleep(0.2)
            self.handle_message(done_msg)
            # a gracefully removed node reverts to a single-node cluster
            # view of itself; best-effort notification
            for n in removed:
                try:
                    self.client.send_message(n.host, {
                        "type": "resize-complete",
                        "membership": [{"id": n.id, "uri": n.host}],
                        "replicaN": 1, "epoch": new_epoch})
                # lint: allow(swallowed-exception) — best-effort notify
                # of a node leaving the cluster; the probe safety net in
                # probe_peers re-delivers the single-node revert
                except Exception:
                    pass
            if unacked:
                # keep the job record: probe reconciliation (and a
                # restart's _recover_resize_job) re-push resize-complete,
                # epoch-gated, until the stragglers converge
                for nid in unacked:
                    self._mark_down(nid)
            else:
                self._clear_resize_job()
        finally:
            if not completed:
                # abort (phase 1 failed): restore every participant to
                # NORMAL under the OLD membership — no node dropped data
                # in phase 1, so the cluster simply resumes and the resize
                # can be retried
                for nid, host in participants.items():
                    if nid != self.node_id:
                        try:
                            self.client.send_message(
                                host, {"type": "set-state",
                                       "state": STATE_NORMAL})
                        # lint: allow(swallowed-exception) — abort-path
                        # state restore; an unreachable participant
                        # unlatches via the probe_peers safety net
                        except Exception:
                            pass
            if self.state == STATE_RESIZING:
                self.state = STATE_NORMAL
                self._update_state()

    def _apply_resize_fetch(self, msg: dict):
        """Phase 1: copy fragments this node will own but lacks.  State is
        driven by the coordinator's set-state / resize-complete messages,
        not here — a node must not latch RESIZING it cannot exit."""
        from ..storage.roaring_io import unpack_roaring

        self.handle_message({"type": "apply-schema",
                             "schema": msg.get("schema", [])})
        for item in msg.get("fetch", []):
            index, shard, src = item["index"], item["shard"], item["source"]
            try:
                frag_list = self.client.fragment_list(src, index, shard)
            except Exception as e:
                raise ClusterError(
                    f"resize fetch: cannot list fragments of shard "
                    f"{shard} from {src}: {e}")
            idx = self.holder.index(index)
            for field, view in frag_list:
                f = idx.field(field)
                if f is None:
                    continue
                blob = self.client.fragment_data(src, index, field, view,
                                                 shard)
                rows, cols = unpack_roaring(blob, self.holder.max_row_id)
                frag = f._create_view_if_not_exists(view) \
                    .create_fragment_if_not_exists(shard)
                frag.bulk_import(rows, cols)

    def _apply_resize_complete(self, msg: dict):
        """Phase 2: adopt the new membership and GC unowned fragments.
        Epoch-gated: a duplicate/re-driven resize-complete (coordinator
        retry, crash recovery, probe reconciliation) for an epoch we
        already hold is an idempotent no-op ack."""
        msg_epoch = int(msg.get("epoch", self.epoch + 1))
        if msg_epoch > self.epoch:
            # data-loss prune, on FIRST application of an epoch only:
            # shards forgotten in a data-loss removal leave this node's
            # per-index AND per-field availability maps, or its poll
            # replies would re-propagate them cluster-wide.  A re-driven
            # duplicate (same or older epoch — coordinator retry, probe
            # reconciliation) must NOT re-prune: the shards may have been
            # legitimately re-imported since the first application.
            for index_name, lost_list in \
                    (msg.get("lostShards") or {}).items():
                drop = {int(s) for s in lost_list}
                with self._shards_lock:
                    known = self._remote_shards.get(index_name)
                    if known is not None:
                        known -= drop
                idx = self.holder.index(index_name) if self.holder \
                    else None
                if idx is not None:
                    for f in idx.fields.values():
                        f.remote_available_shards -= drop
        if msg_epoch <= self.epoch:
            if self.state == STATE_RESIZING:
                self.state = STATE_NORMAL
                self._update_state()
            return
        membership = msg["membership"]
        self.replica_n = msg.get("replicaN", self.replica_n)
        if self.node_id not in {m["id"] for m in membership}:
            # we were removed; keep serving a single-node view of ourselves
            membership = [{"id": self.node_id, "uri": self.local.host}]
        old_states = {n.id: n.state for n in self.nodes}
        self.nodes = [Node(m["id"], m["uri"]) for m in membership]
        for n in self.nodes:
            n.state = old_states.get(n.id, NODE_READY)
        self.by_id = {n.id: n for n in self.nodes}
        self.placement = Placement([n.id for n in self.nodes],
                                   replica_n=self.replica_n,
                                   hasher=self.placement.hasher)
        self.epoch = msg_epoch
        events.emit("cluster.resize", epoch=msg_epoch,
                    nodes=[m["id"] for m in membership])
        # a membership resize reshuffles jump-hash placement wholesale:
        # the overlay (tuned for the OLD placement) is dropped on every
        # node and the balancer re-detects hot spots under the new
        # placement.  The epoch bump is UNCONDITIONAL so every node
        # moves in lockstep regardless of its table content — a node
        # carrying stale entries (missed a delete-index) bumping while a
        # clean coordinator did not would end up AHEAD and silently
        # reject the coordinator's next legitimate overlay broadcast
        with self._overlay_lock:
            self._overlay = {}
            self.overlay_epoch += 1
        self._save_topology()
        self.state = STATE_NORMAL
        self._update_state()
        # Fragment GC is DEFERRED (cluster.go holderCleaner runs on a
        # schedule, not inline): queries keep serving during the resize,
        # and nodes adopt the new membership at slightly different
        # moments — a read routed by the old placement in that window
        # must still find data on the old owner.  The grace covers the
        # adoption skew; the anti-entropy loop also re-runs the cleaner.
        if self.cleaner_grace <= 0:
            self._holder_cleaner()
        else:
            t = threading.Timer(self.cleaner_grace, self._cleaner_tick)
            t.daemon = True
            t.start()

    def _cleaner_tick(self):
        # same guard as the AE backstop: a stale grace timer must not GC
        # fragments a SUBSEQUENT resize just fetched (they are unowned
        # under the still-current placement until that resize completes)
        if not self._closing.is_set() and self.state != STATE_RESIZING:
            try:
                self._holder_cleaner()
            except Exception as e:
                # a dead cleaner means unowned fragments pile up
                # invisibly; surface it on the AE health counters
                self._note_ae_error("holder cleaner", e)

    def _holder_cleaner(self):
        """Drop fragments this node no longer owns under the current
        placement (holder.go:1131 holderCleaner)."""
        for index_name, idx in list(self.holder.indexes.items()):
            for f in list(idx.fields.values()):
                for v in list(f.views.values()):
                    for shard in list(v.fragments):
                        if self.node_id not in self.shard_owner_nodes(
                                index_name, shard):
                            frag = v.fragments.pop(shard)
                            try:
                                frag.close()
                            # lint: allow(swallowed-exception) — the
                            # fragment is already unowned and popped; a
                            # close failure leaks an fd, not data
                            except Exception:
                                pass

    # -- internal HTTP routes (handler.go:302-314 /internal/*) -------------

    def register_routes(self, router, server=None):
        cluster = self
        if server is not None:
            # load piggybacks (local_load) report this server's
            # admission pools
            self._server = server

        def _exec_multi(req, index, calls_wire, shards):
            """Execute a multi-call batch and build its piggybacks —
            shared by the JSON and PTPUQRY1 branches so the two wires
            can never drift in semantics.  Returns (results, trailer):
            the trailer is the piggyback dict (execS, gens, quarantined,
            load, spans) that the JSON wire inlines into its response
            object and the binary wire ships as its trailer frame."""
            from ..cache.results import gen_summary
            calls = [call_from_wire(c) for c in calls_wire]
            t0 = time.perf_counter()
            res = cluster.api.executor.execute(
                index, Query(calls), shards or [], translate=False)
            # post-execution gen summary: lets the coordinator key its
            # cross-node result-cache entries to the data this answer
            # was computed from
            trailer = {"execS": time.perf_counter() - t0,
                       "gens": list(gen_summary(cluster.holder, index))}
            # quarantined fragments answered as EMPTY: piggyback the
            # count so the coordinator's response says so
            # (utils/degraded.py, docs/robustness.md)
            nq = len(cluster.holder.quarantined_fragments(index))
            if nq:
                trailer["quarantined"] = nq
            # admission depth piggyback (parallel/routing.py): every
            # answered sub-query refreshes the coordinator's load view
            # of this node, like the gen summaries above
            trailer["load"] = cluster.local_load()
            # span summaries piggyback like the gen summaries: the
            # handler collected this request's finished spans (and its
            # own in-flight HTTP span) so the coordinator can adopt
            # them into one cluster-wide trace tree
            spans = getattr(req, "_span_collect", None)
            if spans is not None:
                spans = list(spans)
                hs = getattr(req, "_trace_span", None)
                if hs is not None and hs.sampled:
                    spans.append(hs.to_dict())
                trailer["spans"] = spans
            return res, trailer

        def internal_query(req, args):
            if req.headers.get("Content-Type", "").split(";")[0].strip() \
                    == qwire.CONTENT_TYPE:
                # PTPUQRY1 binary wire (docs/cluster.md "Internal query
                # wire").  A node pinned to internal-wire=json answers
                # 415 — the capability-mismatch signal the client's
                # negotiation downgrades on (it retries as JSON).
                from ..api import UnsupportedMediaTypeError
                if cluster.internal_wire != qwire.WIRE_BIN1:
                    raise UnsupportedMediaTypeError(
                        "internal query wire is pinned to json")
                try:
                    calls_wire, shards, nreq = qwire.decode_request(
                        req.body)
                except qwire.FrameError as e:
                    from ..api import ApiError
                    raise ApiError(f"bad query wire request: {e}")
                res, trailer = _exec_multi(req, args["index"],
                                           calls_wire, shards)
                payload, nresp = qwire.encode_response(res, trailer)
                if cluster.stats is not None:
                    cluster.stats.count("cluster.wire_bytes_rx",
                                        len(req.body))
                    cluster.stats.count("cluster.wire_bytes_tx",
                                        len(payload))
                    cluster.stats.count("cluster.wire_frames",
                                        nreq + nresp)
                return qwire.CONTENT_TYPE, payload
            body = req.json()
            shards = body.get("shards")
            if "calls" in body:
                res, trailer = _exec_multi(req, args["index"],
                                           body["calls"], shards)
                out = {"results": [result_to_wire(r) for r in res]}
                out.update(trailer)
                return out
            call = call_from_wire(body["call"])
            result = cluster._local_exec(args["index"], call, shards or [])
            return {"result": result_to_wire(result)}

        # gate="internal": admission rides the SEPARATE internal slot
        # pool so coordinator fan-out can never self-deadlock behind
        # public traffic (server/admission.py); the deadline header is
        # parsed by the handler and flows into the executor via the
        # current query context
        router.add("POST", "/internal/query/{index}", internal_query,
                   gate="internal")

        def cluster_message(req, args):
            cluster.handle_message(req.json())
            return {}

        router.add("POST", "/internal/cluster/message", cluster_message)

        def internal_import(req, args):
            body = req.json()
            if "views" in body:
                views = {k: base64.b64decode(v)
                         for k, v in body["views"].items()}
                cluster.api.apply_import_roaring_local(
                    args["index"], args["field"], int(body["shard"]),
                    views, body.get("clear", False))
            else:
                cluster.api.apply_import_local(args["index"], args["field"],
                                               body)
            return {}

        router.add("POST", "/internal/import/{index}/{field}",
                   internal_import)

        def internal_import_roaring(req, args):
            """Raw roaring blob, one view per POST (the binary forward
            half of the octet-stream import path; docs/ingest.md)."""
            view = req.query.get("view", ["standard"])[0]
            clear = req.query.get("clear", ["false"])[0] == "true"
            cluster.api.apply_import_roaring_local(
                args["index"], args["field"], int(args["shard"]),
                {view: req.body}, clear)
            return {}

        router.add("POST",
                   "/internal/import-roaring/{index}/{field}/{shard}",
                   internal_import_roaring)

        def internal_translate(req, args):
            """Coordinator-side key<->id service (http/translator.go)."""
            idx = cluster.holder.index(args["index"])
            if idx is None:
                raise ClusterError(f"index not found: {args['index']}")
            if "field" in args:
                f = idx.field(args["field"])
                if f is None:
                    raise ClusterError(f"field not found: {args['field']}")
                store = f.translate_store()
            else:
                store = idx.translate_store()
            body = req.json()
            if "keys" in body:
                return {"ids": store.translate_keys(body["keys"])}
            if "after" in body:
                # replica catch-up stream (holder.go:812; translate.go:82).
                # A missing/0 limit clamps to one page — the server, not
                # client politeness, enforces the pagination bound.
                limit = int(body.get("limit") or 0)
                page = RemoteTranslateStore.SYNC_PAGE
                limit = min(limit, page) if limit > 0 else page
                return {"entries": store.entries_from(
                    int(body["after"]), limit)}
            return {"keys": store.translate_ids(body.get("ids", []))}

        router.add("POST", "/internal/translate/{index}", internal_translate)
        router.add("POST", "/internal/translate/{index}/{field}",
                   internal_translate)

        def index_shards(req, args):
            idx = cluster.holder.index(args["index"])
            shards = sorted(idx.available_shards()) if idx else []
            return {"shards": shards}

        router.add("GET", "/internal/index/{index}/shards", index_shards)

        def _frag(req):
            index = req.query.get("index", [""])[0]
            field = req.query.get("field", [""])[0]
            view = req.query.get("view", ["standard"])[0]
            shard = int(req.query.get("shard", ["0"])[0])
            return cluster.holder.fragment(index, field, view, shard)

        def fragment_blocks(req, args):
            frag = _frag(req)
            if frag is None:
                return {"blocks": {}}
            if frag.quarantined is not None:
                # the empty block map is corruption fallout, not data:
                # flag it so callers exclude this replica from consensus
                return {"blocks": {}, "quarantined": True}
            return {"blocks": {str(b): ck.hex()
                               for b, ck in frag.blocks().items()}}

        router.add("GET", "/internal/fragment/blocks", fragment_blocks)

        def block_data(req, args):
            frag = _frag(req)
            block = int(req.query.get("block", ["0"])[0])
            if frag is None:
                return {"rows": [], "cols": []}
            rows, cols = frag.block_data(block)
            return {"rows": rows.tolist(), "cols": cols.tolist()}

        router.add("GET", "/internal/fragment/block/data", block_data)

        def block_repair(req, args):
            """Receive a merge-consensus diff push (fragment.go:2995)."""
            body = req.json()
            idx = cluster.holder.index(body["index"])
            if idx is None:
                return {}
            f = idx.field(body["field"])
            if f is None:
                return {}
            frag = f._create_view_if_not_exists(body["view"]) \
                .create_fragment_if_not_exists(int(body["shard"]))
            if frag.quarantined is not None:
                # block diffs can't heal a quarantined fragment (and its
                # writes are refused); wholesale repair will restore it
                return {}
            sr = np.asarray(body.get("setRows", []), dtype=np.int64)
            sc = np.asarray(body.get("setCols", []), dtype=np.int64)
            cr = np.asarray(body.get("clearRows", []), dtype=np.int64)
            cc = np.asarray(body.get("clearCols", []), dtype=np.int64)
            if sr.size:
                frag.bulk_import(sr, sc)
            if cr.size:
                frag.bulk_import(cr, cc, clear=True)
            return {}

        router.add("POST", "/internal/fragment/block/repair", block_repair)

        def attr_diff(req, args):
            """Return our attrs for blocks whose checksum differs from the
            caller's (holder.go:1002 ColumnAttrDiff/RowAttrDiff)."""
            body = req.json()
            idx = cluster.holder.index(body["index"])
            if idx is None:
                return {"attrs": {}}
            if body.get("field"):
                f = idx.field(body["field"])
                if f is None:
                    return {"attrs": {}}
                store = f.row_attrs
            else:
                store = idx.column_attrs
            caller = body.get("blocks", {})
            out = {}
            for b, ck in store.blocks().items():
                if caller.get(str(b)) != ck.hex():
                    out.update(store.block_data(b))
            return {"attrs": {str(i): a for i, a in out.items()}}

        router.add("POST", "/internal/attr/diff", attr_diff)

        def fragment_data(req, args):
            from ..api import ConflictError
            from ..storage.roaring_io import pack_roaring
            from ..ops import bitset
            frag = _frag(req)
            if frag is not None and frag.quarantined is not None:
                # a resize/bootstrap copy from a quarantined source would
                # propagate its emptiness cluster-wide as if it were data
                raise ConflictError("fragment quarantined")
            if frag is None:
                rows = cols = np.zeros(0, dtype=np.int64)
            else:
                rows, cols = bitset.unpack_fragment(frag.words)
            return ("application/octet-stream", pack_roaring(rows, cols))

        router.add("GET", "/internal/fragment/data", fragment_data)

        def fragment_fetch(req, args):
            """Checksummed whole-fragment snapshot bytes — the replica
            repair source (docs/robustness.md).  Refuses for missing or
            quarantined fragments: repair must converge on HEALTHY data."""
            from ..api import ConflictError, NotFoundError
            frag = _frag(req)
            if frag is None:
                raise NotFoundError("fragment not found")
            if frag.quarantined is not None:
                raise ConflictError("fragment quarantined")
            return ("application/octet-stream", frag.snapshot_bytes())

        router.add("GET", "/internal/fragment/fetch", fragment_fetch)

        def fragment_list(req, args):
            index = req.query.get("index", [""])[0]
            shard = int(req.query.get("shard", ["0"])[0])
            out = []
            idx = cluster.holder.index(index)
            if idx is not None:
                for fname, f in idx.fields.items():
                    for vname, v in f.views.items():
                        if v.fragment(shard) is not None:
                            out.append([fname, vname])
            return {"fragments": out}

        router.add("GET", "/internal/fragment/list", fragment_list)

        def resize_add_node(req, args):
            body = req.json()
            cluster.resize_add_node(body["id"], body["host"])
            return {"nodes": cluster.node_statuses()}

        router.add("POST", "/cluster/resize/add-node", resize_add_node)

        def resize_remove_node(req, args):
            body = req.json()
            cluster.resize_remove_node(body["id"])
            return {"nodes": cluster.node_statuses()}

        router.add("POST", "/cluster/resize/remove-node", resize_remove_node)
