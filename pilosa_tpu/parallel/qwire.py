"""Binary /internal/query wire: CRC-framed roaring query transport.

The cluster fan-out's JSON envelope zlib+base64-encodes every row
segment as a FULL dense 2^20-bit bitmap string (128 KiB of words per
shard before the 4/3 base64 blowup) and round-trips every result through
``json.dumps``/``loads`` — at fan-out rates the envelope IS the hop
(ISSUE 16; the reference ships protobuf, encoding/proto/proto.go).  This
module speaks a length-prefixed CRC-framed binary stream instead, built
from the same two primitives as the ingest wire (ingest/wire.py) and the
framed WAL: an 8-byte magic, then frames of

    <u32 payload_len, u32 payload_crc> payload

where ``payload_crc`` is ``utils.durable.checksum`` (zlib crc32) over
the payload.  The first payload byte is the record type; records that
carry packed arrays follow it with an explicit endianness tag byte
(``ENDIAN_LE``) so a future big-endian or u64-word peer is rejected
loudly instead of silently mis-merging (the old JSON segment codec left
byte order implicit in ``tobytes()``).

Word order (the frame spec the endianness tag guards): segments travel
as ``SHARD_WORDS`` uint32 words, little-endian bytes within each word,
word ``i`` covering bits ``[32*i, 32*(i+1))`` of the shard span with the
lowest bit in the word's least-significant position — exactly the dense
layout of ``ops/bitset.py`` (uint32 words carrying the reference's u64
semantics two words at a time).

Request stream (client -> server): magic, then exactly two frames —
``REC_CALLS`` (endian tag + the JSON call batch, the ``pql.wire`` call
dicts verbatim: the AST is pointer-shaped and tiny, the win is in the
results) and ``REC_SHARDS`` (endian tag + the pinned shard list as a
packed ``<i8`` array).

Response stream (server -> client): magic, one typed frame per result,
then exactly one ``REC_TRAILER`` frame — the compact-JSON piggybacks
(execS, gens, quarantined, load, spans) the routing/result-cache/tracing
folds already consume, doubled as the end-of-stream marker so truncation
at a frame boundary is detected by its absence.  Result records:

    REC_JSONRES   the JSON ``result_to_wire`` dict (groups, raw values,
                  and any shape the typed encoders decline)
    REC_ROW       row segments, each roaring-packed through the existing
                  ``ops/containers.pack_words`` codec (wire bytes scale
                  with cardinality) with a raw-dense-words fallback per
                  segment, whichever is smaller
    REC_VALCOUNT  one packed (val, count) scalar pair
    REC_ROWIDS    row ids as one packed ``<i8`` array (+ JSON keys)
    REC_PAIRS     TopN pairs as packed ``<i8`` id and count arrays
                  (+ JSON keys) — no per-element Python on either side

Malformed input raises ``FrameError`` (bad magic, CRC mismatch, bad
record type, bad endian tag, truncated or oversized frame); the server
answers 400 and the client falls back to the JSON wire.  Negotiation and
fallback semantics live in ``parallel/cluster.py`` (InternalClient) and
docs/cluster.md "Internal query wire".
"""

from __future__ import annotations

import json
import struct

import numpy as np

from ..core import SHARD_WORDS
from ..executor.results import Pair, RowIdentifiers, RowResult, ValCount
from ..ops import containers
from ..utils.durable import checksum

MAGIC = b"PTPUQRY1"
FRAME = struct.Struct("<II")

# wire-mode names (the /status capability advertisement + the
# internal-wire knob vocabulary)
WIRE_JSON = "json"
WIRE_BIN1 = "bin1"

# Content type of a PTPUQRY1 request/response body.  An old peer answers
# a POST of this type 400 ("invalid JSON body"); a new peer with
# internal-wire=json answers 415 — either way the client downgrades.
CONTENT_TYPE = "application/x-ptpu-query"

# Explicit byte-order tag (see module docstring for the word order it
# guards).  The only defined value today; a decoder seeing anything else
# must reject the stream rather than byte-swap-guess.
ENDIAN_LE = 0

# result record types (first payload byte)
REC_JSONRES = 0
REC_ROW = 1
REC_VALCOUNT = 2
REC_ROWIDS = 3
REC_PAIRS = 4
REC_TRAILER = 9
# request record types
REC_CALLS = 16
REC_SHARDS = 17

# per-segment encodings inside a REC_ROW record
SEG_RAW = 0      # SHARD_WORDS uint32 dense words verbatim
SEG_PACKED = 1   # ops/containers Packed stream (keys/types/counts/
#                  offsets int32 tables + uint32 payload words)

# Frame ceiling: a response frame carries ONE result, which for a row
# over a large pinned shard group is bounded by group size x 128 KiB
# dense; 256 MiB is far above any real group and still bounds a
# corrupted length field.
MAX_FRAME_BYTES = 256 << 20

_SEG_HEAD = struct.Struct("<QBI")   # shard id, encoding, byte length
_PACKED_HEAD = struct.Struct("<II")  # container count, payload words
_VALCOUNT = struct.Struct("<qq")
_U32 = struct.Struct("<I")

_RAW_SEG_BYTES = SHARD_WORDS * 4


class FrameError(ValueError):
    """Malformed query wire stream (bad magic, CRC mismatch, bad record
    type or endian tag, oversized or truncated frame).  The server
    answers 400; the client counts ``cluster.wire_fallback`` and retries
    the idempotent read over the JSON wire."""


def _dumps(obj) -> bytes:
    return json.dumps(obj, separators=(",", ":")).encode()


def encode_frame(payload: bytes) -> bytes:
    """One framed payload (no magic — the stream carries it once)."""
    return FRAME.pack(len(payload), checksum(payload)) + payload


def iter_frames(data: bytes):
    """Yield each verified frame payload of one complete stream.

    The whole body is already in memory (the HTTP client/handler read
    it), so this is a zero-copy walk over memoryview slices; any
    malformed byte raises FrameError."""
    if len(data) < len(MAGIC):
        raise FrameError("query wire stream shorter than its magic")
    view = memoryview(data)
    if bytes(view[:len(MAGIC)]) != MAGIC:
        raise FrameError(f"bad query wire magic (expected {MAGIC!r})")
    off = len(MAGIC)
    n = len(data)
    while off < n:
        if n - off < FRAME.size:
            raise FrameError("truncated query wire frame header")
        plen, crc = FRAME.unpack_from(data, off)
        off += FRAME.size
        if plen == 0 or plen > MAX_FRAME_BYTES:
            raise FrameError(
                f"query wire frame of {plen} bytes outside (0, "
                f"{MAX_FRAME_BYTES}]")
        if n - off < plen:
            raise FrameError("truncated query wire frame")
        payload = view[off: off + plen]
        if checksum(payload) != crc:
            raise FrameError("query wire frame CRC mismatch")
        off += plen
        yield payload


def _check_endian(payload, what: str):
    if len(payload) < 2:
        raise FrameError(f"{what} record shorter than its header")
    if payload[1] != ENDIAN_LE:
        raise FrameError(
            f"{what} record byte order {payload[1]} is not little-endian "
            f"({ENDIAN_LE}); refusing to byte-swap-guess")


# -- segments ---------------------------------------------------------------

def encode_segment(seg) -> tuple[int, bytes]:
    """(encoding, blob) for one dense segment: the roaring Packed stream
    when it is smaller than the raw words, the raw words otherwise (a
    dense-majority segment never pays container overhead).  The cheap
    index-count bound skips packing entirely when it cannot win."""
    words = np.ascontiguousarray(np.asarray(seg, dtype="<u4"))
    if words.size != SHARD_WORDS:
        raise ValueError(f"bad segment size {words.size}")
    idx = np.flatnonzero(words)
    # estimate_packed_bytes is an upper bound that ignores run
    # containers, so on its own it would skip packing exactly the
    # clustered data runs exist for (Store'd full rows compress to a
    # few runs per container); a low word-level transition count is the
    # cheap tell that runs will win even when the array/bitmap bound
    # says dense.
    run_friendly = idx.size > 0 and \
        int(np.count_nonzero(np.diff(words.astype(np.int64)))) \
        < SHARD_WORDS // 64
    if run_friendly or containers.estimate_packed_bytes(idx) \
            + _PACKED_HEAD.size < _RAW_SEG_BYTES:
        p = containers.pack_words(idx.astype(np.int64), words[idx])
        blob = b"".join((
            _PACKED_HEAD.pack(p.keys.size, p.payload.size),
            p.keys.astype("<i4", copy=False).tobytes(),
            p.types.astype("<i4", copy=False).tobytes(),
            p.counts.astype("<i4", copy=False).tobytes(),
            p.offsets.astype("<i4", copy=False).tobytes(),
            p.payload.astype("<u4", copy=False).tobytes(),
        ))
        if len(blob) < _RAW_SEG_BYTES:
            return SEG_PACKED, blob
    return SEG_RAW, words.tobytes()


def decode_segment(enc: int, blob) -> np.ndarray:
    """Dense uint32[SHARD_WORDS] words of one segment blob."""
    if enc == SEG_RAW:
        if len(blob) != _RAW_SEG_BYTES:
            raise FrameError(f"bad raw segment size {len(blob)}")
        return np.frombuffer(blob, dtype="<u4")
    if enc != SEG_PACKED:
        raise FrameError(f"unknown segment encoding {enc}")
    if len(blob) < _PACKED_HEAD.size:
        raise FrameError("packed segment shorter than its header")
    c, pw = _PACKED_HEAD.unpack_from(blob, 0)
    want = _PACKED_HEAD.size + 16 * c + 4 * pw
    if len(blob) != want:
        raise FrameError(
            f"packed segment length {len(blob)} != expected {want}")
    off = _PACKED_HEAD.size
    tables = []
    for _ in range(4):
        tables.append(np.frombuffer(blob, dtype="<i4", count=c,
                                    offset=off))
        off += 4 * c
    keys, types, counts, offsets = tables
    payload = np.frombuffer(blob, dtype="<u4", count=pw, offset=off)
    if c and (int(keys.min()) < 0
              or int(keys.max()) >= SHARD_WORDS // containers.CONTAINER_WORDS):
        raise FrameError("packed segment container key out of range")
    p = containers.Packed(keys, types, counts, offsets, payload,
                          a_max=0, r_max=0)
    try:
        return containers.unpack_packed(p, 1, SHARD_WORDS)[0]
    except (IndexError, ValueError) as e:
        # CRC-clean but inconsistent tables (an encoder bug, not line
        # noise) must still reject, never mis-merge
        raise FrameError(f"packed segment tables inconsistent: {e}")


# -- results ----------------------------------------------------------------

def _enc_row(r: RowResult) -> bytes:
    parts = [bytes((REC_ROW, ENDIAN_LE)), _U32.pack(len(r.segments))]
    for shard in sorted(r.segments):
        enc, blob = encode_segment(r.segments[shard])
        parts.append(_SEG_HEAD.pack(int(shard), enc, len(blob)))
        parts.append(blob)
    attrs = _dumps(r.attrs) if r.attrs else b""
    parts.append(_U32.pack(len(attrs)))
    parts.append(attrs)
    return b"".join(parts)


def _dec_row(payload) -> RowResult:
    _check_endian(payload, "row")
    off = 2
    if len(payload) < off + 4:
        raise FrameError("row record truncated")
    (nsegs,) = _U32.unpack_from(payload, off)
    off += 4
    segments = {}
    for _ in range(nsegs):
        if len(payload) < off + _SEG_HEAD.size:
            raise FrameError("row segment header truncated")
        shard, enc, nbytes = _SEG_HEAD.unpack_from(payload, off)
        off += _SEG_HEAD.size
        if len(payload) < off + nbytes:
            raise FrameError("row segment truncated")
        segments[int(shard)] = decode_segment(
            enc, payload[off: off + nbytes])
        off += nbytes
    if len(payload) < off + 4:
        raise FrameError("row attrs header truncated")
    (alen,) = _U32.unpack_from(payload, off)
    off += 4
    if len(payload) != off + alen:
        raise FrameError("row record length mismatch")
    attrs = json.loads(bytes(payload[off:])) if alen else None
    return RowResult(segments, attrs=attrs)


def _enc_valcount(r: ValCount) -> bytes | None:
    if not isinstance(r.count, (int, np.integer)):
        return None
    flags = 0
    val = 0
    if r.val is not None:
        if isinstance(r.val, (bool, np.bool_)) \
                or not isinstance(r.val, (int, float, np.integer,
                                          np.floating)):
            return None
        flags |= 1
        if isinstance(r.val, (float, np.floating)):
            flags |= 2
            val = struct.unpack("<q", struct.pack("<d", float(r.val)))[0]
        else:
            val = int(r.val)
    return bytes((REC_VALCOUNT, ENDIAN_LE, flags)) \
        + _VALCOUNT.pack(val, int(r.count))


def _dec_valcount(payload) -> ValCount:
    _check_endian(payload, "valcount")
    if len(payload) != 3 + _VALCOUNT.size:
        raise FrameError("valcount record length mismatch")
    flags = payload[2]
    raw, count = _VALCOUNT.unpack_from(payload, 3)
    val = None
    if flags & 1:
        val = struct.unpack("<d", struct.pack("<q", raw))[0] \
            if flags & 2 else raw
    return ValCount(val, count)


def _enc_rowids(r: RowIdentifiers) -> bytes | None:
    try:
        rows = np.asarray(list(r.rows), dtype="<i8")
    except (TypeError, ValueError, OverflowError):
        return None
    keys = _dumps(list(r.keys)) if r.keys else b""
    return bytes((REC_ROWIDS, ENDIAN_LE)) + _U32.pack(rows.size) \
        + rows.tobytes() + keys


def _dec_rowids(payload) -> RowIdentifiers:
    _check_endian(payload, "rowids")
    if len(payload) < 6:
        raise FrameError("rowids record truncated")
    (n,) = _U32.unpack_from(payload, 2)
    off = 6
    if len(payload) < off + 8 * n:
        raise FrameError("rowids record truncated")
    rows = np.frombuffer(payload, dtype="<i8", count=n,
                         offset=off).tolist()
    rest = bytes(payload[off + 8 * n:])
    keys = json.loads(rest) if rest else []
    return RowIdentifiers(rows=rows, keys=keys)


def _enc_pairs(r: list) -> bytes | None:
    try:
        ids = np.asarray([p.id for p in r], dtype="<i8")
        counts = np.asarray([p.count for p in r], dtype="<i8")
    except (TypeError, ValueError, OverflowError):
        return None  # keyed pairs with no numeric id ride the JSON record
    keys = [p.key for p in r]
    has_keys = any(keys)  # Pair.key defaults to "" (falsy), not None
    blob = _dumps(keys) if has_keys else b""
    return bytes((REC_PAIRS, ENDIAN_LE, 1 if has_keys else 0)) \
        + _U32.pack(ids.size) + ids.tobytes() + counts.tobytes() + blob


def _dec_pairs(payload) -> list:
    _check_endian(payload, "pairs")
    if len(payload) < 7:
        raise FrameError("pairs record truncated")
    has_keys = payload[2]
    (n,) = _U32.unpack_from(payload, 3)
    off = 7
    if len(payload) < off + 16 * n:
        raise FrameError("pairs record truncated")
    ids = np.frombuffer(payload, dtype="<i8", count=n, offset=off).tolist()
    off += 8 * n
    counts = np.frombuffer(payload, dtype="<i8", count=n,
                           offset=off).tolist()
    off += 8 * n
    if has_keys:
        keys = json.loads(bytes(payload[off:]))
        if len(keys) != n:
            raise FrameError("pairs key list length mismatch")
    else:
        if len(payload) != off:
            raise FrameError("pairs record length mismatch")
        keys = [""] * n  # Pair.key default — matches the JSON wire
    return [Pair(i, c, k) for i, c, k in zip(ids, counts, keys)]


def encode_result(r) -> bytes:
    """One result record payload.  Typed encoders cover the hot shapes;
    anything they decline (GroupCounts, raw values, surprise shapes)
    rides REC_JSONRES carrying the exact JSON-wire dict, so the two
    wires can never disagree on what a result means."""
    payload = None
    if isinstance(r, RowResult):
        payload = _enc_row(r)
    elif isinstance(r, ValCount):
        payload = _enc_valcount(r)
    elif isinstance(r, RowIdentifiers):
        payload = _enc_rowids(r)
    elif isinstance(r, list) and r and isinstance(r[0], Pair):
        payload = _enc_pairs(r)
    if payload is None:
        # deferred import: cluster.py owns the JSON result codec and
        # imports this module at its top — the cycle resolves at call
        # time, long after both modules are loaded
        from .cluster import result_to_wire
        payload = bytes((REC_JSONRES,)) + _dumps(result_to_wire(r))
    return payload


def decode_result(payload):
    if not payload:
        raise FrameError("empty query wire frame")
    rectype = payload[0]
    if rectype == REC_ROW:
        return _dec_row(payload)
    if rectype == REC_VALCOUNT:
        return _dec_valcount(payload)
    if rectype == REC_ROWIDS:
        return _dec_rowids(payload)
    if rectype == REC_PAIRS:
        return _dec_pairs(payload)
    if rectype == REC_JSONRES:
        from .cluster import result_from_wire
        try:
            return result_from_wire(json.loads(bytes(payload[1:])))
        except (ValueError, KeyError, TypeError) as e:
            raise FrameError(f"bad JSON result record: {e}")
    raise FrameError(f"unknown query wire record type {rectype}")


# -- request/response streams -----------------------------------------------

def encode_request(calls_wire: list[dict], shards) -> bytes:
    """Magic + REC_CALLS frame (JSON call batch) + REC_SHARDS frame
    (packed <i8 shard list; flag 0 = unpinned/None)."""
    head = bytes((REC_CALLS, ENDIAN_LE)) + _dumps(calls_wire)
    if shards is None:
        sh = bytes((REC_SHARDS, ENDIAN_LE, 0))
    else:
        arr = np.asarray([int(s) for s in shards], dtype="<i8")
        sh = bytes((REC_SHARDS, ENDIAN_LE, 1)) + _U32.pack(arr.size) \
            + arr.tobytes()
    return MAGIC + encode_frame(head) + encode_frame(sh)


def decode_request(data: bytes) -> tuple[list[dict], list[int] | None, int]:
    """(call batch dicts, pinned shards or None, frame count)."""
    frames = list(iter_frames(data))
    if len(frames) != 2:
        raise FrameError(
            f"query wire request has {len(frames)} frames, expected 2")
    head, sh = frames
    if head[0] != REC_CALLS:
        raise FrameError(f"first request frame is type {head[0]}, "
                         f"expected calls ({REC_CALLS})")
    _check_endian(head, "calls")
    try:
        calls_wire = json.loads(bytes(head[2:]))
    except ValueError as e:
        raise FrameError(f"bad call batch JSON: {e}")
    if not isinstance(calls_wire, list):
        raise FrameError("call batch is not a list")
    if sh[0] != REC_SHARDS:
        raise FrameError(f"second request frame is type {sh[0]}, "
                         f"expected shards ({REC_SHARDS})")
    _check_endian(sh, "shards")
    if len(sh) < 3:
        raise FrameError("shards record truncated")
    if sh[2] == 0:
        if len(sh) != 3:
            raise FrameError("shards record length mismatch")
        return calls_wire, None, len(frames)
    if len(sh) < 7:
        raise FrameError("shards record truncated")
    (n,) = _U32.unpack_from(sh, 3)
    if len(sh) != 7 + 8 * n:
        raise FrameError("shards record length mismatch")
    shards = np.frombuffer(sh, dtype="<i8", count=n, offset=7).tolist()
    return calls_wire, shards, len(frames)


def encode_response(results: list, trailer: dict) -> tuple[bytes, int]:
    """(body, frame count): magic + one frame per result + the trailer
    frame (compact-JSON piggybacks, REQUIRED last — it doubles as the
    end-of-stream marker)."""
    frames = [encode_frame(encode_result(r)) for r in results]
    frames.append(encode_frame(bytes((REC_TRAILER,)) + _dumps(trailer)))
    return MAGIC + b"".join(frames), len(frames)


def decode_response(data: bytes) -> tuple[list, dict, int]:
    """(results, trailer piggybacks, frame count)."""
    results = []
    trailer = None
    nframes = 0
    for payload in iter_frames(data):
        nframes += 1
        if trailer is not None:
            raise FrameError("frame after the response trailer")
        if payload[0] == REC_TRAILER:
            try:
                trailer = json.loads(bytes(payload[1:]))
            except ValueError as e:
                raise FrameError(f"bad response trailer JSON: {e}")
            if not isinstance(trailer, dict):
                raise FrameError("response trailer is not an object")
            continue
        results.append(decode_result(payload))
    if trailer is None:
        raise FrameError(
            "query wire response truncated (no trailer frame)")
    return results, trailer, nframes
