"""Prepared-statement cache: skip parse/resolve/parametrize for repeat
query shapes.

The engine's per-query host cost is dominated by parsing and plan
resolution (the reference pays the same per query — pql.peg machine +
executeCall dispatch — but its per-query device round trip is nanoseconds,
ours is a full dispatch).  Real databases solve this with statement caches
keyed by the query text with literals stripped (Postgres fingerprinting,
Oracle cursor sharing); this module is that, adapted to the plan IR:

1. ``fingerprint`` replaces every integer literal in the PQL text with
   ``?`` (quoted strings and timestamps are preserved) and extracts the
   literal values.  The template string is the cache key.
2. On first sight of a template the query is parsed with literal tagging
   (pql.parser ``mkint`` -> pql.ast.LitInt), resolved and parametrized with
   provenance tracing (plan.parametrize(trace=True)), and the resulting
   slotted plans + batched dispatch structure are stored as a
   ``PreparedEntry``.
3. On a hit, the entry rebuilds each group's ``[B, P]`` params matrix from
   the new literal values with vectorized numpy and dispatches straight to
   the mesh executor — no parsing, no resolution, no per-call Python.

Safety: replaying a resolved plan with new values is only sound when the
new values would have taken the same structural branches during
resolution.  Every value-dependent branch records an interval *guard*
(plan.Resolver._guard); sign regions and row-id bounds are guarded by
``parametrize``; literals that never reached a dynamic param slot are
pinned to exact equality.  Any guard failure falls back to the classic
path (slower, always correct).  Entries are invalidated by the global
schema epoch (core.bump_schema_epoch) on DDL or BSI bit-depth growth.

The reference has no equivalent component (its per-query parse cost is
irrelevant at Go speeds); the closest analog is the executor's per-shape
executable cache mandated by SURVEY.md §7 ("plan->executable cache keyed by
call tree shape"), which this extends from compiled kernels up through the
parser.
"""

from __future__ import annotations

import re
from collections import OrderedDict

import numpy as np

from ..core import schema_epoch
from ..native import fingerprint_native
from ..pql import parse
from ..pql.ast import LitInt, Query
from ..utils.locks import make_lock
from .plan import Resolver, parametrize

# Integer literals only: quoted strings and bare timestamps pass through
# unchanged (they stay part of the template).  The lookaround classes keep
# digits inside identifiers/barewords/floats (``field1``, ``1a2b``, ``1.5``,
# ``2017-01-01``) out of the value list.  The whole pattern is one capture
# group (with the int literal as an inner group) so ``split`` can rebuild
# the template at C speed — a Python callback per match costs ~30 µs/query
# on the serving hot path.
_FP = re.compile(
    r"('(?:[^'\\]|\\.)*'"
    r'|"(?:[^"\\]|\\.)*"'
    r"|\d{4}-[01]\d-[0-3]\dT\d\d:\d\d"
    r"|(?<![\w.:-])(-?\d+)(?![\w.:-]))")


def fingerprint(query: str):
    """(template, values list): the query text with int literals replaced
    by '?' and the literal values in source order."""
    template, values = _fingerprint_fast(query)
    if isinstance(values, np.ndarray):
        values = values.tolist()
    return template, values


def _fingerprint_fast(query: str):
    """Hot-path variant: values may come back as an int64 ndarray (C
    scanner, native/fingerprint.c — memory-speed) or a list of Python
    ints (regex fallback: non-ASCII text, int64 overflow, missing
    toolchain).  Internal because ndarray values break ``==`` users."""
    native = fingerprint_native(query)
    if native is not None:
        return native
    return _fingerprint_py(query)


def _fingerprint_py(query: str):
    """Pure-Python fingerprint: one regex split, list slicing, one join —
    no per-match Python callback."""
    parts = _FP.split(query)
    if len(parts) == 1:
        return query, []
    texts = parts[0::3]
    fulls = parts[1::3]
    ints = parts[2::3]
    values = [int(x) for x in ints if x is not None]
    out = []
    for t, fl, iv in zip(texts, fulls, ints):
        out.append(t)
        out.append("?" if iv is not None else fl)
    out.append(texts[-1])
    return "".join(out), values


def fingerprint_spans(query: str) -> dict[int, int]:
    """token-start -> literal index for the parser's mkint hook (build
    path only — hits never need spans)."""
    spans: dict[int, int] = {}
    i = 0
    for m in _FP.finditer(query):
        if m.group(2) is not None:
            spans[m.start(2)] = i
            i += 1
    return spans


_BATCHABLE = {"Count", "Sum", "TopN"}
_EMPTY_PARAMS = np.zeros(0, dtype=np.int32)


class _Group:
    """One batched dispatch: B same-shape calls -> one executable invocation.

    ``build_params(values)`` reconstructs the [B, P] int32 params matrix:
    params[b, j] = (sgn*(values[lit]+add) >> shift) & mask for dynamic
    slots, the prepared constant for the rest — all vectorized.
    """

    __slots__ = ("kind", "slotted", "call_idxs", "const", "lit", "add",
                 "sgn", "shift", "mask", "extra")

    def __init__(self, kind, slotted, call_idxs, params_rows, prov_rows,
                 extra):
        self.kind = kind
        self.slotted = slotted
        self.call_idxs = call_idxs
        self.extra = extra
        B = len(call_idxs)
        P = params_rows[0].size if params_rows else 0
        self.const = (np.stack(params_rows).astype(np.int64) if P
                      else np.zeros((B, 0), dtype=np.int64))
        lit = np.full((B, P), -1, dtype=np.int64)
        add = np.zeros((B, P), dtype=np.int64)
        sgn = np.ones((B, P), dtype=np.int64)
        shift = np.zeros((B, P), dtype=np.int64)
        mask = np.zeros((B, P), dtype=np.int64)
        for b, prov in enumerate(prov_rows):
            for j, p in enumerate(prov):
                if p is None:
                    continue
                l, a, neg, sh, mk = p
                lit[b, j] = l
                add[b, j] = a
                sgn[b, j] = -1 if neg else 1
                shift[b, j] = sh
                mask[b, j] = mk
        self.lit = lit
        self.add = add
        self.sgn = sgn
        self.shift = shift
        self.mask = mask

    def build_params(self, values: np.ndarray) -> np.ndarray:
        if self.lit.size == 0:
            return self.const.astype(np.int32)
        dyn = self.lit >= 0
        vals = values[np.where(dyn, self.lit, 0)]
        computed = ((self.sgn * (vals + self.add)) >> self.shift) & self.mask
        return np.where(dyn, computed, self.const).astype(np.int32)


class PreparedEntry:
    __slots__ = ("epoch", "n_calls", "groups", "g_lit", "g_lo", "g_hi")

    def __init__(self, epoch, n_calls, groups, guards):
        self.epoch = epoch
        self.n_calls = n_calls
        self.groups = groups
        if guards:
            self.g_lit = np.asarray([g[0] for g in guards], dtype=np.int64)
            self.g_lo = np.asarray([g[1] for g in guards], dtype=np.int64)
            self.g_hi = np.asarray([g[2] for g in guards], dtype=np.int64)
        else:
            self.g_lit = np.zeros(0, dtype=np.int64)
            self.g_lo = self.g_hi = self.g_lit

    def guards_ok(self, values: np.ndarray) -> bool:
        if self.g_lit.size == 0:
            return True
        v = values[self.g_lit]
        return bool(np.all((v >= self.g_lo) & (v <= self.g_hi)))

    def run(self, ex, index: str, values: np.ndarray, shards):
        """Dispatch all groups, then resolve with one device fetch.
        Returns the results list, in call order.  With whole-query on
        (docs/whole-query.md) the WHOLE template replays as one pjit
        program launch; otherwise (or on an unsupported shape) dispatch
        rides the cross-query batcher (parallel/batcher.py) per group.
        Either way concurrent requests replaying the same template fuse
        into one device launch — the serving hot path the dynamic
        batching exists for."""
        from .executor import _resolve_pendings, _run_batched_groups

        holder = ex.holder
        if shards is None:
            idx = holder.index(index)
            shards = sorted(idx.available_shards())
        results: list = [None] * self.n_calls
        groups = [(g.kind, g.slotted, g.build_params(values),
                   g.call_idxs, g.extra) for g in self.groups]
        if ex.wholequery is not None and ex.whole_query:
            from ..parallel.wholequery import WholeQueryUnsupported
            try:
                ex._wq_run_batched(index, shards, groups, results)
                ex.wq_requests += 1
                ex.stats.count("wholequery.requests")
                return _resolve_pendings(results)
            except WholeQueryUnsupported as e:
                ex._note_wq_fallback(index, e)
                results = [None] * self.n_calls
        _run_batched_groups(ex.batcher, holder, index, shards, groups,
                            results)
        return _resolve_pendings(results)


_UNCACHEABLE = "uncacheable"


class PreparedCache:
    """Template -> PreparedEntry, LRU-bounded; thread-safe."""

    def __init__(self, executor, max_entries: int = 256):
        self.executor = executor
        self.max_entries = max_entries
        self._lock = make_lock("prepared")
        self._entries: OrderedDict = OrderedDict()
        # observability (surfaced at /debug/vars via utils.stats)
        self.hits = 0
        self.misses = 0
        self.guard_misses = 0

    # -- lookup/execute ----------------------------------------------------

    def attempt(self, index: str, query: str, shards):
        """Try to serve ``query`` from the cache.  Returns
        (True, results) on a hit; (False, parsed_query_or_None) on a miss
        — the parsed AST (literal-tagged, tags invisible to the classic
        path) is handed back so the caller never parses twice."""
        template, values = _fingerprint_fast(query)
        key = (index, template)
        with self._lock:
            entry = self._entries.get(key)
            if entry is not None:
                self._entries.move_to_end(key)
        if isinstance(values, np.ndarray):
            vals = values
        else:
            try:
                vals = np.asarray(values, dtype=np.int64) if values else \
                    np.zeros(0, dtype=np.int64)
            except OverflowError:
                # a literal beyond int64 can't ride the params machinery;
                # the classic path (arbitrary-precision ints) owns it
                self.misses += 1
                return False, None

        if entry is _UNCACHEABLE:
            self.misses += 1
            return False, None
        if isinstance(entry, PreparedEntry):
            if entry.epoch == schema_epoch() and entry.guards_ok(vals):
                self.hits += 1
                return True, entry.run(self.executor, index, vals, shards)
            if entry.epoch != schema_epoch():
                with self._lock:
                    self._entries.pop(key, None)
            else:
                self.guard_misses += 1
                return False, None  # entry stays; these values take another
                #                     branch -> classic path

        # build: tagged parse + prepare; on ineligibility remember that
        self.misses += 1
        spans = fingerprint_spans(query)
        q = parse(query, mkint=lambda v, s: (
            LitInt(v, spans[s], v - int(values[spans[s]]))
            if s in spans else v))
        entry = self._prepare(index, q, values)
        with self._lock:
            self._entries[key] = entry if entry is not None else _UNCACHEABLE
            while len(self._entries) > self.max_entries:
                self._entries.popitem(last=False)
        if entry is not None:
            return True, entry.run(self.executor, index, vals, shards)
        return False, q

    # -- preparation -------------------------------------------------------

    def _prepare(self, index: str, q: Query, values) -> PreparedEntry | None:
        """Resolve + parametrize every call with provenance; None when the
        template can't be soundly cached (non-batchable calls, key
        translation, wall-clock-dependent time ranges)."""
        ex = self.executor
        if ex.mesh_exec is None:
            return None
        if ex.translator.needs_translation(index):
            return None
        if ex.holder.index(index) is None:
            return None  # classic path raises the proper error
        epoch = schema_epoch()
        guards: list = []
        descs: list = []
        for c in q.calls:
            if c.name not in _BATCHABLE:
                return None
            d = self._desc(index, c, guards)
            if d is None:
                return None
            descs.append(d)

        # literals that never reached a dynamic param slot are structural:
        # pin them to exact equality
        dyn_lits = set()
        for d in descs:
            for p in d["prov"]:
                if p is not None:
                    dyn_lits.add(p[0])
        for i, v in enumerate(values):
            if i not in dyn_lits:
                guards.append((i, v, v))

        groups: dict[tuple, list[int]] = {}
        for i, d in enumerate(descs):
            groups.setdefault(d["key"], []).append(i)
        built = []
        for key, idxs in groups.items():
            ds = [descs[i] for i in idxs]
            extra = ds[0]["extra"]
            if ds[0]["kind"] == "topn":
                # the group key omits n/ids, so calls in one group may
                # carry different ones — keep them per call, matching the
                # classic grouped path
                extra = {"field": extra["field"], "view": extra["view"],
                         "ids_n": [(d["extra"]["ids"], d["extra"]["n"])
                                   for d in ds]}
            built.append(_Group(ds[0]["kind"], ds[0]["slotted"], idxs,
                                [d["params"] for d in ds],
                                [d["prov"] for d in ds], extra))
        return PreparedEntry(epoch, len(q.calls), built, guards)

    def _desc(self, index: str, c, guards: list):
        """Traced analog of Executor._batch_desc.  Appends guards; returns
        None for anything the batched executables can't express."""
        ex = self.executor
        sink: list = []
        resolver = Resolver(ex.holder, index, guard_sink=sink)

        def slot_plan(call):
            plan = resolver.resolve_bitmap(call)
            return parametrize(plan, trace=True)

        if c.name == "Count":
            if len(c.children) != 1:
                return None
            slotted, params, prov, pg = slot_plan(c.children[0])
            if resolver.uncacheable:
                return None
            guards.extend(sink)
            guards.extend(pg)
            return {"kind": "count", "key": ("count", repr(slotted)),
                    "slotted": slotted, "params": params, "prov": prov,
                    "extra": None}
        if c.name == "Sum":
            f = ex._bsi_field(index, c)
            if c.children:
                slotted, params, prov, pg = slot_plan(c.children[0])
            else:
                slotted, params, prov, pg = None, _EMPTY_PARAMS, [], []
            if resolver.uncacheable:
                return None
            guards.extend(sink)
            guards.extend(pg)
            return {"kind": "sum", "key": ("sum", f.name, repr(slotted)),
                    "slotted": slotted, "params": params, "prov": prov,
                    "extra": {"field": f.name, "view": f.bsi_view_name(),
                              "base": f.options.base}}
        # TopN
        from .executor import TOPN_EXTRAS
        if any(k in c.args for k in TOPN_EXTRAS):
            return None  # extras need extra device passes + attr reads
        field_name, ok = c.string_arg("_field")
        if not ok or ex.holder.field(index, field_name) is None:
            return None
        if not c.children and "ids" not in c.args and \
                ex.holder.field(index, field_name).options.cache_type \
                in ("ranked", "lru"):
            # unfiltered TopN on a rank-cached field belongs to the rank
            # cache's exact candidate path (executor._execute_topn ->
            # cache/rank.topn_from_rank) — host-side, no device dispatch;
            # a prepared replay would re-route it to a full device scan
            return None
        if c.children:
            slotted, params, prov, pg = slot_plan(c.children[0])
        else:
            slotted, params, prov, pg = None, _EMPTY_PARAMS, [], []
        if resolver.uncacheable:
            return None
        guards.extend(sink)
        guards.extend(pg)
        n, _ = c.uint_arg("n")
        ids = c.args.get("ids")
        if ids is not None:
            ids = [int(x) for x in ids]
        from ..core import VIEW_STANDARD
        return {"kind": "topn", "key": ("topn", field_name, repr(slotted)),
                "slotted": slotted, "params": params, "prov": prov,
                "extra": {"field": field_name, "view": VIEW_STANDARD,
                          "ids": ids, "n": n}}
