"""Query-time key translation + result back-translation
(executor.go:2610 translateCalls / :2781 translateResults).

Before execution, string keys in the call tree are rewritten to uint64 ids
(creating ids for unknown keys, like the reference's TranslateKey); after
execution, ids in results are mapped back to keys.  In a cluster this runs
once at the coordinating node — fanned-out internal calls carry ids only.
"""

from __future__ import annotations

from ..pql import Call
from ..storage.field import FIELD_TYPE_BOOL
from .results import (
    GroupCount, Pair, RowIdentifiers, RowResult, ValCount,
)


class TranslationError(ValueError):
    pass


class Translator:
    def __init__(self, holder):
        self.holder = holder

    # -- call rewrite (executor.go:2622 translateCall) ---------------------

    def needs_translation(self, index: str) -> bool:
        idx = self.holder.index(index)
        if idx is None:
            return False
        return idx.keys or any(f.options.keys
                               for f in idx.fields.values())

    def translate_query(self, index: str, query):
        idx = self.holder.index(index)
        if idx is None:
            return query
        for c in query.calls:
            self._translate_call(idx, c)
        return query

    def _translate_call(self, idx, c: Call):
        # arg-name switch (executor.go:2624-2644)
        col_key = row_key = field_name = None
        if c.name in ("Set", "Clear", "Row", "Range", "SetColumnAttrs",
                      "ClearRow", "Store"):
            col_key = "_col"
            fa = c.field_arg()
            if fa is not None:
                field_name = row_key = fa[0]
        elif c.name == "SetRowAttrs":
            row_key = "_row"
            field_name, _ = c.string_arg("_field")
        elif c.name == "Rows":
            field_name, _ = c.string_arg("_field")
            row_key = "previous"
            col_key = "column"
        elif c.name == "GroupBy":
            self._translate_group_by(idx, c)
            return
        else:
            col_key = "col"
            field_name, _ = c.string_arg("field")
            row_key = "row"

        # column key (index-level store)
        if col_key is not None and col_key in c.args:
            v = c.args[col_key]
            if idx.keys:
                if v is not None and not isinstance(v, str):
                    raise TranslationError(
                        "column value must be a string when index 'keys' "
                        "option enabled")
                if isinstance(v, str) and v:
                    c.args[col_key] = idx.translate_store().translate_key(v)
            elif isinstance(v, str):
                raise TranslationError(
                    "string 'col' value not allowed unless index 'keys' "
                    "option enabled")

        # row key (field-level store); bool fields translate directly
        # (executor.go:2669-2680)
        if field_name and row_key is not None and row_key in c.args:
            f = idx.field(field_name)
            if f is not None:
                v = c.args[row_key]
                if f.options.type == FIELD_TYPE_BOOL:
                    if isinstance(v, bool):
                        c.args[row_key] = int(v)
                elif f.options.keys:
                    if v is not None and not isinstance(v, str):
                        raise TranslationError(
                            "row value must be a string when field 'keys' "
                            "option enabled")
                    if isinstance(v, str) and v:
                        c.args[row_key] = \
                            f.translate_store().translate_key(v)
                elif isinstance(v, str):
                    raise TranslationError(
                        "string 'row' value not allowed unless field "
                        "'keys' option enabled")

        for child in c.children:
            self._translate_call(idx, child)

    def _translate_group_by(self, idx, c: Call):
        """(executor.go:2716 translateGroupByCall)"""
        for child in c.children:
            self._translate_call(idx, child)
        prev = c.args.get("previous")
        if prev is None:
            return
        if not isinstance(prev, list):
            raise TranslationError("'previous' argument must be a list")
        rows_children = [ch for ch in c.children if ch.name == "Rows"]
        if len(rows_children) != len(prev):
            raise TranslationError(
                f"mismatched lengths for previous: {len(prev)} and "
                f"children: {len(rows_children)}")
        for i, child in enumerate(rows_children):
            fname, _ = child.string_arg("_field")
            f = idx.field(fname)
            if f is None:
                raise TranslationError(f"field not found: {fname}")
            if f.options.keys:
                if not isinstance(prev[i], str):
                    raise TranslationError(
                        "prev value must be a string when field 'keys' "
                        "option enabled")
                prev[i] = f.translate_store().translate_key(prev[i])
            elif isinstance(prev[i], str):
                raise TranslationError(
                    f"got string row val {prev[i]!r} in 'previous' for "
                    f"field {fname} which doesn't use string keys")

    # -- result back-translation (executor.go:2781 translateResults) -------

    def translate_results(self, index: str, calls, results):
        idx = self.holder.index(index)
        if idx is None:
            return results
        return [self._translate_result(idx, c, r)
                for c, r in zip(calls, results)]

    def _field_of(self, idx, c: Call):
        fname, ok = c.string_arg("_field")
        if not ok:
            fa = c.field_arg()
            fname = fa[0] if fa else ""
        return idx.field(fname) if fname else None

    def _translate_result(self, idx, c: Call, r):
        if isinstance(r, RowResult):
            if idx.keys:
                store = idx.translate_store()
                r.keys = [store.translate_id(int(col)) or ""
                          for col in r.columns()]
            return r
        if isinstance(r, RowIdentifiers):
            f = self._field_of(idx, c)
            if f is not None and f.options.keys:
                store = f.translate_store()
                r.keys = [store.translate_id(i) or "" for i in r.rows]
            return r
        if isinstance(r, list) and r and isinstance(r[0], Pair):
            f = self._field_of(idx, c)
            if f is not None and f.options.keys:
                store = f.translate_store()
                for p in r:
                    p.key = store.translate_id(p.id) or ""
            return r
        if isinstance(r, list) and r and isinstance(r[0], GroupCount):
            for g in r:
                for fr in g.group:
                    f = idx.field(fr.field)
                    if f is not None and f.options.keys:
                        fr.row_key = \
                            f.translate_store().translate_id(fr.row_id) or ""
            return r
        if isinstance(r, ValCount):
            return r
        return r
