"""Query result types (reference row.go Row, executor.go ValCount/Pairs/
GroupCount/RowIdentifiers)."""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Any

import numpy as np

from ..core import SHARD_WIDTH
from ..ops import bitset


class RowResult:
    """A query-result bitmap: per-shard segments merged late (row.go:26 Row,
    :332 rowSegment).  Segments stay device-resident (jax arrays) until
    columns()/count() forces them host-side."""

    def __init__(self, segments: dict[int, Any] | None = None,
                 keys: list[str] | None = None, attrs: dict | None = None):
        self.segments = segments or {}   # shard -> uint32[W] (jnp or np)
        self.keys = keys or []
        self.attrs = attrs or {}         # row attrs (row.go Row.Attrs)
        # [{"id", "attrs"}] filled by Options(columnAttrs=true); lifted to
        # the response's top-level "columnAttrs" by the HTTP layer
        self.column_attrs: list = []

    # -- algebra (row.go:67-260) ------------------------------------------

    def _binary(self, other: "RowResult", fn, union_domain: bool):
        out = {}
        shards = set(self.segments) | set(other.segments) if union_domain \
            else set(self.segments) & set(other.segments)
        for s in shards:
            a = self.segments.get(s)
            b = other.segments.get(s)
            if a is None:
                a = np.zeros_like(np.asarray(b))
            if b is None:
                b = np.zeros_like(np.asarray(a))
            out[s] = fn(a, b)
        return RowResult(out)

    def intersect(self, other):
        return self._binary(other, bitset.intersect, union_domain=False)

    def union(self, other):
        return self._binary(other, bitset.union, union_domain=True)

    def difference(self, other):
        out = {}
        for s, a in self.segments.items():
            b = other.segments.get(s)
            out[s] = a if b is None else bitset.difference(a, b)
        return RowResult(out)

    def xor(self, other):
        return self._binary(other, bitset.xor, union_domain=True)

    # -- materialisation ---------------------------------------------------

    def count(self) -> int:
        return sum(int(bitset.count(seg)) for seg in self.segments.values())

    def columns(self) -> np.ndarray:
        """Absolute sorted column ids across shards (row.go Columns)."""
        parts = []
        for shard in sorted(self.segments):
            cols = bitset.unpack_columns(np.asarray(self.segments[shard]))
            parts.append(cols + shard * SHARD_WIDTH)
        if not parts:
            return np.zeros(0, dtype=np.int64)
        return np.concatenate(parts)

    def shard_counts(self) -> dict[int, int]:
        return {s: int(bitset.count(seg)) for s, seg in self.segments.items()}

    def is_empty(self) -> bool:
        return self.count() == 0

    def to_dict(self) -> dict:
        d: dict[str, Any] = {"columns": self.columns().tolist()}
        if self.attrs:
            d["attrs"] = self.attrs
        if self.keys:
            d["keys"] = self.keys
        return d


@dataclass
class ValCount:
    """Sum/Min/Max result (executor.go:2995 ValCount)."""
    val: int = 0
    count: int = 0

    def add(self, other: "ValCount") -> "ValCount":
        return ValCount(self.val + other.val, self.count + other.count)

    def smaller(self, other: "ValCount") -> "ValCount":
        if other.count == 0:
            return self
        if self.count == 0 or other.val < self.val:
            return other
        if other.val == self.val:
            return ValCount(self.val, self.count + other.count)
        return self

    def larger(self, other: "ValCount") -> "ValCount":
        if other.count == 0:
            return self
        if self.count == 0 or other.val > self.val:
            return other
        if other.val == self.val:
            return ValCount(self.val, self.count + other.count)
        return self

    def to_dict(self) -> dict:
        return {"value": self.val, "count": self.count}


@dataclass
class Pair:
    """TopN entry (pilosa.go Pair)."""
    id: int
    count: int
    key: str = ""

    def to_dict(self) -> dict:
        d = {"id": self.id, "count": self.count}
        if self.key:
            d["key"] = self.key
        return d


def acc_counts(acc, counts):
    """Sum two count arrays whose LAST axis lengths differ (row capacities
    vary across shards/groups; leading axes must match).  Mutates and
    returns the longer one."""
    import numpy as np
    counts = np.asarray(counts, dtype=np.int64)
    if counts.shape[-1] > acc.shape[-1]:
        counts = counts.copy()
        counts[..., : acc.shape[-1]] += acc
        return counts
    acc[..., : counts.shape[-1]] += counts
    return acc


def merge_pairs(pair_lists: list[list[Pair]]) -> list[Pair]:
    """Sum counts by id (executor.go:912 Pairs.Add reduce)."""
    acc: dict[int, int] = {}
    for pairs in pair_lists:
        for p in pairs:
            acc[p.id] = acc.get(p.id, 0) + p.count
    return [Pair(i, c) for i, c in acc.items()]


def sort_pairs(pairs: list[Pair], n: int | None = None) -> list[Pair]:
    """Descending by count, ascending id tiebreak (pilosa.go Pairs.Sort)."""
    out = sorted(pairs, key=lambda p: (-p.count, p.id))
    return out[:n] if n else out


def rank_counts(counts, n: int | None = None, ids=None) -> list[Pair]:
    """Vectorized TopN ranking over a per-row count vector: nonzero (or
    ``ids``-selected) rows sorted by (-count, id), materializing Pair
    objects only for the returned n — the fragment.top/rankCache
    replacement must not build a Python object per nonzero row at 50k-row
    cache scale (fragment.go:1570, cache.go:136)."""
    import numpy as np
    counts = np.asarray(counts)
    if ids:  # empty ids list = no filter (fragment.go:1618 len check)
        sel = np.asarray([i for i in ids if 0 <= i < counts.size],
                         dtype=np.int64)
        vals = counts[sel] if sel.size else np.zeros(0, counts.dtype)
        keep = vals > 0
        nz, vals = sel[keep], vals[keep]
    else:
        nz = np.nonzero(counts)[0]
        vals = counts[nz]
    order = np.lexsort((nz, -vals))
    if n:
        order = order[:n]
    return [Pair(int(i), int(c)) for i, c in zip(nz[order], vals[order])]


@dataclass
class FieldRow:
    """One (field, row) of a GroupBy group (executor.go FieldRow)."""
    field: str
    row_id: int
    row_key: str = ""

    def to_dict(self) -> dict:
        d: dict[str, Any] = {"field": self.field, "rowID": self.row_id}
        if self.row_key:
            d["rowKey"] = self.row_key
        return d


@dataclass
class GroupCount:
    group: list[FieldRow]
    count: int

    def to_dict(self) -> dict:
        return {"group": [g.to_dict() for g in self.group],
                "count": self.count}


@dataclass
class RowIdentifiers:
    """Rows() result (executor.go RowIdentifiers)."""
    rows: list[int] = field(default_factory=list)
    keys: list[str] = field(default_factory=list)

    def to_dict(self) -> dict:
        return {"rows": self.rows} if not self.keys else {"keys": self.keys}
