"""Bitmap-call plan IR and the per-shard XLA compiler.

The reference executes call trees interpretively, one roaring op at a time
(executor.go:651 executeBitmapCallShard).  Here a PQL bitmap call tree is
first *resolved* against the schema into a static plan IR — field/view lookup,
BSI base-value computation (field.go:1574 baseValue), time-range view
expansion (executor.go:1441 executeRowShard) — and the IR is then compiled to
ONE jitted XLA computation per (plan, input-shapes) signature, cached.  A
query like Count(Intersect(Row, Row, Not(Row))) runs as a single fused kernel
per shard: every AND/OR/popcount collapses into one pass over HBM.

Plan node types double as cache keys via their repr.
"""

from __future__ import annotations

from dataclasses import dataclass
from datetime import datetime, timedelta, timezone
from typing import Any

import jax
import jax.numpy as jnp
import numpy as np

from ..core import SHARD_WORDS, VIEW_STANDARD
from ..ops import bitset, bsi
from ..pql import BETWEEN, Call, Condition, EQ, GT, GTE, LT, LTE, NEQ
from ..storage.field import FIELD_TYPE_INT, Field
from ..storage import time_quantum as tq


class PlanError(ValueError):
    pass


# -- plan IR ---------------------------------------------------------------

@dataclass(frozen=True)
class RowPlan:
    """Row(field=id) over one or more views (standard or time views)."""
    field: str
    views: tuple[str, ...]
    row_id: int


@dataclass(frozen=True)
class BSIPlan:
    """Row(field <op> value) against a bsig_ view.  op in bsi.range_op's
    vocabulary, plus "notnull" and "empty" specials."""
    field: str
    view: str
    op: str                  # eq|neq|lt|le|gt|ge|between|notnull|empty
    value: int = 0
    value2: int = 0          # between upper bound


@dataclass(frozen=True)
class NotPlan:
    existence: "RowPlan"
    child: Any


@dataclass(frozen=True)
class ShiftPlan:
    child: Any
    n: int


@dataclass(frozen=True)
class NaryPlan:
    op: str                  # intersect|union|difference|xor
    children: tuple[Any, ...]


@dataclass(frozen=True)
class ConstPlan:
    """All-zero segment."""


@dataclass(frozen=True)
class Slot:
    """Dynamic-parameter placeholder inside a plan.

    ``parametrize`` replaces literal row ids and BSI predicate values with
    Slots so the compiled executable is keyed by call-tree SHAPE — every
    ``Count(Row(f=N))`` shares one XLA program with N as a runtime argument
    (SURVEY §7 "one XLA computation per request ... cache keyed by call
    tree shape").  ``idx`` indexes the int32 params vector; ``sign``
    ("pos"/"zero"/"neg", BSI slots only) and ``width`` are structural."""
    idx: int
    sign: str = ""
    width: int = 1

    def __repr__(self):
        return f"${self.idx}:{self.sign}:{self.width}"


@dataclass(frozen=True)
class ReduceNode:
    """Whole-query reducer node (docs/whole-query.md).

    A parsed PQL request compiles to ONE pjit-ed XLA program over the
    global mesh-sharded bitmap arrays (parallel/wholequery.py); each
    call (or batch of same-shape calls) becomes one ReduceNode whose
    reduction — Count popcount-sums, TopN row-count accumulations, BSI
    slice counts, GroupBy combo grids — happens INSIDE the program as a
    partitioned reduction over the shard axis instead of host-assembled
    per-shard segments.  ``repr`` of the node tuple is the program's
    shape cache key (the same convention as the per-shard plan IR
    above): params ride as runtime arguments, so distinct literals
    share one compiled program.

    kind: count | segments | row_counts | bsi_sum | bsi_minmax
          | group_counts
    plan: the slotted bitmap plan (count/segments) or the slotted
          filter plan / None (field reducers)
    primary: (field, view) the reducer reads, () for plan reducers
    extra: structural extras — ("max",)/("min",) for bsi_minmax,
          (prefix_keys..., pad_c) for group_counts
    """
    kind: str
    plan: Any = None
    primary: tuple = ()
    extra: tuple = ()


def parametrize(plan, trace: bool = False):
    """Replace literal row ids / BSI values with Slots; returns
    (slotted_plan, params int32[P]).  repr(slotted_plan) is the shape cache
    key; params ride as a runtime argument.

    With ``trace=True`` returns (slotted, params, prov, guards) for the
    prepared-statement cache: ``prov[j]`` describes how params[j] derives
    from a query-string literal — ``(lit, add, neg, shift, mask)`` meaning
    ``((±(values[lit]+add)) >> shift) & mask`` — or None for a constant;
    ``guards`` are (lit, lo, hi) interval constraints on the raw literal
    values under which this derivation stays valid (sign regions, row-id
    bounds)."""
    from ..pql.ast import LitInt

    params: list[int] = []
    prov: list = []
    guards: list[tuple[int, int, int]] = []
    LO, HI = -(1 << 62), (1 << 62)

    def slot_row(row_id: int) -> Slot:
        s = Slot(len(params))
        params.append(int(row_id))
        if isinstance(row_id, LitInt):
            prov.append((row_id.lit, row_id.add, 0, 0, (1 << 31) - 1))
            # v + add must be a valid non-negative int32 row id
            guards.append((row_id.lit, -row_id.add,
                           (1 << 31) - 1 - row_id.add))
        else:
            prov.append(None)
        return s

    def slot_value(value: int) -> Slot:
        sign = "zero" if value == 0 else ("pos" if value > 0 else "neg")
        s = Slot(len(params), sign, bsi.MAG_BITS)
        mag = abs(int(value))
        tagged = isinstance(value, LitInt)
        if tagged:
            # pin the sign region: it selects the compiled code path
            if sign == "pos":
                guards.append((value.lit, 1 - value.add, HI - value.add))
            elif sign == "neg":
                guards.append((value.lit, LO - value.add, -1 - value.add))
            else:
                guards.append((value.lit, -value.add, -value.add))
        for i in range(bsi.MAG_BITS):
            params.append((mag >> i) & 1)
            # the zero path never reads the magnitude bits (and its guard is
            # exact equality), so they stay constant zeros
            prov.append((value.lit, value.add, int(value < 0), i, 1)
                        if tagged and sign != "zero" else None)
        return s

    def walk(p):
        if isinstance(p, RowPlan):
            return RowPlan(p.field, p.views, slot_row(p.row_id))
        if isinstance(p, BSIPlan):
            if p.op in ("notnull", "empty"):
                return p
            if p.op == "between":
                return BSIPlan(p.field, p.view, p.op,
                               slot_value(p.value), slot_value(p.value2))
            return BSIPlan(p.field, p.view, p.op, slot_value(p.value), 0)
        if isinstance(p, NotPlan):
            return NotPlan(walk(p.existence), walk(p.child))
        if isinstance(p, ShiftPlan):
            return ShiftPlan(walk(p.child), p.n)
        if isinstance(p, NaryPlan):
            return NaryPlan(p.op, tuple(walk(ch) for ch in p.children))
        return p  # ConstPlan

    slotted = walk(plan)
    arr = np.asarray(params, dtype=np.int32)
    if trace:
        return slotted, arr, prov, guards
    return slotted, arr


# -- resolution: pql.Call -> plan IR ---------------------------------------

class Resolver:
    """Resolves bitmap calls against a holder's schema (host-side, once per
    query).

    With a ``guard_sink`` list attached, every schema/value-dependent branch
    taken on a tagged literal (pql.ast.LitInt) appends an interval constraint
    (lit, lo, hi) under which the SAME branch would be taken again — the
    prepared-statement cache replays the resolved plan only while all guards
    hold.  ``uncacheable`` is set when the resolution depends on state that
    can change between calls with identical text (e.g. "now" for an omitted
    time-range end)."""

    def __init__(self, holder, index_name: str, guard_sink=None):
        self.holder = holder
        self.index = holder.index(index_name)
        if self.index is None:
            raise PlanError(f"index not found: {index_name}")
        self.index_name = index_name
        self.guard_sink = guard_sink
        self.uncacheable = False

    def _guard(self, value, lo=None, hi=None):
        """Record: the branch just taken holds while lo <= value <= hi."""
        from ..pql.ast import LitInt
        if self.guard_sink is None or not isinstance(value, LitInt):
            return
        lo = -(1 << 62) if lo is None else lo
        hi = (1 << 62) if hi is None else hi
        self.guard_sink.append((value.lit, lo - value.add, hi - value.add))

    def field(self, name: str) -> Field:
        f = self.index.field(name)
        if f is None:
            raise PlanError(f"field not found: {name}")
        return f

    def resolve_bitmap(self, c: Call):
        name = c.name
        if name in ("Row", "Range"):
            return self._resolve_row(c)
        if name == "Intersect":
            if not c.children:
                raise PlanError("empty Intersect query is currently not "
                                "supported")
            return NaryPlan("intersect", tuple(
                self.resolve_bitmap(ch) for ch in c.children))
        if name == "Union":
            return NaryPlan("union", tuple(
                self.resolve_bitmap(ch) for ch in c.children))
        if name == "Difference":
            return NaryPlan("difference", tuple(
                self.resolve_bitmap(ch) for ch in c.children))
        if name == "Xor":
            return NaryPlan("xor", tuple(
                self.resolve_bitmap(ch) for ch in c.children))
        if name == "Not":
            if not self.index.track_existence:
                raise PlanError(
                    "Not() query requires existence tracking to be enabled "
                    "on the index")
            if len(c.children) != 1:
                raise PlanError("Not() requires exactly one input row")
            from ..core import EXISTENCE_FIELD_NAME
            return NotPlan(
                RowPlan(EXISTENCE_FIELD_NAME, (VIEW_STANDARD,), 0),
                self.resolve_bitmap(c.children[0]))
        if name == "Shift":
            # n defaults to 0 = identity (executor.go:1770, row.go:220)
            n, _ = c.uint_arg("n")
            if len(c.children) != 1:
                raise PlanError("Shift() requires exactly one input row")
            child = self.resolve_bitmap(c.children[0])
            return child if n == 0 else ShiftPlan(child, n)
        raise PlanError(f"unknown bitmap call: {name}")

    def _resolve_row(self, c: Call):
        # BSI condition form: Row(field <op> value)
        cond_arg = c.condition_arg()
        if cond_arg is not None:
            if len(c.args) > 1:
                raise PlanError("Row(): too many arguments")
            return self._resolve_bsi(*cond_arg)

        fa = c.field_arg()
        if fa is None:
            raise PlanError("Row() argument required: field")
        field_name, row_id = fa
        f = self.field(field_name)
        if not isinstance(row_id, int) or isinstance(row_id, bool):
            raise PlanError(f"Row() row id must be an integer, got "
                            f"{row_id!r} (key translation requires keys "
                            f"support)")

        from_arg = c.args.get("from") or c.args.get("_start")
        to_arg = c.args.get("to") or c.args.get("_end")
        if c.name == "Row" and from_arg is None and to_arg is None:
            return RowPlan(field_name, (VIEW_STANDARD,), row_id)

        quantum = f.options.time_quantum
        if not quantum:
            return ConstPlan()
        from_time = tq.parse_time(from_arg) if from_arg else datetime(1, 1, 1)
        if to_arg:
            to_time = tq.parse_time(to_arg)
        else:
            # executor.go:1506: now + 1 day when "to" omitted — the view set
            # depends on the wall clock, so the resolution can't be replayed
            self.uncacheable = True
            to_time = (datetime.now(timezone.utc).replace(tzinfo=None)
                       + timedelta(days=1))
        views = tuple(tq.views_by_time_range(
            VIEW_STANDARD, from_time, to_time, quantum))
        if not views:
            return ConstPlan()
        return RowPlan(field_name, views, row_id)

    def _resolve_bsi(self, field_name: str, cond: Condition):
        """(executor.go:1533 executeRowBSIGroupShard + field.go:1574
        baseValue)"""
        f = self.field(field_name)
        if f.options.type != FIELD_TYPE_INT:
            raise PlanError(f"field {field_name!r} is not an int field")
        view = f.bsi_view_name()
        base = f.options.base
        depth = f.options.bit_depth
        vmin = base - (1 << depth) + 1  # bitDepthMin (field.go:1638)
        vmax = base + (1 << depth) - 1  # bitDepthMax

        if cond.op == NEQ and cond.value is None:
            return BSIPlan(field_name, view, "notnull")
        if cond.op == BETWEEN:
            lo, hi = cond.value
            if hi < vmin:
                self._guard(hi, hi=vmin - 1)
                return BSIPlan(field_name, view, "empty")
            if lo > vmax:
                self._guard(hi, lo=vmin)
                self._guard(lo, lo=vmax + 1)
                return BSIPlan(field_name, view, "empty")
            self._guard(hi, lo=vmin)
            self._guard(lo, hi=vmax)
            if lo <= f.options.min and hi >= f.options.max:
                self._guard(lo, hi=f.options.min)
                self._guard(hi, lo=f.options.max)
                return BSIPlan(field_name, view, "notnull")
            # at least one of (lo > min, hi < max) held; pin the observed one
            if lo > f.options.min:
                self._guard(lo, lo=f.options.min + 1)
            else:
                self._guard(hi, hi=f.options.max - 1)
            # pin the clamp branches of max(lo, vmin) / min(hi, vmax)
            if lo >= vmin:
                self._guard(lo, lo=vmin)
            else:
                self._guard(lo, hi=vmin - 1)
            if hi <= vmax:
                self._guard(hi, hi=vmax)
            else:
                self._guard(hi, lo=vmax + 1)
            lo_b = max(lo, vmin) - base
            hi_b = min(hi, vmax) - base
            return BSIPlan(field_name, view, "between", lo_b, hi_b)

        value = cond.value
        if not isinstance(value, int) or isinstance(value, bool):
            raise PlanError("Row(): conditions only support integer values")

        # full-encompass fast paths -> notNull (executor.go:1650)
        if cond.op == LT and value > f.options.max:
            self._guard(value, lo=f.options.max + 1)
            return BSIPlan(field_name, view, "notnull")
        if cond.op == LTE and value >= f.options.max:
            self._guard(value, lo=f.options.max)
            return BSIPlan(field_name, view, "notnull")
        if cond.op == GT and value < f.options.min:
            self._guard(value, hi=f.options.min - 1)
            return BSIPlan(field_name, view, "notnull")
        if cond.op == GTE and value <= f.options.min:
            self._guard(value, hi=f.options.min)
            return BSIPlan(field_name, view, "notnull")
        # fast paths not taken: pin their complements
        if cond.op == LT:
            self._guard(value, hi=f.options.max)
        elif cond.op == LTE:
            self._guard(value, hi=f.options.max - 1)
        elif cond.op == GT:
            self._guard(value, lo=f.options.min)
        elif cond.op == GTE:
            self._guard(value, lo=f.options.min + 1)

        # baseValue with out-of-range handling (field.go:1574)
        out_of_range = False
        base_value = 0
        if cond.op in (GT, GTE):
            if value > vmax:
                self._guard(value, lo=vmax + 1)
                out_of_range = True
            elif value > vmin:
                self._guard(value, lo=vmin + 1, hi=vmax)
                base_value = value - base
            else:
                self._guard(value, hi=vmin)
                base_value = vmin - base
        elif cond.op in (LT, LTE):
            if value < vmin:
                self._guard(value, hi=vmin - 1)
                out_of_range = True
            elif value > vmax:
                self._guard(value, lo=vmax + 1)
                base_value = vmax - base
            else:
                self._guard(value, lo=vmin, hi=vmax)
                base_value = value - base
        else:  # EQ / NEQ
            if value < vmin:
                self._guard(value, hi=vmin - 1)
                out_of_range = True
            elif value > vmax:
                self._guard(value, lo=vmax + 1)
                out_of_range = True
            else:
                self._guard(value, lo=vmin, hi=vmax)
                base_value = value - base

        if out_of_range:
            if cond.op == NEQ:
                return BSIPlan(field_name, view, "notnull")
            return BSIPlan(field_name, view, "empty")

        op_map = {EQ: "eq", NEQ: "neq", LT: "lt", LTE: "le", GT: "gt",
                  GTE: "ge"}
        return BSIPlan(field_name, view, op_map[cond.op], base_value)


# -- compilation: plan IR -> jitted per-shard function ---------------------

def plan_inputs(plan) -> list[tuple[str, str]]:
    """Deterministic list of (field, view) fragment references of a plan."""
    out: list[tuple[str, str]] = []

    def walk(p):
        if isinstance(p, RowPlan):
            for v in p.views:
                key = (p.field, v)
                if key not in out:
                    out.append(key)
        elif isinstance(p, BSIPlan):
            if (p.field, p.view) not in out:
                out.append((p.field, p.view))
        elif isinstance(p, NotPlan):
            walk(p.existence)
            walk(p.child)
        elif isinstance(p, ShiftPlan):
            walk(p.child)
        elif isinstance(p, NaryPlan):
            for ch in p.children:
                walk(ch)

    walk(plan)
    return out


def eval_plan(plan, frags: dict[tuple[str, str], Any], params=None):
    """Trace a plan over fragment tensors.  ``frags`` maps (field, view) to a
    uint32[n_rows, W] array or None (missing fragment).  Returns uint32[W].

    Literal plans trace their constants into the program; slotted plans
    (``parametrize``) read row ids / predicate bits from the traced
    ``params`` vector so the compiled program is value-independent."""

    def zero():
        return jnp.zeros(SHARD_WORDS, dtype=jnp.uint32)

    def get_row(field, view, row_id):
        frag = frags.get((field, view))
        if frag is None:
            return None
        if isinstance(row_id, Slot):
            if frag.shape[0] == 0:
                return None
            rid = params[row_id.idx]
            return jnp.where(
                rid < frag.shape[0],
                jax.lax.dynamic_index_in_dim(
                    frag, jnp.minimum(rid, frag.shape[0] - 1), axis=0,
                    keepdims=False),
                jnp.zeros(frag.shape[-1], dtype=frag.dtype))
        if row_id >= frag.shape[0]:
            return None
        return frag[row_id]

    def mag_bits(slot: Slot):
        return params[slot.idx:slot.idx + slot.width]

    def ev(p):
        if isinstance(p, ConstPlan):
            return zero()
        if isinstance(p, RowPlan):
            segs = [s for v in p.views
                    if (s := get_row(p.field, v, p.row_id)) is not None]
            if not segs:
                return zero()
            if len(segs) == 1:
                return segs[0]
            return bitset.union_many(jnp.stack(segs))
        if isinstance(p, BSIPlan):
            frag = frags.get((p.field, p.view))
            if frag is None or p.op == "empty":
                return zero()
            if p.op == "notnull":
                return bsi.not_null(frag)
            if isinstance(p.value, Slot):
                if p.op == "between":
                    return bsi.range_between_dyn(
                        frag, p.value.sign, mag_bits(p.value),
                        p.value2.sign, mag_bits(p.value2))
                return bsi.range_op_dyn(frag, p.op, p.value.sign,
                                        mag_bits(p.value))
            if p.op == "between":
                return bsi.range_between(frag, p.value, p.value2)
            return bsi.range_op(frag, p.op, p.value)
        if isinstance(p, NotPlan):
            ex = ev(p.existence)
            return bitset.difference(ex, ev(p.child))
        if isinstance(p, ShiftPlan):
            return bitset.shift(ev(p.child), p.n)
        if isinstance(p, NaryPlan):
            segs = [ev(ch) for ch in p.children]
            if not segs:
                return zero()
            acc = segs[0]
            for s in segs[1:]:
                if p.op == "intersect":
                    acc = bitset.intersect(acc, s)
                elif p.op == "union":
                    acc = bitset.union(acc, s)
                elif p.op == "difference":
                    acc = bitset.difference(acc, s)
                else:
                    acc = bitset.xor(acc, s)
            return acc
        raise PlanError(f"unknown plan node: {p!r}")

    return ev(plan)


class PlanCompiler:
    """Caches jitted executables keyed by (plan SHAPE repr, reducer, input
    shape signature) — the "one XLA computation per request" cache
    (SURVEY.md §7).  Plans are parametrized first, so distinct row ids /
    predicate values reuse one executable with fresh runtime params."""

    REDUCERS = {
        None: lambda seg: seg,
        "count": bitset.count,
    }

    def __init__(self):
        self._cache: dict = {}

    def compiled(self, slotted_plan, input_keys, shapes, reducer=None):
        key = (repr(slotted_plan), tuple(input_keys), tuple(shapes), reducer)
        fn = self._cache.get(key)
        if fn is None:
            reduce_fn = self.REDUCERS[reducer]

            def run(params, *arrays):
                frags = {
                    k: a for k, a in zip(input_keys, arrays) if a is not None
                }
                return reduce_fn(eval_plan(slotted_plan, frags, params))

            fn = jax.jit(run)
            self._cache[key] = fn
        return fn

    def execute_shard(self, plan, holder, index_name: str, shard: int,
                      reducer=None):
        """Gather device inputs for one shard and run the compiled plan."""
        slotted, params = parametrize(plan)
        keys = plan_inputs(plan)
        arrays = []
        for field, view in keys:
            frag = holder.fragment(index_name, field, view, shard)
            arrays.append(None if frag is None else frag.device())
        shapes = tuple(
            None if a is None else a.shape for a in arrays)
        fn = self.compiled(slotted, keys, shapes, reducer)
        return fn(jnp.asarray(params), *arrays)
