"""Query executor: plan compiler + call dispatch (reference executor.go)."""

from .executor import ExecutionError, Executor  # noqa: F401
from .plan import PlanError  # noqa: F401
from .results import (  # noqa: F401
    FieldRow, GroupCount, Pair, RowIdentifiers, RowResult, ValCount,
)
