"""Executor: recursive PQL call dispatch over shards (executor.go:44-339).

The reference fans per-shard work out to a goroutine pool and reduces
streamed results (executor.go:2455 mapReduce).  Here each shard's bitmap
work is one cached XLA computation (see plan.py); shards are dispatched
asynchronously (jax queues them) and reduced on host.  Aggregations ship
only scalars/count-vectors back from the device.
"""

from __future__ import annotations

from datetime import datetime
from typing import Any

import numpy as np

from ..core import SHARD_WIDTH, SHARD_WORDS, VIEW_STANDARD
from ..ops import bitset, bsi
from ..pql import Call, parse
from ..storage.field import FIELD_TYPE_INT, FIELD_TYPE_BOOL
from ..storage import time_quantum as tq
from .plan import PlanCompiler, Resolver, parametrize, plan_inputs
from .results import (
    FieldRow, GroupCount, Pair, RowIdentifiers, RowResult, ValCount,
    acc_counts, rank_counts, sort_pairs,
)

BITMAP_CALLS = {"Row", "Range", "Intersect", "Union", "Difference", "Xor",
                "Not", "Shift"}
WRITE_CALLS = {"Set", "Clear", "ClearRow", "Store", "SetRowAttrs",
               "SetColumnAttrs"}


class ExecutionError(ValueError):
    pass


# TopN args that the batched/prepared fast paths cannot express — queries
# carrying any of them take the per-call path (and the cluster finalizes
# them globally at the coordinator).
TOPN_EXTRAS = ("tanimotoThreshold", "attrName", "attrValues")


def topn_extras(c: Call):
    """(tanimotoThreshold, attrName, attrValues) with the reference's
    argument validation (executor.go:930-960).  Shared by the local
    executor and the cluster fan-out (which must finalize these globally —
    per-node tanimoto would diverge from single-node answers)."""
    tan_thresh = c.args.get("tanimotoThreshold")
    attr_name = c.args.get("attrName")
    attr_values = c.args.get("attrValues")
    if attr_name is not None and attr_values is None:
        raise ExecutionError("TopN(attrName=...) requires attrValues")
    if attr_values is not None and attr_name is None:
        raise ExecutionError("TopN(attrValues=...) requires attrName")
    if tan_thresh is not None:
        if not isinstance(tan_thresh, int) or isinstance(tan_thresh, bool) \
                or not 0 < tan_thresh <= 100:
            raise ExecutionError(
                "tanimotoThreshold must be an integer in (0, 100]")
        if not c.children:
            raise ExecutionError("tanimotoThreshold requires a source row")
    return tan_thresh, attr_name, attr_values


class _PendingGroup:
    """One pending filling MANY result slots: a batched call group's B
    results resolve with ONE vectorized ``fin`` instead of B per-call
    closures (measurably cheaper at B≥1024 on the serving hot path).
    Place the same instance at every slot in ``call_idxs``; ``fin(hp)``
    returns an indexable of per-slot values."""

    __slots__ = ("parts", "pos", "fin", "_vec")

    def __init__(self, parts, call_idxs, fin):
        self.parts = list(parts)
        self.pos = {i: b for b, i in enumerate(call_idxs)}
        self.fin = fin
        self._vec = None

    @classmethod
    def counts(cls, parts, call_idxs):
        """Group of B Counts: per-group [B] vectors summed in one numpy
        op (shared by the grouped executor and the prepared cache)."""
        nB = len(call_idxs)
        return cls(parts, call_idxs,
                   lambda hp: (np.sum(hp, axis=0).tolist()
                               if hp else [0] * nB))


# A batched executable materializes roughly one [B, SHARD_WORDS] u32
# gather temp per params slot per stacked shard (measured: an 8-slot
# Intersect batch at B=16384 on one shard exhausts a 16 GB HBM with
# 8 x 2 GB gather temps).  Batches are therefore dispatched in chunks
# sized so those temps stay under BATCH_TEMP_BYTES, and every chunk is
# padded up to a power of two (repeating its last row — always in-range)
# so arbitrary client batch sizes reuse a bounded set of compiled
# executables instead of compiling one per distinct B (~20-40 s each
# through an accelerator tunnel).
#
# Filtered row_counts/TopN batches ADDITIONALLY materialize one
# [B, rows, W] masked temp per stacked shard (rows = the fragment row
# count, usually >> P): sizing by P alone let bench configs 3-8 OOM
# small-RAM hosts on both dispatch paths (BENCH_r07's skipped legs).
# Callers pass that axis as ``row_weight`` so the chunking budget sees
# the real per-B-row footprint.  The budget itself is the
# ``batch-temp-mb`` knob (the decode-workspace pattern; process-wide,
# most recent Server wins).
BATCH_TEMP_BYTES = 4 << 30
BATCH_CHUNK_MIN, BATCH_CHUNK_MAX = 8, 32768


def batch_chunk_size(P: int, n_shards: int, row_weight: int = 0) -> int:
    """Pow-2 batch-axis chunk size under the batch-temp workspace —
    THE sizing formula, shared by _batch_chunks, the whole-query chunk
    guard, and the cross-query batcher's fusion cap."""
    weight = max(1, P, row_weight) * n_shards * SHARD_WORDS * 4
    chunk = max(BATCH_CHUNK_MIN,
                min(BATCH_CHUNK_MAX, BATCH_TEMP_BYTES // weight))
    return 1 << (chunk.bit_length() - 1)


def _batch_chunks(params_mat: np.ndarray, n_shards: int,
                  row_weight: int = 0):
    """Yield (lo, n, padded_params) covering params_mat[lo:lo+n]; padded
    rows beyond n are duplicates whose results the caller ignores.
    ``n_shards`` is the per-device stacked-shard count — gather temps
    live per device, so the budget divides by the mesh size, not the
    total shard count.  ``n_shards <= 0`` marks a filter-less group whose
    device pass is a B-independent broadcast: it dispatches as ONE chunk
    regardless of B (splitting would repeat the full fragment pass per
    chunk — r5 advisor, the old path still cut at BATCH_CHUNK_MAX).
    ``row_weight``: the rows axis of a [B, rows, W] masked temp
    (filtered row_counts/TopN), 0 for gather-temp-only kinds."""
    B, P = params_mat.shape
    if n_shards <= 0:
        chunk = max(BATCH_CHUNK_MIN, B)
    else:
        chunk = batch_chunk_size(P, n_shards, row_weight)
    for lo in range(0, B, chunk):
        sub = params_mat[lo: lo + chunk]
        n = sub.shape[0]
        pad = 1 << max(0, n - 1).bit_length()
        if pad != n:
            sub = np.concatenate([sub, np.repeat(sub[-1:], pad - n,
                                                 axis=0)])
        yield lo, n, sub


def _group_key_list(mesh, kind, slotted, extra):
    """The exact (field, view) key list the mesh dispatch for this group
    will stack (mesh.batch_keys is the single definition), so the shard
    schedule's prefetch stages the stacks the dispatch will actually
    read."""
    if kind == "count":
        return plan_inputs(slotted)
    return mesh.batch_keys((extra["field"], extra["view"]), slotted)


def _run_batched_groups(batcher, holder, index, shards, groups, results):
    """Dispatch batched call groups chunk-wise and fill ``results``.

    ``groups``: iterable of (kind, slotted, params_mat, call_idxs, extra);
    extra carries kind-specific fields — sum: field/view/base, topn:
    field/view/ids_n with one (ids, n) pair per call.  Shared by the
    classic grouped path and the prepared-statement cache so the chunking
    policy lives in exactly one place.

    Dispatch flows through the cross-query batcher
    (parallel/batcher.py): on the common single-slice schedule each
    chunk becomes a ticket, so concurrent queries replaying the same
    prepared template fuse into one device launch.

    Dispatch order is SLICE-MAJOR over one residency-aware shard schedule
    covering the whole batch: every group's every chunk runs against a
    shard slice before the budget rotates to the next slice.  Chunk-major
    order re-staged the full over-budget working set once per chunk;
    slice-major pays the rotation once for the entire batch, with the
    next slice prefetching while the current one computes.  When the
    working set fits the budget the schedule is a single slice and this
    is exactly the old dispatch."""
    groups = list(groups)
    if not groups:
        return
    mesh = batcher.mesh

    key_lists: list = []
    for kind, slotted, _pm, _ci, extra in groups:
        kl = _group_key_list(mesh, kind, slotted, extra)
        if kl not in key_lists:
            key_lists.append(kl)
    sched = mesh.shard_schedule(holder, index, key_lists, shards)
    # chunk layout must be identical across slices so per-chunk parts can
    # accumulate; size by the largest slice (conservative for the rest)
    per_dev = mesh.stacked_per_device(sched.max_slice_len)
    # multi-slice (over-budget) schedules keep the direct slice-major
    # dispatch; batching a streamed working set would re-stage it whole
    fuse = len(sched.slices) == 1

    def _n_split(kind, slotted):
        # count plans always gather per-row temps; sum/topn without a
        # filter broadcast one pass — single chunk (see _batch_chunks)
        return per_dev if (kind == "count" or slotted is not None) else 0

    def _row_weight(kind, slotted, extra):
        # filtered row_counts launches materialize a [B, rows, W]
        # masked temp per stacked shard: the rows axis must size the
        # chunk budget (BENCH_r07's small-RAM OOM gap)
        if kind != "topn" or slotted is None:
            return 0
        from ..parallel.mesh_exec import field_rows
        return field_rows(holder, index, extra["field"], extra["view"])

    # chunk layouts computed ONCE; on the multi-slice direct path the
    # padded params also go to device once (slice-major iteration would
    # otherwise repeat the concatenate padding and the host->device
    # params transfer per slice on identical data) — fused tickets stay
    # host-side so the batcher can concatenate them across queries
    import jax.numpy as jnp
    group_chunks = [
        [(lo, n_c, sub if fuse else jnp.asarray(sub))
         for lo, n_c, sub in
         _batch_chunks(params_mat, _n_split(kind, slotted),
                       _row_weight(kind, slotted, extra))]
        for kind, slotted, params_mat, _ci, extra in groups]
    # the batch axis split to honor the workspace: visible, not silent
    # (docs/observability.md — `query.batch_temp_splits`)
    n_splits = sum(len(ch) - 1 for ch in group_chunks if len(ch) > 1)
    if n_splits:
        batcher.stats.count("query.batch_temp_splits", n_splits)

    parts_acc: dict[tuple[int, int], list] = {}
    for shard_slice in sched:
        for gi, (kind, slotted, params_mat, call_idxs, extra) \
                in enumerate(groups):
            for lo, _n, sub in group_chunks[gi]:
                if kind == "count":
                    parts = batcher.count_batch(
                        slotted, sub, holder, index, shard_slice,
                        fuse=fuse)
                elif kind == "sum":
                    parts = batcher.bsi_sum_batch(
                        extra["field"], extra["view"], slotted, sub,
                        holder, index, shard_slice, fuse=fuse)
                else:  # topn
                    parts = batcher.row_counts_batch(
                        extra["field"], extra["view"], slotted, sub,
                        holder, index, shard_slice, fuse=fuse)
                parts_acc.setdefault((gi, lo), []).extend(parts)

    # all parts dispatched; build the pendings (finalizers sum/merge the
    # per-slice parts exactly as they previously merged per-shape-group
    # parts — every reduction here is additive over shards)
    for gi, (kind, slotted, params_mat, call_idxs, extra) \
            in enumerate(groups):
        if kind == "sum":
            base = extra["base"]

            def _sum_fin(hp, b, base=base):
                total, cnt = 0, 0
                for p in hp:
                    s, c_ = bsi.weighted_sum(p[b])
                    total += s
                    cnt += c_
                return ValCount(total + cnt * base, cnt)
        elif kind == "topn":
            def _topn_fin(hp, b, ids, n):
                counts = mesh.merge_counts([p[b] for p in hp])
                return rank_counts(counts, n or None, ids)

            ids_n = extra["ids_n"]
        for lo, n_c, _sub in group_chunks[gi]:
            parts = parts_acc.get((gi, lo), [])
            if kind == "count":
                grp = _PendingGroup.counts(parts, call_idxs[lo: lo + n_c])
                for i in call_idxs[lo: lo + n_c]:
                    results[i] = grp
            elif kind == "sum":
                # fin=_sum_fin binds THIS group's finalizer: a free-
                # variable reference would late-bind to the last group's
                # base when one invocation carries several sum groups
                for b in range(n_c):
                    results[call_idxs[lo + b]] = _Pending(
                        parts, lambda hp, b=b, fin=_sum_fin: fin(hp, b))
            else:
                for b in range(n_c):
                    ids, n = ids_n[lo + b]
                    results[call_idxs[lo + b]] = _Pending(
                        parts, lambda hp, b=b, ids=ids, n=n,
                        fin=_topn_fin: fin(hp, b, ids, n))


class _Pending:
    """A dispatched-but-unresolved call result.

    Mesh-path aggregations return these so a multi-call query dispatches
    ALL device work before the first host block (the reference overlaps
    calls via its worker pool, executor.go:80-110).  ``parts`` are the
    call's unfetched device arrays; ``fin`` maps their host copies to the
    final result.  ``execute`` fetches every pending's parts in ONE
    device->host transfer (concatenated), because each separate fetch is a
    full dispatch round trip (~100 ms through a tunnel)."""

    __slots__ = ("parts", "fin")

    def __init__(self, parts, fin):
        self.parts = list(parts)
        self.fin = fin


def _resolve_pendings(results):
    """Resolve all _Pending results with a single device->host fetch.
    Parts shared between pendings (batched call groups) fetch once;
    ``jax.device_get`` on the whole list rides one transfer round trip
    (measured: N serial fetches cost N tunnel RTTs, one device_get of N
    arrays costs one)."""
    unique: dict[int, Any] = {}
    for r in results:
        if isinstance(r, (_Pending, _PendingGroup)):
            for p in r.parts:
                unique.setdefault(id(p), p)
    host: dict[int, np.ndarray] = {}
    if unique:
        import jax
        fetched = jax.device_get(list(unique.values()))
        for pid, arr in zip(unique.keys(), fetched):
            host[pid] = np.asarray(arr)
    out = []
    for i, r in enumerate(results):
        if isinstance(r, _Pending):
            out.append(r.fin([host[id(p)] for p in r.parts]))
        elif isinstance(r, _PendingGroup):
            if r._vec is None:
                r._vec = r.fin([host[id(p)] for p in r.parts])
            out.append(r._vec[r.pos[i]])
        else:
            out.append(r)
    return out


# -- whole-query host finalizers (docs/whole-query.md) ----------------------
# Applied to the fetched device parts of one whole-query launch; each
# mirrors the corresponding legacy per-stage reduction byte-for-byte.

def _wq_sum_fin(hp, b, base):
    total, cnt = 0, 0
    for p in hp:
        s, c_ = bsi.weighted_sum(np.asarray(p[b]))
        total += s
        cnt += c_
    return ValCount(total + cnt * base, cnt)


def _wq_topn_rank(mesh, hp, b, ids, n):
    counts = mesh.merge_counts([p[b] for p in hp])
    return rank_counts(counts, n or None, ids)


def _wq_seg_result(hp, b, groups, empty, attrs):
    segs: dict[int, np.ndarray] = {}
    zero = np.zeros(SHARD_WORDS, dtype=np.uint32)
    for shard_list, arr in zip(groups, hp):
        for i, shard in enumerate(shard_list):
            segs[shard] = arr[i, b]
    for shard in empty:
        segs[shard] = zero
    return RowResult(segs, attrs=attrs)


def _wq_minmax_fin(hp, groups, base, want_max):
    acc = ValCount()
    j = 0
    for shard_list in groups:
        bits, neg, cnt = hp[j], hp[j + 1], hp[j + 2]
        j += 3
        for i in range(len(shard_list)):
            val, c = bsi.reconstruct_min_max(
                np.asarray(bits[i]), int(neg[i]), int(cnt[i]))
            vc = ValCount(val + base if c else 0, c)
            acc = acc.larger(vc) if want_max else acc.smaller(vc)
    return acc


def _wq_minrow_fin(hp, want_max):
    counts = np.asarray(hp[0][0], dtype=np.int64) if hp \
        else np.zeros(0, dtype=np.int64)
    nz = np.nonzero(counts)[0]
    if nz.size == 0:
        return ValCount(0, 0)
    rid = int(nz[-1] if want_max else nz[0])
    return ValCount(rid, int(counts[rid]))


def _wq_rows_fin(hp, limit, previous):
    row_ids: set[int] = set()
    for p in hp:
        row_ids.update(int(i) for i in np.nonzero(np.asarray(p[0]))[0])
    out = sorted(row_ids)
    if previous is not None:
        out = [r for r in out if r > previous]
    if limit is not None:
        out = out[:limit]
    return RowIdentifiers(rows=out)


def _wq_groupby_fin(hp, combos, last_ids, last_field, prev_ids, limit):
    acc = None
    for p in hp:
        a = np.asarray(p, dtype=np.int64)
        acc = a.copy() if acc is None else acc_counts(acc, a)
    out: list[GroupCount] = []
    for ci, combo in enumerate(combos):
        for rid in last_ids:
            cnt = (int(acc[ci, rid]) if acc is not None
                   and rid < acc.shape[1] else 0)
            if cnt > 0:
                group = [FieldRow(fn, ri) for fn, ri in combo]
                group.append(FieldRow(last_field, rid))
                out.append(GroupCount(group, cnt))
    out.sort(key=lambda g: tuple(
        (fr.field, fr.row_id) for fr in g.group))
    if prev_ids is not None:
        out = [g for g in out
               if tuple(fr.row_id for fr in g.group) > prev_ids]
    if limit is not None:
        out = out[:limit]
    return out


class Executor:
    def __init__(self, holder, mesh=None, use_mesh: bool | None = None,
                 stats=None, dispatch_batch: bool = True,
                 dispatch_batch_max: int = 32,
                 dispatch_batch_window_us: float = 200.0,
                 whole_query: bool = True,
                 whole_query_fallback: str = "legacy"):
        """``mesh``: a jax Mesh to execute shard batches on (stacked
        shard_map execution with ICI reductions, parallel/mesh_exec.py).
        When None, per-shard dispatch is used.  ``use_mesh=True`` with no
        mesh builds one over all local devices.  ``stats``: a StatsClient
        for per-phase timings (parse/translate/dispatch/fetch) and cache
        counters, surfaced at /debug/vars (the instrumentation sites of
        executor.go:295-336).  ``dispatch_batch*``: cross-query dynamic
        batching of device dispatch (parallel/batcher.py,
        docs/batching.md) — with it off, the batcher still fronts every
        mesh dispatch but delegates directly.  ``whole_query``: compile
        each read request into ONE pjit program over the mesh
        (parallel/wholequery.py, docs/whole-query.md); off restores the
        legacy per-stage dispatch exactly.  ``whole_query_fallback``:
        "legacy" reroutes unsupported shapes to the per-stage path
        (counted + logged); "error" raises instead — a debugging mode
        that makes every silent slow path loud."""
        self.holder = holder
        self.compiler = PlanCompiler()
        from ..utils.stats import NopStatsClient
        self.stats = stats if stats is not None else NopStatsClient()
        from .translator import Translator
        self.translator = Translator(holder)
        # Generation-keyed result cache (cache/results.py).  Disabled on
        # bare executors (limit 0) so tests and chaos harnesses exercise
        # the real execution path; the server wires ``result-cache-mb``
        # through, and the cluster layer reuses this same instance for
        # coordinator-scope entries (one shared byte budget).
        from ..cache.results import ResultCache
        self.result_cache = ResultCache(stats=self.stats)
        self.mesh_exec = None
        self.batcher = None
        self.prepared = None
        self.wholequery = None
        self.whole_query = bool(whole_query)
        self.whole_query_fallback = whole_query_fallback
        # Server injects its Logger so wholequery.fallback events land in
        # the server log; None (engine/bench standalone) stays silent.
        self.logger = None
        # Warm-start corpus recorder (warmup/corpus.py), injected by the
        # Server like the logger; None (bare executors) records nothing.
        self.warm_recorder = None
        self.wq_requests = 0
        self.wq_fallbacks = 0
        self.wq_last_fallback = ""
        if mesh is not None or use_mesh:
            from ..parallel.batcher import DispatchBatcher
            from ..parallel.mesh_exec import MeshExecutor
            from ..parallel.wholequery import WholeQueryRunner
            from .prepared import PreparedCache
            self.mesh_exec = MeshExecutor(mesh)
            self.batcher = DispatchBatcher(
                self.mesh_exec, enabled=dispatch_batch,
                max_batch=dispatch_batch_max,
                window_us=dispatch_batch_window_us, stats=self.stats)
            self.prepared = PreparedCache(self)
            # multiprocess meshes are statically outside the program's
            # vocabulary — gating here (like the batcher's _use_ticket)
            # keeps them off the per-request exception/fallback-log path
            if not self.mesh_exec.multiprocess:
                self.wholequery = WholeQueryRunner(self.mesh_exec)

    def close(self):
        if self.batcher is not None:
            self.batcher.close()
        if self.mesh_exec is not None:
            self.mesh_exec.close()

    # -- entry point (executor.go:113 Execute) -----------------------------

    def execute(self, index_name: str, query, shards=None,
                translate: bool = True, ctx=None) -> list[Any]:
        """``translate=False`` for internal (already-translated) requests —
        the reference's opt.Remote skipping translateCalls
        (executor.go:147).

        ``ctx``: optional QueryContext (utils/deadline.py).  Defaults to
        the caller's active context; installed as current for the whole
        execution so the mesh shard-slice loops can abort an expired
        query between slices, and checked here between per-call
        dispatches and before the blocking fetch."""
        from ..utils.deadline import activate, check_current, current
        if ctx is None:
            ctx = current()
        with activate(ctx):
            return self._execute_ctx(index_name, query, shards, translate,
                                     check_current)

    def _execute_ctx(self, index_name: str, query, shards, translate,
                     check_current) -> list[Any]:
        from ..utils import profile as qprof
        from ..utils.tracing import GLOBAL_TRACER
        check_current("execute")
        # one span per execution so a remote node's piggybacked trace
        # carries its execution stage (docs/observability.md)
        with GLOBAL_TRACER.span("executor.execute") as espan:
            espan.set_tag("index", index_name)
            return self._execute_stages(index_name, query, shards,
                                        translate, check_current, qprof)

    def _execute_stages(self, index_name: str, query, shards, translate,
                        check_current, qprof) -> list[Any]:
        from ..utils import degraded
        from ..utils import tenant as qtenant
        stats = self.stats
        # warm-start corpus (warmup/corpus.py) records by query TEXT —
        # the only replayable identity across restarts
        qtext = query if isinstance(query, str) else None
        # Result-cache lookup FIRST (before even the parse): node-local
        # entries key on the query text (an AST keys on its normalized
        # repr), the pinned shard set, and the index's fragment
        # generation vector — any mutation bumps a gen and the key stops
        # matching (cache/results.py).
        qkey = ckey = None
        cache = self.result_cache
        if cache is not None and cache.limit_bytes > 0:
            idx0 = self.holder.index(index_name)
            if idx0 is not None:
                if shards is None:
                    shards = sorted(idx0.available_shards())
                from ..core import attr_epoch, schema_epoch
                from ..cache.results import gen_vector
                from ..utils.tracing import GLOBAL_TRACER
                qrepr = query if isinstance(query, str) else repr(query)
                qkey = ("local", index_name, qrepr, tuple(shards),
                        bool(translate))
                ckey = qkey + (gen_vector(self.holder, index_name,
                                          set(shards)),
                               schema_epoch(), attr_epoch())
                with GLOBAL_TRACER.span("resultcache.lookup") as span, \
                        qprof.stage("resultcache.lookup") as pnode:
                    out = cache.lookup(ckey)
                    outcome = "hit" if out is not None else "miss"
                    span.set_tag("outcome", outcome)
                    if pnode is not None:
                        pnode.tags["outcome"] = outcome
                from ..utils import explain as qexplain
                qexplain.note("caches", {
                    "cache": "result", "scope": "local",
                    "outcome": outcome,
                    # the key COMPONENTS, not the raw key: what would
                    # have to change for this entry to stop matching
                    "key": {"index": index_name, "shards": len(shards),
                            "genVector": hash(ckey[5]) & 0xFFFFFFFF,
                            "schemaEpoch": ckey[6],
                            "attrEpoch": ckey[7]}})
                if out is not None:
                    # result-cache entries exist only for read-only
                    # queries (the fill sites gate on it)
                    self._warm_note(index_name, qtext)
                    return out
        if isinstance(query, str):
            if translate and self.prepared is not None:
                with stats.timer("query.prepared"), \
                        qprof.stage("prepared") as pnode:
                    hit, out = self.prepared.attempt(index_name, query,
                                                     shards)
                    if pnode is not None:
                        pnode.tags["outcome"] = "hit" if hit else "miss"
                if hit:
                    stats.count("query.prepared.hit")
                    from ..utils import explain as qexplain
                    # the replay's launch already noted its wholequery
                    # program (or fell back inside the template); this
                    # entry records that the PREPARED cache drove it
                    qexplain.note("plan", {"mode": "prepared",
                                           "shards": len(shards or ())})
                    if ckey is not None and not degraded.is_degraded():
                        # prepared entries exist only for Count/Sum/TopN
                        # templates — read-only by construction; a
                        # quarantined-degraded answer stays uncached
                        cache.fill(qkey, ckey, out,
                                   tenant=qtenant.current_or_none())
                    self._warm_note(index_name, qtext)
                    return out
                stats.count("query.prepared.miss")
                if out is not None:
                    query = out  # parsed (tagged) AST — don't parse twice
            if isinstance(query, str):
                with stats.timer("query.parse"), qprof.stage("parse"):
                    query = parse(query)
        idx = self.holder.index(index_name)
        if idx is None:
            raise ExecutionError(f"index not found: {index_name}")
        if translate:
            # always runs: validates stray string keys even when no store
            # is enabled (executor.go:2658 "string 'col' value not
            # allowed...")
            with stats.timer("query.translate"), qprof.stage("translate"):
                query = self.translator.translate_query(index_name, query)
        if shards is None:
            shards = sorted(idx.available_shards())
        # Batched grouping reorders dispatch, which is only sound when no
        # call mutates state a later call could read — mixed write/read
        # queries run strictly sequentially like the reference.
        with stats.timer("query.dispatch"), \
                qprof.stage("dispatch") as dnode:
            if dnode is not None:
                # device-budget counters bracketing the dispatch: the
                # deltas attribute upload/eviction traffic to THIS query
                # (approximate under concurrency — they are process-wide)
                from ..storage.membudget import DEFAULT_BUDGET
                up0, ev0 = (DEFAULT_BUDGET.upload_bytes,
                            DEFAULT_BUDGET.evictions)
                dnode.tags["calls"] = len(query.calls)
                dnode.tags["shards"] = len(shards)
            read_only = not any(c.name in WRITE_CALLS
                                for c in query.calls)
            results = None
            if self.wholequery is not None and self.whole_query and \
                    read_only:
                # whole-query path (docs/whole-query.md): the entire
                # request compiles to ONE pjit program over the mesh;
                # unsupported shapes fall back below, counted
                results = self._try_whole_query(index_name, query.calls,
                                                shards)
            if results is not None:
                pass
            elif self.mesh_exec is not None and len(query.calls) > 1 and \
                    read_only:
                from ..utils import explain as qexplain
                qexplain.note("plan", {"mode": "legacy-grouped",
                                       "calls": len(query.calls),
                                       "shards": len(shards)})
                results = self._execute_calls_grouped(index_name,
                                                      query.calls, shards)
            else:
                from ..utils import explain as qexplain
                qexplain.note("plan", {"mode": "legacy-per-call",
                                       "calls": len(query.calls),
                                       "readOnly": read_only,
                                       "shards": len(shards)})
                results = []
                for c in query.calls:
                    check_current("call dispatch")
                    results.append(self._execute_call(index_name, c,
                                                      shards))
            if dnode is not None:
                dnode.tags["uploadBytes"] = \
                    DEFAULT_BUDGET.upload_bytes - up0
                dnode.tags["evictions"] = DEFAULT_BUDGET.evictions - ev0
        check_current("result fetch")
        with stats.timer("query.fetch"), qprof.stage("fetch"):
            results = _resolve_pendings(results)
        if translate and self.translator.needs_translation(index_name):
            results = self.translator.translate_results(
                index_name, query.calls, results)
        if ckey is not None and not degraded.is_degraded():
            # degraded answers (quarantined fragments serving empty rows,
            # or shards lost under partialResults) are never memoized: a
            # healthy repeat must recompute
            from ..cache.results import query_is_readonly
            if query_is_readonly(query):
                cache.fill(qkey, ckey, results,
                           tenant=qtenant.current_or_none())
        if read_only:
            self._warm_note(index_name, qtext)
        return results

    def _warm_note(self, index_name: str, qtext):
        """Feed one successfully served read-only string query to the
        warm-start corpus recorder (no-op on bare executors)."""
        rec = self.warm_recorder
        if rec is not None and qtext is not None:
            rec.note(index_name, qtext)

    # -- batched multi-call execution --------------------------------------

    _EMPTY_PARAMS = np.zeros(0, dtype=np.int32)

    # GroupBy row-id grid bounds: total combos cap the int32 count fetch
    # (total x 4 bytes over a ~5 MB/s tunnel), prefix combos cap the
    # dispatched grid (chunked GROUP_CHUNK per executable invocation)
    GROUP_GRID_MAX = 1 << 20
    GROUP_GRID_PREFIX_MAX = 16384

    def _batch_desc(self, index: str, c: Call):
        """(group_key, desc) for calls that can batch into one vmapped
        executable with per-call params rows; None for everything else."""
        if c.name == "Count" and len(c.children) == 1:
            slotted, params = parametrize(self._resolve(index,
                                                        c.children[0]))
            return (("count", repr(slotted)),
                    {"kind": "count", "slotted": slotted, "params": params})
        if c.name == "Sum":
            f = self._bsi_field(index, c)
            fp = self._filter_plan(index, c)
            slotted, params = (None, self._EMPTY_PARAMS) if fp is None \
                else parametrize(fp)
            return (("sum", f.name, repr(slotted)),
                    {"kind": "sum", "slotted": slotted, "params": params,
                     "field": f.name, "view": f.bsi_view_name(),
                     "base": f.options.base})
        if c.name == "TopN":
            if any(k in c.args for k in TOPN_EXTRAS):
                return None  # extras need extra passes: per-call path
            field_name, ok = c.string_arg("_field")
            if not ok or self.holder.field(index, field_name) is None:
                return None  # per-call path raises the proper error
            fp = self._filter_plan(index, c)
            slotted, params = (None, self._EMPTY_PARAMS) if fp is None \
                else parametrize(fp)
            n, _ = c.uint_arg("n")
            return (("topn", field_name, repr(slotted)),
                    {"kind": "topn", "slotted": slotted, "params": params,
                     "field": field_name, "ids": c.args.get("ids"), "n": n})
        return None

    def _execute_calls_grouped(self, index: str, calls, shards):
        """Group same-shape Count/TopN/Sum calls and execute each group as
        ONE device computation over stacked params — the worker-pool
        equivalent for a multi-call query (executor.go:80-110), minus N-1
        dispatch round trips."""
        descs: list = [None] * len(calls)
        groups: dict[tuple, list[int]] = {}
        for i, c in enumerate(calls):
            kd = self._batch_desc(index, c)
            if kd is not None:
                key, d = kd
                descs[i] = d
                groups.setdefault(key, []).append(i)

        results: list = [None] * len(calls)
        batched: set[int] = set()
        to_run = []
        for key, idxs in groups.items():
            if len(idxs) < 2:
                continue
            ds = [descs[i] for i in idxs]
            kind = ds[0]["kind"]
            params_mat = np.stack([d["params"] for d in ds])
            if kind == "sum":
                extra = {"field": ds[0]["field"], "view": ds[0]["view"],
                         "base": ds[0]["base"]}
            elif kind == "topn":
                extra = {"field": ds[0]["field"], "view": VIEW_STANDARD,
                         "ids_n": [(d["ids"], d["n"]) for d in ds]}
            else:
                extra = None
            to_run.append((kind, ds[0]["slotted"], params_mat, idxs, extra))
            batched.update(idxs)
        # ONE invocation for every group: they share one residency-aware
        # shard schedule, so under budget pressure the whole multi-group
        # batch drains against each shard slice before the budget rotates
        _run_batched_groups(self.batcher, self.holder, index, shards,
                            to_run, results)

        for i, c in enumerate(calls):
            if i not in batched:
                results[i] = self._execute_call(index, c, shards)
        return results

    # -- whole-query pjit programs (docs/whole-query.md) -------------------
    # A read request lowers to a tuple of plan.ReduceNode reducers plus
    # one params matrix per node, and the WHOLE request launches as one
    # compiled program over the mesh (parallel/wholequery.py).  Shapes
    # the program cannot express raise WholeQueryUnsupported and the
    # request reroutes to the legacy per-stage dispatch with
    # ``wholequery.fallback`` counted and a structured log event naming
    # the unsupported node — no silent slow paths.

    def _try_whole_query(self, index: str, calls, shards):
        from ..parallel.wholequery import WholeQueryUnsupported
        try:
            results = self._wq_execute(index, calls, shards)
        except WholeQueryUnsupported as e:
            self._note_wq_fallback(index, e)
            return None
        self.wq_requests += 1
        self.stats.count("wholequery.requests")
        return results

    def _note_wq_fallback(self, index: str, e):
        self.wq_fallbacks += 1
        self.wq_last_fallback = e.node if not e.detail \
            else f"{e.node}: {e.detail}"
        self.stats.count("wholequery.fallback")
        from ..utils import events, explain as qexplain
        events.emit("wholequery.fallback", index=index, node=e.node,
                    detail=e.detail or None)
        qexplain.note("plan", {"mode": "legacy-fallback", "node": e.node,
                               "detail": e.detail or None})
        log = self.logger
        if log is not None:
            try:
                log.event("wholequery.fallback", index=index, node=e.node,
                          detail=e.detail)
            # lint: allow(swallowed-exception) — a stale/closed log
            # stream costs a log line, never the query; the fallback is
            # still counted in the stats above
            except Exception:
                pass
        if self.whole_query_fallback == "error":
            raise ExecutionError(
                f"whole-query fallback disabled by the 'error' policy: "
                f"{e.node}"
                + (f": {e.detail}" if e.detail else "")) from e

    def _wq_dispatch(self, index: str, shards, program, mats):
        """One program launch through the dispatch batcher (concurrent
        same-shape requests fuse along the params batch axis)."""
        return self.batcher.whole_query(self.wholequery, program, mats,
                                        self.holder, index, shards)

    @staticmethod
    def _wq_chunk_guard(mat: np.ndarray, n_split: int,
                        row_weight: int = 0):
        """A params batch needing more than one dispatch chunk (device
        temp budget) stays on the legacy chunked path.  Pure arithmetic
        — the same batch_chunk_size sizing as _batch_chunks (including
        the [B, rows, W] row_weight axis for filtered row_counts),
        without materializing a padded chunk just to count them."""
        from ..parallel.wholequery import WholeQueryUnsupported
        B, P = mat.shape
        if n_split <= 0:
            return  # broadcast pass: always one chunk
        if B > batch_chunk_size(P, n_split, row_weight):
            raise WholeQueryUnsupported("batch-chunks", f"B={B}")

    def _wq_run_batched(self, index: str, shards, groups, results):
        """Whole-query dispatch of standard batched call groups —
        (kind, slotted, params_mat, call_idxs, extra) with kind in
        count/sum/topn, the _run_batched_groups contract — as ONE
        program launch.  Used by the prepared-statement replay so a
        whole template is one launch; raises WholeQueryUnsupported for
        shapes the program can't take (caller falls back)."""
        from ..core import VIEW_STANDARD as _STD
        from .plan import ReduceNode
        groups = list(groups)
        if not groups:
            return
        per_dev = self.mesh_exec.stacked_per_device(max(len(shards), 1))
        nodes, mats = [], []
        for kind, slotted, params_mat, call_idxs, extra in groups:
            n_split = per_dev if (kind == "count" or slotted is not None) \
                else 0
            row_weight = 0
            if kind == "topn" and slotted is not None:
                from ..parallel.mesh_exec import field_rows
                row_weight = field_rows(self.holder, index,
                                        extra["field"],
                                        extra.get("view", _STD))
            self._wq_chunk_guard(params_mat, n_split, row_weight)
            if kind == "count":
                nodes.append(ReduceNode("count", slotted))
            elif kind == "sum":
                nodes.append(ReduceNode(
                    "bsi_sum", slotted, (extra["field"], extra["view"])))
            else:  # topn
                nodes.append(ReduceNode(
                    "row_counts", slotted,
                    (extra["field"], extra.get("view", _STD))))
            mats.append(params_mat)
        out = self._wq_dispatch(index, shards, tuple(nodes), mats)
        if self.warm_recorder is not None:
            self.warm_recorder.note_sig(out.sig)
        from ..utils import explain as qexplain
        qexplain.note("plan", {
            "mode": "wholequery", "program": out.sig,
            "compile": "cold" if out.compiled else "warm",
            "nodes": [n.kind for n in nodes],
            "shards": len(shards)})
        mesh = self.mesh_exec
        for gi, (kind, slotted, params_mat, call_idxs, extra) \
                in enumerate(groups):
            parts = out.parts[gi]
            if kind == "count":
                grp = _PendingGroup.counts(parts, call_idxs)
                for i in call_idxs:
                    results[i] = grp
            elif kind == "sum":
                base = extra["base"]
                for b, i in enumerate(call_idxs):
                    results[i] = _Pending(
                        parts, lambda hp, b=b, base=base:
                        _wq_sum_fin(hp, b, base))
            else:
                ids_n = extra["ids_n"]
                for b, i in enumerate(call_idxs):
                    ids, n = ids_n[b]
                    results[i] = _Pending(
                        parts, lambda hp, b=b, ids=ids, n=n, mesh=mesh:
                        _wq_topn_rank(mesh, hp, b, ids, n))

    def _wq_execute(self, index: str, calls, shards):
        """Lower every call of a read request to reducer nodes, launch
        the whole program once, and wire _Pending results (resolved by
        the caller's single fetch).  Raises WholeQueryUnsupported for
        anything outside the program's fallback matrix
        (docs/whole-query.md); real validation errors raise exactly as
        the legacy path would."""
        from .plan import ReduceNode
        idx = self.holder.index(index)
        if idx is None:
            raise ExecutionError(f"index not found: {index}")
        descs = [self._wq_desc(index, c, shards) for c in calls]
        results: list = [None] * len(calls)
        units: list[dict] = []
        by_gkey: dict = {}
        for i, d in enumerate(descs):
            if d["kind"] == "const":
                results[i] = d["result"]
                continue
            gk = d.get("gkey")
            u = by_gkey.get(gk) if gk is not None else None
            if u is None:
                u = {"kind": d["kind"], "descs": [], "idxs": []}
                if gk is not None:
                    by_gkey[gk] = u
                units.append(u)
            u["descs"].append(d)
            u["idxs"].append(i)
        if not units:
            return results

        per_dev = self.mesh_exec.stacked_per_device(max(len(shards), 1))
        nodes, mats, unit_nodes = [], [], []
        for u in units:
            kind, ds = u["kind"], u["descs"]
            lo = len(nodes)
            d0 = ds[0]
            if kind in ("count", "segments"):
                mat = np.stack([d["params"] for d in ds])
                self._wq_chunk_guard(mat, per_dev)
                nodes.append(ReduceNode(kind, d0["slotted"]))
                mats.append(mat)
            elif kind == "sum":
                mat = np.stack([d["params"] for d in ds])
                self._wq_chunk_guard(
                    mat, per_dev if d0["slotted"] is not None else 0)
                nodes.append(ReduceNode("bsi_sum", d0["slotted"],
                                        (d0["field"], d0["view"])))
                mats.append(mat)
            elif kind == "topn":
                mat = np.stack([d["params"] for d in ds])
                from ..parallel.mesh_exec import field_rows
                self._wq_chunk_guard(
                    mat, per_dev if d0["slotted"] is not None else 0,
                    row_weight=field_rows(self.holder, index,
                                          d0["field"], VIEW_STANDARD)
                    if d0["slotted"] is not None else 0)
                nodes.append(ReduceNode("row_counts", d0["slotted"],
                                        (d0["field"], VIEW_STANDARD)))
                mats.append(mat)
                if d0["tan"]:
                    # tanimoto rides two extra reducers in the SAME
                    # program: unfiltered row totals + the source count
                    nodes.append(ReduceNode(
                        "row_counts", None, (d0["field"], VIEW_STANDARD)))
                    mats.append(np.zeros((1, 0), dtype=np.int32))
                    nodes.append(ReduceNode("count", d0["slotted"]))
                    mats.append(mat)
            elif kind == "minmax":
                nodes.append(ReduceNode(
                    "bsi_minmax", d0["slotted"],
                    (d0["field"], d0["view"]),
                    ("max" if d0["want_max"] else "min",)))
                mats.append(np.asarray(d0["params"],
                                       dtype=np.int32).reshape(1, -1))
            elif kind == "minrow":
                nodes.append(ReduceNode(
                    "row_counts", None, (d0["field"], VIEW_STANDARD)))
                mats.append(np.zeros((1, 0), dtype=np.int32))
            elif kind == "rows":
                for vname in d0["views"]:
                    nodes.append(ReduceNode(
                        "row_counts", None, (d0["field"], vname)))
                    mats.append(np.zeros((1, 0), dtype=np.int32))
            else:  # groupby
                nodes.append(ReduceNode(
                    "group_counts", d0["slotted"],
                    (d0["last_field"], VIEW_STANDARD),
                    tuple(d0["prefix_keys"]) + (d0["pad_c"],)))
                mats.append((d0["rids"], d0["params"]))
            unit_nodes.append((lo, len(nodes)))

        out = self._wq_dispatch(index, shards, tuple(nodes), mats)
        if self.warm_recorder is not None:
            self.warm_recorder.note_sig(out.sig)
        from ..utils import explain as qexplain
        qexplain.note("plan", {
            "mode": "wholequery",
            # the compiled program's devobs signature — the SAME id the
            # compile registry and launch ledger record, so the explain
            # record cross-checks the ledger (None = empty launch)
            "program": out.sig,
            # warm: served from a cached/persistent-cache executable;
            # cold: this request paid a trace+compile (docs/warmup.md)
            "compile": "cold" if out.compiled else "warm",
            "nodes": [n.kind for n in nodes],
            "calls": len(calls), "shards": len(shards)})
        for u, (lo, hi) in zip(units, unit_nodes):
            self._wq_wire(u, out, lo, hi, results)
        return results

    def _wq_wire(self, unit, out, lo, hi, results):
        """Attach _Pending finalizers for one unit's calls over its
        nodes' device parts — each finalizer mirrors the legacy path's
        host reduction exactly (results stay byte-identical)."""
        kind, ds, idxs = unit["kind"], unit["descs"], unit["idxs"]
        mesh = self.mesh_exec
        if kind == "count":
            grp = _PendingGroup.counts(out.parts[lo], idxs)
            for i in idxs:
                results[i] = grp
            return
        if kind == "segments":
            parts, meta = out.parts[lo], out.meta[lo]
            for b, i in enumerate(idxs):
                attrs = ds[b].get("attrs")
                results[i] = _Pending(
                    parts, lambda hp, b=b, groups=meta["groups"],
                    empty=meta["empty"], attrs=attrs:
                    _wq_seg_result(hp, b, groups, empty, attrs))
            return
        if kind == "sum":
            parts = out.parts[lo]
            for b, i in enumerate(idxs):
                base = ds[b]["base"]
                results[i] = _Pending(
                    parts, lambda hp, b=b, base=base:
                    _wq_sum_fin(hp, b, base))
            return
        if kind == "topn":
            d0 = ds[0]
            parts = [p for j in range(lo, hi) for p in out.parts[j]]
            k = len(out.parts[lo])
            ku = len(out.parts[lo + 1]) if d0["tan"] else 0
            f = d0["f"]
            for b, i in enumerate(idxs):
                d = ds[b]
                results[i] = _Pending(
                    parts,
                    lambda hp, b=b, ids=d["ids"], n=d["n"], k=k, ku=ku,
                    tan=d["tan"], an=d["attr_name"], av=d["attr_values"],
                    f=f, mesh=mesh:
                    self._topn_finalize(
                        mesh.merge_counts([p[b] for p in hp[:k]]),
                        mesh.merge_counts([p[0] for p in hp[k:k + ku]])
                        if tan else None,
                        sum(int(p[0]) for p in hp[k + ku:]) if tan
                        else 0,
                        ids, n, tan, an, av, f))
            return
        if kind == "minmax":
            d0 = ds[0]
            results[idxs[0]] = _Pending(
                out.parts[lo],
                lambda hp, groups=out.meta[lo]["groups"],
                base=d0["base"], want_max=d0["want_max"]:
                _wq_minmax_fin(hp, groups, base, want_max))
            return
        if kind == "minrow":
            results[idxs[0]] = _Pending(
                out.parts[lo],
                lambda hp, want_max=ds[0]["want_max"]:
                _wq_minrow_fin(hp, want_max))
            return
        if kind == "rows":
            d0 = ds[0]
            parts = [p for j in range(lo, hi) for p in out.parts[j]]
            results[idxs[0]] = _Pending(
                parts, lambda hp, limit=d0["limit"],
                previous=d0["previous"]: _wq_rows_fin(hp, limit,
                                                      previous))
            return
        # groupby
        d0 = ds[0]
        results[idxs[0]] = _Pending(
            out.parts[lo],
            lambda hp, combos=d0["combos"], last_ids=d0["last_ids"],
            last_field=d0["last_field"], prev_ids=d0["prev_ids"],
            limit=d0["limit"]:
            _wq_groupby_fin(hp, combos, last_ids, last_field, prev_ids,
                            limit))

    def _wq_desc(self, index: str, c: Call, shards) -> dict:
        """Lower one call to a whole-query unit descriptor, running the
        same validation (and raising the same errors) as the legacy
        per-call path.  Raises WholeQueryUnsupported for call shapes
        outside the program's vocabulary."""
        from ..parallel.wholequery import WholeQueryUnsupported
        name = c.name
        if name == "Count":
            if len(c.children) != 1:
                raise ExecutionError("Count() requires one input")
            slotted, params = parametrize(
                self._resolve(index, c.children[0]))
            return {"kind": "count", "gkey": ("count", repr(slotted)),
                    "slotted": slotted, "params": params}
        if name == "Sum":
            f = self._bsi_field(index, c)
            fp = self._filter_plan(index, c)
            slotted, params = (None, self._EMPTY_PARAMS) if fp is None \
                else parametrize(fp)
            return {"kind": "sum", "gkey": ("sum", f.name, repr(slotted)),
                    "slotted": slotted, "params": params, "field": f.name,
                    "view": f.bsi_view_name(), "base": f.options.base}
        if name in ("Min", "Max"):
            f = self._bsi_field(index, c)
            fp = self._filter_plan(index, c)
            slotted, params = (None, self._EMPTY_PARAMS) if fp is None \
                else parametrize(fp)
            return {"kind": "minmax", "gkey": None, "slotted": slotted,
                    "params": params, "field": f.name,
                    "view": f.bsi_view_name(), "base": f.options.base,
                    "want_max": name == "Max"}
        if name in ("MinRow", "MaxRow"):
            field_name, ok = c.string_arg("field")
            if not ok:
                raise ExecutionError(f"{c.name}(): field required")
            if self.holder.field(index, field_name) is None:
                raise ExecutionError(f"field not found: {field_name}")
            return {"kind": "minrow", "gkey": None, "field": field_name,
                    "want_max": name == "MaxRow"}
        if name == "TopN":
            return self._wq_desc_topn(index, c, shards)
        if name == "Rows":
            return self._wq_desc_rows(index, c)
        if name == "GroupBy":
            return self._wq_desc_group_by(index, c)
        if name in BITMAP_CALLS:
            plan = self._resolve(index, c)
            slotted, params = parametrize(plan)
            attrs = None
            if c.name in ("Row", "Range"):
                fa = c.field_arg()
                if fa is not None and isinstance(fa[1], int) \
                        and not isinstance(fa[1], bool):
                    f = self.holder.field(index, fa[0])
                    if f is not None:
                        attrs = f.row_attrs.attrs(fa[1]) or None
            return {"kind": "segments",
                    "gkey": ("segments", repr(slotted)),
                    "slotted": slotted, "params": params, "attrs": attrs}
        if name == "Options":
            raise WholeQueryUnsupported("options",
                                        "per-call shard overrides")
        raise ExecutionError(f"unknown call: {name}")

    def _wq_desc_topn(self, index: str, c: Call, shards) -> dict:
        field_name, ok = c.string_arg("_field")
        if not ok:
            raise ExecutionError("TopN() requires a field")
        f = self.holder.field(index, field_name)
        if f is None:
            raise ExecutionError(f"field not found: {field_name}")
        n, _ = c.uint_arg("n")
        ids = c.args.get("ids")
        tan_thresh, attr_name, attr_values = topn_extras(c)
        if not c.children and ids is None and tan_thresh is None \
                and attr_name is None \
                and f.options.cache_type in ("ranked", "lru"):
            from ..cache.rank import topn_from_rank
            pairs = topn_from_rank(f, shards, n, stats=self.stats)
            if pairs is not None:
                return {"kind": "const", "result": pairs}
        fp = self._filter_plan(index, c)
        slotted, params = (None, self._EMPTY_PARAMS) if fp is None \
            else parametrize(fp)
        extras = tan_thresh is not None or attr_name is not None
        return {"kind": "topn",
                "gkey": None if extras
                else ("topn", field_name, repr(slotted)),
                "slotted": slotted, "params": params,
                "field": field_name, "ids": ids, "n": n,
                "tan": tan_thresh, "attr_name": attr_name,
                "attr_values": attr_values, "f": f}

    def _wq_desc_rows(self, index: str, c: Call) -> dict:
        from ..parallel.wholequery import WholeQueryUnsupported
        field_name, ok = c.string_arg("_field")
        if not ok:
            raise ExecutionError("Rows() requires a field")
        f = self.holder.field(index, field_name)
        if f is None:
            raise ExecutionError(f"field not found: {field_name}")
        if c.args.get("column") is not None:
            # a column probe reads one bit per row — the per-shard path
            # owns it (no reduction to express)
            raise WholeQueryUnsupported("rows-column")
        views = [VIEW_STANDARD]
        from_arg, to_arg = c.args.get("from"), c.args.get("to")
        if from_arg or to_arg:
            quantum = f.options.time_quantum
            if not quantum:
                raise ExecutionError(
                    f"field {field_name!r} has no time quantum")
            from_time = tq.parse_time(from_arg) if from_arg \
                else datetime(1, 1, 1)
            to_time = tq.parse_time(to_arg) if to_arg \
                else datetime(9999, 1, 1)
            views = tq.views_by_time_range(VIEW_STANDARD, from_time,
                                           to_time, quantum)
        return {"kind": "rows", "gkey": None, "field": field_name,
                "views": views, "limit": c.args.get("limit"),
                "previous": c.args.get("previous")}

    def _wq_desc_group_by(self, index: str, c: Call) -> dict:
        from ..parallel.mesh_exec import MeshExecutor
        from ..parallel.wholequery import WholeQueryUnsupported
        names, rows_calls, filt_call, limit = self._group_by_parse(index,
                                                                   c)
        fields = self._group_by_grid(index, names, rows_calls)
        if fields is None:
            raise WholeQueryUnsupported(
                "group_counts", "children need Rows execution or the "
                                "grid bounds failed")
        prev_ids = self._group_by_previous(c, fields)
        filter_plan = (self._resolve(index, filt_call)
                       if filt_call is not None else None)
        slotted, params = (None, self._EMPTY_PARAMS) \
            if filter_plan is None else parametrize(filter_plan)
        prefix_fields = fields[:-1]
        last_field, last_ids = fields[-1]
        combos: list[tuple] = [()]
        for fname, ids in prefix_fields:
            combos = [cb + ((fname, rid),) for cb in combos
                      for rid in ids]
        if not combos or not last_ids:
            return {"kind": "const", "result": []}
        if len(combos) > MeshExecutor.GROUP_CHUNK:
            raise WholeQueryUnsupported(
                "group_counts",
                f"{len(combos)} prefix combos exceed one chunk")
        rids = np.asarray([[rid for _, rid in cb] for cb in combos],
                          dtype=np.int32).reshape(len(combos),
                                                  len(prefix_fields))
        pad_c = 1 << max(0, len(combos) - 1).bit_length()
        return {"kind": "groupby", "gkey": None, "slotted": slotted,
                "params": params, "rids": rids, "pad_c": pad_c,
                "prefix_keys": [(fname, VIEW_STANDARD)
                                for fname, _ in prefix_fields],
                "last_field": last_field, "last_ids": last_ids,
                "combos": combos, "prev_ids": prev_ids, "limit": limit}

    # -- dispatch (executor.go:274 executeCall) ----------------------------

    def _execute_call(self, index: str, c: Call, shards: list[int]):
        name = c.name
        if name == "Count":
            return self._execute_count(index, c, shards)
        if name == "Sum":
            return self._execute_sum(index, c, shards)
        if name in ("Min", "Max"):
            return self._execute_min_max(index, c, shards, name == "Max")
        if name in ("MinRow", "MaxRow"):
            return self._execute_min_max_row(index, c, shards, name == "MaxRow")
        if name == "TopN":
            return self._execute_topn(index, c, shards)
        if name == "Rows":
            return self._execute_rows(index, c, shards)
        if name == "GroupBy":
            return self._execute_group_by(index, c, shards)
        if name == "Options":
            return self._execute_options(index, c, shards)
        if name == "Set":
            return self._execute_set(index, c)
        if name == "Clear":
            return self._execute_clear(index, c)
        if name == "ClearRow":
            return self._execute_clear_row(index, c, shards)
        if name == "Store":
            return self._execute_store(index, c, shards)
        if name in ("SetRowAttrs", "SetColumnAttrs"):
            return self._execute_set_attrs(index, c)
        if name in BITMAP_CALLS:
            return self._execute_bitmap(index, c, shards)
        raise ExecutionError(f"unknown call: {name}")

    # -- bitmap calls ------------------------------------------------------

    def _resolve(self, index: str, c: Call):
        return Resolver(self.holder, index).resolve_bitmap(c)

    def _execute_bitmap(self, index: str, c: Call, shards) -> RowResult:
        plan = self._resolve(index, c)
        attrs = None
        if c.name in ("Row", "Range"):
            # a plain Row() result carries its row's attributes
            # (executor.go:651 executeBitmapCallShard -> row.Attrs)
            fa = c.field_arg()
            if fa is not None and isinstance(fa[1], int) \
                    and not isinstance(fa[1], bool):
                f = self.holder.field(index, fa[0])
                if f is not None:
                    attrs = f.row_attrs.attrs(fa[1]) or None
        return RowResult(self._plan_segments(plan, index, shards),
                         attrs=attrs)

    def _plan_segments(self, plan, index: str, shards) -> dict:
        if self.mesh_exec is not None:
            return self.batcher.segments(plan, self.holder, index,
                                         shards)
        return {
            shard: self.compiler.execute_shard(plan, self.holder, index,
                                               shard)
            for shard in shards
        }

    # -- aggregations ------------------------------------------------------

    def _execute_count(self, index: str, c: Call, shards) -> int:
        """(executor.go:1790 executeCount)"""
        if len(c.children) != 1:
            raise ExecutionError("Count() requires one input")
        plan = self._resolve(index, c.children[0])
        if self.mesh_exec is not None:
            parts = self.batcher.count_async(plan, self.holder, index,
                                             shards)
            return _Pending(parts, lambda hp: sum(int(x) for x in hp))
        counts = [
            self.compiler.execute_shard(plan, self.holder, index, shard,
                                        reducer="count")
            for shard in shards
        ]
        return sum(int(x) for x in counts)

    def _bsi_field(self, index: str, c: Call):
        field_name, _ = c.string_arg("field")
        if not field_name:
            fa = c.field_arg()
            if fa is None:
                raise ExecutionError("field required")
            field_name = fa[0]
        f = self.holder.field(index, field_name)
        if f is None:
            raise ExecutionError(f"field not found: {field_name}")
        if f.options.type != FIELD_TYPE_INT:
            raise ExecutionError(f"field {field_name!r} is not an int field")
        return f

    def _filter_segments(self, index: str, c: Call, shards):
        """Evaluate the optional filter child of Sum/Min/Max/TopN."""
        if not c.children:
            return None
        plan = self._resolve(index, c.children[0])
        return self._plan_segments(plan, index, shards)

    def _filter_plan(self, index: str, c: Call):
        """Resolve the optional filter child to a plan (mesh path fuses it
        into the same shard_map computation instead of materialising
        per-shard segments first)."""
        if not c.children:
            return None
        return self._resolve(index, c.children[0])

    def _execute_sum(self, index: str, c: Call, shards) -> ValCount:
        """(executor.go:406 executeSum + fragment.go:1111 sum)"""
        f = self._bsi_field(index, c)
        view = f.bsi_view_name()
        if self.mesh_exec is not None:
            parts = self.batcher.bsi_sum_async(
                f.name, view, self._filter_plan(index, c), self.holder,
                index, shards)

            def _fin(hp, base=f.options.base):
                total, n = 0, 0
                for p in hp:
                    s, cnt = bsi.weighted_sum(p)
                    total += s
                    n += cnt
                return ValCount(total + n * base, n)

            return _Pending(parts, _fin)
        filters = self._filter_segments(index, c, shards)
        total, n = 0, 0
        for shard in shards:
            frag = self.holder.fragment(index, f.name, view, shard)
            if frag is None or frag.n_rows < bsi.OFFSET_ROW + 1:
                continue
            filt = None if filters is None else filters.get(shard)
            counts = np.asarray(bsi.sum_counts(frag.device(), filt))
            s, cnt = bsi.weighted_sum(counts)
            total += s
            n += cnt
        # values are stored base-offset: add base per set column
        # (field.go:1138 Sum: sum + count*base)
        return ValCount(total + n * f.options.base, n)

    def _execute_min_max(self, index: str, c: Call, shards,
                         want_max: bool) -> ValCount:
        """(executor.go:437 executeMin/:472 executeMax)"""
        f = self._bsi_field(index, c)
        view = f.bsi_view_name()
        acc = ValCount()
        if self.mesh_exec is not None:
            per_shard = self.batcher.bsi_min_max(
                f.name, view, self._filter_plan(index, c), self.holder,
                index, shards, want_max=want_max)
            for val, cnt in per_shard:
                vc = ValCount(val + f.options.base if cnt else 0, cnt)
                acc = acc.larger(vc) if want_max else acc.smaller(vc)
            return acc
        filters = self._filter_segments(index, c, shards)
        for shard in shards:
            frag = self.holder.fragment(index, f.name, view, shard)
            if frag is None or frag.n_rows < bsi.OFFSET_ROW + 1:
                continue
            filt = None if filters is None else filters.get(shard)
            bits, neg, cnt = bsi.min_max_bits(frag.device(), filt,
                                              want_max=want_max)
            val, cnt = bsi.reconstruct_min_max(
                np.asarray(bits), int(neg), int(cnt))
            vc = ValCount(val + f.options.base if cnt else 0, cnt)
            acc = acc.larger(vc) if want_max else acc.smaller(vc)
        return acc

    def _execute_min_max_row(self, index: str, c: Call, shards,
                             want_max: bool) -> ValCount:
        """MinRow/MaxRow: extreme row id with any bit set
        (executor.go:506 executeMinRow)."""
        field_name, ok = c.string_arg("field")
        if not ok:
            raise ExecutionError(f"{c.name}(): field required")
        f = self.holder.field(index, field_name)
        if f is None:
            raise ExecutionError(f"field not found: {field_name}")
        if self.mesh_exec is not None:
            counts = self.batcher.row_counts(
                field_name, VIEW_STANDARD, None, self.holder, index, shards)
            nz = np.nonzero(counts)[0]
            if nz.size == 0:
                return ValCount(0, 0)
            rid = int(nz[-1] if want_max else nz[0])
            return ValCount(rid, int(counts[rid]))
        best, best_count = None, 0
        v = f.view(VIEW_STANDARD)
        for shard in shards:
            frag = None if v is None else v.fragment(shard)
            if frag is None or frag.n_rows == 0:
                continue
            counts = np.asarray(bitset.row_counts(frag.device()))
            nz = np.nonzero(counts)[0]
            if nz.size == 0:
                continue
            rid = int(nz[-1] if want_max else nz[0])
            if best is None or (rid > best if want_max else rid < best):
                best, best_count = rid, int(counts[rid])
            elif rid == best:
                best_count += int(counts[rid])
        return ValCount(best or 0, best_count if best is not None else 0)

    # -- TopN (executor.go:860 executeTopN, fragment.go:1570 top) ----------

    @staticmethod
    def _topn_finalize(counts, row_tot, src_count, ids, n, tan_thresh,
                       attr_name, attr_values, field) -> list[Pair]:
        """Shared tail of TopN: tanimoto/attr row filtering + ranking.

        Tanimoto (fragment.go:1704 topBitmapPairs): keep rows where
        100*|row∩src| >= threshold*(|row|+|src|-|row∩src|).  Computed on
        GLOBAL counts (across all shards) rather than per shard — exact
        where the reference's per-shard cache heuristic is approximate.
        Attr filter (executor.go:942-995): keep rows whose row-attribute
        ``attr_name`` value is in ``attr_values``."""
        if tan_thresh:
            size = max(counts.size, row_tot.size)
            c_ = np.zeros(size, dtype=np.int64)
            c_[: counts.size] = counts
            t_ = np.zeros(size, dtype=np.int64)
            t_[: row_tot.size] = row_tot
            denom = t_ + src_count - c_
            ok = (denom > 0) & (100 * c_ >= tan_thresh * denom)
            counts = np.where(ok, c_, 0)
        if attr_name is None:
            # vectorized rank: only the returned n rows materialize Pairs
            return rank_counts(counts, n or None, ids)
        allowed = set(attr_values)
        pairs = [p for p in rank_counts(counts, None, ids)
                 if field.row_attrs.attrs(p.id).get(attr_name) in allowed]
        return pairs[: n or None]

    def _execute_topn(self, index: str, c: Call, shards) -> list[Pair]:
        field_name, ok = c.string_arg("_field")
        if not ok:
            raise ExecutionError("TopN() requires a field")
        f = self.holder.field(index, field_name)
        if f is None:
            raise ExecutionError(f"field not found: {field_name}")
        n, _ = c.uint_arg("n")
        ids = c.args.get("ids")
        tan_thresh, attr_name, attr_values = topn_extras(c)

        # Unfiltered TopN first consults the field's per-fragment rank
        # caches (cache/rank.py; the reference's fragment.go:1570 top →
        # cache.go rankCache hot path).  Candidate pruning stays EXACT:
        # the cache answers only when it can prove the pruned rows cannot
        # reach the top n, and otherwise this falls through to the full
        # scan below.
        if not c.children and ids is None and tan_thresh is None \
                and attr_name is None \
                and f.options.cache_type in ("ranked", "lru"):
            from ..cache.rank import topn_from_rank
            pairs = topn_from_rank(f, shards, n, stats=self.stats)
            if pairs is not None:
                return pairs

        if self.mesh_exec is not None:
            # one shard_map computation: per-row popcounts masked by the
            # filter plan, psum'd over the shard axis (fragment.go:1570 top
            # collapsed into a single ICI all-reduce); tanimoto adds an
            # unfiltered pass + the src count, all dispatched before the
            # single blocking fetch
            filter_plan = self._filter_plan(index, c)
            parts = self.batcher.row_counts_async(
                field_name, VIEW_STANDARD, filter_plan,
                self.holder, index, shards)
            parts_u, parts_src = [], []
            if tan_thresh:
                parts_u = self.batcher.row_counts_async(
                    field_name, VIEW_STANDARD, None, self.holder, index,
                    shards)
                parts_src = self.batcher.count_async(
                    filter_plan, self.holder, index, shards)
            k, ku = len(parts), len(parts_u)

            def _fin(hp, ids=ids, n=n):
                counts = self.mesh_exec.merge_counts(hp[:k])
                row_tot = self.mesh_exec.merge_counts(hp[k: k + ku]) \
                    if tan_thresh else None
                src = sum(int(x) for x in hp[k + ku:]) if tan_thresh else 0
                return self._topn_finalize(
                    counts, row_tot, src, ids, n, tan_thresh, attr_name,
                    attr_values, f)

            return _Pending(parts + parts_u + parts_src, _fin)

        filters = self._filter_segments(index, c, shards)
        v = f.view(VIEW_STANDARD)
        counts = np.zeros(0, dtype=np.int64)
        row_tot = np.zeros(0, dtype=np.int64)
        src_count = 0
        if tan_thresh and filters is not None:
            # src is counted over ALL shards — including ones where the
            # TopN field has no fragment (the mesh path's count_async does
            # the same; skipping them would shrink the denominator)
            src_count = sum(
                int(np.asarray(bitset.count(seg)))
                for seg in filters.values())
        for shard in shards:
            frag = None if v is None else v.fragment(shard)
            if frag is None or frag.n_rows == 0:
                continue
            dev = frag.device()
            filt = None if filters is None else filters.get(shard)
            if filt is not None:
                counts_dev = bitset.row_counts(
                    bitset.intersect(dev, filt[None, :]))
            else:
                counts_dev = bitset.row_counts(dev)
            counts = acc_counts(counts, np.asarray(counts_dev))
            if tan_thresh:
                row_tot = acc_counts(
                    row_tot, np.asarray(bitset.row_counts(dev)))
        return self._topn_finalize(counts, row_tot, src_count, ids, n,
                                   tan_thresh, attr_name, attr_values, f)

    # -- Rows (executor.go:1274 executeRows) -------------------------------

    def _execute_rows(self, index: str, c: Call, shards) -> RowIdentifiers:
        field_name, ok = c.string_arg("_field")
        if not ok:
            raise ExecutionError("Rows() requires a field")
        f = self.holder.field(index, field_name)
        if f is None:
            raise ExecutionError(f"field not found: {field_name}")
        limit = c.args.get("limit")
        previous = c.args.get("previous")
        column = c.args.get("column")

        views = [VIEW_STANDARD]
        from_arg, to_arg = c.args.get("from"), c.args.get("to")
        if from_arg or to_arg:
            quantum = f.options.time_quantum
            if not quantum:
                raise ExecutionError(
                    f"field {field_name!r} has no time quantum")
            from_time = tq.parse_time(from_arg) if from_arg \
                else datetime(1, 1, 1)
            to_time = tq.parse_time(to_arg) if to_arg else datetime(9999, 1, 1)
            views = tq.views_by_time_range(VIEW_STANDARD, from_time, to_time,
                                           quantum)

        row_ids: set[int] = set()
        for vname in views:
            v = f.view(vname)
            if v is None:
                continue
            if self.mesh_exec is not None and column is None:
                counts = self.batcher.row_counts(
                    field_name, vname, None, self.holder, index, shards)
                row_ids.update(int(i) for i in np.nonzero(counts)[0])
                continue
            for shard in shards:
                if column is not None and column // SHARD_WIDTH != shard:
                    continue
                frag = v.fragment(shard)
                if frag is None or frag.n_rows == 0:
                    continue
                dev = frag.device()
                if column is not None:
                    col_local = column % SHARD_WIDTH
                    w, bit = bitset.word_bit_np(col_local)
                    present = np.asarray(dev[:, w]) & bit > 0
                    ids = np.nonzero(present)[0]
                else:
                    counts = np.asarray(bitset.row_counts(dev))
                    ids = np.nonzero(counts)[0]
                row_ids.update(int(i) for i in ids)

        out = sorted(row_ids)
        if previous is not None:
            out = [r for r in out if r > previous]
        if limit is not None:
            out = out[:limit]
        return RowIdentifiers(rows=out)

    # -- GroupBy (executor.go:1068 executeGroupBy) -------------------------

    def _group_by_parse(self, index: str, c: Call):
        """(names, rows_calls, filt_call, limit) with the reference's
        argument validation — shared by the legacy path and the
        whole-query lowering (_wq_desc_group_by)."""
        if not c.children:
            raise ExecutionError("GroupBy requires at least one Rows() child")
        limit = c.args.get("limit")
        filt_call = None
        rows_calls = []
        for ch in c.children:
            if ch.name == "Rows":
                rows_calls.append(ch)
            else:
                filt_call = ch
        if not rows_calls:
            raise ExecutionError("GroupBy requires Rows() children")
        names = []
        for rc in rows_calls:
            fname, ok = rc.string_arg("_field")
            if not ok:
                raise ExecutionError("Rows() requires a field")
            names.append(fname)
        return names, rows_calls, filt_call, limit

    def _group_by_grid(self, index: str, names, rows_calls):
        """Row-id grid fields when every child is a plain Rows(field)
        and the grid bounds hold; None otherwise (the caller executes
        Rows).  Plain Rows() children take a row-id GRID instead of
        executing Rows first: every (field, row<=max_row) combo is
        counted and zero-count groups drop out, which is the same
        answer without the per-child blocking device round trips (the
        odometer seeds of executor.go:3058, folded into the combo
        dispatch).  Only the PREFIX fields' product is dispatched (the
        last field rides each dispatch's per-row count vector), so the
        grid bounds are: prefix combos per wave (chunked to GROUP_CHUNK
        per executable call, all async) and the total combo count
        (which sizes the count fetch: total x 4 bytes).  The r4 cap of
        4096 TOTAL combos fell back to blocking per-child Rows round
        trips for e.g. a 128x128 two-field GroupBy whose dispatch cost
        is actually one 128-combo wave."""
        if not all(set(rc.args) == {"_field"} for rc in rows_calls):
            return None
        caps = []
        for fname in names:
            f = self.holder.field(index, fname)
            if f is None:
                raise ExecutionError(f"field not found: {fname}")
            v = f.view(VIEW_STANDARD)
            cap = 0 if v is None else max(
                (fr.max_row_id() + 1 for fr in v.fragments.values()
                 if fr.host_bytes()), default=0)
            caps.append(cap)
        total = 1
        for c_ in caps:
            total *= c_
        prefix_total = 1
        for c_ in caps[:-1]:
            prefix_total *= c_
        if 0 < total <= self.GROUP_GRID_MAX and \
                prefix_total <= self.GROUP_GRID_PREFIX_MAX:
            return [(fname, list(range(c_)))
                    for fname, c_ in zip(names, caps)]
        return None

    @staticmethod
    def _group_by_previous(c: Call, fields):
        """previous=[row per Rows child]: resume pagination strictly
        after that group (executor.go:1403, :3058 groupByIterator
        seek)."""
        previous = c.args.get("previous")
        if previous is None:
            return None
        if not isinstance(previous, list) or \
                len(previous) != len(fields):
            raise ExecutionError(
                "GroupBy previous= must list one row per Rows child")
        return tuple(int(p) for p in previous)

    def _execute_group_by(self, index: str, c: Call,
                          shards) -> list[GroupCount]:
        names, rows_calls, filt_call, limit = self._group_by_parse(index,
                                                                   c)
        fields = []
        if self.mesh_exec is not None:
            fields = self._group_by_grid(index, names, rows_calls) or []
        if not fields:
            for fname, rc in zip(names, rows_calls):
                ids = self._execute_rows(index, rc, shards).rows
                fields.append((fname, ids))

        prev_ids = self._group_by_previous(c, fields)

        def _paginate(groups_out):
            if prev_ids is not None:
                groups_out = [
                    g for g in groups_out
                    if tuple(fr.row_id for fr in g.group) > prev_ids]
            if limit is not None:
                groups_out = groups_out[:limit]
            return groups_out

        # Count each combination: per shard, AND the group rows' segments +
        # optional filter, popcount.  The innermost field is batched on
        # device; on the mesh path the whole inner loop is ONE psum'd
        # shard_map call per combo with dynamic prefix row ids.
        results: list[GroupCount] = []
        last_field, last_ids = fields[-1]
        prefix_fields = fields[:-1]

        def prefix_combos(i=0, combo=()):
            if i == len(prefix_fields):
                yield combo
                return
            fname, ids = prefix_fields[i]
            for rid in ids:
                yield from prefix_combos(i + 1, combo + ((fname, rid),))

        if self.mesh_exec is not None:
            filter_plan = (self._resolve(index, filt_call)
                           if filt_call is not None else None)
            prefix_keys = [(fname, VIEW_STANDARD) for fname, _ in
                           prefix_fields]
            combos = list(prefix_combos())
            if not combos:
                return []
            mat = np.asarray(
                [[rid for _, rid in combo] for combo in combos],
                dtype=np.int32).reshape(len(combos), len(prefix_fields))
            # A handful of executable invocations cover every combo
            # (vmapped combo axis, chunked to bound device memory) — the
            # odometer's per-combo round trips (executor.go:3058) collapse
            # into one dispatch per 256 combos, resolved by a single fetch
            chunked = self.batcher.group_counts_batch_async(
                (last_field, VIEW_STANDARD), prefix_keys, mat, filter_plan,
                self.holder, index, shards)
            all_parts = [p for _, _, ps in chunked for p in ps]

            def _fin(hp, combos=combos, last_ids=last_ids):
                out: list[GroupCount] = []
                i = 0
                for lo, hi, ps in chunked:
                    acc = None
                    for p in hp[i: i + len(ps)]:
                        a = np.asarray(p, dtype=np.int64)
                        acc = a.copy() if acc is None else acc_counts(acc, a)
                    i += len(ps)
                    for ci in range(lo, hi):
                        combo = combos[ci]
                        for rid in last_ids:
                            cnt = (int(acc[ci - lo, rid])
                                   if acc is not None
                                   and rid < acc.shape[1] else 0)
                            if cnt > 0:
                                group = [FieldRow(fn, ri)
                                         for fn, ri in combo]
                                group.append(FieldRow(last_field, rid))
                                out.append(GroupCount(group, cnt))
                out.sort(key=lambda g: tuple(
                    (fr.field, fr.row_id) for fr in g.group))
                return _paginate(out)

            return _Pending(all_parts, _fin)

        filter_segs = None
        if filt_call is not None:
            plan = self._resolve(index, filt_call)
            filter_segs = {
                s: self.compiler.execute_shard(plan, self.holder, index, s)
                for s in shards
            }

        last_pos = {r: j for j, r in enumerate(last_ids)}
        for combo in prefix_combos():
            counts_acc = np.zeros(len(last_ids), dtype=np.int64)
            for shard in shards:
                prefix_seg = None
                empty = False
                for fname, rid in combo:
                    frag = self.holder.fragment(index, fname, VIEW_STANDARD,
                                                shard)
                    if frag is None or rid >= frag.n_rows:
                        empty = True
                        break
                    seg = frag.device()[rid]
                    prefix_seg = seg if prefix_seg is None else \
                        bitset.intersect(prefix_seg, seg)
                if empty:
                    continue
                if filter_segs is not None:
                    fseg = filter_segs[shard]
                    prefix_seg = fseg if prefix_seg is None else \
                        bitset.intersect(prefix_seg, fseg)
                frag = self.holder.fragment(index, last_field, VIEW_STANDARD,
                                            shard)
                if frag is None or frag.n_rows == 0:
                    continue
                dev = frag.device()
                valid = [r for r in last_ids if r < frag.n_rows]
                if not valid:
                    continue
                sel = dev[np.array(valid)]
                if prefix_seg is None:
                    cnts = np.asarray(bitset.row_counts(sel))
                else:
                    cnts = np.asarray(bitset.row_counts(
                        bitset.intersect(sel, prefix_seg[None, :])))
                for j, r in enumerate(valid):
                    counts_acc[last_pos[r]] += int(cnts[j])
            for j, rid in enumerate(last_ids):
                if counts_acc[j] > 0:
                    group = [FieldRow(fn, ri) for fn, ri in combo]
                    group.append(FieldRow(last_field, rid))
                    results.append(GroupCount(group, int(counts_acc[j])))

        results.sort(key=lambda g: tuple(
            (fr.field, fr.row_id) for fr in g.group))
        return _paginate(results)

    # -- Options (executor.go executeOptionsCall) --------------------------

    @staticmethod
    def _options_bool(c: Call, name: str) -> bool:
        v = c.args.get(name, False)
        if not isinstance(v, bool):
            raise ExecutionError(f"Options() {name} must be a bool")
        return v

    @staticmethod
    def attach_column_attrs(holder, index: str, result):
        """Stash [{"id", "attrs"}] for every result column that has column
        attributes onto the RowResult; the HTTP layer lifts them to the
        response's top-level "columnAttrs" (executor.go:163-192,
        :209 readColumnAttrSets)."""
        if not isinstance(result, RowResult):
            return result
        idx = holder.index(index)
        # one store snapshot + intersect: O(stored attrs), not O(result
        # columns) — results can span millions of columns
        all_attrs = idx.column_attrs.all()
        if not all_attrs:
            result.column_attrs = []
            return result
        attr_ids = np.fromiter(all_attrs.keys(), dtype=np.int64,
                               count=len(all_attrs))
        have = np.intersect1d(attr_ids, result.columns())
        result.column_attrs = [{"id": int(c), "attrs": all_attrs[int(c)]}
                               for c in np.sort(have)]
        return result

    def _execute_options(self, index: str, c: Call, shards):
        """(executor.go:340-403 executeOptionsCall)"""
        if len(c.children) != 1:
            raise ExecutionError("Options() requires exactly one child")
        if "shards" in c.args:
            arg = c.args["shards"]
            if not isinstance(arg, list):
                raise ExecutionError("Options() shards must be a list")
            shards = [int(s) for s in arg]
        column_attrs = self._options_bool(c, "columnAttrs")
        exclude_row_attrs = self._options_bool(c, "excludeRowAttrs")
        exclude_columns = self._options_bool(c, "excludeColumns")
        result = self._execute_call(index, c.children[0], shards)
        if not (column_attrs or exclude_row_attrs or exclude_columns):
            return result

        def _shape(r):
            if isinstance(r, RowResult):
                if exclude_columns:
                    r.segments = {}
                if column_attrs:
                    # after excludeColumns on purpose: both flags yield no
                    # attr sets, matching the reference's response shaping
                    self.attach_column_attrs(self.holder, index, r)
                if exclude_row_attrs:
                    r.attrs = {}
            return r

        if isinstance(result, _Pending):
            inner_fin = result.fin
            result.fin = lambda hp: _shape(inner_fin(hp))
            return result
        return _shape(result)

    # -- writes (executor.go:2067 executeSet etc.) -------------------------

    def _require_col(self, c: Call) -> int:
        col = c.args.get("_col")
        if not isinstance(col, int) or isinstance(col, bool):
            raise ExecutionError(
                f"{c.name}() column argument must be an integer id "
                f"(got {col!r})")
        return col

    def _execute_set(self, index: str, c: Call) -> bool:
        idx = self.holder.index(index)
        col = self._require_col(c)
        fa = c.field_arg()
        if fa is None:
            raise ExecutionError("Set() requires a field=<row> argument")
        field_name, row_val = fa
        f = self.holder.field(index, field_name)
        if f is None:
            raise ExecutionError(f"field not found: {field_name}")

        if f.options.type == FIELD_TYPE_INT:
            if not isinstance(row_val, int):
                raise ExecutionError("Set() int field requires integer value")
            changed = f.set_value(col, row_val)
        else:
            ts = None
            if "_timestamp" in c.args:
                ts = tq.parse_time(c.args["_timestamp"])
            row_val = self._coerce_row(f, row_val)
            changed = f.set_bit(row_val, col, ts=ts)
        idx.add_existence(np.array([col]))
        return changed

    @staticmethod
    def _coerce_row(f, row_val) -> int:
        if isinstance(row_val, bool):
            if f.options.type != FIELD_TYPE_BOOL:
                raise ExecutionError("bool row value on non-bool field")
            return int(row_val)
        if not isinstance(row_val, int):
            raise ExecutionError(
                f"row must be an integer id, got {row_val!r}")
        return row_val

    def _execute_clear(self, index: str, c: Call) -> bool:
        col = self._require_col(c)
        fa = c.field_arg()
        if fa is None:
            raise ExecutionError("Clear() requires a field=<row> argument")
        field_name, row_val = fa
        f = self.holder.field(index, field_name)
        if f is None:
            raise ExecutionError(f"field not found: {field_name}")
        return f.clear_bit(self._coerce_row(f, row_val), col)

    def _execute_clear_row(self, index: str, c: Call, shards) -> bool:
        """(executor.go:1825 executeClearRow)"""
        fa = c.field_arg()
        if fa is None:
            raise ExecutionError("ClearRow() requires a field=<row> argument")
        field_name, row_id = fa
        f = self.holder.field(index, field_name)
        if f is None:
            raise ExecutionError(f"field not found: {field_name}")
        changed = False
        for vname, v in list(f.views.items()):
            if vname.startswith("bsig_"):
                continue
            for shard in shards:
                frag = v.fragment(shard)
                if frag is not None and row_id < frag.n_rows:
                    if frag.row(row_id).any():
                        frag.set_row(row_id, None)
                        changed = True
        return changed

    def _execute_store(self, index: str, c: Call, shards) -> bool:
        """Store(Row(...), field=row) (executor.go:1979 executeSetRow)"""
        fa = c.field_arg()
        if fa is None:
            raise ExecutionError("Store() requires a field=<row> argument")
        field_name, row_id = fa
        f = self.holder.field(index, field_name)
        if f is None:
            f = self.holder.index(index).create_field_if_not_exists(field_name)
        if len(c.children) != 1:
            raise ExecutionError("Store() requires exactly one input row")
        src = self._execute_bitmap(index, c.children[0], shards)
        for shard in shards:
            seg = src.segments.get(shard)
            v = f._create_view_if_not_exists(VIEW_STANDARD)
            frag = v.create_fragment_if_not_exists(shard)
            frag.set_row(row_id, None if seg is None else np.asarray(seg))
        return True

    def _execute_set_attrs(self, index: str, c: Call):
        # Attribute storage arrives with the attrs subsystem (storage/attrs);
        # wired in the API layer.
        from ..storage.attrs import set_attrs_from_call
        return set_attrs_from_call(self.holder, index, c)
