"""Dense uint32 bitset kernels — the TPU-native replacement for the reference
engine's roaring container op matrix (roaring/roaring.go:3160-4770: intersect,
union, difference, xor, shift, flip, intersectionCount, Count/CountRange).

Representation
--------------
A *segment* is one shard-row of bits as a dense ``uint32[SHARD_WORDS]`` vector
(little-endian within each word: shard-column ``c`` lives at word ``c >> 5``,
bit ``c & 31``).  A *fragment tensor* stacks rows: ``uint32[n_rows,
SHARD_WORDS]``.  All ops here are pure jax functions over those arrays; they
are shape-polymorphic so one jitted executable serves every fragment with the
same row count.  The adaptive array/bitmap/run container forms of the
reference survive, but split across two layers: COMPUTE is always dense —
the VPU processes 8x128 lanes of uint32 per cycle, and the branchy
(op x container-type^2) dispatch matrix of the reference would defeat XLA
fusion — while RESIDENCY may be compressed (ops/containers.py): sparse
fragments stay HBM-resident as packed array/bitmap/run container streams
and are decoded to dense tiles on device at op time, inside the same
executable that runs these kernels.  Decode-at-op-time keeps every op
below this line a branch-free dense kernel yet lets residency cost
compressed bytes instead of the 100x dense blowup (docs/memory-budget.md
"Compressed residency").

Host-side packing/unpacking helpers (numpy) live at the bottom; they are the
import/export boundary, mirroring roaring's serializer role.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np

from ..core import SHARD_WORDS, WORD_BITS, WORD_BITS_EXP

_FULL_WORD = np.uint32(0xFFFFFFFF)


def word_bit_np(cols):
    """Column ids -> (word index, single-bit mask) on host (numpy).  The one
    place the word geometry (WORD_BITS_EXP) is spelled out for packing."""
    cols = np.asarray(cols)
    w = cols >> WORD_BITS_EXP
    bit = np.uint32(1) << (cols & (WORD_BITS - 1)).astype(np.uint32)
    return w, bit


def word_bit(cols):
    """Traced variant of word_bit_np for device code."""
    w = cols >> WORD_BITS_EXP
    bit = jnp.uint32(1) << (cols & (WORD_BITS - 1)).astype(jnp.uint32)
    return w, bit


# ---------------------------------------------------------------------------
# Boolean algebra (roaring/roaring.go:3160 intersect, :3382 union, :3828
# difference, :4175 xor).  Trivial on dense bitsets; XLA fuses chains of these
# into a single pass over HBM, which is the whole point.
# ---------------------------------------------------------------------------

def intersect(a, b):
    return jnp.bitwise_and(a, b)


def union(a, b):
    return jnp.bitwise_or(a, b)


def difference(a, b):
    return jnp.bitwise_and(a, jnp.bitwise_not(b))


def xor(a, b):
    return jnp.bitwise_xor(a, b)


def union_many(segs):
    """n-way union (roaring/roaring.go:739 unionInPlace).  ``segs`` is a
    stacked ``uint32[n, W]`` tensor; reduces along axis 0 in one pass."""
    return jax.lax.reduce(
        segs, np.uint32(0), jax.lax.bitwise_or, dimensions=(0,)
    )


# ---------------------------------------------------------------------------
# Population counts (roaring/roaring.go:407 Count, :436 CountRange, :3021
# intersectionCount).  popcount on the VPU + an integer tree-reduce; counts
# fit int32 (<= 2^20 per segment), summed as int32 on device.
# ---------------------------------------------------------------------------

def popcount_words(a):
    return jax.lax.population_count(a).astype(jnp.int32)


def count(seg):
    """Total set bits of a segment (or of each row if given [n, W]: reduces
    over every axis — use row_counts for per-row)."""
    return jnp.sum(popcount_words(seg), dtype=jnp.int32)


def row_counts(frag):
    """Per-row popcount of a fragment tensor uint32[n, W] -> int32[n]."""
    return jnp.sum(popcount_words(frag), axis=-1, dtype=jnp.int32)


def intersection_count(a, b):
    """popcount(a & b) without materialising the intersection
    (roaring/roaring.go:3021-3158)."""
    return jnp.sum(popcount_words(jnp.bitwise_and(a, b)), dtype=jnp.int32)


@jax.jit
def intersection_counts_matrix(a, b):
    """Pairwise intersection counts between two row sets:
    uint32[n, W] x uint32[m, W] -> int32[n, m].

    This is the GroupBy hot loop (executor.go:3058 groupByIterator does it
    pair-at-a-time over roaring containers); batching it into one
    popcount-and-reduce lets the VPU stream both operand sets once per tile.
    """
    return jnp.sum(
        popcount_words(a[:, None, :] & b[None, :, :]), axis=-1, dtype=jnp.int32
    )


# ---------------------------------------------------------------------------
# Range masks and ranged ops (roaring/roaring.go:436 CountRange, :2982 flip,
# :562 OffsetRange).
# ---------------------------------------------------------------------------

def _range_mask(start: int, end: int, words: int = SHARD_WORDS):
    """uint32[words] mask with bits [start, end) set.  start/end are traced or
    static scalars in [0, words*32]."""
    start = jnp.asarray(start, jnp.int32)
    end = jnp.asarray(end, jnp.int32)
    base = jnp.arange(words, dtype=jnp.int32) * WORD_BITS
    lo = jnp.clip(start - base, 0, WORD_BITS)
    hi = jnp.clip(end - base, 0, WORD_BITS)
    # (1<<hi)-1 with hi==32 overflows 32-bit shifts; build from the top:
    # mask_hi = all bits below hi = ~0 >> (32-hi), except hi==0 -> 0.
    full = jnp.uint32(0xFFFFFFFF)
    mask_hi = jnp.where(
        hi == 0, jnp.uint32(0), full >> (WORD_BITS - hi).astype(jnp.uint32)
    )
    mask_lo = jnp.where(
        lo == 0, jnp.uint32(0), full >> (WORD_BITS - lo).astype(jnp.uint32)
    )
    return mask_hi & ~mask_lo


def count_range(seg, start, end):
    """Count bits in [start, end) (roaring/roaring.go:436)."""
    mask = _range_mask(start, end, seg.shape[-1])
    return jnp.sum(popcount_words(seg & mask), dtype=jnp.int32)


def flip(seg, start, end):
    """Toggle bits in [start, end) (roaring/roaring.go:2982)."""
    return seg ^ _range_mask(start, end, seg.shape[-1])


def keep_range(seg, start, end):
    """Zero every bit outside [start, end)."""
    return seg & _range_mask(start, end, seg.shape[-1])


# ---------------------------------------------------------------------------
# Shift (roaring/roaring.go:4288): move every bit up by one column.  Used by
# PQL Shift(row, n).  Bits shifted past the shard boundary are dropped, which
# matches per-segment shift in the reference (row.go:248 Shift).
# ---------------------------------------------------------------------------

def shift(seg, n: int = 1):
    """Shift bits toward higher column ids by static ``n`` >= 0."""
    if n == 0:
        return seg
    word_shift, bit_shift = divmod(n, WORD_BITS)
    w = seg.shape[-1]
    if word_shift:
        pad = [(0, 0)] * (seg.ndim - 1) + [(word_shift, 0)]
        seg = jnp.pad(seg, pad)[..., :w]
    if bit_shift:
        lo = seg << np.uint32(bit_shift)
        carry = seg >> np.uint32(WORD_BITS - bit_shift)
        pad = [(0, 0)] * (seg.ndim - 1) + [(1, 0)]
        carry = jnp.pad(carry, pad)[..., :w]
        seg = lo | carry
    return seg


# ---------------------------------------------------------------------------
# Batched mutation.  The reference mutates roaring containers in place
# (roaring.go:228 Add); under XLA we batch positions and scatter into a
# donated buffer.  The storage layer keeps the authoritative copy host-side
# (see storage/fragment.py) and uses these for device-resident updates.
# ---------------------------------------------------------------------------

def _word_updates(frag, rows, cols):
    """Collapse a (row, col) batch into per-word OR masks with *unique* target
    words.  XLA has no scatter-OR, and ``.at[].set`` keeps an arbitrary
    duplicate, so positions sharing a 32-bit word must be pre-combined: sort
    by flat word index, OR bits of equal keys with an associative scan, and
    keep only the last (fully accumulated) entry of each run.

    Returns (targets, masks): int32 flat word indices (invalid/duplicate
    entries pointed one-past-the-end, to be dropped) and the OR-mask per
    entry.  Fragment must have < 2^31 / W rows (always true: W=32768 allows
    65k rows; real fragments are far smaller).
    """
    n_words = frag.shape[-1]
    total = frag.size
    if total >= 2**31:
        raise ValueError(
            f"fragment too large for int32 scatter keys: {frag.shape} "
            f"(max {2**31 // n_words - 1} rows at {n_words} words)"
        )
    valid = rows >= 0
    r = jnp.maximum(rows, 0).astype(jnp.int32)
    w, bit = word_bit(cols)
    w = w.astype(jnp.int32)
    bit = jnp.where(valid, bit, jnp.uint32(0))
    key = r * n_words + w
    key = jnp.where(valid, key, total)  # sort invalid entries to the end
    order = jnp.argsort(key)
    key, bit = key[order], bit[order]

    def comb(x, y):
        kx, bx = x
        ky, by = y
        return ky, by | jnp.where(kx == ky, bx, jnp.uint32(0))

    key, acc = jax.lax.associative_scan(comb, (key, bit))
    is_last = jnp.concatenate(
        [key[1:] != key[:-1], jnp.ones((1,), dtype=bool)]
    )
    targets = jnp.where(is_last, key, total)  # total = out of bounds -> drop
    return targets, acc


@functools.partial(jax.jit, donate_argnums=0)
def set_bits(frag, rows, cols):
    """Set bits (rows[i], cols[i]) in fragment uint32[n, W].  Duplicate
    positions and positions sharing a word are handled correctly; padding
    entries may use row == -1 (ignored)."""
    targets, masks = _word_updates(frag, rows, cols)
    flat = frag.reshape(-1)
    cur = flat.at[targets].get(mode="fill", fill_value=0)
    out = flat.at[targets].set(cur | masks, mode="drop")
    return out.reshape(frag.shape)


@functools.partial(jax.jit, donate_argnums=0)
def clear_bits(frag, rows, cols):
    """Clear bits (rows[i], cols[i]); same duplicate/padding semantics as
    set_bits."""
    targets, masks = _word_updates(frag, rows, cols)
    flat = frag.reshape(-1)
    cur = flat.at[targets].get(mode="fill", fill_value=0)
    out = flat.at[targets].set(cur & ~masks, mode="drop")
    return out.reshape(frag.shape)


# ---------------------------------------------------------------------------
# Host-side packing (numpy) — the import/export boundary.  Mirrors the role of
# roaring's serializer (roaring/roaring.go:1046 WriteTo / 1258 iterator).
# ---------------------------------------------------------------------------

def pack_columns(cols: np.ndarray, words: int = SHARD_WORDS) -> np.ndarray:
    """Sorted-or-not shard-local column ids -> uint32[words] bitset."""
    out = np.zeros(words, dtype=np.uint32)
    w, bit = word_bit_np(np.asarray(cols, dtype=np.int64))
    np.bitwise_or.at(out, w, bit)
    return out


def pack_fragment(rows: np.ndarray, cols: np.ndarray, n_rows: int,
                  words: int = SHARD_WORDS) -> np.ndarray:
    """(row, col) pairs -> uint32[n_rows, words] fragment tensor."""
    out = np.zeros((n_rows, words), dtype=np.uint32)
    rows = np.asarray(rows, dtype=np.int64)
    w, bit = word_bit_np(np.asarray(cols, dtype=np.int64))
    np.bitwise_or.at(out, (rows, w), bit)
    return out


def unpack_columns(seg: np.ndarray) -> np.ndarray:
    """uint32[words] bitset -> sorted int64 column ids."""
    seg = np.ascontiguousarray(np.asarray(seg, dtype=np.uint32))
    bits = np.unpackbits(seg.view(np.uint8), bitorder="little")
    return np.nonzero(bits)[0].astype(np.int64)


def unpack_fragment(frag: np.ndarray):
    """uint32[n, words] -> (row_ids, col_ids) int64 arrays, row-major order."""
    frag = np.ascontiguousarray(np.asarray(frag, dtype=np.uint32))
    n, w = frag.shape
    bits = np.unpackbits(frag.view(np.uint8), bitorder="little").reshape(n, w * 32)
    r, c = np.nonzero(bits)
    return r.astype(np.int64), c.astype(np.int64)
