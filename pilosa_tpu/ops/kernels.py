"""Pallas container kernels: HBM->VMEM decode + fused bitwise-op/popcount
in one launch (docs/architecture.md "On native code and Pallas").

The compressed-residency layer (ops/containers.py) decodes packed
array/bitmap/run container streams to dense tiles with pure-jnp
gather/scatter — XLA schedules that decode through HBM-resident
temporaries bounded only by ``decode-workspace-mb``.  This module is the
hand-scheduled alternative: Pallas kernels that walk the same PR 7
key/type/count/offset/payload tables CONTAINER-TILE-BY-TILE, so each
2048-word dense tile is materialised in a VMEM block, consumed, and
overwritten by the next grid step instead of round-tripping through HBM.
Two kernels ship:

* ``decode_block`` — drop-in for ``containers.decode_block`` (same
  signature, same answer): grid over the fragment's ``rows x 16`` output
  container tiles, each step decoding one container form (bitmap:
  dynamic-slice copy; array: one-hot scatter of (slot, value) entries;
  run: per-word range masks OR-reduced) into its (16, 128) VMEM block.
* ``fused_row_counts`` — the headline fusion: decode + optional AND with
  a dense filter segment + per-row popcount accumulation in ONE kernel,
  so the decoded words never exist outside the tile at all (the
  TopN/Rows ``row_counts`` hot path, parallel/mesh_exec.py).

Backend selection rides the ``container-kernels`` knob
(``CONTAINER_KERNELS``, set process-wide from the server config like
``DECODE_WORKSPACE_BYTES``): ``auto`` resolves to the Pallas kernels on
TPU and the jnp decode elsewhere, ``pallas`` forces the kernels
(executing through the Pallas INTERPRETER off-TPU, so the whole path is
differentially testable in CPU tier-1), and ``jnp`` is the kill switch
restoring the PR 7 path exactly.  The resolved backend is part of every
compressed ``Fragment.device_sig()`` (the kernel-backend axis), so a
flip changes the group signatures, rebuilds stacks, and recompiles
executables instead of silently replaying a jnp-compiled program.

TPU-lowering caveat: the kernel bodies use word-granularity dynamic
slices and gathers that the Pallas interpreter (and a TPU with relaxed
layout constraints) accepts but that may need 128-lane alignment work
before they lower on every real-TPU toolchain; the interpret-mode
differential pins the SEMANTICS now so the r10 on-TPU round only has to
tune the schedule.  Buckets whose per-tile working set (whole payload +
form intermediates) exceeds ``VMEM_TILE_BUDGET_BYTES`` fall back to the
jnp decode — the VMEM budget rule — statically per signature, so the
choice is trace-stable.
"""

from __future__ import annotations

import functools

import numpy as np

from ..core import CONTAINER_WORDS, SHARD_WORDS, WORD_BITS
from .containers import TYPE_ARRAY, TYPE_BITMAP, TYPE_RUN

# Container-decode kernel backend: "auto" | "pallas" | "jnp".
# Process-wide, set from the server config (container-kernels) like
# fragment.COMPRESSED_RESIDENT; bench legs and tests flip it directly.
CONTAINER_KERNELS = "auto"

# One container's 2048 words as a VMEM tile: 16 sublanes x 128 lanes.
TILE_ROWS = CONTAINER_WORDS // 128    # 16
TILE_LANES = 128
TILES_PER_SHARD_ROW = SHARD_WORDS // CONTAINER_WORDS  # 16

# The VMEM budget rule: a decode bucket only takes the Pallas path when
# its per-tile working set — the whole (pow2-bucketed) payload the
# kernel keeps VMEM-resident plus the array/run form intermediates and
# the tile itself — fits under this.  Over-budget buckets fall back to
# the jnp decode; the decision depends only on signature fields, so it
# is identical on every trace of one executable.
VMEM_TILE_BUDGET_BYTES = 12 << 20


@functools.lru_cache(maxsize=1)
def _platform() -> str:
    """Device platform this process compiles for (fixed per process —
    jax picks the backend once)."""
    import jax
    return jax.default_backend()


@functools.lru_cache(maxsize=1)
def _pallas_available() -> bool:
    """Whether the installed jax ships jax.experimental.pallas — gated
    so a trimmed install degrades to the jnp backend instead of an
    ImportError on the query path."""
    try:
        from jax.experimental import pallas  # noqa: F401
    except ImportError:
        return False
    return True


def resolve(mode: str | None = None) -> str:
    """Resolved backend ("pallas" | "jnp") for the given knob value
    (default: the process-wide ``CONTAINER_KERNELS``)."""
    m = CONTAINER_KERNELS if mode is None else mode
    if m == "jnp":
        return "jnp"
    if m == "pallas":
        return "pallas" if _pallas_available() else "jnp"
    # auto: kernels where they pay (TPU), jnp elsewhere — CPU tier-1
    # exercises the kernels only when a test/bench forces "pallas"
    return "pallas" if (_platform() == "tpu" and _pallas_available()) \
        else "jnp"


def interpret_mode() -> bool:
    """Off-TPU the kernels run through the Pallas interpreter — same
    kernel logic, XLA:CPU execution — so tier-1 can differentially test
    the exact code path the TPU compiles."""
    return _platform() != "tpu"


def sig_tag() -> str:
    """The kernel-backend axis of compressed ``Fragment.device_sig()``
    tuples (storage/fragment.py): the RESOLVED backend, so an
    auto->pallas TPU process and an auto->jnp CPU process produce
    distinct signatures and a knob flip rebuilds stacks/executables."""
    return resolve()


def sig_backend(sig) -> str:
    """Backend recorded in a compressed group signature ('z', rows, C,
    P, A, R, backend); signatures minted before the backend axis read as
    jnp (the decode they compiled)."""
    return sig[6] if len(sig) > 6 else "jnp"


def fits_vmem(payload_bucket: int, a_bucket: int, r_bucket: int) -> bool:
    """The VMEM budget rule (module docstring): whether a decode
    bucket's per-tile working set fits ``VMEM_TILE_BUDGET_BYTES``."""
    est = (max(payload_bucket, CONTAINER_WORDS)
           + a_bucket * CONTAINER_WORDS      # one-hot scatter compare
           + r_bucket * CONTAINER_WORDS      # per-run range masks
           + CONTAINER_WORDS) * 4
    return est <= VMEM_TILE_BUDGET_BYTES


def _tile_slots(keys, tiles: int):
    """int32[tiles] inverse container map: output tile t's index into
    the container tables, -1 where no container covers the tile.  Keys
    are unique and padding rows carry key -1, so one drop-mode scatter
    (outside the kernel) builds it."""
    import jax.numpy as jnp
    C = keys.shape[0]
    idx = jnp.where(keys >= 0, keys, tiles).astype(jnp.int32)
    return jnp.full((tiles,), -1, dtype=jnp.int32).at[idx].set(
        jnp.arange(C, dtype=jnp.int32), mode="drop")


def _pad_payload(payload):
    """Payload padded to at least one container tile so the kernel's
    static-size bitmap dynamic-slice never exceeds the buffer."""
    import jax.numpy as jnp
    P = payload.shape[0]
    if P >= CONTAINER_WORDS:
        return payload
    return jnp.zeros(CONTAINER_WORDS, dtype=jnp.uint32).at[:P].set(payload)


def _container_tile(pv, typ, cnt, off, a_bucket: int, r_bucket: int):
    """One container's dense (TILE_ROWS, TILE_LANES) word tile, decoded
    from the VMEM-resident payload ``pv`` — the per-grid-step body both
    kernels share.  Mirrors containers.decode_block's per-container
    math exactly (bitmap copy / array one-hot scatter / run range
    masks); a_bucket/r_bucket of 0 compile that form out."""
    import jax
    import jax.numpy as jnp
    cw = CONTAINER_WORDS
    # bitmap: contiguous copy.  dynamic_slice clamps the start, so a
    # non-bitmap off near the buffer end reads garbage that the where()
    # discards — never out of bounds.
    bm = jax.lax.dynamic_slice(pv, (off,), (cw,))
    tile = jnp.where(typ == TYPE_BITMAP, bm, jnp.uint32(0))
    j = jnp.arange(cw, dtype=jnp.int32)
    if a_bucket:
        e = jnp.arange(a_bucket, dtype=jnp.int32)
        live = (e < cnt) & (typ == TYPE_ARRAY)
        slots = jnp.where(live, pv.at[off + e].get(
            mode="fill", fill_value=0).astype(jnp.int32), -1)
        vals = pv.at[off + cnt + e].get(mode="fill", fill_value=0)
        hit = slots[:, None] == j[None, :]               # [a_bucket, cw]
        tile = tile | jax.lax.reduce(
            jnp.where(hit, vals[:, None], jnp.uint32(0)), np.uint32(0),
            jax.lax.bitwise_or, dimensions=(0,))
    if r_bucket:
        r = jnp.arange(r_bucket, dtype=jnp.int32)
        live = (r < cnt) & (typ == TYPE_RUN)
        rs = jnp.where(live, pv.at[off + 2 * r].get(
            mode="fill", fill_value=0).astype(jnp.int32), 0)
        re_ = jnp.where(live, pv.at[off + 2 * r + 1].get(
            mode="fill", fill_value=0).astype(jnp.int32), 0)
        base = j * WORD_BITS
        lo = jnp.clip(rs[:, None] - base[None, :], 0, WORD_BITS)
        hi = jnp.clip(re_[:, None] - base[None, :], 0, WORD_BITS)
        full = jnp.uint32(0xFFFFFFFF)
        mhi = jnp.where(hi == 0, jnp.uint32(0),
                        full >> (WORD_BITS - hi).astype(jnp.uint32))
        mlo = jnp.where(lo == 0, jnp.uint32(0),
                        full >> (WORD_BITS - lo).astype(jnp.uint32))
        tile = tile | jax.lax.reduce(mhi & ~mlo, np.uint32(0),
                                     jax.lax.bitwise_or, dimensions=(0,))
    return tile.reshape(TILE_ROWS, TILE_LANES)


def decode_block(keys, types, counts, offsets, payload, *, rows: int,
                 words: int = SHARD_WORDS, a_bucket: int = 0,
                 r_bucket: int = 0):
    """Pallas drop-in for ``containers.decode_block``: decode one
    fragment's packed stream to dense ``uint32[rows, words]``, one
    container tile per grid step.  Same arguments, same answer; buckets
    over the VMEM budget rule (and degenerate shapes) fall back to the
    jnp decode."""
    import jax
    import jax.numpy as jnp

    from . import containers

    C = keys.shape[0]
    if (C == 0 or rows == 0 or words % CONTAINER_WORDS
            or not fits_vmem(payload.shape[0], a_bucket, r_bucket)):
        return containers.decode_block(
            keys, types, counts, offsets, payload, rows=rows, words=words,
            a_bucket=a_bucket, r_bucket=r_bucket)
    from jax.experimental import pallas as pl

    tiles = rows * (words // CONTAINER_WORDS)
    slot = _tile_slots(keys, tiles)
    pay = _pad_payload(payload)

    def kernel(slot_ref, types_ref, counts_ref, offsets_ref, pay_ref,
               out_ref):
        t = pl.program_id(0)
        c = slot_ref[...][t]
        live = c >= 0
        ci = jnp.where(live, c, 0)
        typ = jnp.where(live, types_ref[...][ci], -1)
        cnt = jnp.where(live, counts_ref[...][ci], 0)
        off = jnp.where(live, offsets_ref[...][ci], 0)
        out_ref[...] = _container_tile(pay_ref[...], typ, cnt, off,
                                       a_bucket, r_bucket)

    full = [slot, types, counts, offsets, pay]
    out = pl.pallas_call(
        kernel,
        grid=(tiles,),
        in_specs=[pl.BlockSpec(a.shape, _full_block) for a in full],
        out_specs=pl.BlockSpec((TILE_ROWS, TILE_LANES), lambda t: (t, 0)),
        out_shape=jax.ShapeDtypeStruct((tiles * TILE_ROWS, TILE_LANES),
                                       jnp.uint32),
        interpret=interpret_mode(),
    )(*full)
    return out.reshape(rows, words)


def _full_block(t):
    # whole-array input block every grid step (tables + payload stay
    # VMEM-resident across the container tiles of one fragment)
    return (0,)


def fused_row_counts(keys, types, counts, offsets, payload, filt=None, *,
                     rows: int, words: int = SHARD_WORDS,
                     a_bucket: int = 0, r_bucket: int = 0):
    """Decode + optional AND-with-filter + per-row popcount in ONE
    kernel launch: int32[rows] set-bit counts of a packed fragment,
    optionally masked by a dense ``uint32[words]`` segment.  The decoded
    words exist only as the grid step's VMEM tile — no dense
    ``[rows, words]`` temporary at all (the jnp path's decode output).
    Falls back to decode+popcount via jnp under the same conditions as
    ``decode_block``."""
    import jax
    import jax.numpy as jnp

    from . import containers

    C = keys.shape[0]
    if (C == 0 or rows == 0 or words % CONTAINER_WORDS
            or not fits_vmem(payload.shape[0], a_bucket, r_bucket)):
        frag = containers.decode_block(
            keys, types, counts, offsets, payload, rows=rows, words=words,
            a_bucket=a_bucket, r_bucket=r_bucket)
        if filt is not None:
            frag = frag & filt[None, :]
        return jnp.sum(jax.lax.population_count(frag).astype(jnp.int32),
                       axis=-1)
    from jax.experimental import pallas as pl

    tpr = words // CONTAINER_WORDS
    tiles = rows * tpr
    slot = _tile_slots(keys, tiles)
    pay = _pad_payload(payload)

    def kernel(slot_ref, types_ref, counts_ref, offsets_ref, pay_ref,
               *rest):
        filt_out = rest
        t = pl.program_id(0)
        c = slot_ref[...][t]
        live = c >= 0
        ci = jnp.where(live, c, 0)
        typ = jnp.where(live, types_ref[...][ci], -1)
        cnt = jnp.where(live, counts_ref[...][ci], 0)
        off = jnp.where(live, offsets_ref[...][ci], 0)
        tile = _container_tile(pay_ref[...], typ, cnt, off,
                               a_bucket, r_bucket)
        if len(filt_out) == 2:
            tile = tile & filt_out[0][...]
        out_ref = filt_out[-1]
        # out block (1, 1) revisited by the row's tpr consecutive steps:
        # zero on the first, accumulate the tile popcount on each
        @pl.when(t % tpr == 0)
        def _init():
            out_ref[...] = jnp.zeros_like(out_ref)
        out_ref[...] += jnp.sum(
            jax.lax.population_count(tile).astype(jnp.int32))[None, None]

    full = [slot, types, counts, offsets, pay]
    in_specs = [pl.BlockSpec(a.shape, _full_block) for a in full]
    if filt is not None:
        # the filter segment's matching container tile rides in a
        # (16, 128) block indexed by the step's position within the row
        full.append(filt.reshape(tpr * TILE_ROWS, TILE_LANES))
        in_specs.append(pl.BlockSpec((TILE_ROWS, TILE_LANES),
                                     lambda t, _tpr=tpr: (t % _tpr, 0)))
    out = pl.pallas_call(
        kernel,
        grid=(tiles,),
        in_specs=in_specs,
        out_specs=pl.BlockSpec((1, 1), lambda t, _tpr=tpr: (t // _tpr, 0)),
        out_shape=jax.ShapeDtypeStruct((rows, 1), jnp.int32),
        interpret=interpret_mode(),
    )(*full)
    return out[:, 0]
