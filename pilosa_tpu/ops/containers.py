"""Compressed container stream + device decode kernels — roaring's
array/bitmap/run container algebra lowered to the TPU (PAPER.md's stated
target; ROADMAP item 1).

A fragment's device mirror no longer has to be the dense
``uint32[rows, SHARD_WORDS]`` tensor: it can stay HBM-resident as a
*packed container stream* — per-container key/type/count/offset tables
plus one payload word buffer — and be decoded to dense tiles ON DEVICE
only at op time, inside the same XLA program that runs the query op.
Residency then costs compressed bytes (8 bytes per non-zero word for
uniformly sparse data, a few words per run for clustered data) instead of
the full dense footprint — the 100x dense blowup that made over-budget
working sets stream at ~1/340th of resident throughput (BENCH_r05_local
leg 6 vs 5).

Container forms (the word-granularity analog of roaring/roaring.go:64-69;
a container covers ``CONTAINER_WORDS`` = 2048 words = 2^16 bits):

* **array** (type 0): ``count`` (word-slot, word-value) entries — payload
  is ``count`` u32 slot indices followed by ``count`` u32 word values.
  Chosen for sparse containers (fewer than 1024 non-zero words, where
  2 words/entry beats the bitmap's 2048).  Decodes by scatter.
* **bitmap** (type 1): the container's 2048 words verbatim.  Chosen for
  dense containers; decodes by contiguous copy — compression-neutral by
  design, so dense corpora never regress.
* **run** (type 2): ``count`` bit-level [start, end) pairs (u32 each,
  within the container's 2^16-bit span).  Chosen when few runs cover the
  container's bits (Store'd full rows, clustered ingests); decodes via
  per-word range masks.

Decode is a pure jax function (``decode_block``) compiled
shape-polymorphically per (rows, container-count, payload, array-entry,
run-count) power-of-two bucket, so one executable serves every fragment
in a bucket; the mesh executor calls it INSIDE its vmapped shard_map
bodies so decoded dense tiles exist only as XLA temporaries for the
duration of one launch (the reusable dense workspace,
docs/memory-budget.md), never as persistent HBM residents.

Everything here runs through XLA (gather/scatter/mask ops the TPU VPU
executes at full lane width).  The hand-scheduled Pallas variant that
decodes containers HBM->VMEM tile-by-tile lives in ops/kernels.py behind
the same ``decode_block`` signature, selected by the
``container-kernels`` knob (``kernels.resolve()``); this module is the
``jnp`` backend — the kill switch — and the host-side pack/oracle layer
both backends share.
"""

from __future__ import annotations

import dataclasses
import functools

import numpy as np

from ..core import CONTAINER_WORDS, SHARD_WORDS, WORD_BITS

# Container type codes (device-side selectors; padding rows use -1).
TYPE_ARRAY = 0
TYPE_BITMAP = 1
TYPE_RUN = 2

# Array form wins while 2 payload words per entry undercut the bitmap's
# CONTAINER_WORDS; at >= CONTAINER_WORDS // 2 non-zero words the bitmap
# copy is smaller AND decodes cheaper.
ARRAY_WORDS_MAX = CONTAINER_WORDS // 2 - 1  # 1023

# Run containers are only chosen up to this many runs: device decode
# costs O(runs x CONTAINER_WORDS) per container (each run contributes a
# masked OR over the tile), so unbounded run counts would trade HBM for
# unbounded VPU work.  Clustered data this form exists for (Store'd
# rows, range ingests) sits at 1-16 runs.
RUN_MAX = 64

# Dense fragments beyond this many rows never compress: the decode
# scatter's flat int32 indices must stay below 2^31 (rows * SHARD_WORDS).
MAX_COMPRESSED_ROWS = (1 << 31) // SHARD_WORDS - 1


def pow2_bucket(n: int) -> int:
    """Smallest power of two >= n (0 stays 0) — the shape-bucketing unit
    that keeps one compiled decode executable serving many fragments."""
    return 0 if n <= 0 else 1 << (int(n) - 1).bit_length()


@dataclasses.dataclass(frozen=True)
class Packed:
    """One fragment's packed container stream (host arrays, built from
    the sparse word store without materialising the dense tensor)."""
    keys: np.ndarray      # int32[C] container ids (flat_word // 2048), sorted
    types: np.ndarray     # int32[C] TYPE_*
    counts: np.ndarray    # int32[C] entries (array) / words (bitmap) / runs
    offsets: np.ndarray   # int32[C] payload word offset
    payload: np.ndarray   # uint32[P]
    a_max: int            # largest array-container entry count
    r_max: int            # largest run-container run count

    @property
    def nbytes(self) -> int:
        return int(self.keys.nbytes + self.types.nbytes +
                   self.counts.nbytes + self.offsets.nbytes +
                   self.payload.nbytes)

    def type_histogram(self) -> dict[str, int]:
        t = self.types
        return {"array": int(np.count_nonzero(t == TYPE_ARRAY)),
                "bitmap": int(np.count_nonzero(t == TYPE_BITMAP)),
                "run": int(np.count_nonzero(t == TYPE_RUN))}


def _bit_runs(dense_words: np.ndarray) -> tuple[np.ndarray, np.ndarray]:
    """([starts], [ends]) of the set-bit runs of one container's 2048
    words, bit-level [start, end) within the 2^16-bit span."""
    bits = np.unpackbits(dense_words.view(np.uint8), bitorder="little")
    d = np.diff(bits.astype(np.int8))
    starts = np.nonzero(d == 1)[0] + 1
    ends = np.nonzero(d == -1)[0] + 1
    if bits[0]:
        starts = np.concatenate(([0], starts))
    if bits[-1]:
        ends = np.concatenate((ends, [bits.size]))
    return starts, ends


def estimate_packed_bytes(idx: np.ndarray) -> int:
    """Upper bound on pack_words' output size from the sparse indices
    alone (run containers only shrink it) — the cheap density-heuristic
    input that decides compressed vs dense residency without packing."""
    if idx.size == 0:
        return 0
    _, cnt = np.unique(idx // CONTAINER_WORDS, return_counts=True)
    payload_words = int(np.minimum(2 * cnt, CONTAINER_WORDS).sum())
    return 4 * payload_words + 16 * cnt.size


def pack_words(idx: np.ndarray, val: np.ndarray) -> Packed:
    """Pack a fragment's sparse word store (sorted flat indices + word
    values, storage/fragment.py) into a container stream, choosing the
    cheapest form per container (the optimize heuristic of
    roaring.go:2232, word-granular)."""
    cid = idx // CONTAINER_WORDS
    uniq, start, cnt = np.unique(cid, return_index=True,
                                 return_counts=True)
    C = uniq.size
    keys = uniq.astype(np.int32)
    types = np.empty(C, dtype=np.int32)
    counts = np.empty(C, dtype=np.int32)
    offsets = np.empty(C, dtype=np.int32)
    parts: list[np.ndarray] = []
    off = 0
    a_max = r_max = 0
    for i in range(C):
        a, n = int(start[i]), int(cnt[i])
        w_off = (idx[a: a + n] % CONTAINER_WORDS).astype(np.uint32)
        w_val = val[a: a + n]
        ctype = -1
        dense = None
        # bit-run candidacy prefilter: every gap between non-adjacent
        # stored words forces a separate bit run, so the word-run count
        # lower-bounds the bit-run count — skip the unpackbits scan when
        # it already exceeds RUN_MAX
        if int(np.count_nonzero(np.diff(w_off.astype(np.int64)) != 1)) \
                + 1 <= RUN_MAX:
            dense = np.zeros(CONTAINER_WORDS, dtype=np.uint32)
            dense[w_off] = w_val
            starts_b, ends_b = _bit_runs(dense)
            nr = starts_b.size
            if nr <= RUN_MAX and 2 * nr < min(2 * n, CONTAINER_WORDS):
                ctype = TYPE_RUN
                pl = np.empty(2 * nr, dtype=np.uint32)
                pl[0::2] = starts_b
                pl[1::2] = ends_b
                counts[i] = nr
                r_max = max(r_max, nr)
        if ctype < 0:
            if n <= ARRAY_WORDS_MAX:
                ctype = TYPE_ARRAY
                pl = np.concatenate([w_off, w_val])
                counts[i] = n
                a_max = max(a_max, n)
            else:
                ctype = TYPE_BITMAP
                if dense is None:
                    dense = np.zeros(CONTAINER_WORDS, dtype=np.uint32)
                    dense[w_off] = w_val
                pl = dense
                counts[i] = CONTAINER_WORDS
        types[i] = ctype
        offsets[i] = off
        parts.append(pl)
        off += pl.size
    payload = np.concatenate(parts) if parts \
        else np.zeros(0, dtype=np.uint32)
    return Packed(keys, types, counts, offsets, payload, a_max, r_max)


def unpack_packed(p: Packed, rows: int,
                  words: int = SHARD_WORDS) -> np.ndarray:
    """Host (numpy) decode oracle: the dense tensor a Packed stream
    represents — the differential reference for the device kernel."""
    out = np.zeros(rows * words, dtype=np.uint32)
    for i in range(p.keys.size):
        base = int(p.keys[i]) * CONTAINER_WORDS
        off = int(p.offsets[i])
        n = int(p.counts[i])
        t = int(p.types[i])
        if t == TYPE_BITMAP:
            out[base: base + CONTAINER_WORDS] = \
                p.payload[off: off + CONTAINER_WORDS]
        elif t == TYPE_ARRAY:
            slots = p.payload[off: off + n].astype(np.int64)
            out[base + slots] = p.payload[off + n: off + 2 * n]
        else:  # TYPE_RUN
            pairs = p.payload[off: off + 2 * n].astype(np.int64)
            for s, e in pairs.reshape(n, 2):
                w0, w1 = s // WORD_BITS, (e - 1) // WORD_BITS
                for w in range(w0, w1 + 1):
                    lo = max(s - w * WORD_BITS, 0)
                    hi = min(e - w * WORD_BITS, WORD_BITS)
                    m = ((1 << hi) - 1) & ~((1 << lo) - 1)
                    out[base + w] |= np.uint32(m & 0xFFFFFFFF)
    return out.reshape(rows, words)


# ---------------------------------------------------------------------------
# Device decode.  Pure jnp — callable inside vmapped shard_map bodies
# (the decode fuses into the op's executable) or standalone via
# upload_decode (Fragment.device()'s compressed upload path).
# ---------------------------------------------------------------------------

def decode_block(keys, types, counts, offsets, payload, *, rows: int,
                 words: int = SHARD_WORDS, a_bucket: int = 0,
                 r_bucket: int = 0):
    """Decode one fragment's packed container stream to dense
    ``uint32[rows, words]`` on device.

    ``keys/types/counts/offsets``: int32[C] (padded entries use key -1 /
    type -1 — they decode to nothing).  ``payload``: uint32[P].
    ``a_bucket``/``r_bucket``: static per-bucket maxima of array entries
    and run counts; 0 compiles that container form out entirely (a
    sparse-only corpus pays no run-mask code, a run-only corpus no
    scatter).

    Each container computes its 2048-word dense tile (bitmap: payload
    gather; array: scatter of (slot, value) entries; run: OR of per-word
    range masks), selected by type; tiles then scatter into the flat
    dense output at ``key * CONTAINER_WORDS``.  Tile indices are unique
    by construction (one container per key, unique slots within one), so
    plain scatter-set is exact.
    """
    import jax
    import jax.numpy as jnp

    total = rows * words
    if keys.shape[0] == 0 or rows == 0:
        return jnp.zeros((rows, words), dtype=jnp.uint32)
    cw = CONTAINER_WORDS
    j = jnp.arange(cw, dtype=jnp.int32)

    def tile(key, typ, cnt, off):
        bm = payload.at[off + j].get(mode="fill", fill_value=0)
        t = jnp.where(typ == TYPE_BITMAP, bm, jnp.uint32(0))
        if a_bucket:
            e = jnp.arange(a_bucket, dtype=jnp.int32)
            slots = payload.at[off + e].get(
                mode="fill", fill_value=0).astype(jnp.int32)
            vals = payload.at[off + cnt + e].get(mode="fill",
                                                 fill_value=0)
            slots = jnp.where((e < cnt) & (typ == TYPE_ARRAY), slots, cw)
            t = t | jnp.zeros(cw, dtype=jnp.uint32).at[slots].set(
                vals, mode="drop")
        if r_bucket:
            r = jnp.arange(r_bucket, dtype=jnp.int32)
            valid = (r < cnt) & (typ == TYPE_RUN)
            rs = jnp.where(valid, payload.at[off + 2 * r].get(
                mode="fill", fill_value=0).astype(jnp.int32), 0)
            re = jnp.where(valid, payload.at[off + 2 * r + 1].get(
                mode="fill", fill_value=0).astype(jnp.int32), 0)
            base = j * WORD_BITS                       # [cw]
            lo = jnp.clip(rs[:, None] - base[None, :], 0, WORD_BITS)
            hi = jnp.clip(re[:, None] - base[None, :], 0, WORD_BITS)
            full = jnp.uint32(0xFFFFFFFF)
            mhi = jnp.where(hi == 0, jnp.uint32(0),
                            full >> (WORD_BITS - hi).astype(jnp.uint32))
            mlo = jnp.where(lo == 0, jnp.uint32(0),
                            full >> (WORD_BITS - lo).astype(jnp.uint32))
            t = t | jax.lax.reduce(mhi & ~mlo, np.uint32(0),
                                   jax.lax.bitwise_or, dimensions=(0,))
        return t

    tiles = jax.vmap(tile)(keys, types, counts, offsets)    # [C, cw]
    flat_idx = jnp.where(keys[:, None] < 0, total,
                         keys[:, None] * cw + j[None, :])
    flat = jnp.zeros(total, dtype=jnp.uint32).at[flat_idx].set(
        tiles, mode="drop")
    return flat.reshape(rows, words)


def pad_packed(p: Packed) -> tuple[np.ndarray, ...]:
    """Pad a Packed stream's arrays to their pow2 buckets (padding
    containers use key/type -1) — the per-fragment staging unit the
    compiled decode buckets expect."""
    cb = pow2_bucket(p.keys.size)
    pb = pow2_bucket(p.payload.size)
    keys = np.full(cb, -1, dtype=np.int32)
    types = np.full(cb, -1, dtype=np.int32)
    counts = np.zeros(cb, dtype=np.int32)
    offsets = np.zeros(cb, dtype=np.int32)
    c = p.keys.size
    keys[:c] = p.keys
    types[:c] = p.types
    counts[:c] = p.counts
    offsets[:c] = p.offsets
    payload = np.zeros(pb, dtype=np.uint32)
    payload[: p.payload.size] = p.payload
    return keys, types, counts, offsets, payload


@functools.lru_cache(maxsize=None)
def _decode_jit(rows: int, words: int, a_bucket: int, r_bucket: int,
                backend: str = "jnp"):
    import jax

    def _traced(*a, **k):
        # runs only while jax traces — the compile registry's exact
        # per-bucket compile detector (docs/observability.md)
        from ..utils import devobs
        devobs.COMPILES.mark_traced()
        if backend == "pallas":
            from . import kernels
            return kernels.decode_block(*a, **k)
        return decode_block(*a, **k)

    return jax.jit(functools.partial(
        _traced, rows=rows, words=words, a_bucket=a_bucket,
        r_bucket=r_bucket))


def upload_decode(p: Packed, rows: int, target=None,
                  words: int = SHARD_WORDS):
    """Ship a packed stream to the device and decode it there to the
    dense mirror — Fragment.device()'s compressed upload path.  The
    transfer moves compressed bytes; the sparse->dense expansion happens
    on device instead of in host memory + on the wire.  Each (rows,
    buckets) decode bucket reports its compiles to the device compile
    registry like the mesh executables do."""
    import time as _time

    import jax

    from ..utils import devobs

    from . import kernels

    arrs = [jax.device_put(a, target) for a in pad_packed(p)]
    a_b, r_b = pow2_bucket(p.a_max), pow2_bucket(p.r_max)
    backend = kernels.resolve()
    fn = _decode_jit(rows, words, a_b, r_b, backend)
    reg = devobs.COMPILES
    reg.begin_call()
    t0 = _time.perf_counter()
    out = fn(*arrs)
    if reg.traced():
        # the container/payload pow2 buckets are intended shape
        # polymorphism (one jit, one specialization per bucket), so they
        # belong IN the signature — without them a second bucket of the
        # same jit would read as a false retrace alarm.  The backend tag
        # splits the pallas and jnp executables the same way (a knob
        # flip is a new signature, not a retrace).
        c_b = pow2_bucket(p.keys.size)
        p_b = pow2_bucket(p.payload.size)
        reg.note_call(
            f"decode:{rows}x{words}:c{c_b}:p{p_b}:a{a_b}:r{r_b}"
            f":{backend}",
            "decode", _time.perf_counter() - t0,
            devobs.fingerprint(arrs))
    if backend == "pallas":
        tiles = rows * max(words // CONTAINER_WORDS, 1)
        devobs.LEDGER.record(
            sig=f"decode:{rows}x{words}:c{pow2_bucket(p.keys.size)}"
                f":p{pow2_bucket(p.payload.size)}:a{a_b}:r{r_b}:pallas",
            kind="decode", shards=1, shards_padded=1, batch_rows=rows,
            batch_rows_padded=rows, queue_s=0.0,
            dispatch_s=_time.perf_counter() - t0, decode_bytes=0,
            compiled=reg.traced(), kernel_launches=1, kernel_tiles=tiles)
    return out
