"""Kernel layer: dense bitset + BSI ops (the roaring/ equivalent)."""

from . import bitset, bsi  # noqa: F401
