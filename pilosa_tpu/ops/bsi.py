"""Bit-sliced-index (BSI) kernels for integer fields.

Mirrors the reference layout exactly (fragment.go:90-93, field.go:1564-1647):
a BSI fragment tensor is ``uint32[2 + depth, SHARD_WORDS]`` with

* row 0 — existence ("not null") bit per column     (bsiExistsBit)
* row 1 — sign bit (set = negative)                 (bsiSignBit)
* row 2+i — bit i of the magnitude, LSB first       (bsiOffsetBit + i)

All comparison/aggregation scans are O(depth) vector passes, the same
complexity as the reference's per-slice roaring scans (fragment.go:1111 sum,
:1147 min, :1189 max, :1288-1538 rangeEQ/LT/GT/Between) but each pass is a
fused popcount/bit-op over the dense segment.

Depth is static at trace time (it is the fragment's row count minus 2), so the
per-bit loops below unroll into straight-line XLA — no dynamic control flow.

64-bit-safe aggregation: device popcounts are int32 (each <= 2^20); the 2^i
weighting that would overflow is done host-side in Python ints (see
``weighted_sum``), keeping the device path free of int64 emulation.
"""

from __future__ import annotations

import jax.numpy as jnp
import numpy as np

from .bitset import popcount_words, word_bit_np

EXISTS_ROW = 0
SIGN_ROW = 1
OFFSET_ROW = 2


def depth_of(bsi_frag) -> int:
    return bsi_frag.shape[0] - OFFSET_ROW


def not_null(bsi_frag, filter_seg=None):
    """Columns with a value set (fragment.go:1269 notNull)."""
    seg = bsi_frag[EXISTS_ROW]
    if filter_seg is not None:
        seg = seg & filter_seg
    return seg


def _magnitude_compare(bsi_frag, pred_mag: int, candidates):
    """Classic bit-sliced comparison of per-column magnitudes against a
    constant, MSB->LSB (the loop structure of fragment.go:1349 rangeLT /
    :1436 rangeGT collapsed into one pass).

    Returns (lt, eq, gt) segments partitioning ``candidates`` by
    magnitude <, ==, > ``pred_mag``.
    """
    depth = depth_of(bsi_frag)
    eq = candidates
    lt = jnp.zeros_like(candidates)
    gt = jnp.zeros_like(candidates)
    for i in range(depth - 1, -1, -1):
        bit = bsi_frag[OFFSET_ROW + i]
        if (pred_mag >> i) & 1:
            lt = lt | (eq & ~bit)
            eq = eq & bit
        else:
            gt = gt | (eq & bit)
            eq = eq & ~bit
    if pred_mag >> depth:
        # Predicate magnitude exceeds representable range: everything is less.
        lt = lt | eq | gt
        eq = jnp.zeros_like(eq)
        gt = jnp.zeros_like(gt)
    return lt, eq, gt


def range_op(bsi_frag, op: str, value: int, filter_seg=None):
    """Signed comparison of every column's value against ``value``.

    op in {"eq","neq","lt","le","gt","ge"} — the executor lowers PQL
    conditions (pql/ast.go Condition) and Between to these plus intersections
    (fragment.go:1273 rangeOp dispatch).
    """
    exists = not_null(bsi_frag, filter_seg)
    sign = bsi_frag[SIGN_ROW]
    pos = exists & ~sign
    neg = exists & sign
    mag = abs(int(value))

    if value > 0:
        plt, peq, pgt = _magnitude_compare(bsi_frag, mag, pos)
        # every negative value is < a positive predicate
        lt = neg | plt
        eq = peq
        gt = pgt
    elif value == 0:
        plt, peq, pgt = _magnitude_compare(bsi_frag, 0, pos)
        # magnitude-0 columns with the sign bit set still hold value 0
        _, neg_zero, _ = _magnitude_compare(bsi_frag, 0, neg)
        eq = peq | neg_zero
        lt = neg & ~neg_zero
        gt = pgt
    else:
        nlt, neq_, ngt = _magnitude_compare(bsi_frag, mag, neg)
        # for negatives: larger magnitude -> smaller value
        lt = ngt
        eq = neq_
        gt = pos | nlt

    if op == "eq":
        return eq
    if op == "neq":
        return exists & ~eq
    if op == "lt":
        return lt
    if op == "le":
        return lt | eq
    if op == "gt":
        return gt
    if op == "ge":
        return gt | eq
    raise ValueError(f"unknown range op {op!r}")


def range_between(bsi_frag, lo: int, hi: int, filter_seg=None):
    """lo <= value <= hi (fragment.go:1461 rangeBetween)."""
    ge = range_op(bsi_frag, "ge", lo, filter_seg)
    le = range_op(bsi_frag, "le", hi, filter_seg)
    return ge & le


# -- dynamic-predicate variants ---------------------------------------------
# The predicate magnitude arrives as a traced bit vector instead of a Python
# int, so every query against the same field shape shares ONE compiled
# executable (the plan cache is keyed by call-tree shape, SURVEY §7) — the
# per-slice branch on the predicate bit becomes a select.

MAG_BITS = 63  # max magnitude bits of an int64 predicate


def _magnitude_compare_dyn(bsi_frag, mag_bits, candidates):
    """_magnitude_compare with the predicate's bits as a traced int32[63]
    vector (LSB first).  Bits at positions >= depth mean the predicate
    exceeds the representable range: everything is less."""
    depth = depth_of(bsi_frag)
    eq = candidates
    lt = jnp.zeros_like(candidates)
    gt = jnp.zeros_like(candidates)
    for i in range(depth - 1, -1, -1):
        bit = bsi_frag[OFFSET_ROW + i]
        b = mag_bits[i] > 0
        new_lt = jnp.where(b, lt | (eq & ~bit), lt)
        new_gt = jnp.where(b, gt, gt | (eq & bit))
        eq = jnp.where(b, eq & bit, eq & ~bit)
        lt, gt = new_lt, new_gt
    if depth < MAG_BITS:
        ovf = jnp.sum(mag_bits[depth:MAG_BITS]) > 0
        lt = jnp.where(ovf, lt | eq | gt, lt)
        eq = jnp.where(ovf, jnp.zeros_like(eq), eq)
        gt = jnp.where(ovf, jnp.zeros_like(gt), gt)
    return lt, eq, gt


def range_op_dyn(bsi_frag, op: str, sign: str, mag_bits, filter_seg=None):
    """range_op with a dynamic predicate: ``sign`` ("pos"|"zero"|"neg") is
    structural (it selects the code path), ``mag_bits`` is the traced
    magnitude bit vector."""
    exists = not_null(bsi_frag, filter_seg)
    sgn = bsi_frag[SIGN_ROW]
    pos = exists & ~sgn
    neg = exists & sgn

    if sign == "pos":
        plt, peq, pgt = _magnitude_compare_dyn(bsi_frag, mag_bits, pos)
        lt = neg | plt
        eq = peq
        gt = pgt
    elif sign == "zero":
        # predicate 0 needs no dynamic bits (the zero compare is static)
        plt, peq, pgt = _magnitude_compare(bsi_frag, 0, pos)
        _, neg_zero, _ = _magnitude_compare(bsi_frag, 0, neg)
        eq = peq | neg_zero
        lt = neg & ~neg_zero
        gt = pgt
    else:
        nlt, neq_, ngt = _magnitude_compare_dyn(bsi_frag, mag_bits, neg)
        lt = ngt
        eq = neq_
        gt = pos | nlt

    if op == "eq":
        return eq
    if op == "neq":
        return exists & ~eq
    if op == "lt":
        return lt
    if op == "le":
        return lt | eq
    if op == "gt":
        return gt
    if op == "ge":
        return gt | eq
    raise ValueError(f"unknown range op {op!r}")


def range_between_dyn(bsi_frag, lo_sign, lo_bits, hi_sign, hi_bits,
                      filter_seg=None):
    ge = range_op_dyn(bsi_frag, "ge", lo_sign, lo_bits, filter_seg)
    le = range_op_dyn(bsi_frag, "le", hi_sign, hi_bits, filter_seg)
    return ge & le


def sum_counts(bsi_frag, filter_seg=None):
    """Device half of Sum (fragment.go:1111): per-bit-slice popcounts split by
    sign.  Returns int32[2, depth+1]: row 0 = positive-side counts (count of
    filter&exists&~sign per magnitude bit, last entry = total positive count),
    row 1 = same for the negative side.  Host reconstructs the exact int sum
    via ``weighted_sum``."""
    exists = not_null(bsi_frag, filter_seg)
    sign = bsi_frag[SIGN_ROW]
    pos = exists & ~sign
    neg = exists & sign
    depth = depth_of(bsi_frag)
    slices = bsi_frag[OFFSET_ROW:OFFSET_ROW + depth]
    pos_counts = jnp.sum(popcount_words(slices & pos[None, :]), axis=-1,
                         dtype=jnp.int32)
    neg_counts = jnp.sum(popcount_words(slices & neg[None, :]), axis=-1,
                         dtype=jnp.int32)
    pos_total = jnp.sum(popcount_words(pos), dtype=jnp.int32)
    neg_total = jnp.sum(popcount_words(neg), dtype=jnp.int32)
    return jnp.stack([
        jnp.concatenate([pos_counts, pos_total[None]]),
        jnp.concatenate([neg_counts, neg_total[None]]),
    ])


def weighted_sum(counts: np.ndarray):
    """Host half of Sum: exact Python-int reconstruction.

    Returns (sum, count) like fragment.go:1111 (sum of values, number of
    non-null columns in the filter)."""
    counts = np.asarray(counts)
    depth = counts.shape[1] - 1
    pos = sum(int(counts[0, i]) << i for i in range(depth))
    neg = sum(int(counts[1, i]) << i for i in range(depth))
    total = int(counts[0, depth]) + int(counts[1, depth])
    return pos - neg, total


def min_max_bits(bsi_frag, filter_seg=None, want_max=False):
    """Device half of Min/Max (fragment.go:1147 min, :1189 max).

    Narrows the candidate set bit-by-bit from the MSB.  Returns
    (value_bits int32[depth], negative int32, count int32):
    the chosen magnitude bit per slice, whether the extremum is negative, and
    how many columns attain it.  Host reconstructs the Python int.
    """
    exists = not_null(bsi_frag, filter_seg)
    sign = bsi_frag[SIGN_ROW]
    pos = exists & ~sign
    neg = exists & sign
    pos_count = jnp.sum(popcount_words(pos), dtype=jnp.int32)
    neg_count = jnp.sum(popcount_words(neg), dtype=jnp.int32)

    if want_max:
        # max: prefer positives; among positives maximise magnitude, among
        # negatives (only if no positives) minimise magnitude.
        use_neg = pos_count == 0
        cand = jnp.where(use_neg, neg, pos)
        prefer_set = ~use_neg  # maximise magnitude iff positive side
    else:
        use_neg = neg_count > 0
        cand = jnp.where(use_neg, neg, pos)
        prefer_set = use_neg  # minimise value = maximise magnitude if negative

    depth = depth_of(bsi_frag)
    bits = []
    for i in range(depth - 1, -1, -1):
        slice_i = bsi_frag[OFFSET_ROW + i]
        with_bit = cand & slice_i
        without_bit = cand & ~slice_i
        n_with = jnp.sum(popcount_words(with_bit), dtype=jnp.int32)
        n_without = jnp.sum(popcount_words(without_bit), dtype=jnp.int32)
        # prefer_set: take the bit=1 branch when non-empty; else bit=0 branch.
        take_set = jnp.where(prefer_set, n_with > 0, n_without == 0)
        cand = jnp.where(take_set, with_bit, without_bit)
        bits.append(take_set.astype(jnp.int32))
    bits.reverse()
    n_att = jnp.sum(popcount_words(cand), dtype=jnp.int32)
    return jnp.stack(bits), use_neg.astype(jnp.int32), n_att


def reconstruct_min_max(bits, negative, count):
    """Host half of Min/Max: (value, count) from min_max_bits output.

    When the candidate set is empty (no non-null columns under the filter)
    the device bit pattern is meaningless; this returns (0, 0) and callers
    must treat count == 0 as "no value" (the reference returns an empty
    ValCount, executor.go:2995)."""
    if int(count) == 0:
        return 0, 0
    bits = np.asarray(bits)
    mag = sum(int(bits[i]) << i for i in range(bits.shape[0]))
    val = -mag if int(negative) else mag
    return val, int(count)


def pack_values(cols: np.ndarray, values: np.ndarray, depth: int,
                words: int) -> np.ndarray:
    """Host-side construction of a BSI fragment tensor from (column, value)
    pairs — the import path's equivalent of fragment.go:977 setValueBase."""
    out = np.zeros((OFFSET_ROW + depth, words), dtype=np.uint32)
    cols = np.asarray(cols, dtype=np.int64)
    values = np.asarray(values, dtype=np.int64)
    if values.size and int(np.abs(values).max()) >> depth:
        raise ValueError(
            f"value magnitude {int(np.abs(values).max())} does not fit in "
            f"depth={depth} bits; widen the fragment (the storage layer "
            f"auto-sizes depth like the reference's setValueBase grows "
            f"bitDepth, fragment.go:977)"
        )
    w, bit = word_bit_np(cols)
    np.bitwise_or.at(out[EXISTS_ROW], w, bit)
    negmask = values < 0
    if negmask.any():
        np.bitwise_or.at(out[SIGN_ROW], w[negmask], bit[negmask])
    mags = np.abs(values)
    for i in range(depth):
        sel = (mags >> i) & 1 > 0
        if sel.any():
            np.bitwise_or.at(out[OFFSET_ROW + i], w[sel], bit[sel])
    return out


def unpack_values(bsi_frag: np.ndarray):
    """Host-side extraction: (cols int64[], values int64[]) for set columns."""
    from .bitset import unpack_columns

    bsi_frag = np.asarray(bsi_frag)
    cols = unpack_columns(bsi_frag[EXISTS_ROW])
    if cols.size == 0:
        return cols, np.zeros(0, dtype=np.int64)
    depth = bsi_frag.shape[0] - OFFSET_ROW
    w, bit = word_bit_np(cols)
    vals = np.zeros(cols.shape, dtype=np.int64)
    for i in range(depth):
        vals |= ((bsi_frag[OFFSET_ROW + i, w] & bit) > 0).astype(np.int64) << i
    sign = (bsi_frag[SIGN_ROW, w] & bit) > 0
    vals[sign] = -vals[sign]
    return cols, vals
