"""Index: a top-level namespace of fields sharing a column space
(index.go:37-69)."""

from __future__ import annotations

import json
import os

import numpy as np

from ..core import EXISTENCE_FIELD_NAME, VIEW_STANDARD
from .attrs import AttrStore
from .field import Field, FieldOptions, FIELD_TYPE_SET, CACHE_TYPE_NONE
from ..utils.locks import make_rlock


class IndexError_(ValueError):
    pass


class Index:
    def __init__(self, path: str | None, name: str,
                 keys: bool = False, track_existence: bool = True,
                 max_op_n: int | None = None, create: bool = False,
                 row_id_cap: int | None = None):
        """``create=True`` for brand-new indexes (materialises the _exists
        field immediately); when reopening from disk, open() reads .meta
        first so a trackExistence=False index is not polluted with a
        spurious _exists field."""
        self.path = path
        self.name = name
        self.keys = keys
        self.track_existence = track_existence
        self.max_op_n = max_op_n
        self.row_id_cap = row_id_cap
        self.fields: dict[str, Field] = {}
        self.column_attrs = AttrStore(
            None if path is None else os.path.join(path, ".column_attrs"))
        # (path, index, field|None) -> store; None = local file-backed
        # (cluster replicas swap in a coordinator-routed store)
        self.translate_factory = None
        self._translate_store = None
        self._lock = make_rlock("index")

        if create and track_existence:
            self._open_existence_field()

    # -- persistence -------------------------------------------------------

    def _meta_path(self) -> str:
        return os.path.join(self.path, ".meta")

    def save_meta(self):
        if self.path is None:
            return
        os.makedirs(self.path, exist_ok=True)
        with open(self._meta_path(), "w") as f:
            json.dump({"keys": self.keys,
                       "trackExistence": self.track_existence}, f)

    def open(self):
        if self.path is None:
            return
        if os.path.exists(self._meta_path()):
            with open(self._meta_path()) as f:
                meta = json.load(f)
            self.keys = meta.get("keys", False)
            self.track_existence = meta.get("trackExistence", True)
        fields_dir = os.path.join(self.path, "fields")
        if os.path.isdir(fields_dir):
            for fname in os.listdir(fields_dir):
                f = self._make_field(fname)
                f.open()
                self.fields[fname] = f
        if self.track_existence:
            self._open_existence_field()

    def close(self):
        with self._lock:
            for f in self.fields.values():
                f.close()
            if self._translate_store is not None:
                self._translate_store.close()
                self._translate_store = None

    # -- fields ------------------------------------------------------------

    def _field_path(self, name: str) -> str | None:
        if self.path is None:
            return None
        return os.path.join(self.path, "fields", name)

    def _make_field(self, name: str,
                    options: FieldOptions | None = None) -> Field:
        f = Field(self._field_path(name), self.name, name, options,
                  max_op_n=self.max_op_n, row_id_cap=self.row_id_cap)
        f.translate_factory = self.translate_factory
        return f

    def translate_store(self):
        """Column-key store for this index (index.go: per-index
        TranslateStore; keys live in <index>/.keys)."""
        with self._lock:
            if self._translate_store is None:
                from .translate import TranslateStore
                path = None if self.path is None \
                    else os.path.join(self.path, ".keys")
                if self.translate_factory is not None:
                    self._translate_store = self.translate_factory(
                        path, self.name, None)
                else:
                    self._translate_store = TranslateStore(path)
            return self._translate_store

    def _open_existence_field(self):
        """(index.go:215 openExistenceField): internal `_exists` field,
        no cache."""
        if EXISTENCE_FIELD_NAME not in self.fields:
            opts = FieldOptions(type=FIELD_TYPE_SET,
                                cache_type=CACHE_TYPE_NONE, cache_size=0)
            f = self._make_field(EXISTENCE_FIELD_NAME, opts)
            f.save_meta()
            self.fields[EXISTENCE_FIELD_NAME] = f

    def existence_field(self) -> Field | None:
        return self.fields.get(EXISTENCE_FIELD_NAME) \
            if self.track_existence else None

    def field(self, name: str) -> Field | None:
        return self.fields.get(name)

    def create_field(self, name: str,
                     options: FieldOptions | None = None) -> Field:
        with self._lock:
            if name in self.fields:
                raise FileExistsError(f"field already exists: {name}")
            if name != EXISTENCE_FIELD_NAME:
                from ..core import validate_name
                try:
                    validate_name(name, "field name")
                except ValueError as e:
                    raise IndexError_(str(e))
            f = self._make_field(name, options)
            f.save_meta()
            self.fields[name] = f
            from ..core import bump_schema_epoch
            bump_schema_epoch()
            return f

    def create_field_if_not_exists(self, name: str,
                                   options: FieldOptions | None = None):
        with self._lock:
            if name in self.fields:
                return self.fields[name]
            return self.create_field(name, options)

    def delete_field(self, name: str):
        with self._lock:
            f = self.fields.pop(name, None)
            if f is None:
                raise IndexError_(f"field not found: {name}")
            from ..core import bump_schema_epoch
            bump_schema_epoch()
            f.close()
            if f.path is not None and os.path.isdir(f.path):
                import shutil
                shutil.rmtree(f.path)

    def public_fields(self) -> list[Field]:
        return [f for n, f in sorted(self.fields.items())
                if n != EXISTENCE_FIELD_NAME]

    # -- shards ------------------------------------------------------------

    def available_shards(self) -> set[int]:
        """Union over all fields (index.go:292 AvailableShards); empty
        indexes still answer shard 0 queries."""
        out: set[int] = set()
        for f in self.fields.values():
            out |= f.available_shards()
        return out or {0}

    # -- column existence --------------------------------------------------

    def add_existence(self, cols: np.ndarray):
        ef = self.existence_field()
        if ef is not None and len(cols):
            cols = np.asarray(cols, dtype=np.int64)
            ef.import_bits(np.zeros(cols.size, dtype=np.int64), cols)

    def existence_row(self) -> dict[int, np.ndarray]:
        ef = self.existence_field()
        if ef is None:
            return {}
        return ef.row(0, VIEW_STANDARD)
