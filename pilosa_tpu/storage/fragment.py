"""Fragment: one (field, view, shard) bitmap, host-authoritative with a
device-resident HBM mirror.

The reference's fragment (fragment.go:100-159) is an mmap'd roaring file with
an append-only op log and background snapshot rewrites.  Here the
authoritative copy is a dense ``uint32[n_rows, SHARD_WORDS]`` numpy array on
the host; mutations (set/clear/setValue/import) update it immediately and
append to a write-ahead op log.  The device mirror is uploaded lazily on first
query after a write burst and stays resident in HBM (the mmap replacement) so
repeated queries never re-cross PCIe/DCN.  Snapshots rewrite the on-disk file
and truncate the WAL after ``max_op_n`` ops (fragment.go:84 MaxOpN, :2311
snapshot).

Row capacity grows by doubling so device executable shapes change rarely
(each distinct row count compiles its own XLA plan).
"""

from __future__ import annotations

import hashlib
import os
import struct
import threading

import numpy as np

from ..core import (
    DEFAULT_FRAGMENT_MAX_OP_N,
    DEFAULT_MAX_ROW_ID,
    HASH_BLOCK_SIZE,
    SHARD_WIDTH,
    SHARD_WORDS,
)
from ..ops import bitset, bsi

# On-disk snapshot format: magic, n_rows, words, nnz then nnz LE
# (flat_word_index u32, word_value u32) pairs — sparse, so a 20k-bit fragment
# snapshot is ~tens of KB instead of a dense n_rows*128KB image.
_MAGIC = b"PTPUFRG2"
_HEADER = struct.Struct("<8sIIQ")

# WAL record: op(u8) row(i64) col(i64)  (roaring.go:4359 opType add/remove;
# batch ops are written as runs of single records).
_OP = struct.Struct("<Bqq")
_OP_SET, _OP_CLEAR = 0, 1

_MIN_ROWS = 4


class Fragment:
    """One (index, field, view, shard) bitmap."""

    def __init__(self, path: str | None, index: str, field: str, view: str,
                 shard: int, max_op_n: int = DEFAULT_FRAGMENT_MAX_OP_N,
                 row_id_cap: int | None = None):
        self.path = path  # None = purely in-memory (tests)
        self.index = index
        self.field = field
        self.view = view
        self.shard = shard
        self.max_op_n = max_op_n
        # Guard against hostile row ids forcing terabyte-scale dense
        # allocations (core.DEFAULT_MAX_ROW_ID); threaded per-instance from
        # the server config (Holder -> Index -> Field -> View) so multiple
        # servers in one process keep independent caps.
        if row_id_cap is not None:
            self.row_id_cap = row_id_cap

        self.words = np.zeros((0, SHARD_WORDS), dtype=np.uint32)
        self._mirrors = {}        # device -> cached jax.Array mirror
        self._device_dirty = True
        self._op_n = 0
        self._dirty_data = False  # mutated since last snapshot?
        self._wal_file = None
        self._lock = threading.RLock()

        if path is not None:
            self._open_storage()

    # -- lifecycle ---------------------------------------------------------

    def _snapshot_path(self) -> str:
        return self.path

    def _wal_path(self) -> str:
        return self.path + ".wal"

    def _open_storage(self):
        """Load snapshot + replay WAL (fragment.go:311 openStorage)."""
        os.makedirs(os.path.dirname(self.path), exist_ok=True)
        if os.path.exists(self.path):
            with open(self.path, "rb") as f:
                magic, n_rows, words, nnz = _HEADER.unpack(
                    f.read(_HEADER.size))
                if magic != _MAGIC:
                    raise ValueError(f"bad fragment file magic in {self.path}")
                pairs = np.fromfile(f, dtype="<u4", count=2 * nnz)
            if words != SHARD_WORDS:
                raise ValueError(
                    f"fragment file {self.path} has {words} words/row, "
                    f"expected {SHARD_WORDS}")
            # Row capacity doubles, so a legitimately-written snapshot never
            # declares more than 2*(cap+1) rows; beyond that the header is
            # corrupt or was written under a larger max_row_id config — an
            # explicit error either way, instead of a terabyte np.zeros.
            if n_rows > 2 * (self.row_id_cap + 1):
                raise ValueError(
                    f"fragment file {self.path} declares {n_rows} rows, "
                    f"above the configured max_row_id {self.row_id_cap}; "
                    f"raise max_row_id if this data was written with a "
                    f"larger cap")
            self.words = np.zeros((n_rows, words), dtype=np.uint32)
            if nnz:
                flat = self.words.reshape(-1)
                flat[pairs[0::2].astype(np.int64)] = pairs[1::2]
        if os.path.exists(self._wal_path()):
            with open(self._wal_path(), "rb") as f:
                buf = f.read()
            for off in range(0, len(buf) - len(buf) % _OP.size, _OP.size):
                op, row, col = _OP.unpack_from(buf, off)
                try:
                    if op == _OP_SET:
                        self._set_bit_mem(row, col)
                    else:
                        self._clear_bit_mem(row, col)
                except ValueError as e:
                    raise ValueError(
                        f"replaying WAL {self._wal_path()}: {e}; raise "
                        f"max_row_id if this data was written with a larger "
                        f"cap") from e
            self._op_n = len(buf) // _OP.size
        self._wal_file = open(self._wal_path(), "ab", buffering=0)

    def close(self):
        with self._lock:
            if self._wal_file is not None:
                if self._dirty_data or self._op_n:
                    self.snapshot()
                self._wal_file.close()
                self._wal_file = None
            self._mirrors.clear()

    def snapshot(self):
        """Rewrite the snapshot file and truncate the WAL
        (fragment.go:2311 snapshot)."""
        with self._lock:
            if self.path is None:
                self._op_n = 0
                return
            tmp = self.path + ".snapshotting"
            with open(tmp, "wb") as f:
                n_rows, words = self.words.shape
                flat = self.words.reshape(-1)
                idx = np.nonzero(flat)[0]
                if idx.size and int(idx[-1]) >> 32:
                    raise ValueError("fragment too large for u32 flat index")
                f.write(_HEADER.pack(_MAGIC, n_rows, words, idx.size))
                pairs = np.empty(2 * idx.size, dtype="<u4")
                pairs[0::2] = idx.astype(np.uint32)
                pairs[1::2] = flat[idx]
                pairs.tofile(f)
            os.replace(tmp, self.path)
            self._dirty_data = False
            if self._wal_file is not None:
                self._wal_file.close()
            self._wal_file = open(self._wal_path(), "wb", buffering=0)
            self._op_n = 0

    # -- geometry ----------------------------------------------------------

    @property
    def n_rows(self) -> int:
        return self.words.shape[0]

    def max_row_id(self) -> int:
        """Highest row with any bit set (fragment.go maxRow)."""
        nz = np.nonzero(self.words.any(axis=1))[0]
        return int(nz[-1]) if nz.size else 0

    # Default cap when none is threaded in (class fallback keeps in-memory
    # test fragments working without plumbing).
    row_id_cap = DEFAULT_MAX_ROW_ID

    def _ensure_rows(self, row_id: int):
        if row_id < self.n_rows:
            return
        if row_id > self.row_id_cap:
            raise ValueError(
                f"row id {row_id} exceeds the configured maximum "
                f"{self.row_id_cap} (max_row_id)")
        new_rows = max(_MIN_ROWS, self.n_rows)
        while new_rows <= row_id:
            new_rows *= 2
        grown = np.zeros((new_rows, SHARD_WORDS), dtype=np.uint32)
        grown[: self.n_rows] = self.words
        self.words = grown
        self._mirrors.clear()
        self._device_dirty = True

    # -- mutation ----------------------------------------------------------

    def _set_bit_mem(self, row: int, col: int) -> bool:
        self._ensure_rows(row)
        w, bit = bitset.word_bit_np(col)
        changed = not (self.words[row, w] & bit)
        if changed:
            self.words[row, w] |= bit
            self._device_dirty = True
            self._dirty_data = True
        return changed

    def _clear_bit_mem(self, row: int, col: int) -> bool:
        if row >= self.n_rows:
            return False
        w, bit = bitset.word_bit_np(col)
        changed = bool(self.words[row, w] & bit)
        if changed:
            self.words[row, w] &= ~bit
            self._device_dirty = True
            self._dirty_data = True
        return changed

    def _log_op(self, op: int, row: int, col: int):
        if self._wal_file is not None:
            self._wal_file.write(_OP.pack(op, row, col))
        self._op_n += 1
        if self._op_n >= self.max_op_n:
            if self._wal_file is not None:
                self._wal_file.flush()
            self.snapshot()

    def set_bit(self, row: int, col: int) -> bool:
        """Set one bit; col is shard-local.  Returns True if changed
        (fragment.go:647 setBit)."""
        with self._lock:
            changed = self._set_bit_mem(row, col)
            if changed:
                self._log_op(_OP_SET, row, col)
            return changed

    def clear_bit(self, row: int, col: int) -> bool:
        with self._lock:
            changed = self._clear_bit_mem(row, col)
            if changed:
                self._log_op(_OP_CLEAR, row, col)
            return changed

    def bulk_import(self, rows: np.ndarray, cols: np.ndarray,
                    clear: bool = False) -> int:
        """Batched import of shard-local (row, col) bits
        (fragment.go:1997 bulkImport / 2053 importPositions).  Returns the
        number of changed bits."""
        rows = np.asarray(rows, dtype=np.int64)
        cols = np.asarray(cols, dtype=np.int64)
        if rows.size == 0:
            return 0
        with self._lock:
            self._ensure_rows(int(rows.max()))
            w, bit = bitset.word_bit_np(cols)
            # Only touched rows participate; avoids streaming the whole
            # fragment for small imports.
            urows = np.unique(rows)
            delta = np.zeros((urows.size, self.words.shape[1]),
                             dtype=np.uint32)
            rpos = np.searchsorted(urows, rows)
            np.bitwise_or.at(delta, (rpos, w), bit)
            target = self.words[urows]
            if clear:
                changed_words = target & delta
                self.words[urows] = target & ~delta
            else:
                changed_words = ~target & delta
                self.words[urows] = target | delta
            n_changed = int(np.bitwise_count(changed_words).sum())
            if n_changed:
                self._device_dirty = True
                self._dirty_data = True
                op = _OP_CLEAR if clear else _OP_SET
                if self._wal_file is not None:
                    recs = b"".join(
                        _OP.pack(op, int(r), int(c))
                        for r, c in zip(rows, cols))
                    self._wal_file.write(recs)
                self._op_n += rows.size
                if self._op_n >= self.max_op_n:
                    self.snapshot()
            return n_changed

    def mutex_import(self, rows: np.ndarray, cols: np.ndarray) -> int:
        """Batched import with mutex semantics: at most one row per column,
        last write in the batch wins (fragment.go:2106 bulkImportMutex).
        Returns changed-bit count."""
        rows = np.asarray(rows, dtype=np.int64)
        cols = np.asarray(cols, dtype=np.int64)
        if rows.size == 0:
            return 0
        # keep the last occurrence of each column
        last = {}
        for i in range(rows.size):
            last[int(cols[i])] = int(rows[i])
        ucols = np.fromiter(last.keys(), dtype=np.int64, count=len(last))
        urow = np.fromiter(last.values(), dtype=np.int64, count=len(last))
        with self._lock:
            self._ensure_rows(int(urow.max()))
            w, bit = bitset.word_bit_np(ucols)
            colmask = np.zeros(self.words.shape[1], dtype=np.uint32)
            np.bitwise_or.at(colmask, w, bit)
            before = int(np.bitwise_count(self.words & colmask).sum())
            pre_winner = int(np.count_nonzero(self.words[urow, w] & bit))
            # clear every row's bits at the target columns, then set winners
            self.words &= ~colmask
            np.bitwise_or.at(self.words, (urow, w), bit)
            # changed = bits cleared off losers + winner bits newly set
            n_changed = (before - pre_winner) + (ucols.size - pre_winner)
            self._device_dirty = True
            self._dirty_data = True
            if self._wal_file is not None:
                self.snapshot()
            return max(n_changed, 0)

    def set_row(self, row: int, seg: np.ndarray | None):
        """Replace an entire row's bits (Store/SetRow, fragment.go setRow)."""
        with self._lock:
            self._ensure_rows(row)
            if seg is None:
                self.words[row] = 0
            else:
                self.words[row] = np.asarray(seg, dtype=np.uint32)
            self._device_dirty = True
            self._dirty_data = True
            self.snapshot()  # row stores bypass the op log

    # -- BSI mutation (int fields) ----------------------------------------

    def bit_depth(self) -> int:
        return max(0, self.n_rows - bsi.OFFSET_ROW)

    def set_value(self, col: int, bit_depth: int, value: int) -> bool:
        """Set a column's integer value (fragment.go:977 setValueBase).
        Grows depth rows as needed; clears stale magnitude bits.  Each
        changed bit is WAL-logged so values survive a crash like set bits
        do."""
        with self._lock:
            self._ensure_rows(bsi.OFFSET_ROW + bit_depth - 1)
            mag = abs(value)
            ops: list[tuple[int, int]] = []
            for i in range(bit_depth):
                row = bsi.OFFSET_ROW + i
                want = (mag >> i) & 1
                ops.append((_OP_SET if want else _OP_CLEAR, row))
            ops.append((_OP_SET if value < 0 else _OP_CLEAR, bsi.SIGN_ROW))
            ops.append((_OP_SET, bsi.EXISTS_ROW))
            changed = False
            for op, row in ops:
                if op == _OP_SET:
                    if self._set_bit_mem(row, col):
                        self._log_op(_OP_SET, row, col)
                        changed = True
                else:
                    if self._clear_bit_mem(row, col):
                        self._log_op(_OP_CLEAR, row, col)
                        changed = True
            return changed

    def import_values(self, cols: np.ndarray, values: np.ndarray,
                      bit_depth: int) -> None:
        """Batched setValue (fragment.go:2205 importValue)."""
        cols = np.asarray(cols, dtype=np.int64)
        values = np.asarray(values, dtype=np.int64)
        with self._lock:
            self._ensure_rows(bsi.OFFSET_ROW + bit_depth - 1)
            w, bit = bitset.word_bit_np(cols)
            # clear all target columns' bits first (stale values)
            mask = np.zeros(SHARD_WORDS, dtype=np.uint32)
            np.bitwise_or.at(mask, w, bit)
            self.words[: bsi.OFFSET_ROW + bit_depth] &= ~mask
            packed = bsi.pack_values(cols, values, depth=bit_depth,
                                     words=SHARD_WORDS)
            self.words[: packed.shape[0]] |= packed
            self._device_dirty = True
            self._dirty_data = True
            self.snapshot()

    def clear_values(self, cols: np.ndarray) -> None:
        """Remove columns' values entirely (exists+sign+magnitude cleared) —
        the clear half of importValue (fragment.go:2205 importValue with
        clear)."""
        cols = np.asarray(cols, dtype=np.int64)
        if cols.size == 0 or self.n_rows == 0:
            return
        with self._lock:
            w, bit = bitset.word_bit_np(cols)
            mask = np.zeros(SHARD_WORDS, dtype=np.uint32)
            np.bitwise_or.at(mask, w, bit)
            self.words &= ~mask
            self._device_dirty = True
            self._dirty_data = True
            self.snapshot()

    # -- reads -------------------------------------------------------------

    def row(self, row_id: int) -> np.ndarray:
        """Host copy of one row's segment (fragment.go:602 row)."""
        with self._lock:
            if row_id >= self.n_rows:
                return np.zeros(SHARD_WORDS, dtype=np.uint32)
            return self.words[row_id].copy()

    def row_columns(self, row_id: int) -> np.ndarray:
        return bitset.unpack_columns(self.row(row_id))

    def device(self, target=None):
        """The HBM-resident mirror (uploads if stale).  This is the query hot
        path's input — equivalent to the mmap'd storage the reference queries
        against (fragment.go:311).

        ``target``: an optional jax Device to place the mirror on.  Mesh
        executors pass a device from their own mesh when the mesh's platform
        differs from the default backend (e.g. a virtual CPU mesh under a
        TPU default); mirrors are cached per target.  ``None`` stays
        UNCOMMITTED (and is its own cache key) so results can combine freely
        with mesh-sharded arrays — callers on the default platform should
        pass None to share this entry rather than duplicating the upload
        under a concrete-device key."""
        import jax

        with self._lock:
            if self._device_dirty:
                self._mirrors.clear()
                self._device_dirty = False
            mirror = self._mirrors.get(target)
            if mirror is None:
                mirror = jax.device_put(self.words, target)
                self._mirrors[target] = mirror
            return mirror

    # -- anti-entropy block checksums (fragment.go:1778 Blocks) ------------

    def blocks(self) -> dict[int, bytes]:
        """Checksum per HASH_BLOCK_SIZE-row block of non-empty rows."""
        out = {}
        with self._lock:
            for start in range(0, self.n_rows, HASH_BLOCK_SIZE):
                blk = self.words[start:start + HASH_BLOCK_SIZE]
                if not blk.any():
                    continue
                if blk.shape[0] < HASH_BLOCK_SIZE:
                    # pad so the digest depends only on logical content, not
                    # on the doubling-based row capacity
                    pad = np.zeros(
                        (HASH_BLOCK_SIZE - blk.shape[0], blk.shape[1]),
                        dtype=np.uint32)
                    blk = np.concatenate([blk, pad])
                out[start // HASH_BLOCK_SIZE] = hashlib.blake2b(
                    blk.tobytes(), digest_size=16).digest()
        return out

    def block_data(self, block_id: int) -> tuple[np.ndarray, np.ndarray]:
        """(rows, cols) pairs of one block (fragment.go:1859 blockData)."""
        start = block_id * HASH_BLOCK_SIZE
        with self._lock:
            blk = self.words[start:start + HASH_BLOCK_SIZE]
            r, c = bitset.unpack_fragment(blk)
            return r + start, c
