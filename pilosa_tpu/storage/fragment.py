"""Fragment: one (field, view, shard) bitmap, host-sparse with dense
device mirrors under an HBM budget.

The reference's fragment (fragment.go:100-159) is an mmap'd roaring file
with an append-only op log and background snapshot rewrites.  Here the
authoritative copy is a SPARSE word store: sorted flat indices
(``row * SHARD_WORDS + word``) with their non-zero uint32 word values —
the in-memory form of the snapshot format itself.  Host memory is
proportional to set bits (a 954-shard index with a few bits per row loads
in megabytes, where a dense ``[rows, 32768]`` tensor per fragment would
need terabytes), replacing roaring's array/run containers as the sparsity
mechanism (roaring/roaring.go:64-69).

The device mirror takes one of two forms, chosen per fragment by a
density heuristic (``device_form``).  Dense fragments materialise the
full ``uint32[cap_rows, SHARD_WORDS]`` tensor — dense tiles are what the
TPU bit-kernels operate on (see core.py).  Sparse fragments (under a
configured device budget) stay HBM-resident in COMPRESSED form instead: a
packed array/bitmap/run container stream (ops/containers.py, the
word-granularity analog of roaring/roaring.go:64-69) that the mesh
executor decodes to dense tiles ON DEVICE at op time, inside the query's
own XLA program.  Residency then costs compressed bytes — ~8 bytes per
non-zero word, a few words per run — so over-budget dense working sets
become resident compressed ones (docs/memory-budget.md).  The heuristic
falls back to dense where density warrants (``compress-max-density``), so
dense corpora never pay decode cost or the ~1x "compression" of
all-bitmap streams.  Mirrors and packed streams register with a
DeviceBudget: under a configured limit the least-recently-used entries
are evicted and transparently re-staged on next use (the HBM analog of
the reference's mmap paging + syswrap map caps, syswrap/mmap.go:46).

Mutations update the sparse store immediately and append to a write-ahead
op log; snapshots rewrite the on-disk file and truncate the WAL after
``max_op_n`` ops (fragment.go:84 MaxOpN, :2311 snapshot).  Row capacity
grows by doubling so device executable shapes change rarely.
"""

from __future__ import annotations

import hashlib
import itertools
import json
import os
import struct

import numpy as np

from ..core import (
    DEFAULT_FRAGMENT_MAX_OP_N,
    DEFAULT_MAX_ROW_ID,
    HASH_BLOCK_SIZE,
    SHARD_WIDTH,
    SHARD_WORDS,
)
from ..ops import bitset, bsi
from ..utils import events
from ..utils.durable import checksum, durable_replace, fsync_dir, fsync_file
from ..utils.faults import FAULTS
from ..utils.locks import make_lock, make_rlock
from . import membudget as _membudget
from .membudget import DEFAULT_BUDGET, HOST_STAGE_BUDGET, INGEST_DELTA_BUDGET
from .roaring_io import SnapshotFormatError, pack_snapshot, unpack_snapshot

# On-disk snapshot format: see storage/roaring_io.py (pack_snapshot /
# unpack_snapshot) — v4 (PTPUFRG4) carries header + payload CRCs; the
# unchecksummed v2/v3 predecessors load leniently.

# WAL record: op(u8) row(i64) col(i64)  (roaring.go:4359 opType add/remove;
# batch ops are written as runs of single records).
_OP = struct.Struct("<Bqq")
_OP_SET, _OP_CLEAR = 0, 1
# numpy view of the same record layout for vectorized batch serialization
# (a 1M-bit import must not do 1M struct.packs in a Python loop)
_OP_DTYPE = np.dtype([("op", "u1"), ("row", "<i8"), ("col", "<i8")])
assert _OP_DTYPE.itemsize == _OP.size

# CRC-framed WAL (docs/robustness.md "Durability & recovery"): the file
# opens with an 8-byte magic, then frames of <u32 payload_len, u32
# payload_crc> + payload, where payload is 1..N op records appended in ONE
# write() call (a kill -9 can therefore only tear a frame at the OS/crash
# level, never interleave them).  Files without the magic are legacy bare
# record streams and keep appending in that format until the next
# snapshot truncation upgrades them.
_WAL_MAGIC = b"PTPUWAL1"
_WAL_FRAME = struct.Struct("<II")
_WAL_MAX_FRAME = 1 << 30

# Process-wide storage knobs, set from the server config (the same
# most-recent-Server-wins convention as membudget.DEFAULT_BUDGET and
# cache.rank.RANK_REBUILD_ROWS).  WAL_CRC: frame new WAL files with
# length+CRC records (off = write the legacy bare stream, for
# differential testing and old-reader compatibility).
# QUARANTINE_ON_CORRUPTION: a corrupt snapshot/WAL quarantines the
# fragment (serve-empty + refuse writes + heal from a replica) instead of
# raising out of open().
WAL_CRC = True
QUARANTINE_ON_CORRUPTION = True

# Compressed-resident device mirrors (docs/memory-budget.md "Compressed
# residency"): under a configured device budget, fragments whose packed
# container stream is small enough stay HBM-resident compressed and are
# decoded to dense tiles on device at op time.  COMPRESSED_RESIDENT
# disables the path wholesale; COMPRESS_MAX_DENSITY is the fallback
# knob — a fragment compresses only when its estimated packed bytes are
# at most this fraction of its dense footprint (dense corpora pack into
# all-bitmap streams at ~1.01x dense and must stay on the dense path).
# Process-wide, set from the server config like WAL_CRC above.
COMPRESSED_RESIDENT = True
COMPRESS_MAX_DENSITY = 0.5

# Storage-event counters (surfaced at /debug/vars and /metrics via
# Server.update_storage_gauges): process-wide, like the knobs above.
_EVENTS = {"quarantine": 0, "torn_tail_recovered": 0, "repair": 0,
           "attr_corrupt": 0}
_EVENTS_LOCK = make_lock("fragment-events")

# True once ANY fragment in this process has entered quarantine
# (including sidecar re-detection, which doesn't count an event).
# Holder.quarantined_fragments fast-outs on this so the per-query /
# per-probe / per-scrape degraded checks stay O(1) in the healthy case
# instead of scanning every fragment of every index.  Never reset:
# after a quarantine the full scan is the price of accuracy.
QUARANTINE_SEEN = False


def _bump(event: str, n: int = 1):
    with _EVENTS_LOCK:
        _EVENTS[event] += n


def storage_events() -> dict:
    """Snapshot of the process-wide storage event counters."""
    with _EVENTS_LOCK:
        return dict(_EVENTS)


class FragmentQuarantinedError(RuntimeError):
    """Write refused: this fragment is quarantined after on-disk
    corruption.  RETRYABLE — replica-driven repair (anti-entropy /
    repair-interval) restores the fragment from a healthy peer, after
    which writes succeed again; the HTTP layer maps this to 503 +
    Retry-After."""


_MIN_ROWS = 4


def _pairs_to_words(rows: np.ndarray, cols: np.ndarray):
    """Aggregate (row, col) bit pairs into unique sorted flat word indices
    + OR-combined word values."""
    flat = rows.astype(np.int64) * SHARD_WORDS + (cols >> 5)
    bit = (np.uint32(1) << (cols & 31).astype(np.uint32))
    uniq, inv = np.unique(flat, return_inverse=True)
    out = np.zeros(uniq.size, dtype=np.uint32)
    np.bitwise_or.at(out, inv, bit)
    return uniq, out


def _expand_words(idx: np.ndarray, val: np.ndarray):
    """Inverse of _pairs_to_words: (rows, shard-local cols) of every set
    bit, ordered by (row, col)."""
    rows_out, cols_out = [], []
    for b in range(32):
        sel = (val >> np.uint32(b)) & np.uint32(1) > 0
        if sel.any():
            f = idx[sel]
            rows_out.append(f // SHARD_WORDS)
            cols_out.append((f % SHARD_WORDS) * 32 + b)
    if not rows_out:
        return (np.zeros(0, dtype=np.int64), np.zeros(0, dtype=np.int64))
    rows = np.concatenate(rows_out)
    cols = np.concatenate(cols_out)
    order = np.lexsort((cols, rows))
    return rows[order], cols[order]


class Fragment:
    """One (index, field, view, shard) bitmap."""

    def __init__(self, path: str | None, index: str, field: str, view: str,
                 shard: int, max_op_n: int = DEFAULT_FRAGMENT_MAX_OP_N,
                 row_id_cap: int | None = None, budget=None):
        self.path = path  # None = purely in-memory (tests)
        self.index = index
        self.field = field
        self.view = view
        self.shard = shard
        self.max_op_n = max_op_n
        # Guard against hostile row ids forcing terabyte-scale dense
        # allocations (core.DEFAULT_MAX_ROW_ID); threaded per-instance from
        # the server config (Holder -> Index -> Field -> View) so multiple
        # servers in one process keep independent caps.
        if row_id_cap is not None:
            self.row_id_cap = row_id_cap
        self.budget = budget if budget is not None else DEFAULT_BUDGET

        # sparse word store: sorted flat indices + non-zero word values
        self._idx = np.zeros(0, dtype=np.int64)
        self._val = np.zeros(0, dtype=np.uint32)
        self._cap_rows = 0        # device-shape row capacity (pow2 growth)
        self._mirrors = {}        # device -> cached jax.Array mirror
        # Data-generation stamp: unique across all fragments and bumped on
        # every mutation.  Derived caches (mesh stacked blocks) key their
        # validity on this instead of mirror identity, so they need not pin
        # mirrors alive (and a recreated fragment can never alias a stale
        # cache entry).
        self.gen = next(self._GEN)
        # Ingest delta overlay (docs/ingest.md): device_gen is the gen the
        # device-resident forms (mirrors, mesh stacks, packed streams)
        # reflect.  Ingest flushes (ingest_apply) update the sparse store
        # and bump gen WITHOUT invalidating device state — the new bits
        # ride in the journal, a list of (epoch, flat word idx, word val)
        # chunks OR'd into resident device arrays as overlays.  Any other
        # mutation (or a fold) clears the journal and re-anchors
        # device_gen = gen, so device consumers see exactly one of: a
        # current form, a current-at-device_gen form plus the journal that
        # upgrades it, or a dirty flag.
        self.device_gen = self.gen
        self.ingest_epoch = 0
        self._journal: list[tuple[int, np.ndarray, np.ndarray]] = []
        self._journal_bytes = 0
        self._mirror_epoch: dict = {}
        # Corruption quarantine (docs/robustness.md): non-None = the
        # reason string.  Quarantined fragments answer reads as EMPTY,
        # refuse writes with FragmentQuarantinedError, and are healed
        # wholesale from a replica by the anti-entropy repair pass.
        self.quarantined: str | None = None
        # whether the open WAL file is CRC-framed (decided by the file's
        # own leading magic at open; new/truncated files follow WAL_CRC)
        self._wal_framed = WAL_CRC
        # Per-fragment rank cache (cache/rank.py RankCache), attached by
        # the owning View for fields with cacheType ranked/lru; None for
        # cacheType none, BSI views, and bare test fragments.  Maintained
        # incrementally by the mutators below via _note_rank /
        # _rank_invalidate.
        self.rank_cache = None
        # host-side dense staging cache: (gen, dense block) — see
        # staged_dense()
        self._stage = None
        # packed container stream cache: (gen, ops.containers.Packed) —
        # see packed_host(); _comp_est is the (gen, bytes) estimate the
        # density heuristic uses without packing, and _psig the (gen,
        # sig tuple) bucket signature so stack tokens never repack
        self._packed = None
        self._comp_est = None
        self._psig = None
        self._device_dirty = True
        self._op_n = 0
        self._dirty_data = False  # mutated since last snapshot?
        self._wal_file = None
        self._lock = make_rlock("fragment")

        if path is not None:
            self._open_storage()

    # -- lifecycle ---------------------------------------------------------

    def _wal_path(self) -> str:
        return (self.path or "<memory>") + ".wal"

    def _quarantine_path(self) -> str:
        return (self.path or "<memory>") + ".quarantine"

    def _open_storage(self):
        """Load snapshot + replay WAL (fragment.go:311 openStorage).

        NEVER raises on corrupt on-disk state (with the default
        quarantine-on-corruption config): a torn WAL tail is truncated at
        the last valid frame boundary and serving continues; anything
        worse quarantines the fragment (empty reads, refused writes,
        replica repair heals it)."""
        os.makedirs(os.path.dirname(self.path), exist_ok=True)
        if QUARANTINE_ON_CORRUPTION and \
                os.path.exists(self._quarantine_path()):
            # quarantined by a previous run: don't re-parse known-bad
            # files; the sidecar carries the original reason.  With
            # quarantine OFF (fail-stop: cli check/inspect forensics,
            # quarantine-on-corruption=false servers) the sidecar is
            # ignored and the files re-parse so the REAL error raises —
            # an integrity tool must never report corrupt data as an
            # empty-but-healthy fragment.
            try:
                with open(self._quarantine_path()) as f:
                    reason = json.load(f).get("reason", "unknown")
            except (OSError, ValueError):
                reason = "unreadable quarantine marker"
            self._enter_quarantine(reason, persist=False, count=False)
            return
        try:
            self._load_files()
        except (ValueError, OSError) as e:
            # SnapshotFormatError is a ValueError; OSError covers I/O
            # faults reading either file
            if not QUARANTINE_ON_CORRUPTION:
                raise
            self._enter_quarantine(str(e))
            return
        self._wal_file = self._open_wal_append()

    def _load_files(self):
        if os.path.exists(self.path):
            with open(self.path, "rb") as f:
                data = f.read()
            try:
                cap_rows, idx, val = unpack_snapshot(
                    data, SHARD_WORDS, self.row_id_cap)
            except SnapshotFormatError as e:
                raise SnapshotFormatError(f"{self.path}: {e}") from e
            self._idx, self._val, self._cap_rows = idx, val, cap_rows
        if os.path.exists(self._wal_path()):
            with open(self._wal_path(), "rb") as f:
                buf = f.read()
            if buf.startswith(_WAL_MAGIC):
                self._wal_framed = True
                keep, ops = self._replay_framed_wal(buf)
                if keep < len(buf):
                    self._truncate_wal(keep)
                self._op_n = ops
            elif buf:
                # legacy bare record stream (pre-CRC files): replay as
                # before, keep appending in the same format so a mixed
                # file never exists; the next snapshot truncation
                # upgrades it
                self._wal_framed = False
                self._replay_wal(buf)
                keep = len(buf) - len(buf) % _OP.size
                if keep < len(buf):
                    # a torn trailing record (or a torn magic write
                    # shorter than one record) was DROPPED by replay —
                    # truncate it on disk too, or the next append lands
                    # after the garbage and shifts every later record
                    self._truncate_wal(keep)
                self._op_n = keep // _OP.size

    def _open_wal_append(self):
        fresh = not os.path.exists(self._wal_path()) \
            or os.path.getsize(self._wal_path()) == 0
        f = open(self._wal_path(), "ab", buffering=0)
        if fresh:
            self._wal_framed = WAL_CRC
            if self._wal_framed:
                f.write(_WAL_MAGIC)
        return f

    def _replay_framed_wal(self, buf: bytes) -> tuple[int, int]:
        """Replay a CRC-framed WAL.  Returns (keep_offset, op_count):
        keep_offset < len(buf) means a torn/garbage tail was detected
        after the last valid frame and the file must be truncated there.
        Raises ValueError on MID-log corruption (a bad frame with valid
        data after it — truncating would silently drop acknowledged
        writes, so the fragment quarantines instead)."""
        off = len(_WAL_MAGIC)
        ops = 0
        n = len(buf)
        while off < n:
            if n - off < _WAL_FRAME.size:
                break  # torn frame header
            plen, crc = _WAL_FRAME.unpack_from(buf, off)
            if plen == 0 or plen % _OP.size or plen > _WAL_MAX_FRAME:
                # an all-zero tail is the classic torn-write artifact
                # (journal replay after power loss); anything else in a
                # length field is corruption we cannot skip safely
                if any(buf[off:]):
                    raise ValueError(
                        f"corrupt WAL {self._wal_path()}: bad frame "
                        f"header at byte {off}")
                break
            end = off + _WAL_FRAME.size + plen
            if end > n:
                break  # incomplete final append
            payload = buf[off + _WAL_FRAME.size: end]
            if checksum(payload) != crc:
                if end == n:
                    break  # torn/garbage final frame
                raise ValueError(
                    f"corrupt WAL {self._wal_path()}: frame CRC mismatch "
                    f"at byte {off} with valid data after it")
            self._apply_wal_records(payload)
            ops += plen // _OP.size
            off = end
        return off, ops

    def _apply_wal_records(self, payload: bytes):
        """Apply one frame's op records in order (vectorized per
        same-op run)."""
        recs = np.frombuffer(payload, dtype=_OP_DTYPE)
        op_arr = recs["op"]
        rows = recs["row"].astype(np.int64)
        cols = recs["col"].astype(np.int64)
        if not bool(np.all((op_arr == _OP_SET) | (op_arr == _OP_CLEAR))):
            raise ValueError(
                f"corrupt WAL {self._wal_path()}: unknown op code")
        if rows.size and (int(rows.min()) < 0 or int(cols.min()) < 0
                          or int(cols.max()) >= SHARD_WIDTH):
            raise ValueError(
                f"corrupt WAL {self._wal_path()}: record out of range")
        starts = [0] + (np.nonzero(np.diff(op_arr))[0] + 1).tolist() \
            + [rows.size]
        for a, b in zip(starts[:-1], starts[1:]):
            if a == b:
                continue
            try:
                self._apply_bits(rows[a:b], cols[a:b],
                                 clear=(op_arr[a] == _OP_CLEAR))
            except ValueError as e:
                raise ValueError(
                    f"replaying WAL {self._wal_path()}: {e}; raise "
                    f"max_row_id if this data was written with a larger "
                    f"cap") from e

    def _replay_wal(self, buf: bytes):
        """Apply legacy (unframed) WAL records in order, batching
        consecutive same-op runs.  Corrupt records (unknown op,
        out-of-range row/col) raise ValueError rather than silently
        mis-importing; a trailing partial record (torn write on crash) is
        dropped."""
        n = len(buf) - len(buf) % _OP.size
        run_op, run_rows, run_cols = None, [], []

        def flush():
            nonlocal run_rows, run_cols
            if not run_rows:
                return
            rows = np.asarray(run_rows, dtype=np.int64)
            cols = np.asarray(run_cols, dtype=np.int64)
            try:
                self._apply_bits(rows, cols, clear=(run_op == _OP_CLEAR))
            except ValueError as e:
                raise ValueError(
                    f"replaying WAL {self._wal_path()}: {e}; raise "
                    f"max_row_id if this data was written with a larger "
                    f"cap") from e
            run_rows, run_cols = [], []

        for off in range(0, n, _OP.size):
            op, row, col = _OP.unpack_from(buf, off)
            if op not in (_OP_SET, _OP_CLEAR):
                raise ValueError(
                    f"corrupt WAL {self._wal_path()}: unknown op {op} at "
                    f"byte {off}")
            if row < 0 or col < 0 or col >= SHARD_WIDTH:
                raise ValueError(
                    f"corrupt WAL {self._wal_path()}: record ({row}, {col}) "
                    f"out of range at byte {off}")
            if op != run_op:
                flush()
                run_op = op
            run_rows.append(row)
            run_cols.append(col)
        flush()

    def _truncate_wal(self, keep: int):
        """Truncate a torn/garbage WAL tail at the last valid frame
        boundary, durably (the recovery itself must survive a crash —
        a re-run replays the same valid prefix and truncates again)."""
        FAULTS.hit("fragment.wal.truncate", key=self.path or "")
        with open(self._wal_path(), "r+b") as f:
            f.truncate(keep)
            os.fsync(f.fileno())
        fsync_dir(os.path.dirname(self._wal_path()) or ".")
        _bump("torn_tail_recovered")

    # -- quarantine (docs/robustness.md "Corruption quarantine") -----------

    def _enter_quarantine(self, reason: str, persist: bool = True,
                          count: bool = True):
        """Reset to the quarantined state: empty store, no WAL handle, a
        sidecar marker so restarts skip re-parsing the corrupt files.
        The corrupt snapshot/WAL bytes stay on disk for forensics until
        repair replaces them."""
        global QUARANTINE_SEEN
        QUARANTINE_SEEN = True
        self.quarantined = reason
        self._idx = np.zeros(0, dtype=np.int64)
        self._val = np.zeros(0, dtype=np.uint32)
        self._cap_rows = 0
        self._op_n = 0
        self._dirty_data = False
        self._device_dirty = True
        self.gen = next(self._GEN)  # derived caches must not serve stale
        self.device_gen = self.gen
        self._clear_journal()
        self._stage = None
        if self._wal_file is not None:
            try:
                self._wal_file.close()
            except OSError:
                pass
            self._wal_file = None
        self._rank_invalidate()
        if persist and self.path is not None:
            tmp = self._quarantine_path() + ".tmp"
            try:
                with open(tmp, "w") as f:
                    json.dump({"reason": reason}, f)
                    fsync_file(f)
                durable_replace(tmp, self._quarantine_path())
            except OSError:
                pass  # marker is an optimization; reopen re-detects
        if count:
            _bump("quarantine")
            # journaled state transition (docs/observability.md "Cluster
            # plane"); sidecar reloads (count=False) are not new events
            events.emit("storage.quarantine", index=self.index,
                        field=self.field, view=self.view,
                        shard=self.shard, reason=str(reason)[:160])

    def _check_writable(self):
        if self.quarantined is not None:
            raise FragmentQuarantinedError(
                f"fragment {self.index}/{self.field}/{self.view}/"
                f"{self.shard} is quarantined ({self.quarantined}); "
                f"writes are refused until replica repair restores it")

    def snapshot_bytes(self) -> bytes:
        """Serialize the CURRENT in-memory state (snapshot + replayed
        WAL) to checksummed v4 snapshot bytes — the payload of
        /internal/fragment/fetch (replica repair)."""
        with self._lock:
            return pack_snapshot(self._cap_rows, self._idx, self._val,
                                 SHARD_WORDS)

    def restore_snapshot_bytes(self, blob: bytes):
        """Replace this fragment's entire contents from checksummed
        snapshot bytes (replica repair receive path).  Verifies the CRCs
        BEFORE touching anything, swaps the file in via the durable
        tmp+rename path, truncates the WAL, clears the quarantine
        marker, and bumps the generation so every derived cache (device
        mirrors, mesh stacks, result caches) invalidates."""
        cap_rows, idx, val = unpack_snapshot(blob, SHARD_WORDS,
                                             self.row_id_cap)
        with self._lock:
            if self.path is not None:
                tmp = self.path + ".repair"
                with open(tmp, "wb") as f:
                    f.write(blob)
                    fsync_file(f)
                durable_replace(tmp, self.path)
                if self._wal_file is not None:
                    try:
                        self._wal_file.close()
                    except OSError:
                        pass
                    self._wal_file = None
                try:
                    os.remove(self._quarantine_path())
                except FileNotFoundError:
                    pass
                fsync_dir(os.path.dirname(self.path) or ".")
            self._idx, self._val, self._cap_rows = idx, val, cap_rows
            self.quarantined = None
            self._op_n = 0
            self._dirty_data = False
            self._stage = None
            self._mark_device_dirty()
            self._dirty_data = False  # state matches the file just written
            self._rank_invalidate()
            if self.path is not None:
                self._wal_file = open(self._wal_path(), "wb", buffering=0)
                self._wal_framed = WAL_CRC
                if self._wal_framed:
                    self._wal_file.write(_WAL_MAGIC)
        _bump("repair")

    def close(self):
        with self._lock:
            if self._wal_file is not None:
                # flush+fsync the WAL FIRST: even if the snapshot below
                # fails (disk full, injected fault), every acknowledged
                # append is on stable storage and a reopen replays to the
                # identical bitmap
                try:
                    fsync_file(self._wal_file)
                except OSError:
                    pass
                try:
                    if self._dirty_data or self._op_n:
                        self.snapshot()
                finally:
                    if self._wal_file is not None:
                        self._wal_file.close()
                        self._wal_file = None
            self._drop_mirrors()
            self._drop_stage()

    def snapshot(self):
        """Rewrite the snapshot file (checksummed v4) and truncate the
        WAL (fragment.go:2311 snapshot)."""
        with self._lock:
            if self.quarantined is not None:
                return  # nothing trustworthy to persist
            if self.path is None:
                self._op_n = 0
                return
            tmp = self.path + ".snapshotting"
            FAULTS.hit("fragment.snapshot", key=self.path)
            with open(tmp, "wb") as f:
                f.write(pack_snapshot(self._cap_rows, self._idx, self._val,
                                      SHARD_WORDS))
                # fsync BEFORE the rename: the write lands in the page
                # cache, and a crash after os.replace would otherwise lose
                # an acknowledged snapshot (the WAL it replaced is
                # truncated)
                fsync_file(f)
            FAULTS.hit("fragment.snapshot.rename", key=self.path)
            durable_replace(tmp, self.path)
            self._dirty_data = False
            if self._wal_file is not None:
                self._wal_file.close()
            self._wal_file = open(self._wal_path(), "wb", buffering=0)
            # truncation is the format upgrade point for legacy WALs
            self._wal_framed = WAL_CRC
            if self._wal_framed:
                self._wal_file.write(_WAL_MAGIC)
            self._op_n = 0

    # -- geometry ----------------------------------------------------------

    @property
    def n_rows(self) -> int:
        """Device-shape row capacity (doubling growth)."""
        return self._cap_rows

    def max_row_id(self) -> int:
        """Highest row with any bit set (fragment.go maxRow)."""
        return int(self._idx[-1] // SHARD_WORDS) if self._idx.size else 0

    def host_bytes(self) -> int:
        """Host memory held by the sparse store."""
        return int(self._idx.nbytes + self._val.nbytes)

    # Default cap when none is threaded in (class fallback keeps in-memory
    # test fragments working without plumbing).
    row_id_cap = DEFAULT_MAX_ROW_ID

    def _ensure_rows(self, row_id: int):
        if row_id < self._cap_rows:
            return
        if row_id > self.row_id_cap:
            raise ValueError(
                f"row id {row_id} exceeds the configured maximum "
                f"{self.row_id_cap} (max_row_id)")
        new_rows = max(_MIN_ROWS, self._cap_rows)
        while new_rows <= row_id:
            new_rows *= 2
        self._cap_rows = new_rows
        self._mark_device_dirty()

    _GEN = itertools.count(1)

    def _mark_device_dirty(self):
        self._device_dirty = True
        self._dirty_data = True
        self.gen = next(self._GEN)
        # any non-ingest mutation (or an explicit fold) supersedes the
        # overlay journal: device forms rebuild from the sparse store,
        # which already holds every journaled bit
        self.device_gen = self.gen
        self._clear_journal()

    def _clear_journal(self):
        if self._journal:
            self._journal.clear()
            self._journal_bytes = 0
            INGEST_DELTA_BUDGET.unregister(("delta", id(self)))
        self._mirror_epoch.clear()

    def _fold_journal_locked(self):
        """Merge step: device forms rebuild from the (already-current)
        sparse store on next use.  NOT a data mutation — gen is
        unchanged, so result caches keyed on it stay valid; only the
        device-residency anchor moves."""
        self._device_dirty = True
        self.device_gen = self.gen
        self._clear_journal()

    def _note_rank(self, rows):
        """Incremental rank-cache maintenance after a successful mutation
        touching ``rows`` (called under self._lock)."""
        if self.rank_cache is not None:
            self.rank_cache.note_write(self, rows)

    def _rank_invalidate(self):
        """Bulk mutation whose touched rows aren't cheaply known (row
        stores, mutex imports): rebuild the rank cache lazily."""
        if self.rank_cache is not None:
            self.rank_cache.invalidate()

    # -- sparse store primitives -------------------------------------------

    def _locate(self, nidx: np.ndarray):
        """(positions, exists-mask) of nidx in the store."""
        pos = np.searchsorted(self._idx, nidx)
        if self._idx.size:
            exists = (pos < self._idx.size) & \
                (self._idx[np.minimum(pos, self._idx.size - 1)] == nidx)
        else:
            exists = np.zeros(nidx.shape, dtype=bool)
        return pos, exists

    def _or_words(self, nidx: np.ndarray, nval: np.ndarray) -> int:
        """OR word values into the store; returns changed-bit count."""
        pos, exists = self._locate(nidx)
        changed = 0
        upd = pos[exists]
        if upd.size:
            old = self._val[upd]
            new = old | nval[exists]
            changed += int(np.bitwise_count(new & ~old).sum())
            self._val[upd] = new
        ins = ~exists
        if ins.any():
            changed += int(np.bitwise_count(nval[ins]).sum())
            self._idx = np.insert(self._idx, pos[ins], nidx[ins])
            self._val = np.insert(self._val, pos[ins], nval[ins])
        return changed

    def _andnot_words(self, nidx: np.ndarray, nval: np.ndarray) -> int:
        """Clear word bits; returns changed-bit count."""
        pos, exists = self._locate(nidx)
        upd = pos[exists]
        if not upd.size:
            return 0
        old = self._val[upd]
        new = old & ~nval[exists]
        changed = int(np.bitwise_count(old & ~new).sum())
        if changed:
            self._val[upd] = new
            keep = self._val != 0
            if not keep.all():
                self._idx, self._val = self._idx[keep], self._val[keep]
        return changed

    def _apply_bits(self, rows, cols, clear: bool) -> int:
        if rows.size == 0:
            return 0
        if clear:
            # Rows at/above capacity cannot hold set bits: drop them rather
            # than growing capacity (which would change the device tensor
            # shape and force a recompile for a guaranteed no-op), and never
            # raise on row ids beyond the cap — clearing them is a no-op.
            keep = rows < self._cap_rows
            if not keep.all():
                rows, cols = rows[keep], cols[keep]
            if rows.size == 0:
                return 0
        else:
            self._ensure_rows(int(rows.max()))
        nidx, nval = _pairs_to_words(rows, cols)
        n = self._andnot_words(nidx, nval) if clear \
            else self._or_words(nidx, nval)
        if n:
            self._mark_device_dirty()
        return n

    def _delete_range(self, lo: int, hi: int):
        """Remove stored words with lo <= flat < hi."""
        a = np.searchsorted(self._idx, lo)
        b = np.searchsorted(self._idx, hi)
        if b > a:
            self._idx = np.delete(self._idx, slice(a, b))
            self._val = np.delete(self._val, slice(a, b))

    def _column_mask_clear(self, cols: np.ndarray, max_row=None) -> int:
        """AND-out the given shard-local columns' bits from every stored
        word (optionally only rows < max_row); returns changed bits."""
        if self._idx.size == 0 or cols.size == 0:
            return 0
        w, bit = bitset.word_bit_np(cols)
        mask = np.zeros(SHARD_WORDS, dtype=np.uint32)
        np.bitwise_or.at(mask, w, bit)
        w_of = (self._idx % SHARD_WORDS).astype(np.int64)
        sel = mask[w_of] != 0
        if max_row is not None:
            sel &= (self._idx // SHARD_WORDS) < max_row
        if not sel.any():
            return 0
        old = self._val[sel]
        new = old & ~mask[w_of[sel]]
        changed = int(np.bitwise_count(old & ~new).sum())
        if changed:
            self._val[sel] = new
            keep = self._val != 0
            if not keep.all():
                self._idx, self._val = self._idx[keep], self._val[keep]
        return changed

    # -- mutation ----------------------------------------------------------

    def _frame(self, payload: bytes) -> bytes:
        """Wrap a batch of op records in one length+CRC frame (or pass
        through bare for legacy-format files).  Header and payload go to
        the file in ONE write() call — frames are never interleaved or
        split by the process itself."""
        if not self._wal_framed:
            return payload
        return _WAL_FRAME.pack(len(payload), checksum(payload)) + payload

    def _log_op(self, op: int, row: int, col: int):
        if self._wal_file is not None:
            FAULTS.hit("fragment.wal", key=self.path or "")
            self._wal_file.write(self._frame(_OP.pack(op, row, col)))
        self._op_n += 1
        if self._op_n >= self.max_op_n:
            if self._wal_file is not None:
                self._wal_file.flush()
            self.snapshot()

    def _log_ops(self, op: int, rows: np.ndarray, cols: np.ndarray):
        """Vectorized batch append: one record-array build + one write
        (one CRC frame per batch — the group-commit framing unit)."""
        if self._wal_file is not None:
            FAULTS.hit("fragment.wal", key=self.path or "")
            recs = np.empty(rows.size, dtype=_OP_DTYPE)
            recs["op"] = op
            recs["row"] = rows
            recs["col"] = cols
            payload = recs.tobytes()
            # replay rejects frames beyond _WAL_MAX_FRAME as corrupt, so
            # the writer must chunk giant imports below it
            step = (_WAL_MAX_FRAME // _OP.size) * _OP.size
            for i in range(0, len(payload), step):
                self._wal_file.write(self._frame(payload[i:i + step]))
        self._op_n += rows.size
        if self._op_n >= self.max_op_n:
            self.snapshot()

    def set_bit(self, row: int, col: int) -> bool:
        """Set one bit; col is shard-local.  Returns True if changed
        (fragment.go:647 setBit)."""
        with self._lock:
            self._check_writable()
            changed = self._apply_bits(np.asarray([row], dtype=np.int64),
                                       np.asarray([col], dtype=np.int64),
                                       clear=False) > 0
            if changed:
                self._note_rank([row])
                self._log_op(_OP_SET, row, col)
            return changed

    def clear_bit(self, row: int, col: int) -> bool:
        with self._lock:
            self._check_writable()
            changed = self._apply_bits(np.asarray([row], dtype=np.int64),
                                       np.asarray([col], dtype=np.int64),
                                       clear=True) > 0
            if changed:
                self._note_rank([row])
                self._log_op(_OP_CLEAR, row, col)
            return changed

    def bulk_import(self, rows: np.ndarray, cols: np.ndarray,
                    clear: bool = False) -> int:
        """Batched import of shard-local (row, col) bits
        (fragment.go:1997 bulkImport / 2053 importPositions).  Returns the
        number of changed bits."""
        rows = np.asarray(rows, dtype=np.int64)
        cols = np.asarray(cols, dtype=np.int64)
        if rows.size == 0:
            return 0
        with self._lock:
            self._check_writable()
            n_changed = self._apply_bits(rows, cols, clear=clear)
            if n_changed:
                self._note_rank(rows)
                self._log_ops(_OP_CLEAR if clear else _OP_SET, rows, cols)
            return n_changed

    def mutex_import(self, rows: np.ndarray, cols: np.ndarray) -> int:
        """Batched import with mutex semantics: at most one row per column,
        last write in the batch wins (fragment.go:2106 bulkImportMutex).
        Returns changed-bit count."""
        rows = np.asarray(rows, dtype=np.int64)
        cols = np.asarray(cols, dtype=np.int64)
        if rows.size == 0:
            return 0
        # keep the last occurrence of each column
        last = {}
        for i in range(rows.size):
            last[int(cols[i])] = int(rows[i])
        ucols = np.fromiter(last.keys(), dtype=np.int64, count=len(last))
        urow = np.fromiter(last.values(), dtype=np.int64, count=len(last))
        with self._lock:
            self._check_writable()
            self._ensure_rows(int(urow.max()))
            # Winner bits already set are cleared by _column_mask_clear and
            # re-set by _apply_bits; they are no-ops and must not count
            # (fragment.go:2106 bulkImportMutex reports real changes only).
            nidx, nval = _pairs_to_words(urow, ucols)
            pos, exists = self._locate(nidx)
            pre_winner = int(np.bitwise_count(
                self._val[pos[exists]] & nval[exists]).sum())
            gen0, dev_dirty0, data_dirty0 = \
                self.gen, self._device_dirty, self._dirty_data
            cleared = self._column_mask_clear(ucols)
            set_changed = self._apply_bits(urow, ucols, clear=False)
            n_changed = cleared + set_changed - 2 * pre_winner
            if n_changed:
                self._rank_invalidate()  # cleared rows aren't enumerated
                self._mark_device_dirty()
                if self._wal_file is not None:
                    self.snapshot()
            else:
                # idempotent re-import: the store's final state equals its
                # initial state — restore the stamps so downstream caches
                # (device mirrors, mesh stacks) are not invalidated
                self.gen = gen0
                self._device_dirty = dev_dirty0
                self._dirty_data = data_dirty0
            return n_changed

    def set_row(self, row: int, seg: np.ndarray | None):
        """Replace an entire row's bits (Store/SetRow, fragment.go setRow)."""
        with self._lock:
            self._check_writable()
            self._ensure_rows(row)
            base = row * SHARD_WORDS
            self._delete_range(base, base + SHARD_WORDS)
            if seg is not None:
                seg = np.asarray(seg, dtype=np.uint32)
                nz = np.nonzero(seg)[0]
                if nz.size:
                    self._or_words(base + nz.astype(np.int64), seg[nz])
            self._note_rank([row])
            self._mark_device_dirty()
            self.snapshot()  # row stores bypass the op log

    # -- BSI mutation (int fields) ----------------------------------------

    def bit_depth(self) -> int:
        return max(0, self._cap_rows - bsi.OFFSET_ROW)

    def set_value(self, col: int, bit_depth: int, value: int) -> bool:
        """Set a column's integer value (fragment.go:977 setValueBase).
        Grows depth rows as needed; clears stale magnitude bits.  Only the
        bits that actually change are applied AND logged — the old
        log-everything-on-any-change scheme bloated the WAL toward
        premature snapshots (r3 verdict)."""
        with self._lock:
            self._check_writable()
            self._ensure_rows(bsi.OFFSET_ROW + bit_depth - 1)
            mag = abs(value)
            want = {bsi.EXISTS_ROW}
            for i in range(bit_depth):
                if (mag >> i) & 1:
                    want.add(bsi.OFFSET_ROW + i)
            if value < 0:
                want.add(bsi.SIGN_ROW)
            managed = sorted({bsi.EXISTS_ROW, bsi.SIGN_ROW} | {
                bsi.OFFSET_ROW + i for i in range(bit_depth)})
            # targeted probe of only the managed rows' words — NOT a full
            # rows_with_bit scan (O(log nnz) per row vs O(nnz) per write)
            mrows = np.asarray(managed, dtype=np.int64)
            w = col >> 5
            bit = np.uint32(1 << (col & 31))
            pos, exists = self._locate(mrows * SHARD_WORDS + w)
            has = np.zeros(mrows.size, dtype=bool)
            has[exists] = (self._val[pos[exists]] & bit) > 0
            cur = {int(r) for r, h in zip(mrows, has) if h}
            to_set = sorted(want - cur)
            to_clear = sorted(cur - want)
            if to_set:
                rows = np.asarray(to_set, dtype=np.int64)
                cols = np.full(rows.size, col, dtype=np.int64)
                self._apply_bits(rows, cols, clear=False)
                self._log_ops(_OP_SET, rows, cols)
            if to_clear:
                rows = np.asarray(to_clear, dtype=np.int64)
                cols = np.full(rows.size, col, dtype=np.int64)
                self._apply_bits(rows, cols, clear=True)
                self._log_ops(_OP_CLEAR, rows, cols)
            return bool(to_set or to_clear)

    def import_values(self, cols: np.ndarray, values: np.ndarray,
                      bit_depth: int) -> None:
        """Batched setValue (fragment.go:2205 importValue)."""
        cols = np.asarray(cols, dtype=np.int64)
        values = np.asarray(values, dtype=np.int64)
        with self._lock:
            self._check_writable()
            self._ensure_rows(bsi.OFFSET_ROW + bit_depth - 1)
            # clear all target columns' bits first (stale values)
            self._column_mask_clear(cols, max_row=bsi.OFFSET_ROW + bit_depth)
            packed = bsi.pack_values(cols, values, depth=bit_depth,
                                     words=SHARD_WORDS)
            flat = packed.reshape(-1)
            nz = np.nonzero(flat)[0]
            if nz.size:
                self._or_words(nz.astype(np.int64), flat[nz])
            self._mark_device_dirty()
            self.snapshot()

    def clear_values(self, cols: np.ndarray) -> None:
        """Remove columns' values entirely (exists+sign+magnitude cleared) —
        the clear half of importValue (fragment.go:2205 importValue with
        clear)."""
        cols = np.asarray(cols, dtype=np.int64)
        if cols.size == 0 or self._idx.size == 0:
            return
        with self._lock:
            self._check_writable()
            if self._column_mask_clear(cols):
                self._mark_device_dirty()
            self.snapshot()

    # -- reads -------------------------------------------------------------

    def row(self, row_id: int) -> np.ndarray:
        """Host copy of one row's segment (fragment.go:602 row)."""
        with self._lock:
            out = np.zeros(SHARD_WORDS, dtype=np.uint32)
            if row_id >= self._cap_rows:
                return out
            base = row_id * SHARD_WORDS
            a = np.searchsorted(self._idx, base)
            b = np.searchsorted(self._idx, base + SHARD_WORDS)
            if b > a:
                out[self._idx[a:b] - base] = self._val[a:b]
            return out

    def row_columns(self, row_id: int) -> np.ndarray:
        return bitset.unpack_columns(self.row(row_id))

    def rows_with_bit(self, col: int) -> np.ndarray:
        """Sorted row ids whose bit at shard-local ``col`` is set (the
        column read under mutex/bool semantics and BSI value())."""
        with self._lock:
            if self._idx.size == 0:
                return np.zeros(0, dtype=np.int64)
            w = col >> 5
            bit = np.uint32(1 << (col & 31))
            sel = (self._idx % SHARD_WORDS == w) & (self._val & bit > 0)
            return (self._idx[sel] // SHARD_WORDS).astype(np.int64)

    def row_counts_host(self, rows: np.ndarray) -> np.ndarray:
        """Exact per-row set-bit counts for the given rows, from the host
        sparse store (no device touch).  Popcounts only each requested
        row's word range (O(log nnz) locate + O(row words) per row) —
        this runs on EVERY single-bit write of a rank-cached field, so a
        whole-store scan here would make writes O(nnz)."""
        rows = np.asarray(rows, dtype=np.int64)
        with self._lock:
            out = np.zeros(rows.size, dtype=np.int64)
            if self._idx.size == 0 or rows.size == 0:
                return out
            a = np.searchsorted(self._idx, rows * SHARD_WORDS)
            b = np.searchsorted(self._idx, (rows + 1) * SHARD_WORDS)
            for i in range(rows.size):
                if b[i] > a[i]:
                    out[i] = int(np.bitwise_count(
                        self._val[a[i]: b[i]]).sum())
            return out

    def row_counts_all_host(self) -> tuple[np.ndarray, np.ndarray]:
        """(row ids, exact counts) of every row with any set bit, from the
        host sparse store — the rank-cache rebuild scan (O(nnz))."""
        with self._lock:
            if self._idx.size == 0:
                z = np.zeros(0, dtype=np.int64)
                return z, z
            rows_of = self._idx // SHARD_WORDS
            pops = np.bitwise_count(self._val).astype(np.int64)
            uniq, start = np.unique(rows_of, return_index=True)
            return uniq, np.add.reduceat(pops, start)

    def pairs(self) -> tuple[np.ndarray, np.ndarray]:
        """(rows, shard-local cols) of every set bit, (row, col)-ordered —
        the export/iteration surface (fragment.go:2771 rowIterator)."""
        with self._lock:
            return _expand_words(self._idx, self._val)

    def to_dense(self) -> np.ndarray:
        """Materialise the dense [cap_rows, SHARD_WORDS] tensor (device
        upload + compatibility paths).  O(cap_rows x 128KB) — transient."""
        with self._lock:
            out = np.zeros((self._cap_rows, SHARD_WORDS), dtype=np.uint32)
            if self._idx.size:
                out.reshape(-1)[self._idx] = self._val
            return out

    @property
    def words(self) -> np.ndarray:
        """Dense view for compatibility/oracle paths; materialises on each
        access — do not use on hot paths."""
        return self.to_dense()

    def staged_dense(self) -> np.ndarray:
        """Dense block via the host staging cache.  After an HBM eviction
        the re-upload reads this cached expansion instead of re-running
        the sparse->dense scatter — under budget pressure the expansion,
        not the transfer, dominates cold re-stages.  Keyed by the data
        generation (any mutation invalidates); HOST_STAGE_BUDGET bounds
        total cached host bytes LRU-wise (limit 0 disables caching).
        With no device-budget limit nothing is ever evicted, so there is
        no re-upload to accelerate — caching would only grow host RSS —
        and the expansion stays transient like to_dense().

        The returned array is SHARED — callers must treat it read-only
        (device uploads and stacked-block fills copy out of it)."""
        if HOST_STAGE_BUDGET.limit_bytes == 0 or \
                self.budget.limit_bytes is None:
            return self.to_dense()
        with self._lock:
            st = self._stage
            if st is not None and st[0] == self.gen:
                HOST_STAGE_BUDGET.touch(("stage", id(self)))
                return st[1]
            dense = self.to_dense()
            self._stage = (self.gen, dense)
            HOST_STAGE_BUDGET.register(("stage", id(self)), dense.nbytes,
                                       self._evict_stage)
            return dense

    def _evict_stage(self):
        # host-stage budget callback: drop the cached expansion only
        self._stage = None

    def _drop_stage(self):
        HOST_STAGE_BUDGET.unregister(("stage", id(self)))
        HOST_STAGE_BUDGET.unregister(("packed", id(self)))
        INGEST_DELTA_BUDGET.unregister(("delta", id(self)))
        self._stage = None
        self._packed = None

    # -- compressed-resident form (ops/containers.py) ----------------------

    def packed_host(self):
        """This fragment's packed container stream (array/bitmap/run
        containers over the sparse word store), built host-side WITHOUT
        materialising the dense tensor and cached by data generation —
        snapshot load + packing never allocates cap_rows x 128KB.  The
        cache registers with HOST_STAGE_BUDGET like the dense stage (a
        re-stage accelerator, evictable under host pressure; limit 0
        disables caching and the pack stays transient)."""
        from ..ops import containers
        with self._lock:
            p = self._packed
            if p is not None and p[0] == self.device_gen:
                HOST_STAGE_BUDGET.touch(("packed", id(self)))
                return p[1]
            packed = containers.pack_words(self._idx, self._val)
            # exact packed bytes supersede the census upper bound as the
            # density-heuristic input, for free.  Keyed by device_gen, not
            # gen: while an ingest journal is active the device-facing
            # pack/estimate/signature are FROZEN at the journal's base so
            # stack tokens stay stable between folds (packing is only
            # requested with an empty journal, where the two gens agree).
            self._comp_est = (self.device_gen, packed.nbytes)
            if HOST_STAGE_BUDGET.limit_bytes != 0:
                self._packed = (self.device_gen, packed)
                HOST_STAGE_BUDGET.register(("packed", id(self)),
                                           packed.nbytes,
                                           self._evict_packed)
            return packed

    def _evict_packed(self):
        # host-stage budget callback: drop the cached pack only
        self._packed = None

    def _compressed_est(self) -> int:
        """Gen-cached upper bound on the packed stream's bytes (cheap:
        container census over the sparse indices, no packing)."""
        from ..ops import containers
        with self._lock:
            e = self._comp_est
            if e is not None and e[0] == self.device_gen:
                return e[1]
            est = containers.estimate_packed_bytes(self._idx)
            self._comp_est = (self.device_gen, est)
            return est

    def device_form(self) -> str:
        """'compressed' | 'dense': which device-resident form this
        fragment's data warrants.  Compressed only under a configured
        device budget (with unlimited HBM the dense mirror is strictly
        faster — no decode per launch — exactly as staged_dense only
        caches under a limit) and only when the density heuristic says
        the packed stream actually undercuts the dense footprint."""
        from ..ops.containers import MAX_COMPRESSED_ROWS
        if not COMPRESSED_RESIDENT or self.budget.limit_bytes is None:
            return "dense"
        dense = self._cap_rows * SHARD_WORDS * 4
        if dense == 0 or self._cap_rows > MAX_COMPRESSED_ROWS:
            return "dense"
        return "compressed" \
            if self._compressed_est() <= COMPRESS_MAX_DENSITY * dense \
            else "dense"

    def device_nbytes(self) -> int:
        """Bytes this fragment's device-resident form occupies — the
        residency unit the budget and the shard-slice planner account
        (compressed bytes for compressed-form fragments, the dense
        tensor for the rest)."""
        if self.device_form() == "compressed":
            return self.packed_host().nbytes
        return self._cap_rows * SHARD_WORDS * 4

    def device_sig(self) -> tuple:
        """Stacked-group shape signature for the mesh executor: dense
        fragments keep the (rows, words) tensor shape; compressed ones
        carry ('z', rows, C, P, A, R, backend) with pow2-bucketed
        container, payload, array-entry and run counts so one compiled
        decode executable serves every fragment in a bucket.  The
        trailing element is the RESOLVED container-kernels backend
        (ops/kernels.py): the decode code compiled into the executable
        is part of its shape, so a knob flip mints new signatures —
        new plans, new stacks, fresh compiles — instead of replaying a
        jnp-compiled program through the pallas path (the PR 7 retrace
        class)."""
        if self.device_form() == "dense":
            return (self.n_rows, SHARD_WORDS)
        from ..ops import kernels
        from ..ops.containers import pow2_bucket
        backend = kernels.sig_tag()
        with self._lock:
            s = self._psig
            if s is not None and s[0] == (self.device_gen, backend):
                return s[1]
        p = self.packed_host()
        sig = ("z", self.n_rows, pow2_bucket(p.keys.size),
               pow2_bucket(p.payload.size), pow2_bucket(p.a_max),
               pow2_bucket(p.r_max), backend)
        with self._lock:
            self._psig = ((self.device_gen, backend), sig)
        return sig

    def packed_stats(self) -> dict | None:
        """Container-type histogram of the CURRENT packed stream, or
        None when no current pack exists (never packs on demand — this
        feeds metric scrapes, which must stay O(1) per fragment)."""
        with self._lock:
            p = self._packed
            if p is None or p[0] != self.device_gen:
                return None
            return p[1].type_histogram()

    # -- ingest delta overlay (docs/ingest.md) -----------------------------

    def ingest_apply(self, rows: np.ndarray, cols: np.ndarray) -> int:
        """Group-commit apply of a flush's set bits for this fragment:
        ONE sparse-store merge, ONE WAL frame, ONE generation bump, ONE
        rank-cache touch — and, when a device-resident form exists, the
        new words land in the overlay journal instead of invalidating it
        (mirrors/stacks OR the journal in at next use; the sparse store
        is the source of truth either way, so every host read is current
        immediately).  Returns the changed-bit count; a fully idempotent
        re-ingest (no bit changed) is a no-op — no WAL frame, no gen
        bump — which is what makes client retries after a 503 safe."""
        rows = np.asarray(rows, dtype=np.int64)
        cols = np.asarray(cols, dtype=np.int64)
        if rows.size == 0:
            return 0
        with self._lock:
            self._check_writable()
            limit = _membudget.INGEST_DELTA_LIMIT_BYTES
            if int(rows.max()) >= self._cap_rows:
                # capacity growth changes the device tensor shape — no
                # overlay can cover that; _ensure_rows folds the journal
                self._ensure_rows(int(rows.max()))
            # Overlay only for dense-form fragments: compressed packed
            # streams cannot absorb a scatter — those fold per flush (the
            # flush is still one gen bump, the win over per-call
            # bulk_import remains).  A dirty device state doesn't matter:
            # consumers built later stage from the sparse store (already
            # current) and record the epochs they captured.
            overlay = limit > 0 and self.device_form() == "dense"
            nidx, nval = _pairs_to_words(rows, cols)
            changed = self._or_words(nidx, nval)
            if changed == 0:
                return 0
            if not overlay:
                self._mark_device_dirty()
            else:
                self._dirty_data = True
                self.gen = next(self._GEN)
                self.ingest_epoch += 1
                self._journal.append((self.ingest_epoch, nidx, nval))
                self._journal_bytes += int(nidx.nbytes + nval.nbytes)
                INGEST_DELTA_BUDGET.register(
                    ("delta", id(self)), self._journal_bytes,
                    lambda: None)  # accounting-only; folds are cooperative
                # per-fragment share of the delta budget: one hot
                # fragment must not monopolise it before the committer's
                # cross-fragment merge pass can react
                if self._journal_bytes > max(limit // 8, 1 << 20):
                    self._fold_journal_locked()
            self._note_rank(rows)
            self._log_ops(_OP_SET, rows, cols)
            return changed

    def delta_chunks(self, after_epoch: int) -> list:
        """Journal chunks newer than ``after_epoch`` — what a device
        consumer (mirror, mesh stack) must OR in to reach the current
        generation.  Chunks are immutable once appended; the list copy
        makes iteration safe outside the lock."""
        with self._lock:
            return [c for c in self._journal if c[0] > after_epoch]

    def delta_bytes(self) -> int:
        return self._journal_bytes

    def fold_delta(self) -> bool:
        """Fold the overlay journal into a plain device-dirty state (the
        background-merge step): the next staging rebuilds mirrors/stacks
        and the packed form from the sparse store, which already holds
        every journaled bit.  Returns True if there was anything to
        fold."""
        with self._lock:
            if not self._journal:
                return False
            self._fold_journal_locked()
            return True

    def device(self, target=None):
        """The HBM-resident mirror (uploads if stale).  This is the query
        hot path's input — equivalent to the mmap'd storage the reference
        queries against (fragment.go:311).

        ``target``: an optional jax Device to place the mirror on.  Mesh
        executors pass a device from their own mesh when the mesh's platform
        differs from the default backend (e.g. a virtual CPU mesh under a
        TPU default); mirrors are cached per target.  ``None`` stays
        UNCOMMITTED (and is its own cache key) so results can combine freely
        with mesh-sharded arrays — callers on the default platform should
        pass None to share this entry rather than duplicating the upload
        under a concrete-device key.

        Every mirror registers with the fragment's DeviceBudget; under a
        configured limit the LRU mirror is dropped and re-uploaded on next
        use."""
        import jax

        with self._lock:
            if self._device_dirty:
                self._drop_mirrors()
                self._device_dirty = False
            mirror = self._mirrors.get(target)
            key = (id(self), target)
            if mirror is not None and \
                    self._mirror_epoch.get(target, 0) < self.ingest_epoch \
                    and self._journal:
                # ingest delta overlay (docs/ingest.md): OR the journal
                # chunks this mirror hasn't seen into it ON DEVICE — a
                # flush's worth of words travels instead of the whole
                # dense tensor
                from ..ingest.delta import apply_overlay, merge_chunks
                chunks = self.delta_chunks(self._mirror_epoch.get(target, 0))
                didx, dval = merge_chunks(chunks)
                if didx.size:
                    mirror = apply_overlay(mirror, didx, dval, SHARD_WORDS)
                    self._mirrors[target] = mirror
                self._mirror_epoch[target] = self.ingest_epoch
            if mirror is None:
                if self.device_form() == "compressed":
                    # compressed upload: ship the packed container
                    # stream (compressed bytes on the wire) and decode
                    # to the dense mirror ON DEVICE — the host-side
                    # sparse->dense expansion and the dense transfer
                    # both disappear.  The mirror itself is dense (this
                    # per-shard path indexes rows directly), so it
                    # registers at dense bytes like any other mirror;
                    # compressed RESIDENCY lives on the mesh path
                    # (parallel/mesh_exec.py), which keeps the packed
                    # stream itself as the resident form.
                    from ..ops.containers import upload_decode
                    mirror = upload_decode(self.packed_host(),
                                           self._cap_rows, target)
                else:
                    mirror = jax.device_put(self.staged_dense(), target)
                self._mirrors[target] = mirror
                # fresh uploads stage from the sparse store, which holds
                # every journaled bit already
                self._mirror_epoch[target] = self.ingest_epoch
                self.budget.register(
                    key, self._cap_rows * SHARD_WORDS * 4,
                    lambda t=target: self._evict_mirror(t))
            else:
                self.budget.touch(key)
            return mirror

    def _evict_mirror(self, target):
        # budget eviction callback: drop our reference only (in-flight
        # computations keep theirs)
        self._mirrors.pop(target, None)

    def _drop_mirrors(self):
        for target in list(self._mirrors):
            self.budget.unregister((id(self), target))
        self._mirrors.clear()

    # -- anti-entropy block checksums (fragment.go:1778 Blocks) ------------

    def blocks(self) -> dict[int, bytes]:
        """Checksum per HASH_BLOCK_SIZE-row block of non-empty rows."""
        out = {}
        with self._lock:
            if self._idx.size == 0:
                return out
            block_of = self._idx // (HASH_BLOCK_SIZE * SHARD_WORDS)
            for blk_id in np.unique(block_of):
                blk = self._dense_block(int(blk_id))
                out[int(blk_id)] = hashlib.blake2b(
                    blk.tobytes(), digest_size=16).digest()
        return out

    def _dense_block(self, block_id: int) -> np.ndarray:
        """Dense HASH_BLOCK_SIZE-row block (padded, digest-stable)."""
        base = block_id * HASH_BLOCK_SIZE * SHARD_WORDS
        a = np.searchsorted(self._idx, base)
        b = np.searchsorted(self._idx, base + HASH_BLOCK_SIZE * SHARD_WORDS)
        blk = np.zeros((HASH_BLOCK_SIZE, SHARD_WORDS), dtype=np.uint32)
        if b > a:
            blk.reshape(-1)[self._idx[a:b] - base] = self._val[a:b]
        return blk

    def block_data(self, block_id: int) -> tuple[np.ndarray, np.ndarray]:
        """(rows, cols) pairs of one block (fragment.go:1859 blockData)."""
        with self._lock:
            start = block_id * HASH_BLOCK_SIZE
            base = start * SHARD_WORDS
            a = np.searchsorted(self._idx, base)
            b = np.searchsorted(self._idx,
                                base + HASH_BLOCK_SIZE * SHARD_WORDS)
            r, c = _expand_words(self._idx[a:b] - base, self._val[a:b])
            return r + start, c
