"""Fragment: one (field, view, shard) bitmap, host-sparse with dense
device mirrors under an HBM budget.

The reference's fragment (fragment.go:100-159) is an mmap'd roaring file
with an append-only op log and background snapshot rewrites.  Here the
authoritative copy is a SPARSE word store: sorted flat indices
(``row * SHARD_WORDS + word``) with their non-zero uint32 word values —
the in-memory form of the snapshot format itself.  Host memory is
proportional to set bits (a 954-shard index with a few bits per row loads
in megabytes, where a dense ``[rows, 32768]`` tensor per fragment would
need terabytes), replacing roaring's array/run containers as the sparsity
mechanism (roaring/roaring.go:64-69).

The device mirror is materialised DENSE (``uint32[cap_rows, SHARD_WORDS]``)
on first query and stays resident in HBM — dense tiles are what the TPU
bit-kernels operate on (see core.py).  Mirrors register with a
DeviceBudget: under a configured limit the least-recently-used mirrors are
evicted and transparently re-uploaded on next use (the HBM analog of the
reference's mmap paging + syswrap map caps, syswrap/mmap.go:46).

Container-tile block-sparsity on the DEVICE (uploading only non-empty
2048-word tiles plus a key table) was considered and deferred: with
uniformly sparse data every tile is non-empty (a 0.1%-density row still
touches every container), the roaring array-container win only appears
under heavy clustering, and tile gather/scatter puts a data-dependent
indirection on the hot path that XLA cannot fuse.  The budget + eviction
path bounds worst-case HBM instead; revisit if profiles show clustered
tiles dominating.

Mutations update the sparse store immediately and append to a write-ahead
op log; snapshots rewrite the on-disk file and truncate the WAL after
``max_op_n`` ops (fragment.go:84 MaxOpN, :2311 snapshot).  Row capacity
grows by doubling so device executable shapes change rarely.
"""

from __future__ import annotations

import hashlib
import itertools
import os
import struct
import threading

import numpy as np

from ..core import (
    DEFAULT_FRAGMENT_MAX_OP_N,
    DEFAULT_MAX_ROW_ID,
    HASH_BLOCK_SIZE,
    SHARD_WIDTH,
    SHARD_WORDS,
)
from ..ops import bitset, bsi
from ..utils.durable import durable_replace, fsync_file
from ..utils.faults import FAULTS
from .membudget import DEFAULT_BUDGET, HOST_STAGE_BUDGET

# On-disk snapshot formats.
# v2 (magic PTPUFRG2): header then nnz LE (flat u32, word u32) interleaved
# pairs — read-compatible.
# v3 (magic PTPUFRG3): header then nnz LE u64 flat indices, then nnz LE u32
# words — supports tall sparse fragments whose flat index exceeds u32.
_MAGIC_V2 = b"PTPUFRG2"
_MAGIC_V3 = b"PTPUFRG3"
_HEADER = struct.Struct("<8sIIQ")

# WAL record: op(u8) row(i64) col(i64)  (roaring.go:4359 opType add/remove;
# batch ops are written as runs of single records).
_OP = struct.Struct("<Bqq")
_OP_SET, _OP_CLEAR = 0, 1
# numpy view of the same record layout for vectorized batch serialization
# (a 1M-bit import must not do 1M struct.packs in a Python loop)
_OP_DTYPE = np.dtype([("op", "u1"), ("row", "<i8"), ("col", "<i8")])
assert _OP_DTYPE.itemsize == _OP.size

_MIN_ROWS = 4


def _pairs_to_words(rows: np.ndarray, cols: np.ndarray):
    """Aggregate (row, col) bit pairs into unique sorted flat word indices
    + OR-combined word values."""
    flat = rows.astype(np.int64) * SHARD_WORDS + (cols >> 5)
    bit = (np.uint32(1) << (cols & 31).astype(np.uint32))
    uniq, inv = np.unique(flat, return_inverse=True)
    out = np.zeros(uniq.size, dtype=np.uint32)
    np.bitwise_or.at(out, inv, bit)
    return uniq, out


def _expand_words(idx: np.ndarray, val: np.ndarray):
    """Inverse of _pairs_to_words: (rows, shard-local cols) of every set
    bit, ordered by (row, col)."""
    rows_out, cols_out = [], []
    for b in range(32):
        sel = (val >> np.uint32(b)) & np.uint32(1) > 0
        if sel.any():
            f = idx[sel]
            rows_out.append(f // SHARD_WORDS)
            cols_out.append((f % SHARD_WORDS) * 32 + b)
    if not rows_out:
        return (np.zeros(0, dtype=np.int64), np.zeros(0, dtype=np.int64))
    rows = np.concatenate(rows_out)
    cols = np.concatenate(cols_out)
    order = np.lexsort((cols, rows))
    return rows[order], cols[order]


class Fragment:
    """One (index, field, view, shard) bitmap."""

    def __init__(self, path: str | None, index: str, field: str, view: str,
                 shard: int, max_op_n: int = DEFAULT_FRAGMENT_MAX_OP_N,
                 row_id_cap: int | None = None, budget=None):
        self.path = path  # None = purely in-memory (tests)
        self.index = index
        self.field = field
        self.view = view
        self.shard = shard
        self.max_op_n = max_op_n
        # Guard against hostile row ids forcing terabyte-scale dense
        # allocations (core.DEFAULT_MAX_ROW_ID); threaded per-instance from
        # the server config (Holder -> Index -> Field -> View) so multiple
        # servers in one process keep independent caps.
        if row_id_cap is not None:
            self.row_id_cap = row_id_cap
        self.budget = budget if budget is not None else DEFAULT_BUDGET

        # sparse word store: sorted flat indices + non-zero word values
        self._idx = np.zeros(0, dtype=np.int64)
        self._val = np.zeros(0, dtype=np.uint32)
        self._cap_rows = 0        # device-shape row capacity (pow2 growth)
        self._mirrors = {}        # device -> cached jax.Array mirror
        # Data-generation stamp: unique across all fragments and bumped on
        # every mutation.  Derived caches (mesh stacked blocks) key their
        # validity on this instead of mirror identity, so they need not pin
        # mirrors alive (and a recreated fragment can never alias a stale
        # cache entry).
        self.gen = next(self._GEN)
        # Per-fragment rank cache (cache/rank.py RankCache), attached by
        # the owning View for fields with cacheType ranked/lru; None for
        # cacheType none, BSI views, and bare test fragments.  Maintained
        # incrementally by the mutators below via _note_rank /
        # _rank_invalidate.
        self.rank_cache = None
        # host-side dense staging cache: (gen, dense block) — see
        # staged_dense()
        self._stage = None
        self._device_dirty = True
        self._op_n = 0
        self._dirty_data = False  # mutated since last snapshot?
        self._wal_file = None
        self._lock = threading.RLock()

        if path is not None:
            self._open_storage()

    # -- lifecycle ---------------------------------------------------------

    def _wal_path(self) -> str:
        return (self.path or "<memory>") + ".wal"

    def _open_storage(self):
        """Load snapshot + replay WAL (fragment.go:311 openStorage)."""
        os.makedirs(os.path.dirname(self.path), exist_ok=True)
        if os.path.exists(self.path):
            with open(self.path, "rb") as f:
                magic, n_rows, words, nnz = _HEADER.unpack(
                    f.read(_HEADER.size))
                if magic not in (_MAGIC_V2, _MAGIC_V3):
                    raise ValueError(
                        f"bad fragment file magic in {self.path}")
                if words != SHARD_WORDS:
                    raise ValueError(
                        f"fragment file {self.path} has {words} words/row, "
                        f"expected {SHARD_WORDS}")
                # Row capacity doubles, so a legitimately-written snapshot
                # never declares more than 2*(cap+1) rows; beyond that the
                # header is corrupt or was written under a larger
                # max_row_id config.
                if n_rows > 2 * (self.row_id_cap + 1):
                    raise ValueError(
                        f"fragment file {self.path} declares {n_rows} rows, "
                        f"above the configured max_row_id "
                        f"{self.row_id_cap}; raise max_row_id if this data "
                        f"was written with a larger cap")
                if magic == _MAGIC_V2:
                    pairs = np.fromfile(f, dtype="<u4", count=2 * nnz)
                    self._idx = pairs[0::2].astype(np.int64)
                    self._val = pairs[1::2].astype(np.uint32)
                else:
                    self._idx = np.fromfile(f, dtype="<u8",
                                            count=nnz).astype(np.int64)
                    self._val = np.fromfile(f, dtype="<u4", count=nnz)
            keep = self._val != 0
            if not keep.all():
                self._idx, self._val = self._idx[keep], self._val[keep]
            self._cap_rows = n_rows
        if os.path.exists(self._wal_path()):
            with open(self._wal_path(), "rb") as f:
                buf = f.read()
            self._replay_wal(buf)
            self._op_n = len(buf) // _OP.size
        self._wal_file = open(self._wal_path(), "ab", buffering=0)

    def _replay_wal(self, buf: bytes):
        """Apply WAL records in order, batching consecutive same-op runs.
        Corrupt records (unknown op, out-of-range row/col) raise ValueError
        rather than silently mis-importing; a trailing partial record (torn
        write on crash) is dropped."""
        n = len(buf) - len(buf) % _OP.size
        run_op, run_rows, run_cols = None, [], []

        def flush():
            nonlocal run_rows, run_cols
            if not run_rows:
                return
            rows = np.asarray(run_rows, dtype=np.int64)
            cols = np.asarray(run_cols, dtype=np.int64)
            try:
                self._apply_bits(rows, cols, clear=(run_op == _OP_CLEAR))
            except ValueError as e:
                raise ValueError(
                    f"replaying WAL {self._wal_path()}: {e}; raise "
                    f"max_row_id if this data was written with a larger "
                    f"cap") from e
            run_rows, run_cols = [], []

        for off in range(0, n, _OP.size):
            op, row, col = _OP.unpack_from(buf, off)
            if op not in (_OP_SET, _OP_CLEAR):
                raise ValueError(
                    f"corrupt WAL {self._wal_path()}: unknown op {op} at "
                    f"byte {off}")
            if row < 0 or col < 0 or col >= SHARD_WIDTH:
                raise ValueError(
                    f"corrupt WAL {self._wal_path()}: record ({row}, {col}) "
                    f"out of range at byte {off}")
            if op != run_op:
                flush()
                run_op = op
            run_rows.append(row)
            run_cols.append(col)
        flush()

    def close(self):
        with self._lock:
            if self._wal_file is not None:
                if self._dirty_data or self._op_n:
                    self.snapshot()
                self._wal_file.close()
                self._wal_file = None
            self._drop_mirrors()
            self._drop_stage()

    def snapshot(self):
        """Rewrite the snapshot file and truncate the WAL
        (fragment.go:2311 snapshot)."""
        with self._lock:
            if self.path is None:
                self._op_n = 0
                return
            tmp = self.path + ".snapshotting"
            FAULTS.hit("fragment.snapshot", key=self.path)
            with open(tmp, "wb") as f:
                f.write(_HEADER.pack(_MAGIC_V3, self._cap_rows, SHARD_WORDS,
                                     self._idx.size))
                self._idx.astype("<u8").tofile(f)
                self._val.astype("<u4").tofile(f)
                # fsync BEFORE the rename: tofile lands in the page cache,
                # and a crash after os.replace would otherwise lose an
                # acknowledged snapshot (the WAL it replaced is truncated)
                fsync_file(f)
            durable_replace(tmp, self.path)
            self._dirty_data = False
            if self._wal_file is not None:
                self._wal_file.close()
            self._wal_file = open(self._wal_path(), "wb", buffering=0)
            self._op_n = 0

    # -- geometry ----------------------------------------------------------

    @property
    def n_rows(self) -> int:
        """Device-shape row capacity (doubling growth)."""
        return self._cap_rows

    def max_row_id(self) -> int:
        """Highest row with any bit set (fragment.go maxRow)."""
        return int(self._idx[-1] // SHARD_WORDS) if self._idx.size else 0

    def host_bytes(self) -> int:
        """Host memory held by the sparse store."""
        return int(self._idx.nbytes + self._val.nbytes)

    # Default cap when none is threaded in (class fallback keeps in-memory
    # test fragments working without plumbing).
    row_id_cap = DEFAULT_MAX_ROW_ID

    def _ensure_rows(self, row_id: int):
        if row_id < self._cap_rows:
            return
        if row_id > self.row_id_cap:
            raise ValueError(
                f"row id {row_id} exceeds the configured maximum "
                f"{self.row_id_cap} (max_row_id)")
        new_rows = max(_MIN_ROWS, self._cap_rows)
        while new_rows <= row_id:
            new_rows *= 2
        self._cap_rows = new_rows
        self._mark_device_dirty()

    _GEN = itertools.count(1)

    def _mark_device_dirty(self):
        self._device_dirty = True
        self._dirty_data = True
        self.gen = next(self._GEN)

    def _note_rank(self, rows):
        """Incremental rank-cache maintenance after a successful mutation
        touching ``rows`` (called under self._lock)."""
        if self.rank_cache is not None:
            self.rank_cache.note_write(self, rows)

    def _rank_invalidate(self):
        """Bulk mutation whose touched rows aren't cheaply known (row
        stores, mutex imports): rebuild the rank cache lazily."""
        if self.rank_cache is not None:
            self.rank_cache.invalidate()

    # -- sparse store primitives -------------------------------------------

    def _locate(self, nidx: np.ndarray):
        """(positions, exists-mask) of nidx in the store."""
        pos = np.searchsorted(self._idx, nidx)
        if self._idx.size:
            exists = (pos < self._idx.size) & \
                (self._idx[np.minimum(pos, self._idx.size - 1)] == nidx)
        else:
            exists = np.zeros(nidx.shape, dtype=bool)
        return pos, exists

    def _or_words(self, nidx: np.ndarray, nval: np.ndarray) -> int:
        """OR word values into the store; returns changed-bit count."""
        pos, exists = self._locate(nidx)
        changed = 0
        upd = pos[exists]
        if upd.size:
            old = self._val[upd]
            new = old | nval[exists]
            changed += int(np.bitwise_count(new & ~old).sum())
            self._val[upd] = new
        ins = ~exists
        if ins.any():
            changed += int(np.bitwise_count(nval[ins]).sum())
            self._idx = np.insert(self._idx, pos[ins], nidx[ins])
            self._val = np.insert(self._val, pos[ins], nval[ins])
        return changed

    def _andnot_words(self, nidx: np.ndarray, nval: np.ndarray) -> int:
        """Clear word bits; returns changed-bit count."""
        pos, exists = self._locate(nidx)
        upd = pos[exists]
        if not upd.size:
            return 0
        old = self._val[upd]
        new = old & ~nval[exists]
        changed = int(np.bitwise_count(old & ~new).sum())
        if changed:
            self._val[upd] = new
            keep = self._val != 0
            if not keep.all():
                self._idx, self._val = self._idx[keep], self._val[keep]
        return changed

    def _apply_bits(self, rows, cols, clear: bool) -> int:
        if rows.size == 0:
            return 0
        if clear:
            # Rows at/above capacity cannot hold set bits: drop them rather
            # than growing capacity (which would change the device tensor
            # shape and force a recompile for a guaranteed no-op), and never
            # raise on row ids beyond the cap — clearing them is a no-op.
            keep = rows < self._cap_rows
            if not keep.all():
                rows, cols = rows[keep], cols[keep]
            if rows.size == 0:
                return 0
        else:
            self._ensure_rows(int(rows.max()))
        nidx, nval = _pairs_to_words(rows, cols)
        n = self._andnot_words(nidx, nval) if clear \
            else self._or_words(nidx, nval)
        if n:
            self._mark_device_dirty()
        return n

    def _delete_range(self, lo: int, hi: int):
        """Remove stored words with lo <= flat < hi."""
        a = np.searchsorted(self._idx, lo)
        b = np.searchsorted(self._idx, hi)
        if b > a:
            self._idx = np.delete(self._idx, slice(a, b))
            self._val = np.delete(self._val, slice(a, b))

    def _column_mask_clear(self, cols: np.ndarray, max_row=None) -> int:
        """AND-out the given shard-local columns' bits from every stored
        word (optionally only rows < max_row); returns changed bits."""
        if self._idx.size == 0 or cols.size == 0:
            return 0
        w, bit = bitset.word_bit_np(cols)
        mask = np.zeros(SHARD_WORDS, dtype=np.uint32)
        np.bitwise_or.at(mask, w, bit)
        w_of = (self._idx % SHARD_WORDS).astype(np.int64)
        sel = mask[w_of] != 0
        if max_row is not None:
            sel &= (self._idx // SHARD_WORDS) < max_row
        if not sel.any():
            return 0
        old = self._val[sel]
        new = old & ~mask[w_of[sel]]
        changed = int(np.bitwise_count(old & ~new).sum())
        if changed:
            self._val[sel] = new
            keep = self._val != 0
            if not keep.all():
                self._idx, self._val = self._idx[keep], self._val[keep]
        return changed

    # -- mutation ----------------------------------------------------------

    def _log_op(self, op: int, row: int, col: int):
        if self._wal_file is not None:
            FAULTS.hit("fragment.wal", key=self.path or "")
            self._wal_file.write(_OP.pack(op, row, col))
        self._op_n += 1
        if self._op_n >= self.max_op_n:
            if self._wal_file is not None:
                self._wal_file.flush()
            self.snapshot()

    def _log_ops(self, op: int, rows: np.ndarray, cols: np.ndarray):
        """Vectorized batch append: one record-array build + one write."""
        if self._wal_file is not None:
            FAULTS.hit("fragment.wal", key=self.path or "")
            recs = np.empty(rows.size, dtype=_OP_DTYPE)
            recs["op"] = op
            recs["row"] = rows
            recs["col"] = cols
            self._wal_file.write(recs.tobytes())
        self._op_n += rows.size
        if self._op_n >= self.max_op_n:
            self.snapshot()

    def set_bit(self, row: int, col: int) -> bool:
        """Set one bit; col is shard-local.  Returns True if changed
        (fragment.go:647 setBit)."""
        with self._lock:
            changed = self._apply_bits(np.asarray([row], dtype=np.int64),
                                       np.asarray([col], dtype=np.int64),
                                       clear=False) > 0
            if changed:
                self._note_rank([row])
                self._log_op(_OP_SET, row, col)
            return changed

    def clear_bit(self, row: int, col: int) -> bool:
        with self._lock:
            changed = self._apply_bits(np.asarray([row], dtype=np.int64),
                                       np.asarray([col], dtype=np.int64),
                                       clear=True) > 0
            if changed:
                self._note_rank([row])
                self._log_op(_OP_CLEAR, row, col)
            return changed

    def bulk_import(self, rows: np.ndarray, cols: np.ndarray,
                    clear: bool = False) -> int:
        """Batched import of shard-local (row, col) bits
        (fragment.go:1997 bulkImport / 2053 importPositions).  Returns the
        number of changed bits."""
        rows = np.asarray(rows, dtype=np.int64)
        cols = np.asarray(cols, dtype=np.int64)
        if rows.size == 0:
            return 0
        with self._lock:
            n_changed = self._apply_bits(rows, cols, clear=clear)
            if n_changed:
                self._note_rank(rows)
                self._log_ops(_OP_CLEAR if clear else _OP_SET, rows, cols)
            return n_changed

    def mutex_import(self, rows: np.ndarray, cols: np.ndarray) -> int:
        """Batched import with mutex semantics: at most one row per column,
        last write in the batch wins (fragment.go:2106 bulkImportMutex).
        Returns changed-bit count."""
        rows = np.asarray(rows, dtype=np.int64)
        cols = np.asarray(cols, dtype=np.int64)
        if rows.size == 0:
            return 0
        # keep the last occurrence of each column
        last = {}
        for i in range(rows.size):
            last[int(cols[i])] = int(rows[i])
        ucols = np.fromiter(last.keys(), dtype=np.int64, count=len(last))
        urow = np.fromiter(last.values(), dtype=np.int64, count=len(last))
        with self._lock:
            self._ensure_rows(int(urow.max()))
            # Winner bits already set are cleared by _column_mask_clear and
            # re-set by _apply_bits; they are no-ops and must not count
            # (fragment.go:2106 bulkImportMutex reports real changes only).
            nidx, nval = _pairs_to_words(urow, ucols)
            pos, exists = self._locate(nidx)
            pre_winner = int(np.bitwise_count(
                self._val[pos[exists]] & nval[exists]).sum())
            gen0, dev_dirty0, data_dirty0 = \
                self.gen, self._device_dirty, self._dirty_data
            cleared = self._column_mask_clear(ucols)
            set_changed = self._apply_bits(urow, ucols, clear=False)
            n_changed = cleared + set_changed - 2 * pre_winner
            if n_changed:
                self._rank_invalidate()  # cleared rows aren't enumerated
                self._mark_device_dirty()
                if self._wal_file is not None:
                    self.snapshot()
            else:
                # idempotent re-import: the store's final state equals its
                # initial state — restore the stamps so downstream caches
                # (device mirrors, mesh stacks) are not invalidated
                self.gen = gen0
                self._device_dirty = dev_dirty0
                self._dirty_data = data_dirty0
            return n_changed

    def set_row(self, row: int, seg: np.ndarray | None):
        """Replace an entire row's bits (Store/SetRow, fragment.go setRow)."""
        with self._lock:
            self._ensure_rows(row)
            base = row * SHARD_WORDS
            self._delete_range(base, base + SHARD_WORDS)
            if seg is not None:
                seg = np.asarray(seg, dtype=np.uint32)
                nz = np.nonzero(seg)[0]
                if nz.size:
                    self._or_words(base + nz.astype(np.int64), seg[nz])
            self._note_rank([row])
            self._mark_device_dirty()
            self.snapshot()  # row stores bypass the op log

    # -- BSI mutation (int fields) ----------------------------------------

    def bit_depth(self) -> int:
        return max(0, self._cap_rows - bsi.OFFSET_ROW)

    def set_value(self, col: int, bit_depth: int, value: int) -> bool:
        """Set a column's integer value (fragment.go:977 setValueBase).
        Grows depth rows as needed; clears stale magnitude bits.  Only the
        bits that actually change are applied AND logged — the old
        log-everything-on-any-change scheme bloated the WAL toward
        premature snapshots (r3 verdict)."""
        with self._lock:
            self._ensure_rows(bsi.OFFSET_ROW + bit_depth - 1)
            mag = abs(value)
            want = {bsi.EXISTS_ROW}
            for i in range(bit_depth):
                if (mag >> i) & 1:
                    want.add(bsi.OFFSET_ROW + i)
            if value < 0:
                want.add(bsi.SIGN_ROW)
            managed = sorted({bsi.EXISTS_ROW, bsi.SIGN_ROW} | {
                bsi.OFFSET_ROW + i for i in range(bit_depth)})
            # targeted probe of only the managed rows' words — NOT a full
            # rows_with_bit scan (O(log nnz) per row vs O(nnz) per write)
            mrows = np.asarray(managed, dtype=np.int64)
            w = col >> 5
            bit = np.uint32(1 << (col & 31))
            pos, exists = self._locate(mrows * SHARD_WORDS + w)
            has = np.zeros(mrows.size, dtype=bool)
            has[exists] = (self._val[pos[exists]] & bit) > 0
            cur = {int(r) for r, h in zip(mrows, has) if h}
            to_set = sorted(want - cur)
            to_clear = sorted(cur - want)
            if to_set:
                rows = np.asarray(to_set, dtype=np.int64)
                cols = np.full(rows.size, col, dtype=np.int64)
                self._apply_bits(rows, cols, clear=False)
                self._log_ops(_OP_SET, rows, cols)
            if to_clear:
                rows = np.asarray(to_clear, dtype=np.int64)
                cols = np.full(rows.size, col, dtype=np.int64)
                self._apply_bits(rows, cols, clear=True)
                self._log_ops(_OP_CLEAR, rows, cols)
            return bool(to_set or to_clear)

    def import_values(self, cols: np.ndarray, values: np.ndarray,
                      bit_depth: int) -> None:
        """Batched setValue (fragment.go:2205 importValue)."""
        cols = np.asarray(cols, dtype=np.int64)
        values = np.asarray(values, dtype=np.int64)
        with self._lock:
            self._ensure_rows(bsi.OFFSET_ROW + bit_depth - 1)
            # clear all target columns' bits first (stale values)
            self._column_mask_clear(cols, max_row=bsi.OFFSET_ROW + bit_depth)
            packed = bsi.pack_values(cols, values, depth=bit_depth,
                                     words=SHARD_WORDS)
            flat = packed.reshape(-1)
            nz = np.nonzero(flat)[0]
            if nz.size:
                self._or_words(nz.astype(np.int64), flat[nz])
            self._mark_device_dirty()
            self.snapshot()

    def clear_values(self, cols: np.ndarray) -> None:
        """Remove columns' values entirely (exists+sign+magnitude cleared) —
        the clear half of importValue (fragment.go:2205 importValue with
        clear)."""
        cols = np.asarray(cols, dtype=np.int64)
        if cols.size == 0 or self._idx.size == 0:
            return
        with self._lock:
            if self._column_mask_clear(cols):
                self._mark_device_dirty()
            self.snapshot()

    # -- reads -------------------------------------------------------------

    def row(self, row_id: int) -> np.ndarray:
        """Host copy of one row's segment (fragment.go:602 row)."""
        with self._lock:
            out = np.zeros(SHARD_WORDS, dtype=np.uint32)
            if row_id >= self._cap_rows:
                return out
            base = row_id * SHARD_WORDS
            a = np.searchsorted(self._idx, base)
            b = np.searchsorted(self._idx, base + SHARD_WORDS)
            if b > a:
                out[self._idx[a:b] - base] = self._val[a:b]
            return out

    def row_columns(self, row_id: int) -> np.ndarray:
        return bitset.unpack_columns(self.row(row_id))

    def rows_with_bit(self, col: int) -> np.ndarray:
        """Sorted row ids whose bit at shard-local ``col`` is set (the
        column read under mutex/bool semantics and BSI value())."""
        with self._lock:
            if self._idx.size == 0:
                return np.zeros(0, dtype=np.int64)
            w = col >> 5
            bit = np.uint32(1 << (col & 31))
            sel = (self._idx % SHARD_WORDS == w) & (self._val & bit > 0)
            return (self._idx[sel] // SHARD_WORDS).astype(np.int64)

    def row_counts_host(self, rows: np.ndarray) -> np.ndarray:
        """Exact per-row set-bit counts for the given rows, from the host
        sparse store (no device touch).  Popcounts only each requested
        row's word range (O(log nnz) locate + O(row words) per row) —
        this runs on EVERY single-bit write of a rank-cached field, so a
        whole-store scan here would make writes O(nnz)."""
        rows = np.asarray(rows, dtype=np.int64)
        with self._lock:
            out = np.zeros(rows.size, dtype=np.int64)
            if self._idx.size == 0 or rows.size == 0:
                return out
            a = np.searchsorted(self._idx, rows * SHARD_WORDS)
            b = np.searchsorted(self._idx, (rows + 1) * SHARD_WORDS)
            for i in range(rows.size):
                if b[i] > a[i]:
                    out[i] = int(np.bitwise_count(
                        self._val[a[i]: b[i]]).sum())
            return out

    def row_counts_all_host(self) -> tuple[np.ndarray, np.ndarray]:
        """(row ids, exact counts) of every row with any set bit, from the
        host sparse store — the rank-cache rebuild scan (O(nnz))."""
        with self._lock:
            if self._idx.size == 0:
                z = np.zeros(0, dtype=np.int64)
                return z, z
            rows_of = self._idx // SHARD_WORDS
            pops = np.bitwise_count(self._val).astype(np.int64)
            uniq, start = np.unique(rows_of, return_index=True)
            return uniq, np.add.reduceat(pops, start)

    def pairs(self) -> tuple[np.ndarray, np.ndarray]:
        """(rows, shard-local cols) of every set bit, (row, col)-ordered —
        the export/iteration surface (fragment.go:2771 rowIterator)."""
        with self._lock:
            return _expand_words(self._idx, self._val)

    def to_dense(self) -> np.ndarray:
        """Materialise the dense [cap_rows, SHARD_WORDS] tensor (device
        upload + compatibility paths).  O(cap_rows x 128KB) — transient."""
        with self._lock:
            out = np.zeros((self._cap_rows, SHARD_WORDS), dtype=np.uint32)
            if self._idx.size:
                out.reshape(-1)[self._idx] = self._val
            return out

    @property
    def words(self) -> np.ndarray:
        """Dense view for compatibility/oracle paths; materialises on each
        access — do not use on hot paths."""
        return self.to_dense()

    def staged_dense(self) -> np.ndarray:
        """Dense block via the host staging cache.  After an HBM eviction
        the re-upload reads this cached expansion instead of re-running
        the sparse->dense scatter — under budget pressure the expansion,
        not the transfer, dominates cold re-stages.  Keyed by the data
        generation (any mutation invalidates); HOST_STAGE_BUDGET bounds
        total cached host bytes LRU-wise (limit 0 disables caching).
        With no device-budget limit nothing is ever evicted, so there is
        no re-upload to accelerate — caching would only grow host RSS —
        and the expansion stays transient like to_dense().

        The returned array is SHARED — callers must treat it read-only
        (device uploads and stacked-block fills copy out of it)."""
        if HOST_STAGE_BUDGET.limit_bytes == 0 or \
                self.budget.limit_bytes is None:
            return self.to_dense()
        with self._lock:
            st = self._stage
            if st is not None and st[0] == self.gen:
                HOST_STAGE_BUDGET.touch(("stage", id(self)))
                return st[1]
            dense = self.to_dense()
            self._stage = (self.gen, dense)
            HOST_STAGE_BUDGET.register(("stage", id(self)), dense.nbytes,
                                       self._evict_stage)
            return dense

    def _evict_stage(self):
        # host-stage budget callback: drop the cached expansion only
        self._stage = None

    def _drop_stage(self):
        HOST_STAGE_BUDGET.unregister(("stage", id(self)))
        self._stage = None

    def device(self, target=None):
        """The HBM-resident mirror (uploads if stale).  This is the query
        hot path's input — equivalent to the mmap'd storage the reference
        queries against (fragment.go:311).

        ``target``: an optional jax Device to place the mirror on.  Mesh
        executors pass a device from their own mesh when the mesh's platform
        differs from the default backend (e.g. a virtual CPU mesh under a
        TPU default); mirrors are cached per target.  ``None`` stays
        UNCOMMITTED (and is its own cache key) so results can combine freely
        with mesh-sharded arrays — callers on the default platform should
        pass None to share this entry rather than duplicating the upload
        under a concrete-device key.

        Every mirror registers with the fragment's DeviceBudget; under a
        configured limit the LRU mirror is dropped and re-uploaded on next
        use."""
        import jax

        with self._lock:
            if self._device_dirty:
                self._drop_mirrors()
                self._device_dirty = False
            mirror = self._mirrors.get(target)
            key = (id(self), target)
            if mirror is None:
                mirror = jax.device_put(self.staged_dense(), target)
                self._mirrors[target] = mirror
                self.budget.register(
                    key, self._cap_rows * SHARD_WORDS * 4,
                    lambda t=target: self._evict_mirror(t))
            else:
                self.budget.touch(key)
            return mirror

    def _evict_mirror(self, target):
        # budget eviction callback: drop our reference only (in-flight
        # computations keep theirs)
        self._mirrors.pop(target, None)

    def _drop_mirrors(self):
        for target in list(self._mirrors):
            self.budget.unregister((id(self), target))
        self._mirrors.clear()

    # -- anti-entropy block checksums (fragment.go:1778 Blocks) ------------

    def blocks(self) -> dict[int, bytes]:
        """Checksum per HASH_BLOCK_SIZE-row block of non-empty rows."""
        out = {}
        with self._lock:
            if self._idx.size == 0:
                return out
            block_of = self._idx // (HASH_BLOCK_SIZE * SHARD_WORDS)
            for blk_id in np.unique(block_of):
                blk = self._dense_block(int(blk_id))
                out[int(blk_id)] = hashlib.blake2b(
                    blk.tobytes(), digest_size=16).digest()
        return out

    def _dense_block(self, block_id: int) -> np.ndarray:
        """Dense HASH_BLOCK_SIZE-row block (padded, digest-stable)."""
        base = block_id * HASH_BLOCK_SIZE * SHARD_WORDS
        a = np.searchsorted(self._idx, base)
        b = np.searchsorted(self._idx, base + HASH_BLOCK_SIZE * SHARD_WORDS)
        blk = np.zeros((HASH_BLOCK_SIZE, SHARD_WORDS), dtype=np.uint32)
        if b > a:
            blk.reshape(-1)[self._idx[a:b] - base] = self._val[a:b]
        return blk

    def block_data(self, block_id: int) -> tuple[np.ndarray, np.ndarray]:
        """(rows, cols) pairs of one block (fragment.go:1859 blockData)."""
        with self._lock:
            start = block_id * HASH_BLOCK_SIZE
            base = start * SHARD_WORDS
            a = np.searchsorted(self._idx, base)
            b = np.searchsorted(self._idx,
                                base + HASH_BLOCK_SIZE * SHARD_WORDS)
            r, c = _expand_words(self._idx[a:b] - base, self._val[a:b])
            return r + start, c
