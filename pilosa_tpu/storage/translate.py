"""Key translation: string key <-> uint64 id stores (translate.go:35-70
TranslateStore interface, :195-381 in-memory implementation,
boltdb/translate.go:48-397 persistent store).

A store maps string keys to sequentially-allocated ids starting at 1.
``translate_key`` auto-creates missing keys — exactly like the reference's
``TranslateKey`` — so reads of unknown keys produce fresh (empty) ids
rather than errors.  Persistence is an append-only log of key records;
the id IS the record's ordinal, so replay rebuilds both directions.

Cluster note: the reference writes keys on the primary only and streams
the log to replicas (holder.go:812 holderTranslateStoreReplicator).  The
TPU-native cluster routes translation to the coordinator via
RemoteTranslateStore (parallel/cluster.py) with a read-through cache —
lazy replication over the same internal RPC plane.
"""

from __future__ import annotations

import os
import struct

from ..utils.locks import make_rlock

_REC = struct.Struct("<I")  # key byte-length; key bytes follow


class TranslateStore:
    """In-memory bidirectional map + append-only log file."""

    def __init__(self, path: str | None):
        self.path = path
        self._key_to_id: dict[str, int] = {}
        self._id_to_key: dict[int, str] = {}
        self._file = None
        self._lock = make_rlock("translate")
        if path is not None:
            self._open()

    def _open(self):
        os.makedirs(os.path.dirname(self.path), exist_ok=True)
        if os.path.exists(self.path):
            with open(self.path, "rb") as f:
                buf = f.read()
            off = 0
            while off + _REC.size <= len(buf):
                (klen,) = _REC.unpack_from(buf, off)
                off += _REC.size
                if off + klen > len(buf):
                    break  # truncated tail record (partial write) — drop
                key = buf[off:off + klen].decode("utf-8", errors="replace")
                off += klen
                self._append_mem(key)
        self._file = open(self.path, "ab", buffering=0)

    def close(self):
        with self._lock:
            if self._file is not None:
                self._file.close()
                self._file = None

    def _append_mem(self, key: str) -> int:
        new_id = len(self._key_to_id) + 1
        self._key_to_id[key] = new_id
        self._id_to_key[new_id] = key
        return new_id

    def __len__(self) -> int:
        return len(self._key_to_id)

    # -- the TranslateStore interface (translate.go:35) --------------------

    def translate_key(self, key: str) -> int:
        """key -> id, creating if missing (translate.go TranslateKey)."""
        with self._lock:
            kid = self._key_to_id.get(key)
            if kid is not None:
                return kid
            kid = self._append_mem(key)
            if self._file is not None:
                data = key.encode()
                self._file.write(_REC.pack(len(data)) + data)
            return kid

    def translate_keys(self, keys) -> list[int]:
        return [self.translate_key(k) for k in keys]

    def translate_id(self, kid: int) -> str | None:
        """id -> key; None when unknown (translate.go TranslateID)."""
        with self._lock:
            return self._id_to_key.get(kid)

    def translate_ids(self, ids) -> list[str | None]:
        with self._lock:
            return [self._id_to_key.get(i) for i in ids]

    def find_key(self, key: str) -> int | None:
        """Lookup without create (used by replicas' read-through cache)."""
        with self._lock:
            return self._key_to_id.get(key)

    # -- replication support (translate.go:82 TranslateEntryReader) --------

    def entries_from(self, after_id: int,
                     limit: int | None = None) -> list[tuple[int, str]]:
        """Up to ``limit`` (id, key) pairs with id > after_id, in order —
        the replication/stream payload (paginated so one request neither
        holds the store lock for a full-table copy nor exceeds a response
        timeout)."""
        with self._lock:
            hi = len(self._id_to_key) + 1
            if limit is not None:
                hi = min(hi, after_id + 1 + limit)
            return [(i, self._id_to_key[i])
                    for i in range(after_id + 1, hi)]
