"""Device-memory budget: LRU accounting of device-resident bytes.

The reference's memory story is mmap + the OS page cache (fragments are
lazily paged, syswrap caps map counts — syswrap/mmap.go:46, fragment.go:311).
On TPU the equivalent scarce resource is HBM: every fragment queried gets a
dense device mirror, and mesh execution additionally keeps stacked shard
blocks resident.  This registry tracks those allocations against a
configurable budget and evicts the least-recently-used entries (dropping
the owner's reference so the buffer frees) when a new allocation would
exceed it.

One process-wide default budget keeps wiring simple (Server config
``device_budget_mb`` / PILOSA_TPU_DEVICE_BUDGET_MB sets it); tests construct
private instances.
"""

from __future__ import annotations

import threading
from collections import OrderedDict
from typing import Callable


class DeviceBudget:
    def __init__(self, limit_bytes: int | None = None):
        self.limit_bytes = limit_bytes  # None = unlimited (accounting only)
        self._entries: OrderedDict[tuple, tuple[int, Callable[[], None]]] = \
            OrderedDict()
        self._total = 0
        self._peak = 0
        self.evictions = 0
        self._lock = threading.RLock()

    @property
    def resident_bytes(self) -> int:
        return self._total

    def _evict_lru_locked(self, incoming: int) -> list[Callable[[], None]]:
        """Pop LRU entries until ``incoming`` more bytes fit the limit;
        returns their callbacks for the caller to run OUTSIDE the lock
        (owners may take their own locks without ordering against this
        one).  Caller must hold self._lock."""
        to_evict: list[Callable[[], None]] = []
        if self.limit_bytes is not None:
            while self._entries and \
                    self._total + incoming > self.limit_bytes:
                _, (freed, cb) = self._entries.popitem(last=False)
                self._total -= freed
                self.evictions += 1
                to_evict.append(cb)
        return to_evict

    @staticmethod
    def _run_evictions(to_evict: list[Callable[[], None]]):
        for cb in to_evict:
            try:
                cb()
            except Exception:
                pass

    def register(self, key: tuple, nbytes: int, evict: Callable[[], None]):
        """Account ``nbytes`` under ``key``; ``evict`` drops the owner's
        reference when called.  Evicts LRU entries first if needed (never
        evicting the incoming entry itself)."""
        with self._lock:
            old = self._entries.pop(key, None)
            if old is not None:
                self._total -= old[0]
            to_evict = self._evict_lru_locked(nbytes)
            self._entries[key] = (nbytes, evict)
            self._total += nbytes
            self._peak = max(self._peak, self._total)
        self._run_evictions(to_evict)

    def reset_peak(self):
        """Restart the high-water mark from the current residency (bench /
        diagnostics epochs; the gauge analog of prometheus' counter
        resets)."""
        with self._lock:
            self._peak = self._total

    def shrink_to_limit(self):
        """Evict LRU entries until residency fits the (possibly just
        lowered) limit — ``register`` only evicts on new allocations, so a
        runtime limit decrease applies lazily without this."""
        with self._lock:
            to_evict = self._evict_lru_locked(0)
        self._run_evictions(to_evict)

    def touch(self, key: tuple):
        with self._lock:
            if key in self._entries:
                self._entries.move_to_end(key)

    def unregister(self, key: tuple):
        with self._lock:
            e = self._entries.pop(key, None)
            if e is not None:
                self._total -= e[0]

    def stats(self) -> dict:
        with self._lock:
            return {
                "residentBytes": self._total,
                "peakBytes": self._peak,
                "limitBytes": self.limit_bytes,
                "entries": len(self._entries),
                "evictions": self.evictions,
            }


# Process-wide default (accounting-only until a limit is configured).
DEFAULT_BUDGET = DeviceBudget()
