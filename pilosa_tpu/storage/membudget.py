"""Device-memory budget: LRU accounting of device-resident bytes.

The reference's memory story is mmap + the OS page cache (fragments are
lazily paged, syswrap caps map counts — syswrap/mmap.go:46, fragment.go:311).
On TPU the equivalent scarce resource is HBM: every fragment queried gets a
dense device mirror, and mesh execution additionally keeps stacked shard
blocks resident.  This registry tracks those allocations against a
configurable budget and evicts the least-recently-used entries (dropping
the owner's reference so the buffer frees) when a new allocation would
exceed it.

One process-wide default budget keeps wiring simple (Server config
``device_budget_mb`` / PILOSA_TPU_DEVICE_BUDGET_MB sets it); tests construct
private instances.
"""

from __future__ import annotations

import threading
from collections import OrderedDict
from typing import Callable


class DeviceBudget:
    def __init__(self, limit_bytes: int | None = None):
        self.limit_bytes = limit_bytes  # None = unlimited (accounting only)
        self._entries: OrderedDict[tuple, tuple[int, Callable[[], None]]] = \
            OrderedDict()
        self._total = 0
        self._peak = 0
        self.evictions = 0
        self._lock = threading.RLock()

    @property
    def resident_bytes(self) -> int:
        return self._total

    def register(self, key: tuple, nbytes: int, evict: Callable[[], None]):
        """Account ``nbytes`` under ``key``; ``evict`` drops the owner's
        reference when called.  Evicts LRU entries first if needed.
        Eviction callbacks run OUTSIDE the budget lock so owners may take
        their own locks without ordering against this one."""
        to_evict: list[Callable[[], None]] = []
        with self._lock:
            old = self._entries.pop(key, None)
            if old is not None:
                self._total -= old[0]
            if self.limit_bytes is not None:
                # evict until the new entry fits (never evicting itself)
                while self._entries and \
                        self._total + nbytes > self.limit_bytes:
                    _, (freed, cb) = self._entries.popitem(last=False)
                    self._total -= freed
                    self.evictions += 1
                    to_evict.append(cb)
            self._entries[key] = (nbytes, evict)
            self._total += nbytes
            self._peak = max(self._peak, self._total)
        for cb in to_evict:
            try:
                cb()
            except Exception:
                pass

    def touch(self, key: tuple):
        with self._lock:
            if key in self._entries:
                self._entries.move_to_end(key)

    def unregister(self, key: tuple):
        with self._lock:
            e = self._entries.pop(key, None)
            if e is not None:
                self._total -= e[0]

    def stats(self) -> dict:
        with self._lock:
            return {
                "residentBytes": self._total,
                "peakBytes": self._peak,
                "limitBytes": self.limit_bytes,
                "entries": len(self._entries),
                "evictions": self.evictions,
            }


# Process-wide default (accounting-only until a limit is configured).
DEFAULT_BUDGET = DeviceBudget()
