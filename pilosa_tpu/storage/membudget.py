"""Device-memory budget: LRU accounting of device-resident bytes, with
pinning for in-flight work.

The reference's memory story is mmap + the OS page cache (fragments are
lazily paged, syswrap caps map counts — syswrap/mmap.go:46, fragment.go:311).
On TPU the equivalent scarce resource is HBM: every fragment queried gets a
dense device mirror, and mesh execution additionally keeps stacked shard
blocks resident.  This registry tracks those allocations against a
configurable budget and evicts the least-recently-used entries (dropping
the owner's reference so the buffer frees) when a new allocation would
exceed it.

Entries referenced by an in-flight plan or a prefetch in progress are
PINNED: eviction skips them (preferring the unpinned-coldest) and a fully
pinned budget admits the incoming entry over-limit rather than dropping a
buffer out from under a dispatch.  The budget also keeps streaming
counters — cumulative upload bytes, prefetch hits/misses, evictions —
surfaced through ``stats()`` at /debug/vars and the runtime gauges.

One process-wide default budget keeps wiring simple (Server config
``device_budget_mb`` / PILOSA_TPU_DEVICE_BUDGET_MB sets it); tests construct
private instances.  ``HOST_STAGE_BUDGET`` is a second instance bounding the
HOST-side dense staging cache (storage/fragment.py staged_dense) with the
same LRU machinery — there "upload bytes" counts staged host bytes.
"""

from __future__ import annotations

from collections import OrderedDict
from typing import Callable

from ..utils import tenant as qtenant
from ..utils.locks import make_rlock


class DeviceBudget:
    def __init__(self, limit_bytes: int | None = None,
                 tenant_quota_bytes: int = 0):
        self.limit_bytes = limit_bytes  # None = unlimited (accounting only)
        # Per-tenant residency cap (``tenant-cache-quota-mb``; 0 = off):
        # a tenant staging past it evicts ITS OWN unpinned-coldest
        # entries, and global pressure prefers over-quota tenants'
        # entries — one index's working set cannot flush the fleet's
        # (docs/robustness.md "Tenant isolation").
        self.tenant_quota_bytes = tenant_quota_bytes
        # key -> [nbytes, evict cb, pin count, compressed bytes, tenant]
        self._entries: OrderedDict[tuple, list] = OrderedDict()
        self._tenant_bytes: dict[str, int] = {}
        self.quota_evictions = 0
        self._total = 0
        self._compressed = 0  # portion of _total held in packed form
        self._peak = 0
        self.evictions = 0
        self.evicted_bytes = 0  # an eviction storm's size, not just count
        self.evict_errors = 0   # callbacks that raised (leaked residency)
        # streaming pipeline counters (parallel/mesh_exec.py): bytes
        # (re-)registered = bytes shipped to the device, and whether a
        # scheduled slice's prefetch completed before the consumer
        # reached it
        self.upload_bytes = 0
        self.prefetch_hits = 0
        self.prefetch_misses = 0
        self._lock = make_rlock("budget")
        # last eviction-pressure event (monotonic): one journal entry
        # per PRESSURE_EVENT_MIN_S under sustained thrash, not one per
        # make-room pass
        self._pressure_emitted_at: float | None = None

    # One make-room pass evicting this fraction of the limit is an
    # eviction storm worth a timeline entry (docs/observability.md
    # "Cluster plane"); smaller churn stays a counter.
    PRESSURE_EVENT_FRACTION = 0.125
    PRESSURE_EVENT_MIN_S = 5.0

    @property
    def resident_bytes(self) -> int:
        return self._total

    def _pop_locked(self, key: tuple) -> list:
        """Pop ``key`` keeping the byte ledgers (total, compressed,
        per-tenant) consistent.  Caller must hold self._lock."""
        e = self._entries.pop(key)
        self._total -= e[0]
        self._compressed -= e[3]
        t = e[4]
        if t is not None:
            left = self._tenant_bytes.get(t, 0) - e[0]
            if left > 0:
                self._tenant_bytes[t] = left
            else:
                self._tenant_bytes.pop(t, None)
        return e

    def _over_quota_locked(self) -> set:
        if self.tenant_quota_bytes <= 0:
            return set()
        return {t for t, b in self._tenant_bytes.items()
                if b > self.tenant_quota_bytes}

    def _evict_lru_locked(self, incoming: int) -> list[Callable[[], None]]:
        """Pop LRU entries until ``incoming`` more bytes fit the limit;
        returns their callbacks for the caller to run OUTSIDE the lock
        (owners may take their own locks without ordering against this
        one).  Caller must hold self._lock.

        Pinned entries are NEVER popped — an in-flight dispatch or a
        prefetch holds them — so eviction takes the unpinned-coldest,
        preferring entries of tenants OVER their residency quota (the
        over-quota tenant pays for the pressure it created); when
        everything left is pinned, the budget runs transiently
        over-limit instead of corrupting in-flight work."""
        to_evict: list[Callable[[], None]] = []
        if self.limit_bytes is None:
            return to_evict
        while self._entries and self._total + incoming > self.limit_bytes:
            victim = None
            over = self._over_quota_locked()
            if over:
                for key, e in self._entries.items():  # LRU -> MRU order
                    if e[2] == 0 and e[4] in over:
                        victim = key
                        self.quota_evictions += 1
                        break
            if victim is None:
                for key, e in self._entries.items():
                    if e[2] == 0:
                        victim = key
                        break
            if victim is None:
                break  # all pinned: admit over-limit
            e = self._pop_locked(victim)
            self.evictions += 1
            self.evicted_bytes += e[0]
            to_evict.append(e[1])
        return to_evict

    def _evict_tenant_locked(self, tenant, keep: tuple
                             ) -> list[Callable[[], None]]:
        """Per-tenant quota pressure: pop ``tenant``'s unpinned-coldest
        entries until it fits its quota, never popping ``keep`` (the
        entry being registered) — a lone over-quota entry runs
        transiently over, like the all-pinned case.  Caller holds
        self._lock; returns callbacks to run outside it."""
        to_evict: list[Callable[[], None]] = []
        if self.tenant_quota_bytes <= 0 or tenant is None:
            return to_evict
        while self._tenant_bytes.get(tenant, 0) > self.tenant_quota_bytes:
            victim = None
            for key, e in self._entries.items():  # LRU -> MRU order
                if e[4] == tenant and e[2] == 0 and key != keep:
                    victim = key
                    break
            if victim is None:
                break
            e = self._pop_locked(victim)
            self.evictions += 1
            self.quota_evictions += 1
            self.evicted_bytes += e[0]
            to_evict.append(e[1])
        return to_evict

    def _run_evictions(self, to_evict: list[Callable[[], None]]):
        for cb in to_evict:
            try:
                cb()
            except Exception:
                # the entry is already unaccounted; a failed callback
                # means its owner may still hold the buffer (leaked
                # residency) — that must be visible in stats(), not
                # silent (the budget itself must survive regardless).
                # Counted under the lock like every other counter:
                # callbacks run outside it, so concurrent failures race.
                with self._lock:
                    self.evict_errors += 1

    def register(self, key: tuple, nbytes: int, evict: Callable[[], None],
                 compressed_bytes: int = 0, tenant: str | None = None):
        """Account ``nbytes`` under ``key``; ``evict`` drops the owner's
        reference when called.  Evicts LRU entries first if needed (never
        evicting the incoming entry itself).  Re-registering an existing
        key keeps its pin count (the owner re-staged data an in-flight
        user still holds pinned).  ``compressed_bytes`` is the portion of
        ``nbytes`` held as packed container streams rather than dense
        tensors (docs/memory-budget.md "Compressed residency") — it
        splits the resident gauge, not the accounting.  ``tenant``
        charges the bytes against that tenant's residency quota (None
        falls back to the ambient request tenant)."""
        if tenant is None:
            tenant = qtenant.current_or_none()
        with self._lock:
            pins = 0
            if key in self._entries:
                pins = self._pop_locked(key)[2]
            evicted0 = self.evicted_bytes
            to_evict = self._evict_lru_locked(nbytes)
            freed = self.evicted_bytes - evicted0
            self._entries[key] = [nbytes, evict, pins, compressed_bytes,
                                  tenant]
            self._total += nbytes
            self._compressed += compressed_bytes
            if tenant is not None:
                self._tenant_bytes[tenant] = \
                    self._tenant_bytes.get(tenant, 0) + nbytes
                quota0 = self.evicted_bytes
                quota_evict = self._evict_tenant_locked(tenant, key)
                quota_freed = self.evicted_bytes - quota0
                to_evict.extend(quota_evict)
            else:
                quota_evict, quota_freed = [], 0
            self._peak = max(self._peak, self._total)
            self.upload_bytes += nbytes
        if quota_evict:
            qtenant.REGISTRY.note_quota_evict(tenant, quota_freed)
        self._note_pressure(freed, len(to_evict))
        self._run_evictions(to_evict)

    def _note_pressure(self, freed: int, n_evicted: int):
        """Journal an eviction storm: one make-room pass that evicted a
        large slice of the budget (rate-limited — sustained thrash is
        one timeline entry per interval, with the counters carrying the
        magnitude)."""
        if self.limit_bytes is None or freed < max(
                int(self.limit_bytes * self.PRESSURE_EVENT_FRACTION), 1):
            return
        import time as _time
        now = _time.monotonic()
        last = self._pressure_emitted_at
        if last is not None and now - last < self.PRESSURE_EVENT_MIN_S:
            return
        self._pressure_emitted_at = now
        from ..utils import events
        events.emit("membudget.pressure", freedBytes=freed,
                    entries=n_evicted, limitBytes=self.limit_bytes,
                    residentBytes=self._total)

    def reset_peak(self):
        """Restart the high-water mark from the current residency (bench /
        diagnostics epochs; the gauge analog of prometheus' counter
        resets)."""
        with self._lock:
            self._peak = self._total

    def shrink_to_limit(self):
        """Evict LRU entries until residency fits the (possibly just
        lowered) limit — ``register`` only evicts on new allocations, so a
        runtime limit decrease applies lazily without this."""
        with self._lock:
            to_evict = self._evict_lru_locked(0)
        self._run_evictions(to_evict)

    def touch(self, key: tuple):
        with self._lock:
            if key in self._entries:
                self._entries.move_to_end(key)

    def pin(self, key: tuple) -> bool:
        """Mark ``key`` in use by an in-flight plan or prefetch: eviction
        will not pop it until every pin is released.  Returns False (and
        pins nothing) when the key is not registered — callers proceed
        unprotected; correctness is unaffected because jax keeps device
        buffers alive for enqueued computations, pinning only prevents a
        wasteful re-stage."""
        with self._lock:
            e = self._entries.get(key)
            if e is None:
                return False
            e[2] += 1
            return True

    def unpin(self, key: tuple):
        with self._lock:
            e = self._entries.get(key)
            if e is not None and e[2] > 0:
                e[2] -= 1

    def note_prefetch(self, hit: bool):
        """Record whether a scheduled slice was already staged when the
        consumer reached it (parallel/mesh_exec.py streaming)."""
        with self._lock:
            if hit:
                self.prefetch_hits += 1
            else:
                self.prefetch_misses += 1

    def unregister(self, key: tuple):
        with self._lock:
            if key in self._entries:
                self._pop_locked(key)

    def stats(self) -> dict:
        with self._lock:
            pinned_bytes = sum(e[0] for e in self._entries.values()
                               if e[2] > 0)
            return {
                "residentBytes": self._total,
                "compressedBytes": self._compressed,
                "denseBytes": self._total - self._compressed,
                "peakBytes": self._peak,
                "limitBytes": self.limit_bytes,
                "entries": len(self._entries),
                "evictions": self.evictions,
                "evictedBytes": self.evicted_bytes,
                "evictErrors": self.evict_errors,
                "uploadBytes": self.upload_bytes,
                "prefetchHits": self.prefetch_hits,
                "prefetchMisses": self.prefetch_misses,
                "pinnedBytes": pinned_bytes,
                "tenantQuotaBytes": self.tenant_quota_bytes,
                "quotaEvictions": self.quota_evictions,
                "tenantBytes": dict(self._tenant_bytes),
            }


# Process-wide default (accounting-only until a limit is configured).
DEFAULT_BUDGET = DeviceBudget()

# Ingest delta-overlay budget (docs/ingest.md): accounts the host-side
# journals whose bits are OR'd into resident device state as overlays
# (storage/fragment.py ingest_apply, parallel/mesh_exec.py).  This
# instance is ACCOUNTING-ONLY (limit stays None): folding a journal must
# take the owning fragment's lock, and running that as a register-time
# eviction callback while ANOTHER fragment's lock is held would order
# fragment locks against each other (deadlock).  The limit lives in
# INGEST_DELTA_LIMIT_BYTES instead, enforced cooperatively — a fragment
# self-folds past its per-fragment share, and the ingest committer's
# flush loop (the only cross-fragment folder, single-threaded) folds the
# rest when the total runs over.  ``ingest-delta-mb`` sets it; 0 disables
# overlay journaling entirely (every flush folds immediately).
INGEST_DELTA_BUDGET = DeviceBudget()
INGEST_DELTA_LIMIT_BYTES = 64 << 20

# Host-side dense staging cache budget (fragment.staged_dense): bounds the
# expanded dense blocks kept around so a re-upload after HBM eviction
# skips the sparse->dense expansion.  limit 0 = staging disabled (every
# upload re-expands), None = unbounded.  Server config ``host_stage_mb``
# sets it; 4 GiB default keeps steady-state re-uploads at transfer speed
# without letting staging rival the sparse store for host memory.
HOST_STAGE_BUDGET = DeviceBudget(limit_bytes=4 << 30)
