"""View: a physical grouping of fragments inside a field (view.go:44-63).

Names: "standard", time views "standard_YYYY[MM[DD[HH]]]", and BSI views
"bsig_<field>".  A view owns one fragment per shard that has data.
"""

from __future__ import annotations

import os
import threading

from .fragment import Fragment


class View:
    def __init__(self, path: str | None, index: str, field: str, name: str,
                 max_op_n: int | None = None,
                 row_id_cap: int | None = None):
        self.path = path
        self.index = index
        self.field = field
        self.name = name
        self.max_op_n = max_op_n
        self.row_id_cap = row_id_cap
        self.fragments: dict[int, Fragment] = {}
        self._lock = threading.RLock()

    def fragment(self, shard: int) -> Fragment | None:
        return self.fragments.get(shard)

    def create_fragment_if_not_exists(self, shard: int) -> Fragment:
        """(view.go:263 CreateFragmentIfNotExists)"""
        with self._lock:
            frag = self.fragments.get(shard)
            if frag is None:
                frag_path = None
                if self.path is not None:
                    frag_path = os.path.join(self.path, "fragments", str(shard))
                kwargs = {}
                if self.max_op_n is not None:
                    kwargs["max_op_n"] = self.max_op_n
                frag = Fragment(frag_path, self.index, self.field, self.name,
                                shard, row_id_cap=self.row_id_cap, **kwargs)
                self.fragments[shard] = frag
            return frag

    def available_shards(self) -> set[int]:
        return set(self.fragments)

    def open(self):
        """Discover fragment files on disk (view.go openFragments)."""
        if self.path is None:
            return
        frag_dir = os.path.join(self.path, "fragments")
        if not os.path.isdir(frag_dir):
            return
        for name in os.listdir(frag_dir):
            if name.endswith(".wal"):
                name = name[:-4]
            try:
                shard = int(name)
            except ValueError:
                continue
            self.create_fragment_if_not_exists(shard)

    def close(self):
        with self._lock:
            for frag in self.fragments.values():
                frag.close()
