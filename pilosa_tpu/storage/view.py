"""View: a physical grouping of fragments inside a field (view.go:44-63).

Names: "standard", time views "standard_YYYY[MM[DD[HH]]]", and BSI views
"bsig_<field>".  A view owns one fragment per shard that has data.
"""

from __future__ import annotations

import os

from ..core import VIEW_STANDARD
from .fragment import Fragment
from ..utils.locks import make_rlock


class View:
    def __init__(self, path: str | None, index: str, field: str, name: str,
                 max_op_n: int | None = None,
                 row_id_cap: int | None = None,
                 cache_type: str | None = None, cache_size: int = 0):
        """``cache_type``/``cache_size``: the owning field's rank-cache
        options (field.go cacheType/cacheSize), threaded down so the
        STANDARD view's fragments of a ranked/lru field get a RankCache
        attached.  Time and BSI views never cache — TopN pruning reads
        only the standard view (and BSI rows are bit slices, not rank
        candidates; the reference likewise forces CacheTypeNone on int
        fields)."""
        self.path = path
        self.index = index
        self.field = field
        self.name = name
        self.max_op_n = max_op_n
        self.row_id_cap = row_id_cap
        self.cache_type = cache_type
        self.cache_size = cache_size
        self.fragments: dict[int, Fragment] = {}
        self._lock = make_rlock("view")

    def fragment(self, shard: int) -> Fragment | None:
        return self.fragments.get(shard)

    def create_fragment_if_not_exists(self, shard: int) -> Fragment:
        """(view.go:263 CreateFragmentIfNotExists)"""
        with self._lock:
            frag = self.fragments.get(shard)
            if frag is None:
                frag_path = None
                if self.path is not None:
                    frag_path = os.path.join(self.path, "fragments", str(shard))
                kwargs = {}
                if self.max_op_n is not None:
                    kwargs["max_op_n"] = self.max_op_n
                frag = Fragment(frag_path, self.index, self.field, self.name,
                                shard, row_id_cap=self.row_id_cap, **kwargs)
                # Only the STANDARD view caches: TopN candidate pruning
                # reads exclusively from it (cache/rank.topn_from_rank),
                # so rank maintenance on time/BSI views would be pure
                # write-path overhead with no reader.
                if self.cache_type in ("ranked", "lru") and \
                        self.name == VIEW_STANDARD:
                    from ..cache.rank import RankCache
                    frag.rank_cache = RankCache(self.cache_type,
                                                self.cache_size)
                self.fragments[shard] = frag
            return frag

    def available_shards(self) -> set[int]:
        return set(self.fragments)

    def open(self):
        """Discover fragment files on disk (view.go openFragments)."""
        if self.path is None:
            return
        frag_dir = os.path.join(self.path, "fragments")
        if not os.path.isdir(frag_dir):
            return
        for name in os.listdir(frag_dir):
            if name.endswith(".wal"):
                name = name[:-4]
            try:
                shard = int(name)
            except ValueError:
                continue
            self.create_fragment_if_not_exists(shard)

    def close(self):
        with self._lock:
            for frag in self.fragments.values():
                frag.close()
