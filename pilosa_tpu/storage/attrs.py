"""Attribute storage: arbitrary key/value metadata on rows and columns
(reference attr.go:34-44 AttrStore, boltdb/attrstore.go).

The reference uses BoltDB; here a JSON-file-backed store with in-memory maps
(attrs are metadata, never on the query hot path).  Block checksums for
anti-entropy diffing mirror attrBlocks (attr.go:86-120).
"""

from __future__ import annotations

import hashlib
import json
import os

from ..utils.durable import durable_replace, fsync_file
from ..utils.locks import make_rlock

_BLOCK_SIZE = 100  # ids per checksum block (attr.go attrBlockSize)


class AttrStore:
    def __init__(self, path: str | None = None):
        self.path = path
        self._attrs: dict[int, dict] = {}
        # non-None = the store file was corrupt at open; the bad bytes
        # were moved aside and the store started empty (anti-entropy attr
        # sync pulls the content back from peers — attrs are repairable
        # metadata, so startup must not die on them)
        self.corrupt: str | None = None
        self._lock = make_rlock("attrs")
        if path is not None and os.path.exists(path):
            try:
                with open(path) as f:
                    self._attrs = {int(k): v
                                   for k, v in json.load(f).items()}
            except (ValueError, OSError) as e:
                self.corrupt = str(e)
                from .fragment import _bump
                _bump("attr_corrupt")
                try:
                    os.replace(path, path + ".corrupt")
                except OSError:
                    pass

    def _save(self):
        if self.path is None:
            return
        os.makedirs(os.path.dirname(self.path), exist_ok=True)
        tmp = self.path + ".tmp"
        with open(tmp, "w") as f:
            json.dump({str(k): v for k, v in self._attrs.items()}, f)
            # fsync before the rename + dir fsync after: a crash right
            # after os.replace must not lose an acknowledged attr write
            fsync_file(f)
        durable_replace(tmp, self.path)

    def attrs(self, id_: int) -> dict:
        with self._lock:
            return dict(self._attrs.get(id_, {}))

    def set_attrs(self, id_: int, attrs: dict):
        """Merge semantics; a None value deletes the key
        (attr.go SetAttrs)."""
        from ..core import bump_attr_epoch
        with self._lock:
            cur = self._attrs.setdefault(id_, {})
            for k, v in attrs.items():
                if v is None:
                    cur.pop(k, None)
                else:
                    cur[k] = v
            if not cur:
                self._attrs.pop(id_, None)
            self._save()
        bump_attr_epoch()

    def set_bulk_attrs(self, items: dict[int, dict]):
        from ..core import bump_attr_epoch
        with self._lock:
            for id_, attrs in items.items():
                cur = self._attrs.setdefault(id_, {})
                cur.update({k: v for k, v in attrs.items() if v is not None})
            self._save()
        bump_attr_epoch()

    def all(self) -> dict[int, dict]:
        with self._lock:
            return {k: dict(v) for k, v in self._attrs.items()}

    def blocks(self) -> dict[int, bytes]:
        """Checksum per 100-id block for anti-entropy diff
        (attr.go:86 attrBlocks)."""
        with self._lock:
            out: dict[int, bytes] = {}
            by_block: dict[int, list] = {}
            for id_ in sorted(self._attrs):
                by_block.setdefault(id_ // _BLOCK_SIZE, []).append(id_)
            for blk, ids in by_block.items():
                h = hashlib.blake2b(digest_size=16)
                for id_ in ids:
                    h.update(json.dumps(
                        [id_, self._attrs[id_]], sort_keys=True).encode())
                out[blk] = h.digest()
            return out

    def block_data(self, block_id: int) -> dict[int, dict]:
        with self._lock:
            lo = block_id * _BLOCK_SIZE
            hi = lo + _BLOCK_SIZE
            return {i: dict(a) for i, a in self._attrs.items()
                    if lo <= i < hi}


# -- executor glue ---------------------------------------------------------

def _attr_args(call) -> dict:
    return {k: v for k, v in call.args.items() if not k.startswith("_")}


def set_attrs_from_call(holder, index_name: str, call):
    """SetRowAttrs/SetColumnAttrs dispatch (executor.go:2207-2412)."""
    idx = holder.index(index_name)
    if idx is None:
        raise ValueError(f"index not found: {index_name}")
    attrs = _attr_args(call)
    if call.name == "SetColumnAttrs":
        col = call.args.get("_col")
        if isinstance(col, bool) or not isinstance(col, int):
            raise ValueError("SetColumnAttrs requires an integer column id")
        idx.column_attrs.set_attrs(col, attrs)
        return None
    field_name = call.args.get("_field")
    f = idx.field(field_name) if field_name else None
    if f is None:
        raise ValueError(f"field not found: {field_name}")
    row = call.args.get("_row")
    if isinstance(row, bool) or not isinstance(row, int):
        raise ValueError("SetRowAttrs requires an integer row id")
    f.row_attrs.set_attrs(row, attrs)
    return None
