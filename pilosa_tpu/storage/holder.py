"""Holder: root of all local data (holder.go:50-87)."""

from __future__ import annotations

import os
import shutil

from .fragment import Fragment
from .index import Index
from .field import Field, FieldOptions
from ..utils.locks import make_rlock


class Holder:
    def __init__(self, path: str | None = None,
                 max_op_n: int | None = None,
                 max_row_id: int | None = None):
        self.path = path
        self.max_op_n = max_op_n
        self.max_row_id = max_row_id  # per-fragment row-id cap (None=default)
        self.indexes: dict[str, Index] = {}
        # key-translation store factory propagated to indexes/fields;
        # None = local file-backed stores (cluster replicas set a
        # coordinator-routed factory before open())
        self.translate_factory = None
        self._lock = make_rlock("holder")

    # -- lifecycle (holder.go:137 Open) ------------------------------------

    def open(self):
        if self.path is None:
            return
        os.makedirs(self.path, exist_ok=True)
        for name in sorted(os.listdir(self.path)):
            idx_path = os.path.join(self.path, name)
            if not os.path.isdir(idx_path):
                continue
            # hidden dirs are infrastructure, not indexes (the warm-start
            # compile cache lives at <data-dir>/.compile-cache)
            if name.startswith("."):
                continue
            idx = Index(idx_path, name, max_op_n=self.max_op_n,
                        row_id_cap=self.max_row_id)
            idx.translate_factory = self.translate_factory
            idx.open()
            for f in idx.fields.values():
                f.translate_factory = self.translate_factory
            self.indexes[name] = idx

    def close(self):
        with self._lock:
            for idx in self.indexes.values():
                idx.close()

    # -- index management --------------------------------------------------

    def _index_path(self, name: str) -> str | None:
        return None if self.path is None else os.path.join(self.path, name)

    def index(self, name: str) -> Index | None:
        return self.indexes.get(name)

    def create_index(self, name: str, keys: bool = False,
                     track_existence: bool = True) -> Index:
        """(holder.go:396 CreateIndex)"""
        with self._lock:
            if name in self.indexes:
                raise FileExistsError(f"index already exists: {name}")
            from ..core import validate_name
            validate_name(name, "index name")
            idx = Index(self._index_path(name), name, keys=keys,
                        track_existence=track_existence,
                        max_op_n=self.max_op_n, create=True,
                        row_id_cap=self.max_row_id)
            idx.translate_factory = self.translate_factory
            idx.save_meta()
            self.indexes[name] = idx
            from ..core import bump_schema_epoch
            bump_schema_epoch()
            return idx

    def create_index_if_not_exists(self, name: str, **kw) -> Index:
        with self._lock:
            if name in self.indexes:
                return self.indexes[name]
            return self.create_index(name, **kw)

    def delete_index(self, name: str):
        with self._lock:
            idx = self.indexes.pop(name, None)
            if idx is None:
                raise ValueError(f"index not found: {name}")
            from ..core import bump_schema_epoch
            bump_schema_epoch()
            idx.close()
            if idx.path is not None and os.path.isdir(idx.path):
                shutil.rmtree(idx.path)

    # -- accessors (holder.go:373-531) ------------------------------------

    def field(self, index: str, field: str) -> Field | None:
        idx = self.indexes.get(index)
        return None if idx is None else idx.field(field)

    def fragment(self, index: str, field: str, view: str,
                 shard: int) -> Fragment | None:
        f = self.field(index, field)
        if f is None:
            return None
        v = f.view(view)
        return None if v is None else v.fragment(shard)

    def iter_fragments(self, index: str | None = None):
        """Yield (index, field, view, shard, fragment) over local data
        (optionally one index) — the quarantine/repair scan surface."""
        items = [(index, self.indexes[index])] if index is not None \
            and index in self.indexes else list(self.indexes.items())
        for iname, idx in items:
            for fname, f in list(idx.fields.items()):
                for vname, v in list(f.views.items()):
                    for shard, frag in list(v.fragments.items()):
                        yield iname, fname, vname, shard, frag

    def quarantined_fragments(self, index: str | None = None) -> list[dict]:
        """Currently-quarantined fragments (docs/robustness.md): the
        degraded-state surface for /status, /debug/vars and query
        responses.  Called on every public query / health probe /
        metrics scrape, so the healthy case (no quarantine has EVER
        happened in this process) fast-outs without scanning the
        holder."""
        from .fragment import QUARANTINE_SEEN
        if not QUARANTINE_SEEN:
            return []
        out = []
        for iname, fname, vname, shard, frag in self.iter_fragments(index):
            if frag.quarantined is not None:
                out.append({"index": iname, "field": fname, "view": vname,
                            "shard": shard, "reason": frag.quarantined})
        return out

    def container_stats(self, index: str | None = None) -> dict:
        """Aggregate container-type histogram of the fragments currently
        holding a packed (compressed-resident) stream, plus how many
        fragments are in each device form (docs/memory-budget.md
        "Compressed residency").  Never packs on demand — fragments
        without a current pack count as dense-form or uncounted, keeping
        metric scrapes O(fragments) with O(1) work each."""
        out = {"array": 0, "bitmap": 0, "run": 0,
               "compressedFragments": 0, "denseFragments": 0}
        for *_ignored, frag in self.iter_fragments(index):
            st = frag.packed_stats()
            if st is not None and frag.device_form() == "compressed":
                out["array"] += st["array"]
                out["bitmap"] += st["bitmap"]
                out["run"] += st["run"]
                out["compressedFragments"] += 1
            else:
                out["denseFragments"] += 1
        return out

    def corrupt_attr_stores(self, index: str | None = None) -> list[dict]:
        """Attr stores whose JSON was corrupt at open (bad bytes moved
        aside to ``.corrupt``, store restarted empty; attr anti-entropy
        pulls the content back from peers).  Surfaced at /debug/vars so
        the silent reset is visible to operators."""
        from .fragment import storage_events
        if storage_events()["attr_corrupt"] == 0:
            return []  # fast-out: no attr store has EVER reset
        items = [(index, self.indexes[index])] if index is not None \
            and index in self.indexes else list(self.indexes.items())
        out = []
        for iname, idx in items:
            if idx.column_attrs.corrupt is not None:
                out.append({"index": iname, "field": None,
                            "reason": idx.column_attrs.corrupt})
            for fname, f in list(idx.fields.items()):
                if f.row_attrs.corrupt is not None:
                    out.append({"index": iname, "field": fname,
                                "reason": f.row_attrs.corrupt})
        return out

    def schema(self) -> list[dict]:
        """JSON-able schema (holder.go Schema)."""
        out = []
        for iname, idx in sorted(self.indexes.items()):
            out.append({
                "name": iname,
                "options": {"keys": idx.keys,
                            "trackExistence": idx.track_existence},
                "fields": [
                    {"name": f.name, "options": f.options.to_dict(),
                     "views": sorted(f.views)}
                    for f in idx.public_fields()
                ],
            })
        return out
