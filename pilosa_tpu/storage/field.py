"""Field: a row-space within an index (field.go:65-96).

Types (field.go:56-62): set, int (BSI), time, mutex, bool.  A field owns
views: "standard" for set bits, time-quantum views for timestamped bits, and
"bsig_<field>" for integer values.  Integer values are stored base-offset
(field.go:1551 bsiBase: stored = value - base) with an auto-growing bit depth
(field.go:1088-1105).
"""

from __future__ import annotations

import json
import os
from dataclasses import dataclass
from datetime import datetime

import numpy as np

from ..core import (
    SHARD_WIDTH,
    VIEW_BSI_GROUP_PREFIX,
    VIEW_STANDARD,
)
from ..ops import bsi
from ..utils.locks import make_rlock
from .attrs import AttrStore
from . import time_quantum as tq
from .view import View

FIELD_TYPE_SET = "set"
FIELD_TYPE_INT = "int"
FIELD_TYPE_TIME = "time"
FIELD_TYPE_MUTEX = "mutex"
FIELD_TYPE_BOOL = "bool"

CACHE_TYPE_RANKED = "ranked"
CACHE_TYPE_LRU = "lru"
CACHE_TYPE_NONE = "none"

DEFAULT_CACHE_SIZE = 50000


class FieldError(ValueError):
    pass


def bsi_base(min_v: int, max_v: int) -> int:
    """Default base for an int field (field.go:1554 bsiBase)."""
    if min_v > 0:
        return min_v
    if max_v < 0:
        return max_v
    return 0


def bit_depth(v: int) -> int:
    """Bits required to store abs(v) (field.go:1665 bitDepth)."""
    v = abs(v)
    for i in range(63):
        if v < (1 << i):
            return i
    return 63


@dataclass
class FieldOptions:
    """(field.go:1421 FieldOptions)

    ``min``/``max`` default to None; for int fields an omitted bound
    resolves to the full int64 range (the reference defaults omitted
    min/max to MinInt64/MaxInt64, http/handler.go:781) so a bare
    {"type": "int"} field accepts every value instead of rejecting all
    non-zero writes against a 0/0 declared range."""
    type: str = FIELD_TYPE_SET
    cache_type: str = CACHE_TYPE_RANKED
    cache_size: int = DEFAULT_CACHE_SIZE
    min: int | None = None
    max: int | None = None
    base: int = 0
    bit_depth: int = 0
    time_quantum: str = ""
    keys: bool = False

    def __post_init__(self):
        # Reject bad cache options AT FIELD CREATION (a 400 through the
        # API) instead of silently persisting an arbitrary cacheType
        # string into the schema where every later TopN would have to
        # guess at it (field.go:1462 validates the same way).
        if self.cache_type not in (CACHE_TYPE_RANKED, CACHE_TYPE_LRU,
                                   CACHE_TYPE_NONE):
            raise FieldError(
                f"invalid cacheType {self.cache_type!r} (expected one of "
                f"'ranked', 'lru', 'none')")
        if not isinstance(self.cache_size, int) \
                or isinstance(self.cache_size, bool) or self.cache_size < 0:
            raise FieldError(
                f"invalid cacheSize {self.cache_size!r} (expected a "
                f"non-negative integer)")
        if self.type == FIELD_TYPE_INT:
            # Magnitude is stored sign+magnitude in 63 BSI rows, so the
            # representable floor is -(2^63-1), not MinInt64; defaulting to
            # MinInt64 would let set_value(-2**63) silently truncate to 0.
            if self.min is None:
                self.min = -((1 << 63) - 1)
            if self.max is None:
                self.max = (1 << 63) - 1
        else:
            if self.min is None:
                self.min = 0
            if self.max is None:
                self.max = 0

    def to_dict(self) -> dict:
        return {
            "type": self.type,
            "cacheType": self.cache_type,
            "cacheSize": self.cache_size,
            "min": self.min,
            "max": self.max,
            "base": self.base,
            "bitDepth": self.bit_depth,
            "timeQuantum": self.time_quantum,
            "keys": self.keys,
        }

    @classmethod
    def from_dict(cls, d: dict, lenient: bool = False) -> "FieldOptions":
        """``lenient=True`` for the DISK LOAD path: schemas persisted
        before cache-option validation existed may carry arbitrary
        cacheType strings / bad sizes, and a node must not refuse to
        start over them.  Unknown types coerce to 'none' — exactly the
        pre-validation behavior, where an unrecognized cacheType meant no
        cache was ever consulted.  API field creation stays strict
        (400)."""
        cache_type = d.get("cacheType", CACHE_TYPE_RANKED)
        cache_size = d.get("cacheSize", DEFAULT_CACHE_SIZE)
        if lenient:
            if cache_type not in (CACHE_TYPE_RANKED, CACHE_TYPE_LRU,
                                  CACHE_TYPE_NONE):
                cache_type = CACHE_TYPE_NONE
            if not isinstance(cache_size, int) \
                    or isinstance(cache_size, bool) or cache_size < 0:
                cache_size = DEFAULT_CACHE_SIZE
        return cls(
            type=d.get("type", FIELD_TYPE_SET),
            cache_type=cache_type,
            cache_size=cache_size,
            min=d.get("min"),
            max=d.get("max"),
            base=d.get("base", 0),
            bit_depth=d.get("bitDepth", 0),
            time_quantum=d.get("timeQuantum", ""),
            keys=d.get("keys", False),
        )


class Field:
    def __init__(self, path: str | None, index: str, name: str,
                 options: FieldOptions | None = None,
                 max_op_n: int | None = None,
                 row_id_cap: int | None = None):
        self.path = path
        self.index = index
        self.name = name
        self.options = options or FieldOptions()
        self.max_op_n = max_op_n
        self.row_id_cap = row_id_cap
        self.views: dict[str, View] = {}
        self.row_attrs = AttrStore(
            None if path is None else os.path.join(path, ".row_attrs"))
        self._lock = make_rlock("field")
        # shards known to have data on remote nodes (field.go:263)
        self.remote_available_shards: set[int] = set()
        # row-key translation (field.go: per-field TranslateStore)
        self.translate_factory = None
        self._translate_store = None

        if self.options.type == FIELD_TYPE_INT:
            if self.options.base == 0:
                self.options.base = bsi_base(self.options.min, self.options.max)
            # bit_depth intentionally starts at 0 and grows with the values
            # actually written (field.go:1088-1105), NOT with the declared
            # min/max: BSI range scans are O(bit_depth), so a field declared
            # wide but used narrow stays cheap.  Declared-range enforcement
            # on writes (_check_value) keeps options.min/max sound for the
            # planner's shortcut paths.
        if self.options.type == FIELD_TYPE_TIME:
            tq.validate_quantum(self.options.time_quantum)

    # -- persistence -------------------------------------------------------

    def _meta_path(self) -> str:
        return os.path.join(self.path, ".meta")

    def save_meta(self):
        if self.path is None:
            return
        os.makedirs(self.path, exist_ok=True)
        with open(self._meta_path(), "w") as f:
            json.dump(self.options.to_dict(), f)

    def open(self):
        if self.path is None:
            return
        if os.path.exists(self._meta_path()):
            with open(self._meta_path()) as f:
                self.options = FieldOptions.from_dict(json.load(f),
                                                      lenient=True)
        views_dir = os.path.join(self.path, "views")
        if os.path.isdir(views_dir):
            for vname in os.listdir(views_dir):
                self._create_view_if_not_exists(vname).open()

    def close(self):
        with self._lock:
            for v in self.views.values():
                v.close()
            if self._translate_store is not None:
                self._translate_store.close()
                self._translate_store = None

    def translate_store(self):
        """Row-key store for this field (keys live in <field>/.row_keys)."""
        with self._lock:
            if self._translate_store is None:
                from .translate import TranslateStore
                path = None if self.path is None \
                    else os.path.join(self.path, ".row_keys")
                if self.translate_factory is not None:
                    self._translate_store = self.translate_factory(
                        path, self.index, self.name)
                else:
                    self._translate_store = TranslateStore(path)
            return self._translate_store

    # -- views -------------------------------------------------------------

    def view(self, name: str) -> View | None:
        return self.views.get(name)

    def _create_view_if_not_exists(self, name: str) -> View:
        with self._lock:
            v = self.views.get(name)
            if v is None:
                vpath = None
                if self.path is not None:
                    vpath = os.path.join(self.path, "views", name)
                v = View(vpath, self.index, self.name, name,
                         max_op_n=self.max_op_n, row_id_cap=self.row_id_cap,
                         cache_type=self.options.cache_type,
                         cache_size=self.options.cache_size)
                self.views[name] = v
            return v

    def bsi_view_name(self) -> str:
        return VIEW_BSI_GROUP_PREFIX + self.name

    def available_shards(self) -> set[int]:
        """Union of local fragment shards + remote-known shards
        (field.go:300 AvailableShards)."""
        out = set(self.remote_available_shards)
        for v in self.views.values():
            out |= v.available_shards()
        return out

    # -- bit mutation ------------------------------------------------------

    def _check_row(self, row: int):
        if self.options.type == FIELD_TYPE_BOOL and row not in (0, 1):
            raise FieldError("bool field rows must be 0 (false) or 1 (true)")

    def set_bit(self, row: int, col: int, ts: datetime | None = None) -> bool:
        """Set (row, col); fans out to standard + time views
        (field.go:929 SetBit)."""
        self._check_row(row)
        shard = col // SHARD_WIDTH
        shard_col = col % SHARD_WIDTH
        changed = False

        view_names = [VIEW_STANDARD]
        if ts is not None:
            if not self.options.time_quantum:
                raise FieldError(
                    f"cannot set timed bit on field {self.name!r} with no "
                    f"time quantum")
            view_names += tq.views_by_time(
                VIEW_STANDARD, ts, self.options.time_quantum)

        for vname in view_names:
            frag = self._create_view_if_not_exists(vname) \
                .create_fragment_if_not_exists(shard)
            if self.options.type in (FIELD_TYPE_MUTEX, FIELD_TYPE_BOOL):
                changed |= self._mutex_set(frag, row, shard_col)
            else:
                changed |= frag.set_bit(row, shard_col)
        return changed

    @staticmethod
    def _mutex_set(frag, row: int, shard_col: int) -> bool:
        """Mutex semantics: at most one row per column
        (fragment.go setBit mutex handling / :2106 bulkImportMutex)."""
        changed = False
        for r in frag.rows_with_bit(shard_col):
            if int(r) != row:
                changed |= frag.clear_bit(int(r), shard_col)
        changed |= frag.set_bit(row, shard_col)
        return changed

    def clear_bit(self, row: int, col: int) -> bool:
        """(field.go:1000 ClearBit) — clears from standard and all time
        views."""
        self._check_row(row)
        shard = col // SHARD_WIDTH
        shard_col = col % SHARD_WIDTH
        changed = False
        for vname, v in list(self.views.items()):
            if vname.startswith(VIEW_BSI_GROUP_PREFIX):
                continue
            frag = v.fragment(shard)
            if frag is not None:
                changed |= frag.clear_bit(row, shard_col)
        return changed

    def row(self, row_id: int, view_name: str = VIEW_STANDARD):
        """All shards' segments for a row: {shard: np.uint32[W]}
        (field.go:917 Row)."""
        v = self.views.get(view_name)
        if v is None:
            return {}
        return {shard: frag.row(row_id)
                for shard, frag in v.fragments.items()}

    # -- integer values ----------------------------------------------------

    def _require_int(self):
        if self.options.type != FIELD_TYPE_INT:
            raise FieldError(f"field {self.name!r} is not an int field")

    def _check_value(self, value: int):
        """Declared-range enforcement (field.go:1082-1086
        ErrBSIGroupValueTooLow/High).  This is what makes options.min/max
        true invariants of the stored data, which the planner's
        full-encompass shortcuts rely on (plan.py _resolve_bsi)."""
        if value < self.options.min:
            raise FieldError(
                f"bsigroup value too low: {value} < min {self.options.min}")
        if value > self.options.max:
            raise FieldError(
                f"bsigroup value too high: {value} > max {self.options.max}")

    def set_value(self, col: int, value: int) -> bool:
        """(field.go:1077 SetValue): store value-base; grow bit depth as
        needed (field.go:1088-1105)."""
        self._require_int()
        self._check_value(value)
        base_value = value - self.options.base
        with self._lock:
            required = max(bit_depth(base_value), 1)
            if required > self.options.bit_depth:
                self.options.bit_depth = required
                from ..core import bump_schema_epoch
                bump_schema_epoch()
                self.save_meta()
            depth = self.options.bit_depth
        shard = col // SHARD_WIDTH
        frag = self._create_view_if_not_exists(self.bsi_view_name()) \
            .create_fragment_if_not_exists(shard)
        return frag.set_value(col % SHARD_WIDTH, depth, base_value)

    def value(self, col: int):
        """(field.go:1060 Value) -> (value, exists)."""
        self._require_int()
        v = self.views.get(self.bsi_view_name())
        if v is None:
            return 0, False
        frag = v.fragment(col // SHARD_WIDTH)
        if frag is None:
            return 0, False
        shard_col = col % SHARD_WIDTH
        rows = set(int(r) for r in frag.rows_with_bit(shard_col))
        if bsi.EXISTS_ROW not in rows:
            return 0, False
        mag = 0
        for r in rows:
            if r >= bsi.OFFSET_ROW:
                mag |= 1 << (r - bsi.OFFSET_ROW)
        if bsi.SIGN_ROW in rows:
            mag = -mag
        return mag + self.options.base, True

    # -- import ------------------------------------------------------------

    def import_bits(self, rows: np.ndarray, cols: np.ndarray,
                    timestamps=None, clear: bool = False) -> None:
        """Bulk import of (row, col[, ts]) triples, shard-grouping inside
        (field.go:1206 Import)."""
        rows = np.asarray(rows, dtype=np.int64)
        cols = np.asarray(cols, dtype=np.int64)
        view_bits: dict[str, tuple[list, list]] = {}

        if timestamps is None:
            view_bits[VIEW_STANDARD] = (rows, cols)
        else:
            std_r, std_c = [], []
            timed: dict[str, tuple[list, list]] = {}
            for r, c, ts in zip(rows, cols, timestamps):
                std_r.append(r)
                std_c.append(c)
                if ts is not None:
                    for vn in tq.views_by_time(
                            VIEW_STANDARD, ts, self.options.time_quantum):
                        timed.setdefault(vn, ([], []))
                        timed[vn][0].append(r)
                        timed[vn][1].append(c)
            view_bits[VIEW_STANDARD] = (np.array(std_r), np.array(std_c))
            for vn, (tr, tc) in timed.items():
                view_bits[vn] = (np.array(tr), np.array(tc))

        for vname, (vr, vc) in view_bits.items():
            vr = np.asarray(vr, dtype=np.int64)
            vc = np.asarray(vc, dtype=np.int64)
            view = self._create_view_if_not_exists(vname)
            shards = vc // SHARD_WIDTH
            for shard in np.unique(shards):
                sel = shards == shard
                frag = view.create_fragment_if_not_exists(int(shard))
                if self.options.type in (FIELD_TYPE_MUTEX, FIELD_TYPE_BOOL) \
                        and not clear:
                    frag.mutex_import(vr[sel], vc[sel] % SHARD_WIDTH)
                else:
                    frag.bulk_import(vr[sel], vc[sel] % SHARD_WIDTH,
                                     clear=clear)

    def ingest_import(self, rows: np.ndarray, cols: np.ndarray,
                      timestamps=None) -> int:
        """Group-commit import for the streaming ingest path
        (docs/ingest.md): same view fan-out as ``import_bits`` but each
        fragment takes its batch through ``Fragment.ingest_apply`` — one
        WAL frame, one gen bump, one rank-cache touch per FLUSH, with
        the new bits riding the device delta overlay instead of
        invalidating resident device state.  Mutex/bool fields fall back
        to ``mutex_import`` (their implied clears cannot overlay); the
        flush is still one batch per fragment.  Returns changed bits."""
        rows = np.asarray(rows, dtype=np.int64)
        cols = np.asarray(cols, dtype=np.int64)
        view_bits: dict[str, tuple[np.ndarray, np.ndarray]] = {}
        if timestamps is None:
            view_bits[VIEW_STANDARD] = (rows, cols)
        else:
            timed: dict[str, tuple[list, list]] = {}
            for r, c, ts in zip(rows, cols, timestamps):
                if ts is not None:
                    for vn in tq.views_by_time(
                            VIEW_STANDARD, ts, self.options.time_quantum):
                        timed.setdefault(vn, ([], []))
                        timed[vn][0].append(r)
                        timed[vn][1].append(c)
            view_bits[VIEW_STANDARD] = (rows, cols)
            for vn, (tr, tc) in timed.items():
                view_bits[vn] = (np.asarray(tr, dtype=np.int64),
                                 np.asarray(tc, dtype=np.int64))
        changed = 0
        for vname, (vr, vc) in view_bits.items():
            view = self._create_view_if_not_exists(vname)
            shards = vc // SHARD_WIDTH
            for shard in np.unique(shards):
                sel = shards == shard
                frag = view.create_fragment_if_not_exists(int(shard))
                if self.options.type in (FIELD_TYPE_MUTEX, FIELD_TYPE_BOOL):
                    changed += frag.mutex_import(vr[sel],
                                                 vc[sel] % SHARD_WIDTH)
                else:
                    changed += frag.ingest_apply(vr[sel],
                                                 vc[sel] % SHARD_WIDTH)
        return changed

    def import_values(self, cols: np.ndarray, values: np.ndarray,
                      clear: bool = False) -> None:
        """Bulk BSI import (field.go:1287 importValue); ``clear`` removes
        the columns' values instead."""
        self._require_int()
        cols = np.asarray(cols, dtype=np.int64)
        values = np.asarray(values, dtype=np.int64)
        if cols.size == 0:
            return
        if clear:
            view = self.views.get(self.bsi_view_name())
            if view is None:
                return
            shards = cols // SHARD_WIDTH
            for shard in np.unique(shards):
                frag = view.fragment(int(shard))
                if frag is not None:
                    frag.clear_values(cols[shards == shard] % SHARD_WIDTH)
            return
        self._check_value(int(values.min()))
        self._check_value(int(values.max()))
        base_values = values - self.options.base
        with self._lock:
            required = max(
                bit_depth(int(base_values.min())),
                bit_depth(int(base_values.max())), 1)
            if required > self.options.bit_depth:
                self.options.bit_depth = required
                from ..core import bump_schema_epoch
                bump_schema_epoch()
                self.save_meta()
            depth = self.options.bit_depth
        view = self._create_view_if_not_exists(self.bsi_view_name())
        shards = cols // SHARD_WIDTH
        for shard in np.unique(shards):
            sel = shards == shard
            frag = view.create_fragment_if_not_exists(int(shard))
            # merge with existing values in the fragment
            frag.import_values(cols[sel] % SHARD_WIDTH, base_values[sel],
                               depth)
