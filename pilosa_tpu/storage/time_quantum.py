"""Time quantums: Y/M/D/H granularity view naming and range expansion.

Behavioral port of the reference's time.go (viewsByTime :90-103,
viewsByTimeRange :105-176, minMaxViews :240-275, addMonth :180-190): a time
field materialises one view per enabled time unit per timestamp
("<field>_2017", "<field>_201701", ...), and a range query expands to the
minimal set of views covering [start, end) by walking small units up to
large-unit boundaries and back down.
"""

from __future__ import annotations

from datetime import datetime, timedelta

VALID_QUANTUMS = {"Y", "YM", "YMD", "YMDH", "M", "MD", "MDH", "D", "DH", "H", ""}

# PQL timestamp literal format (pilosa.go TimeFormat "2006-01-02T15:04").
TIME_FORMAT = "%Y-%m-%dT%H:%M"


class InvalidTimeQuantumError(ValueError):
    pass


def validate_quantum(q: str) -> str:
    if q not in VALID_QUANTUMS:
        raise InvalidTimeQuantumError(f"invalid time quantum: {q!r}")
    return q


def parse_time(value) -> datetime:
    """Parse a PQL timestamp arg: '2006-01-02T15:04' string or unix int."""
    if isinstance(value, datetime):
        return value
    if isinstance(value, str):
        try:
            return datetime.strptime(value, TIME_FORMAT)
        except ValueError:
            raise ValueError(f"cannot parse string time: {value!r}")
    if isinstance(value, int):
        return datetime.utcfromtimestamp(value)
    raise ValueError("arg must be a timestamp")


def _fmt(name: str, t: datetime, unit: str) -> str:
    if unit == "Y":
        return f"{name}_{t.strftime('%Y')}"
    if unit == "M":
        return f"{name}_{t.strftime('%Y%m')}"
    if unit == "D":
        return f"{name}_{t.strftime('%Y%m%d')}"
    if unit == "H":
        return f"{name}_{t.strftime('%Y%m%d%H')}"
    raise InvalidTimeQuantumError(unit)


def views_by_time(name: str, t: datetime, quantum: str) -> list[str]:
    """One view name per unit in the quantum (time.go:90 viewsByTime)."""
    return [_fmt(name, t, unit) for unit in quantum]


def _add_month(t: datetime) -> datetime:
    """time.go:180 addMonth: clamp to the 1st for day>28 to avoid Jan 31 +
    1mo = Mar 2 style double-advances."""
    if t.day > 28:
        t = t.replace(day=1, minute=0, second=0, microsecond=0)
    if t.month == 12:
        return t.replace(year=t.year + 1, month=1)
    return t.replace(month=t.month + 1)


def _add_year(t: datetime) -> datetime:
    return t.replace(year=t.year + 1)


def _next_year_gte(t: datetime, end: datetime) -> bool:
    nxt = _add_year(t)
    return nxt.year == end.year or end > nxt


def _next_month_gte(t: datetime, end: datetime) -> bool:
    nxt = _go_add_month(t)
    return (nxt.year, nxt.month) == (end.year, end.month) or end > nxt


def _go_add_month(t: datetime) -> datetime:
    """Go's time.AddDate(0,1,0): month+1 with day-overflow normalisation
    (Jan 31 -> Mar 2/3)."""
    year, month = t.year, t.month + 1
    if month > 12:
        year, month = year + 1, 1
    day = t.day
    # normalise overflow the way Go does: keep day, roll into next month
    while True:
        try:
            return t.replace(year=year, month=month, day=day)
        except ValueError:
            # e.g. Feb 30 -> Mar 2: count days past month end
            from calendar import monthrange
            last = monthrange(year, month)[1]
            overflow = day - last
            nm_year, nm_month = (year + 1, 1) if month == 12 else (year, month + 1)
            return t.replace(year=nm_year, month=nm_month, day=overflow)


def _next_day_gte(t: datetime, end: datetime) -> bool:
    nxt = t + timedelta(days=1)
    return (nxt.year, nxt.month, nxt.day) == (end.year, end.month, end.day) \
        or end > nxt


def views_by_time_range(name: str, start: datetime, end: datetime,
                        quantum: str) -> list[str]:
    """Minimal covering set of views for [start, end)
    (time.go:105 viewsByTimeRange)."""
    has_year = "Y" in quantum
    has_month = "M" in quantum
    has_day = "D" in quantum
    has_hour = "H" in quantum

    t = start
    results: list[str] = []

    # Walk up from smallest units to largest-unit boundaries.
    if has_hour or has_day or has_month:
        while t < end:
            if has_hour:
                if not _next_day_gte(t, end):
                    break
                if t.hour != 0:
                    results.append(_fmt(name, t, "H"))
                    t += timedelta(hours=1)
                    continue
            if has_day:
                if not _next_month_gte(t, end):
                    break
                if t.day != 1:
                    results.append(_fmt(name, t, "D"))
                    t += timedelta(days=1)
                    continue
            if has_month:
                if not _next_year_gte(t, end):
                    break
                if t.month != 1:
                    results.append(_fmt(name, t, "M"))
                    t = _add_month(t)
                    continue
            break

    # Walk back down from largest units.
    while t < end:
        if has_year and _next_year_gte(t, end):
            results.append(_fmt(name, t, "Y"))
            t = _add_year(t)
        elif has_month and _next_month_gte(t, end):
            results.append(_fmt(name, t, "M"))
            t = _add_month(t)
        elif has_day and _next_day_gte(t, end):
            results.append(_fmt(name, t, "D"))
            t += timedelta(days=1)
        elif has_hour:
            results.append(_fmt(name, t, "H"))
            t += timedelta(hours=1)
        else:
            break

    return results


def view_time_part(view: str) -> str:
    return view.rsplit("_", 1)[-1]


def min_max_views(views: list[str], quantum: str) -> tuple[str, str]:
    """Smallest/largest view at the quantum's most significant granularity
    (time.go:240 minMaxViews)."""
    views = sorted(views)
    if "Y" in quantum:
        chars = 4
    elif "M" in quantum:
        chars = 6
    elif "D" in quantum:
        chars = 8
    elif "H" in quantum:
        chars = 10
    else:
        chars = 0
    lo = next((v for v in views if len(view_time_part(v)) == chars), "")
    hi = next((v for v in reversed(views) if len(view_time_part(v)) == chars), "")
    return lo, hi


def time_of_view(view: str, adj: bool = False) -> datetime | None:
    """Parse the time part of a view name back to a datetime; when ``adj``,
    advance by one unit for exclusive upper bounds (time.go:277 timeOfView)."""
    if not view:
        return None
    part = view_time_part(view)
    n = len(part)
    if n == 4:
        t = datetime.strptime(part, "%Y")
        return _add_year(t) if adj else t
    if n == 6:
        t = datetime.strptime(part, "%Y%m")
        return _add_month(t) if adj else t
    if n == 8:
        t = datetime.strptime(part, "%Y%m%d")
        return t + timedelta(days=1) if adj else t
    if n == 10:
        t = datetime.strptime(part, "%Y%m%d%H")
        return t + timedelta(hours=1) if adj else t
    raise ValueError(f"invalid time format on view: {view}")
