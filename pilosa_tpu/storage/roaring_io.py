"""Pilosa 64-bit roaring file format codec (import/export compatibility).

Implements the reference's serialization (roaring/roaring.go:1046 WriteTo,
docs/architecture.md "Roaring bitmap storage format"): little-endian,
cookie = 12348 (low 16 bits) | version<<16 | flags<<24, container count u32,
then per container a descriptive header (key u64, type u16, cardinality-1
u16), an offset header (u32 per container), and container data:

* array (type 1): cardinality x u16
* bitmap (type 2): 1024 x u64
* run (type 3): run count u16 then [start, last] u16 pairs (inclusive)

A fragment's bit (row, col) maps to position pos = row*SHARD_WIDTH + col;
roaring keys are pos >> 16 and containers hold the low 16 bits
(fragment.go:3087 pos, roaring key split).

All parsing is vectorized numpy — container payloads are decoded with
frombuffer/unpackbits, so the Python-level loop is per container, not per
bit.
"""

from __future__ import annotations

import struct

import numpy as np

from ..core import SHARD_WIDTH, SHARD_WIDTH_EXP

MAGIC = 12348
TYPE_ARRAY = 1
TYPE_BITMAP = 2
TYPE_RUN = 3

ARRAY_MAX_SIZE = 4096  # roaring.go:1927


class RoaringFormatError(ValueError):
    pass


def unpack_roaring(data: bytes, row_id_cap: int | None = None
                   ) -> tuple[np.ndarray, np.ndarray]:
    """Parse a pilosa-roaring blob into (rows, shard-local cols) int64
    arrays (roaring/roaring.go:1258 newRoaringIterator).  Raises
    RoaringFormatError (a ValueError) on any malformed input.
    ``row_id_cap`` bounds the highest implied row id (defaults to the
    process-wide DEFAULT_MAX_ROW_ID)."""
    try:
        return _unpack_roaring(data, row_id_cap)
    except RoaringFormatError:
        raise
    except (struct.error, IndexError, OverflowError, ValueError) as e:
        # ValueError: np.frombuffer on a truncated payload
        raise RoaringFormatError(f"malformed roaring data: {e}")


def _unpack_roaring(data: bytes, row_id_cap: int | None = None
                    ) -> tuple[np.ndarray, np.ndarray]:
    if len(data) < 8:
        raise RoaringFormatError("roaring data too short")
    cookie = struct.unpack_from("<I", data, 0)[0]
    if cookie & 0xFFFF != MAGIC:
        raise RoaringFormatError(
            f"bad roaring cookie: {cookie & 0xFFFF} (want {MAGIC})")
    n_containers = struct.unpack_from("<I", data, 4)[0]
    header_off = 8
    offsets_off = header_off + n_containers * 12
    if len(data) < offsets_off + n_containers * 4:
        raise RoaringFormatError(
            f"roaring data truncated: {n_containers} containers declared, "
            f"{len(data)} bytes")

    # Container keys are the high 48 bits of a bit position; reject any key
    # implying a row id above the configured cap BEFORE the signed shift —
    # a key >= 2**47 would overflow int64 and silently alias into valid
    # rows, bypassing the cap (and the allocation guard behind it).
    if row_id_cap is None:
        from ..core import DEFAULT_MAX_ROW_ID
        row_id_cap = DEFAULT_MAX_ROW_ID

    max_key = (((row_id_cap + 1) << SHARD_WIDTH_EXP) - 1) >> 16

    positions = []
    for i in range(n_containers):
        key, ctype, n_minus1 = struct.unpack_from(
            "<QHH", data, header_off + i * 12)
        if key > max_key:
            raise RoaringFormatError(
                f"roaring container key {key} implies a row id above the "
                f"configured maximum {row_id_cap}")
        n = n_minus1 + 1
        off = struct.unpack_from("<I", data, offsets_off + i * 4)[0]
        base = np.int64(key) << 16
        if ctype == TYPE_ARRAY:
            vals = np.frombuffer(data, dtype="<u2", count=n, offset=off)
            positions.append(base + vals.astype(np.int64))
        elif ctype == TYPE_BITMAP:
            words = np.frombuffer(data, dtype="<u8", count=1024, offset=off)
            bits = np.unpackbits(
                words.view(np.uint8), bitorder="little")
            positions.append(base + np.nonzero(bits)[0].astype(np.int64))
        elif ctype == TYPE_RUN:
            run_count = struct.unpack_from("<H", data, off)[0]
            runs = np.frombuffer(data, dtype="<u2", count=run_count * 2,
                                 offset=off + 2).reshape(run_count, 2)
            for start, last in runs.astype(np.int64):
                positions.append(base + np.arange(start, last + 1))
        else:
            raise RoaringFormatError(f"unknown container type {ctype}")

    if not positions:
        return (np.zeros(0, dtype=np.int64),) * 2
    pos = np.concatenate(positions)
    return pos // SHARD_WIDTH, pos % SHARD_WIDTH


def pack_roaring(rows: np.ndarray, cols: np.ndarray) -> bytes:
    """Serialize (row, shard-local col) bits to the pilosa-roaring format
    (array/bitmap containers; runs are valid to read but not emitted,
    mirroring Optimize()'s conservatism)."""
    rows = np.asarray(rows, dtype=np.int64)
    cols = np.asarray(cols, dtype=np.int64)
    pos = np.unique(rows * SHARD_WIDTH + cols)
    keys = pos >> 16
    low = (pos & 0xFFFF).astype("<u2")

    containers: list[tuple[int, int, np.ndarray | bytes]] = []
    for key in np.unique(keys):
        vals = low[keys == key]
        if vals.size <= ARRAY_MAX_SIZE:
            containers.append((int(key), TYPE_ARRAY, vals))
        else:
            words = np.zeros(1024, dtype="<u8")
            v = vals.astype(np.int64)
            np.bitwise_or.at(words, v >> 6,
                             np.uint64(1) << (v & 63).astype(np.uint64))
            containers.append((int(key), TYPE_BITMAP, words))

    out = bytearray()
    out += struct.pack("<I", MAGIC)
    out += struct.pack("<I", len(containers))
    for key, ctype, vals in containers:
        n = vals.size if ctype == TYPE_ARRAY else \
            int(np.bitwise_count(np.asarray(vals).view(np.uint64)).sum())
        out += struct.pack("<QHH", key, ctype, n - 1)
    offset = 8 + len(containers) * 12 + len(containers) * 4
    for key, ctype, vals in containers:
        out += struct.pack("<I", offset)
        offset += vals.size * 2 if ctype == TYPE_ARRAY else 8192
    for key, ctype, vals in containers:
        out += vals.tobytes()
    return bytes(out)
