"""Pilosa 64-bit roaring file format codec (import/export compatibility).

Implements the reference's serialization (roaring/roaring.go:1046 WriteTo,
docs/architecture.md "Roaring bitmap storage format"): little-endian,
cookie = 12348 (low 16 bits) | version<<16 | flags<<24, container count u32,
then per container a descriptive header (key u64, type u16, cardinality-1
u16), an offset header (u32 per container), and container data:

* array (type 1): cardinality x u16
* bitmap (type 2): 1024 x u64
* run (type 3): run count u16 then [start, last] u16 pairs (inclusive)

A fragment's bit (row, col) maps to position pos = row*SHARD_WIDTH + col;
roaring keys are pos >> 16 and containers hold the low 16 bits
(fragment.go:3087 pos, roaring key split).

All parsing is vectorized numpy — container payloads are decoded with
frombuffer/unpackbits, so the Python-level loop is per container, not per
bit.
"""

from __future__ import annotations

import struct

import numpy as np

from ..core import SHARD_WIDTH, SHARD_WIDTH_EXP
from ..utils.durable import checksum

MAGIC = 12348
# official-roaring interop cookies (roaring.go:5020; the reference's
# UnmarshalBinary accepts both its own and the official format)
OFFICIAL_NO_RUNS = 12346
OFFICIAL_RUNS = 12347
TYPE_ARRAY = 1
TYPE_BITMAP = 2
TYPE_RUN = 3

ARRAY_MAX_SIZE = 4096  # roaring.go:1927
RUN_MAX_SIZE = 2048    # roaring.go:1930


class RoaringFormatError(ValueError):
    pass


# -- fragment snapshot codec (docs/robustness.md "Durability & recovery") --
#
# The native snapshot file for Fragment's sparse word store.  Version
# history:
#   v2 (PTPUFRG2): header + nnz LE (flat u32, word u32) pairs — legacy,
#       read-only, no checksums.
#   v3 (PTPUFRG3): header + nnz LE u64 flat indices + nnz LE u32 words —
#       legacy, read-only, no checksums (tall sparse fragments).
#   v4 (PTPUFRG4): checksummed.  Layout:
#       [0:24)   header  <8sIIQ>  magic, cap_rows, words/row, nnz
#       [24:28)  <I> CRC of the header bytes — verified BEFORE nnz is
#                trusted, so a flipped bit in nnz cannot drive a huge
#                allocation or a bogus payload read
#       [28:28+12*nnz)  payload: nnz LE u64 flat indices, nnz LE u32 words
#       trailer  <I> CRC of the payload bytes
#   The total size is fully determined by the header, so truncation and
#   appended garbage are both detected by a length check alone.
#
# All versions go through unpack_snapshot(), which raises
# SnapshotFormatError on ANY malformed input (the caller decides whether
# that quarantines the fragment or propagates).

SNAP_MAGIC_V2 = b"PTPUFRG2"
SNAP_MAGIC_V3 = b"PTPUFRG3"
SNAP_MAGIC_V4 = b"PTPUFRG4"
SNAP_HEADER = struct.Struct("<8sIIQ")
_SNAP_CRC = struct.Struct("<I")


class SnapshotFormatError(ValueError):
    """Malformed/corrupt fragment snapshot bytes."""


def pack_snapshot(cap_rows: int, idx: np.ndarray, val: np.ndarray,
                  words_per_row: int) -> bytes:
    """Serialize a sparse word store to the checksummed v4 format."""
    header = SNAP_HEADER.pack(SNAP_MAGIC_V4, cap_rows, words_per_row,
                              idx.size)
    idx_b = idx.astype("<u8").tobytes()
    val_b = val.astype("<u4").tobytes()
    return b"".join((
        header,
        _SNAP_CRC.pack(checksum(header)),
        idx_b,
        val_b,
        _SNAP_CRC.pack(checksum(val_b, checksum(idx_b))),
    ))


def unpack_snapshot(data: bytes, words_per_row: int,
                    row_id_cap: int | None = None
                    ) -> tuple[int, np.ndarray, np.ndarray]:
    """Parse any snapshot version into (cap_rows, idx int64, val uint32).

    Checksums are verified for v4; v2/v3 predate them and get structural
    validation only (exact length, sorted indices, in-range values) —
    the lenient-load path for files written before this format existed.
    Raises SnapshotFormatError on anything malformed."""
    try:
        return _unpack_snapshot(data, words_per_row, row_id_cap)
    except SnapshotFormatError:
        raise
    except (struct.error, ValueError, OverflowError) as e:
        raise SnapshotFormatError(f"malformed snapshot: {e}")


def _unpack_snapshot(data, words_per_row, row_id_cap):
    if len(data) < SNAP_HEADER.size:
        raise SnapshotFormatError(
            f"snapshot too short ({len(data)} bytes)")
    magic, cap_rows, words, nnz = SNAP_HEADER.unpack_from(data, 0)
    if magic not in (SNAP_MAGIC_V2, SNAP_MAGIC_V3, SNAP_MAGIC_V4):
        raise SnapshotFormatError(f"bad snapshot magic {magic!r}")
    if magic == SNAP_MAGIC_V4:
        # header CRC first: nnz must not be trusted before this passes
        if len(data) < SNAP_HEADER.size + _SNAP_CRC.size:
            raise SnapshotFormatError("snapshot header truncated")
        (hcrc,) = _SNAP_CRC.unpack_from(data, SNAP_HEADER.size)
        if checksum(data[:SNAP_HEADER.size]) != hcrc:
            raise SnapshotFormatError("snapshot header CRC mismatch")
    if words != words_per_row:
        raise SnapshotFormatError(
            f"snapshot has {words} words/row, expected {words_per_row}")
    if row_id_cap is not None and cap_rows > 2 * (row_id_cap + 1):
        # row capacity doubles, so a legitimately-written snapshot never
        # declares more than 2*(cap+1) rows; beyond that the header is
        # corrupt or was written under a larger max_row_id config
        raise SnapshotFormatError(
            f"snapshot declares {cap_rows} rows, above the configured "
            f"max_row_id {row_id_cap}; raise max_row_id if this data "
            f"was written with a larger cap")
    if magic == SNAP_MAGIC_V2:
        want = SNAP_HEADER.size + 8 * nnz
        if len(data) != want:
            raise SnapshotFormatError(
                f"snapshot is {len(data)} bytes, v2 header implies {want}")
        pairs = np.frombuffer(data, dtype="<u4", count=2 * nnz,
                              offset=SNAP_HEADER.size)
        idx = pairs[0::2].astype(np.int64)
        val = pairs[1::2].astype(np.uint32)
    else:
        off = SNAP_HEADER.size
        if magic == SNAP_MAGIC_V4:
            off += _SNAP_CRC.size
        want = off + 12 * nnz
        if magic == SNAP_MAGIC_V4:
            want += _SNAP_CRC.size
        if len(data) != want:
            raise SnapshotFormatError(
                f"snapshot is {len(data)} bytes, header implies {want}")
        idx_b = data[off: off + 8 * nnz]
        val_b = data[off + 8 * nnz: off + 12 * nnz]
        if magic == SNAP_MAGIC_V4:
            (pcrc,) = _SNAP_CRC.unpack_from(data, want - _SNAP_CRC.size)
            if checksum(val_b, checksum(idx_b)) != pcrc:
                raise SnapshotFormatError("snapshot payload CRC mismatch")
        idx = np.frombuffer(idx_b, dtype="<u8").astype(np.int64)
        val = np.frombuffer(val_b, dtype="<u4").astype(np.uint32)
    # structural validation (cheap; the load-bearing defense for the
    # un-checksummed legacy versions): indices sorted/unique/in-range,
    # or every downstream searchsorted silently mis-answers
    if idx.size:
        if int(idx[0]) < 0 or int(idx[-1]) >= cap_rows * words_per_row:
            raise SnapshotFormatError("snapshot index out of range")
        if idx.size > 1 and not bool(np.all(np.diff(idx) > 0)):
            raise SnapshotFormatError(
                "snapshot indices not strictly increasing")
    keep = val != 0
    if not keep.all():
        idx, val = idx[keep], val[keep]
    return cap_rows, idx, val


def unpack_roaring(data: bytes, row_id_cap: int | None = None
                   ) -> tuple[np.ndarray, np.ndarray]:
    """Parse a pilosa-roaring blob into (rows, shard-local cols) int64
    arrays (roaring/roaring.go:1258 newRoaringIterator).  Raises
    RoaringFormatError (a ValueError) on any malformed input.
    ``row_id_cap`` bounds the highest implied row id (defaults to the
    process-wide DEFAULT_MAX_ROW_ID)."""
    try:
        return _unpack_roaring(data, row_id_cap)
    except RoaringFormatError:
        raise
    except (struct.error, IndexError, OverflowError, ValueError) as e:
        # ValueError: np.frombuffer on a truncated payload
        raise RoaringFormatError(f"malformed roaring data: {e}")


def _unpack_roaring(data: bytes, row_id_cap: int | None = None
                    ) -> tuple[np.ndarray, np.ndarray]:
    if len(data) < 8:
        raise RoaringFormatError("roaring data too short")
    cookie = struct.unpack_from("<I", data, 0)[0]
    if cookie & 0xFFFF in (OFFICIAL_NO_RUNS, OFFICIAL_RUNS):
        rows, cols = _unpack_official(data, cookie)
        # apply the same row-id allocation guard as the pilosa path
        # (official keys are u16, but configured caps can sit below the
        # row 4095 a max key implies)
        if row_id_cap is None:
            from ..core import DEFAULT_MAX_ROW_ID
            row_id_cap = DEFAULT_MAX_ROW_ID
        if rows.size and int(rows.max()) > row_id_cap:
            raise RoaringFormatError(
                f"roaring data implies a row id {int(rows.max())} above "
                f"the configured maximum {row_id_cap}")
        return rows, cols
    if cookie & 0xFFFF != MAGIC:
        raise RoaringFormatError(
            f"bad roaring cookie: {cookie & 0xFFFF} (want {MAGIC})")
    n_containers = struct.unpack_from("<I", data, 4)[0]
    header_off = 8
    offsets_off = header_off + n_containers * 12
    if len(data) < offsets_off + n_containers * 4:
        raise RoaringFormatError(
            f"roaring data truncated: {n_containers} containers declared, "
            f"{len(data)} bytes")

    # Container keys are the high 48 bits of a bit position; reject any key
    # implying a row id above the configured cap BEFORE the signed shift —
    # a key >= 2**47 would overflow int64 and silently alias into valid
    # rows, bypassing the cap (and the allocation guard behind it).
    if row_id_cap is None:
        from ..core import DEFAULT_MAX_ROW_ID
        row_id_cap = DEFAULT_MAX_ROW_ID

    max_key = (((row_id_cap + 1) << SHARD_WIDTH_EXP) - 1) >> 16

    positions = []
    for i in range(n_containers):
        key, ctype, n_minus1 = struct.unpack_from(
            "<QHH", data, header_off + i * 12)
        if key > max_key:
            raise RoaringFormatError(
                f"roaring container key {key} implies a row id above the "
                f"configured maximum {row_id_cap}")
        n = n_minus1 + 1
        off = struct.unpack_from("<I", data, offsets_off + i * 4)[0]
        base = np.int64(key) << 16
        if ctype == TYPE_ARRAY:
            vals = np.frombuffer(data, dtype="<u2", count=n, offset=off)
            positions.append(base + vals.astype(np.int64))
        elif ctype == TYPE_BITMAP:
            words = np.frombuffer(data, dtype="<u8", count=1024, offset=off)
            bits = np.unpackbits(
                words.view(np.uint8), bitorder="little")
            positions.append(base + np.nonzero(bits)[0].astype(np.int64))
        elif ctype == TYPE_RUN:
            run_count = struct.unpack_from("<H", data, off)[0]
            runs = np.frombuffer(data, dtype="<u2", count=run_count * 2,
                                 offset=off + 2).reshape(run_count, 2)
            for start, last in runs.astype(np.int64):
                positions.append(base + np.arange(start, last + 1))
        else:
            raise RoaringFormatError(f"unknown container type {ctype}")

    if not positions:
        return (np.zeros(0, dtype=np.int64),) * 2
    pos = np.concatenate(positions)
    return pos // SHARD_WIDTH, pos % SHARD_WIDTH


def _unpack_official(data: bytes, cookie: int
                     ) -> tuple[np.ndarray, np.ndarray]:
    """Official-roaring (32-bit) interop: cookie 12346 (arrays/bitmaps,
    with offset table) or 12347 (run containers flagged in a bitset) —
    roaring.go:5024 readOfficialHeader, :1343
    officialRoaringIterator.Next.  Official run pairs are
    (start, length-1); pilosa's are (start, last).

    Divergence from the reference, on purpose: per the official spec the
    runs cookie also carries an offset table once there are
    NO_OFFSET_THRESHOLD (4) or more containers; the reference assumes
    run-cookie files are always sequential and would misparse such files
    from stock CRoaring/Java writers.  Array containers hold up to 4096
    values INCLUSIVE officially (bitmap only above), where the
    reference's typer uses a strict <, silently misreading a 4096-card
    array (8192 bytes) as a bitmap."""
    NO_OFFSET_THRESHOLD = 4
    pos_off = 4
    if cookie & 0xFFFF == OFFICIAL_NO_RUNS:
        n = struct.unpack_from("<I", data, pos_off)[0]
        pos_off += 4
        run_flags = None
    else:
        n = (cookie >> 16) + 1
        flag_bytes = (n + 7) // 8
        run_flags = np.unpackbits(
            np.frombuffer(data, dtype=np.uint8, count=flag_bytes,
                          offset=pos_off), bitorder="little")
        pos_off += flag_bytes
    if n > (1 << 16):
        raise RoaringFormatError(
            "more than 2^16 containers in official roaring header")
    headers = np.frombuffer(data, dtype="<u2", count=n * 2,
                            offset=pos_off).reshape(n, 2)
    pos_off += n * 4
    offsets = None
    if run_flags is None or n >= NO_OFFSET_THRESHOLD:
        offsets = np.frombuffer(data, dtype="<u4", count=n, offset=pos_off)
        pos_off += n * 4

    positions = []
    cur = pos_off
    for i in range(n):
        key = int(headers[i, 0])
        card = int(headers[i, 1]) + 1
        is_run = run_flags is not None and i < run_flags.size \
            and run_flags[i]
        off = int(offsets[i]) if offsets is not None else cur
        base = np.int64(key) << 16
        if is_run:
            run_count = struct.unpack_from("<H", data, off)[0]
            runs = np.frombuffer(data, dtype="<u2", count=run_count * 2,
                                 offset=off + 2).reshape(run_count, 2)
            for start, length1 in runs.astype(np.int64):
                positions.append(base + np.arange(start,
                                                  start + length1 + 1))
            cur = off + 2 + run_count * 4
        elif card <= ARRAY_MAX_SIZE:
            vals = np.frombuffer(data, dtype="<u2", count=card, offset=off)
            positions.append(base + vals.astype(np.int64))
            cur = off + card * 2
        else:
            words = np.frombuffer(data, dtype="<u8", count=1024, offset=off)
            bits = np.unpackbits(words.view(np.uint8), bitorder="little")
            positions.append(base + np.nonzero(bits)[0].astype(np.int64))
            cur = off + 8192
    if not positions:
        return (np.zeros(0, dtype=np.int64),) * 2
    pos = np.concatenate(positions)
    return pos // SHARD_WIDTH, pos % SHARD_WIDTH


def _count_runs(vals: np.ndarray) -> int:
    """Number of runs in a sorted unique u16 array (roaring.go:2200
    countRuns)."""
    if vals.size == 0:
        return 0
    return int(np.count_nonzero(np.diff(vals.astype(np.int64)) != 1)) + 1


def _choose_container(vals: np.ndarray) -> tuple[int, int, bytes]:
    """(type, cardinality, payload) for one container's sorted unique u16
    values, per the optimize heuristic (roaring.go:2232): runs when run
    count <= RUN_MAX_SIZE and <= N/2, else array when N < ARRAY_MAX_SIZE,
    else bitmap."""
    n = int(vals.size)
    n_runs = _count_runs(vals)
    if n_runs <= RUN_MAX_SIZE and n_runs <= n // 2:
        v = vals.astype(np.int64)
        brk = np.nonzero(np.diff(v) != 1)[0]
        starts = np.concatenate(([v[0]], v[brk + 1]))
        lasts = np.concatenate((v[brk], [v[-1]]))
        payload = struct.pack("<H", n_runs) + np.column_stack(
            (starts, lasts)).astype("<u2").tobytes()
        return TYPE_RUN, n, payload
    if n < ARRAY_MAX_SIZE:
        return TYPE_ARRAY, n, vals.astype("<u2").tobytes()
    words = np.zeros(1024, dtype="<u8")
    v = vals.astype(np.int64)
    np.bitwise_or.at(words, v >> 6,
                     np.uint64(1) << (v & 63).astype(np.uint64))
    return TYPE_BITMAP, n, words.tobytes()


def _assemble(containers: list[tuple[int, int, int, bytes]]) -> bytes:
    """Assemble (key, type, cardinality, payload) containers into a
    pilosa-roaring blob (roaring.go:1046 WriteTo layout)."""
    out = bytearray()
    out += struct.pack("<I", MAGIC)
    out += struct.pack("<I", len(containers))
    for key, ctype, n, _ in containers:
        out += struct.pack("<QHH", key, ctype, n - 1)
    offset = 8 + len(containers) * 12 + len(containers) * 4
    for _, _, _, payload in containers:
        out += struct.pack("<I", offset)
        offset += len(payload)
    for _, _, _, payload in containers:
        out += payload
    return bytes(out)


def pack_roaring(rows: np.ndarray, cols: np.ndarray) -> bytes:
    """Serialize (row, shard-local col) bits to the pilosa-roaring format,
    choosing the cheapest container per key with the reference's optimize
    heuristic (see _choose_container)."""
    rows = np.asarray(rows, dtype=np.int64)
    cols = np.asarray(cols, dtype=np.int64)
    pos = np.unique(rows * SHARD_WIDTH + cols)
    keys = pos >> 16
    low = (pos & 0xFFFF).astype("<u2")
    containers = []
    for key in np.unique(keys):
        ctype, n, payload = _choose_container(low[keys == key])
        containers.append((int(key), ctype, n, payload))
    return _assemble(containers)


def pack_roaring_words(words: np.ndarray) -> bytes:
    """Serialize a dense [rows, SHARD_WORDS] uint32 words block without
    expanding to bit pairs (bulk loaders / bench fixtures).  Dense
    windows (the bitmap-container regime) are memcpy'd straight from the
    word block — a 65536-column window's bitmap payload IS its 8KB word
    slice; sparse/runny windows go through the same per-container
    chooser as pack_roaring."""
    words = np.ascontiguousarray(words, dtype=np.uint32)
    n_rows = words.shape[0]
    per_row = SHARD_WIDTH >> 16  # 65536-col windows per row
    blocks = words.reshape(n_rows * per_row, 2048)
    cards = np.bitwise_count(blocks).sum(axis=1)
    containers = []
    for bi in np.nonzero(cards)[0]:
        key = int(bi)  # key = row * per_row + window, in row-major order
        card = int(cards[bi])
        if card >= ARRAY_MAX_SIZE:
            # candidate bitmap: verify runs don't win without unpacking
            w = blocks[bi].view("<u8")
            shifted = (w << np.uint64(1))
            shifted[1:] |= (w[:-1] >> np.uint64(63))
            n_runs = int(np.bitwise_count(w & ~shifted).sum())
            if not (n_runs <= RUN_MAX_SIZE and n_runs <= card // 2):
                containers.append(
                    (key, TYPE_BITMAP, card, blocks[bi].tobytes()))
                continue
        bits = np.unpackbits(blocks[bi].view(np.uint8),
                             bitorder="little")
        vals = np.nonzero(bits)[0].astype("<u2")
        ctype, n, payload = _choose_container(vals)
        containers.append((key, ctype, n, payload))
    return _assemble(containers)
