"""Storage tree: Holder > Index > Field > View > Fragment (reference
holder.go/index.go/field.go/view.go/fragment.go)."""

from .fragment import Fragment  # noqa: F401
from .view import View  # noqa: F401
from .field import Field, FieldOptions  # noqa: F401
from .index import Index  # noqa: F401
from .holder import Holder  # noqa: F401
