"""Recursive-descent PQL parser — behavioral port of the 83-line PEG grammar
(reference pql/pql.peg; generated parser pql/pql.peg.go).

The grammar is small enough that a hand-written descent is clearer and easier
to keep in sync than a generated PEG machine.  Semantics preserved:

* special call forms: Set, SetRowAttrs, SetColumnAttrs, Clear, ClearRow,
  Store, TopN, Rows, Range (legacy), generic `IDENT(children..., args...)`
* positional args stored under reserved keys: _col, _row, _field, _timestamp
* conditions: `field <op> value` and the double-bound conditional
  `4 <= field < 9` which collapses to a BETWEEN with strict bounds adjusted
  (ast.go:81-100 endConditional)
* value forms: null/true/false, timestamps (bare or quoted), ints, floats,
  bare words, single/double-quoted strings (escapes), lists, nested calls
"""

from __future__ import annotations

import re

from .ast import (
    BETWEEN, Call, Condition, EQ, GT, GTE, LT, LTE, NEQ, Query,
)


class ParseError(ValueError):
    def __init__(self, msg: str, pos: int, text: str):
        line = text.count("\n", 0, pos) + 1
        col = pos - (text.rfind("\n", 0, pos) + 1) + 1
        super().__init__(f"parse error at line {line}:{col}: {msg}")
        self.pos = pos


_TIMESTAMP = re.compile(r"\d{4}-[01]\d-[0-3]\dT\d\d:\d\d")
_IDENT = re.compile(r"[A-Za-z][A-Za-z0-9]*")
_FIELD = re.compile(r"[A-Za-z][A-Za-z0-9_-]*")
_RESERVED_FIELDS = ("_row", "_col", "_start", "_end", "_timestamp", "_field")
_UINT = re.compile(r"0|[1-9]\d*")
_NUMBER = re.compile(r"-?(\d+(\.\d*)?|\.\d+)")
_INT = re.compile(r"-?(0|[1-9]\d*)")
_BAREWORD = re.compile(r"[A-Za-z0-9_:-]+")
_COND_OPS = ("><", "<=", ">=", "==", "!=", "<", ">")  # longest-first


class _Parser:
    def __init__(self, text: str, mkint=None):
        self.text = text
        self.pos = 0
        # mkint(value, token_start) -> int: literal-construction hook used
        # by the prepared-statement cache to tag integer literals with their
        # source position (executor/prepared.py).  Default: identity.
        self.mkint = mkint or (lambda v, start: v)

    # -- low-level ---------------------------------------------------------

    def err(self, msg: str) -> ParseError:
        return ParseError(msg, self.pos, self.text)

    def sp(self):
        while self.pos < len(self.text) and self.text[self.pos] in " \t\n":
            self.pos += 1

    def eof(self) -> bool:
        return self.pos >= len(self.text)

    def peek(self, s: str) -> bool:
        return self.text.startswith(s, self.pos)

    def accept(self, s: str) -> bool:
        if self.peek(s):
            self.pos += len(s)
            return True
        return False

    def expect(self, s: str):
        if not self.accept(s):
            raise self.err(f"expected {s!r}")

    def match(self, rx: re.Pattern) -> str | None:
        m = rx.match(self.text, self.pos)
        if m is None:
            return None
        self.pos = m.end()
        return m.group()

    def comma(self):
        self.sp()
        self.expect(",")
        self.sp()

    def try_comma(self) -> bool:
        save = self.pos
        self.sp()
        if self.accept(","):
            self.sp()
            return True
        self.pos = save
        return False

    # -- grammar -----------------------------------------------------------

    def parse(self) -> Query:
        q = Query()
        self.sp()
        while not self.eof():
            q.calls.append(self.call())
            self.sp()
        return q

    def call(self) -> Call:
        for name in ("SetRowAttrs", "SetColumnAttrs", "Set", "ClearRow",
                     "Clear", "Store", "TopN", "Rows", "Range"):
            save = self.pos
            if self.accept(name):
                # must be followed by '(' (else it's a generic ident prefix
                # like "SetFoo")
                save2 = self.pos
                self.sp()
                if self.peek("("):
                    self.pos = save2
                    return getattr(self, "_call_" + name.lower())()
            self.pos = save
        ident = self.match(_IDENT)
        if ident is None:
            raise self.err("expected call name")
        return self._generic_call(ident)

    def _open(self):
        self.sp()
        self.expect("(")
        self.sp()

    def _close(self):
        self.sp()
        self.expect(")")

    # Set(col, field=row[, timestamp])   (pql.peg Call/Set)
    def _call_set(self) -> Call:
        call = Call("Set")
        self._open()
        call.args["_col"] = self._col_or_key()
        self.comma()
        self._args(call)
        save = self.pos
        if self.try_comma():
            ts = self._timestampfmt()
            if ts is None:
                self.pos = save
            else:
                call.args["_timestamp"] = ts
        self._close()
        return call

    def _call_setrowattrs(self) -> Call:
        call = Call("SetRowAttrs")
        self._open()
        f = self.match(_FIELD)
        if f is None:
            raise self.err("expected field name")
        call.args["_field"] = f
        self.comma()
        call.args["_row"] = self._col_or_key()
        self.comma()
        self._args(call)
        self._close()
        return call

    def _call_setcolumnattrs(self) -> Call:
        call = Call("SetColumnAttrs")
        self._open()
        call.args["_col"] = self._col_or_key()
        self.comma()
        self._args(call)
        self._close()
        return call

    def _call_clear(self) -> Call:
        call = Call("Clear")
        self._open()
        call.args["_col"] = self._col_or_key()
        self.comma()
        self._args(call)
        self._close()
        return call

    def _call_clearrow(self) -> Call:
        call = Call("ClearRow")
        self._open()
        self._arg(call)
        self._close()
        return call

    # Store(Call, field=row)
    def _call_store(self) -> Call:
        call = Call("Store")
        self._open()
        call.children.append(self.call())
        self.comma()
        self._arg(call)
        self._close()
        return call

    def _call_topn(self) -> Call:
        return self._posfield_call("TopN")

    def _call_rows(self) -> Call:
        return self._posfield_call("Rows")

    def _posfield_call(self, name: str) -> Call:
        call = Call(name)
        self._open()
        f = self.match(_FIELD)
        if f is None:
            raise self.err("expected field name")
        call.args["_field"] = f
        if self.try_comma():
            self._allargs(call)
        self._close()
        return call

    # Range(field=value, from, to) — legacy time range (pql.peg Range)
    def _call_range(self) -> Call:
        call = Call("Range")
        self._open()
        f = self._field_name()
        self.sp()
        self.expect("=")
        self.sp()
        call.args[f] = self._value()
        self.comma()
        self.accept("from=")
        call.args["from"] = self._require_timestamp()
        self.comma()
        self.accept("to=")
        self.sp()
        call.args["to"] = self._require_timestamp()
        self._close()
        return call

    def _generic_call(self, name: str) -> Call:
        call = Call(name)
        self._open()
        self._allargs(call)
        self.try_comma()
        self._close()
        return call

    # allargs <- Call (comma Call)* (comma args)? / args / sp
    def _allargs(self, call: Call):
        self.sp()
        if self.peek(")"):
            return
        save = self.pos
        try:
            child = self.call()
        except ParseError:
            self.pos = save
            self._args(call)
            return
        call.children.append(child)
        while True:
            save = self.pos
            if not self.try_comma():
                break
            if self.peek(")"):
                self.pos = save
                break
            save2 = self.pos
            try:
                call.children.append(self.call())
            except ParseError:
                self.pos = save2
                self._args(call)
                break

    # args <- arg (comma args)? sp
    def _args(self, call: Call):
        self._arg(call)
        while True:
            save = self.pos
            if not self.try_comma():
                break
            if self.peek(")"):
                self.pos = save
                break
            save2 = self.pos
            try:
                self._arg(call)
            except ParseError:
                # could be the trailing timestamp of Set; rewind the comma
                self.pos = save
                break

    def _arg(self, call: Call):
        self.sp()
        # conditional: int <[=] field <[=] int
        save = self.pos
        cond = self._try_conditional()
        if cond is not None:
            f, c = cond
            call.args[f] = c
            return
        self.pos = save
        f = self._field_name()
        self.sp()
        if self.accept("="):
            # '==' is a condition, '=' alone an assignment
            if self.peek("="):
                self.pos -= 1
            else:
                self.sp()
                if f in call.args:
                    raise self.err(f"duplicate argument: {f}")
                call.args[f] = self._value()
                return
        for op in _COND_OPS:
            if self.accept(op):
                self.sp()
                if f in call.args:
                    raise self.err(f"duplicate argument: {f}")
                call.args[f] = Condition(op, self._value())
                return
        raise self.err("expected '=' or condition operator after field")

    def _try_conditional(self):
        """conditional <- condint condLT condfield condLT condint
        e.g. `4 <= x < 9` (ast.go:81 endConditional)."""
        lo_start = self.pos
        lo_s = self.match(_INT)
        if lo_s is None:
            return None
        self.sp()
        op1 = "<=" if self.accept("<=") else ("<" if self.accept("<") else None)
        if op1 is None:
            return None
        self.sp()
        f = self.match(_FIELD)
        if f is None:
            return None
        self.sp()
        op2 = "<=" if self.accept("<=") else ("<" if self.accept("<") else None)
        if op2 is None:
            return None
        self.sp()
        hi_start = self.pos
        hi_s = self.match(_INT)
        if hi_s is None:
            return None
        lo = self.mkint(int(lo_s), lo_start)
        hi = self.mkint(int(hi_s), hi_start)
        if op1 == "<":
            lo = lo + 1
        if op2 == "<":
            hi = hi - 1
        return f, Condition(BETWEEN, [lo, hi])

    def _field_name(self) -> str:
        for r in _RESERVED_FIELDS:
            if self.accept(r):
                return r
        f = self.match(_FIELD)
        if f is None:
            raise self.err("expected field name")
        return f

    def _col_or_key(self):
        """col/row: uint or quoted key (pql.peg col/row)."""
        self.sp()
        if self.peek("'") or self.peek('"'):
            return self._quoted_string()
        start = self.pos
        u = self.match(_UINT)
        if u is None:
            raise self.err("expected column/row id or quoted key")
        return self.mkint(int(u), start)

    def _quoted_string(self) -> str:
        quote = self.text[self.pos]
        self.pos += 1
        out = []
        while True:
            if self.eof():
                raise self.err("unterminated string")
            ch = self.text[self.pos]
            if ch == "\\" and self.pos + 1 < len(self.text) and \
                    self.text[self.pos + 1] in (quote, "\\"):
                out.append(self.text[self.pos + 1])
                self.pos += 2
                continue
            if ch == quote:
                self.pos += 1
                return "".join(out)
            out.append(ch)
            self.pos += 1

    def _timestampfmt(self) -> str | None:
        self.sp()
        for quote in ("'", '"'):
            if self.peek(quote):
                save = self.pos
                self.pos += 1
                ts = self.match(_TIMESTAMP)
                if ts is not None and self.accept(quote):
                    return ts
                self.pos = save
                return None
        return self.match(_TIMESTAMP)

    def _require_timestamp(self) -> str:
        self.sp()
        ts = self._timestampfmt()
        if ts is None:
            raise self.err("expected timestamp (YYYY-MM-DDTHH:MM)")
        return ts

    # value <- item / [list]
    def _value(self):
        self.sp()
        if self.accept("["):
            items = []
            self.sp()
            if not self.peek("]"):
                items.append(self._item())
                while self.try_comma():
                    items.append(self._item())
            self.sp()
            self.expect("]")
            return items
        return self._item()

    def _item(self):
        self.sp()
        # null/true/false need a boundary lookahead (pql.peg item)
        for lit, v in (("null", None), ("true", True), ("false", False)):
            if self.peek(lit):
                after = self.pos + len(lit)
                rest = self.text[after:after + 1]
                if rest in ("", ",", ")", " ", "\t", "\n", "]"):
                    self.pos = after
                    return v
        ts = self._timestampfmt()
        if ts is not None:
            return ts
        if self.peek('"') or self.peek("'"):
            return self._quoted_string()
        start = self.pos
        m = self.match(_NUMBER)
        if m is not None:
            # bareword that starts with digits (e.g. 1a2b) must win over a
            # partial number parse
            nxt = self.text[self.pos:self.pos + 1]
            if nxt and (nxt.isalnum() or nxt in "_:-") and "." not in m:
                self.pos -= len(m)
            elif "." in m:
                return float(m)
            else:
                v = int(m)
                if not (-(1 << 63) <= v < (1 << 63)):
                    # int64 range, like the reference's strconv.ParseInt
                    # failure (ast.go addNumVal)
                    raise self.err(f"integer out of int64 range: {m}")
                return self.mkint(v, start)
        save = self.pos
        ident = self.match(_IDENT)
        if ident is not None:
            self.sp()
            if self.peek("("):
                return self._generic_call(ident)
            self.pos = save
        w = self.match(_BAREWORD)
        if w is not None:
            return w
        raise self.err("expected a value")


def parse(text: str, mkint=None) -> Query:
    """(pql/parser.go:48 ParseString).  ``mkint`` tags integer literals with
    source positions for the prepared-statement cache."""
    return _Parser(text, mkint).parse()
