"""PQL: query language AST + parser (reference pql/)."""

from .ast import (  # noqa: F401
    BETWEEN, Call, Condition, EQ, GT, GTE, LT, LTE, NEQ, Query, WRITE_CALLS,
)
from .parser import ParseError, parse  # noqa: F401
