"""PQL AST: Query, Call, Condition (reference pql/ast.go:27-560).

A query is a list of calls; a call has a name, an args dict (string keys to
int/float/str/bool/None/list/Condition values, with positional args under
reserved keys "_col", "_row", "_field", "_timestamp") and child calls.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Any

# Condition operators (pql/token.go / ast.go Condition).
LT, LTE, GT, GTE, EQ, NEQ, BETWEEN = "<", "<=", ">", ">=", "==", "!=", "><"


class LitInt(int):
    """An int carrying the provenance of the query-string literal it came
    from: ``lit`` is the literal's index in the fingerprint's value list and
    ``add`` the affine offset applied since (e.g. the ±1 strict-bound
    adjustment of `4 <= x < 9`, or a BSI base subtraction).  Behaves as a
    plain int everywhere; only the prepared-statement cache
    (executor/prepared.py) looks at the tags.  Affine arithmetic preserves
    provenance; everything else decays to int."""

    def __new__(cls, value, lit: int, add: int = 0):
        x = super().__new__(cls, value)
        x.lit = lit
        x.add = add
        return x

    def __add__(self, other):
        if type(other) is int:
            return LitInt(int(self) + other, self.lit, self.add + other)
        return int(self) + other

    __radd__ = __add__

    def __sub__(self, other):
        if type(other) is int:
            return LitInt(int(self) - other, self.lit, self.add - other)
        return int(self) - other

_COND_STRINGS = {LT: "<", LTE: "<=", GT: ">", GTE: ">=", EQ: "==",
                 NEQ: "!=", BETWEEN: "><"}


@dataclass
class Condition:
    op: str
    value: Any  # int for comparisons, [lo, hi] for BETWEEN

    def string_with_subj(self, subj: str) -> str:
        if self.op == BETWEEN:
            lo, hi = self.value
            return f"{lo} <= {subj} <= {hi}"
        return f"{subj} {self.op} {_value_string(self.value)}"

    def __repr__(self):
        return f"Condition({self.op!r}, {self.value!r})"


def _value_string(v) -> str:
    if isinstance(v, str):
        return f'"{v}"'
    if v is None:
        return "null"
    if v is True:
        return "true"
    if v is False:
        return "false"
    if isinstance(v, list):
        return "[" + ",".join(_value_string(x) for x in v) + "]"
    return str(v)


@dataclass
class Call:
    name: str
    args: dict[str, Any] = field(default_factory=dict)
    children: list["Call"] = field(default_factory=list)

    # -- typed arg accessors (pql/ast.go:220-360) --------------------------

    def arg(self, key: str, default=None):
        return self.args.get(key, default)

    def uint_arg(self, key: str) -> tuple[int, bool]:
        """(value, found); raises on non-integer (ast.go UintArg)."""
        v = self.args.get(key)
        if v is None:
            return 0, False
        if isinstance(v, bool) or not isinstance(v, int):
            raise TypeError(
                f"arg {key!r} of call {self.name!r} must be an integer, "
                f"got {v!r}")
        if v < 0:
            raise ValueError(f"arg {key!r} must be non-negative, got {v}")
        return v, True

    def int_arg(self, key: str) -> tuple[int, bool]:
        v = self.args.get(key)
        if v is None:
            return 0, False
        if isinstance(v, bool) or not isinstance(v, int):
            raise TypeError(
                f"arg {key!r} of call {self.name!r} must be an integer, "
                f"got {v!r}")
        return v, True

    def string_arg(self, key: str) -> tuple[str, bool]:
        v = self.args.get(key)
        if v is None:
            return "", False
        if not isinstance(v, str):
            raise TypeError(f"arg {key!r} must be a string, got {v!r}")
        return v, True

    def bool_arg(self, key: str) -> tuple[bool, bool]:
        v = self.args.get(key)
        if v is None:
            return False, False
        if not isinstance(v, bool):
            raise TypeError(f"arg {key!r} must be a bool, got {v!r}")
        return v, True

    def condition_arg(self) -> tuple[str, "Condition"] | None:
        """First (field, Condition) arg if present — used by Row(a < 4) BSI
        dispatch (executor.go:1452)."""
        for k, v in self.args.items():
            if isinstance(v, Condition):
                return k, v
        return None

    def field_arg(self) -> tuple[str, Any] | None:
        """First non-reserved scalar arg: the (field, row) pair of Row/Set
        (ast.go:430)."""
        for k, v in self.args.items():
            if k.startswith("_") or isinstance(v, Condition):
                continue
            return k, v
        return None

    def has_conditions(self) -> bool:
        return any(isinstance(v, Condition) for v in self.args.values())

    def clone(self) -> "Call":
        return Call(
            self.name,
            dict(self.args),
            [c.clone() for c in self.children],
        )

    def __repr__(self):
        parts = [repr(c) for c in self.children]
        parts += [
            (v.string_with_subj(k) if isinstance(v, Condition)
             else f"{k}={_value_string(v)}")
            for k, v in sorted(self.args.items())
        ]
        return f"{self.name}({', '.join(parts)})"


@dataclass
class Query:
    calls: list[Call] = field(default_factory=list)

    def write_calls(self) -> list[Call]:
        return [c for c in self.calls if c.name in WRITE_CALLS]

    def __repr__(self):
        return "".join(repr(c) for c in self.calls)


WRITE_CALLS = {"Set", "Clear", "ClearRow", "Store", "SetRowAttrs",
               "SetColumnAttrs"}
