"""AST <-> JSON wire codec for node-to-node query forwarding.

The reference re-sends the original PQL string with a protobuf QueryRequest
carrying Remote=true + pinned shards (http/client.go:268 QueryNode,
internal/private.proto QueryRequest).  Here the coordinator fans out
*individual calls*, so the call tree is shipped as JSON — no re-parse on
the remote side, and write-call fan-out can pin exactly one call.
"""

from __future__ import annotations

from typing import Any

from .ast import Call, Condition


def _enc_val(v) -> Any:
    if isinstance(v, Condition):
        return {"$cond": [v.op, v.value]}
    return v


def _dec_val(v) -> Any:
    if isinstance(v, dict) and "$cond" in v:
        op, value = v["$cond"]
        return Condition(op, value)
    return v


def call_to_wire(c: Call) -> dict:
    return {
        "name": c.name,
        "args": {k: _enc_val(v) for k, v in c.args.items()},
        "children": [call_to_wire(ch) for ch in c.children],
    }


def call_from_wire(d: dict) -> Call:
    return Call(
        d["name"],
        {k: _dec_val(v) for k, v in d.get("args", {}).items()},
        [call_from_wire(ch) for ch in d.get("children", [])],
    )
