"""Diagnostics reporting (reference diagnostics.go:42-263).

The reference phones home hourly to a hard-coded vendor endpoint; this
rebuild keeps the subsystem but inverts the default: reporting is OFF
unless the operator configures ``diagnostics_endpoint``, and the payload
goes to THEIR endpoint (fleet monitoring), not a vendor's.  The payload
mirrors the reference's anonymized shape: version, platform, uptime,
schema scale, and runtime gauges.
"""

from __future__ import annotations

import json
import platform
import threading
import time
import urllib.request


class DiagnosticsCollector:
    def __init__(self, server, endpoint: str, interval: float = 3600.0):
        self.server = server
        self.endpoint = endpoint
        self.interval = interval
        # lint: allow(wall-clock) — uptime is operator display on the
        # diagnostics report, never a perf measurement
        self.start_time = time.time()
        self._closing = threading.Event()
        self._thread = None

    def payload(self) -> dict:
        """(diagnostics.go:80-151 CheckVersion/logic, minus identifiers)"""
        from .. import __version__

        holder = self.server.holder
        # schema levels mutate under per-object locks; each list()/len()
        # below is a single GIL-atomic snapshot, so concurrent DDL can
        # skew counts but never break iteration
        indexes = list(holder.indexes.values())
        fields = [f for i in indexes for f in list(i.fields.values())]
        n_fields = len(fields)
        n_frags = sum(len(v.fragments) for f in fields
                      for v in list(f.views.values()))
        out = {
            "version": __version__,
            "platform": platform.platform(),
            "python": platform.python_version(),
            # lint: allow(wall-clock) — uptime display; second-scale
            # NTP slew is irrelevant at hour granularity
            "uptimeSeconds": int(time.time() - self.start_time),
            "numIndexes": len(holder.indexes),
            "numFields": n_fields,
            "numFragments": n_frags,
        }
        cluster = self.server.cluster
        if cluster is not None:
            out["numNodes"] = len(cluster.nodes)
            out["replicaN"] = cluster.replica_n
            out["clusterState"] = cluster.state
        # SLOs & alerting (docs/observability.md): active-alert count
        # and the newest flight-recorder bundle stamp, so fleet
        # monitoring sees "this node is paging" without scraping it
        slo = getattr(self.server, "slo", None)
        if slo is not None:
            summary = slo.vars_summary()
            out["activeAlerts"] = len(summary["active"])
            out["alertsFired"] = summary["firedTotal"]
        rec = getattr(self.server, "flightrec", None)
        if rec is not None:
            out["lastBundle"] = rec.snapshot()["last"]
        return out

    def report_once(self) -> bool:
        try:
            body = json.dumps(self.payload()).encode()
            req = urllib.request.Request(
                self.endpoint, data=body, method="POST",
                headers={"Content-Type": "application/json"})
            with urllib.request.urlopen(req, timeout=10) as resp:
                resp.read()
            return True
        except Exception as e:
            # diagnostics must never take the server down, but a
            # misconfigured endpoint must not fail invisibly either
            self.server.logger.error(f"diagnostics report failed: {e}")
            return False

    def open(self):
        if not self.endpoint or self.interval <= 0:
            return  # interval 0 disables, like the other monitors
        self._thread = threading.Thread(target=self._loop, daemon=True)
        self._thread.start()

    def _loop(self):
        while not self._closing.wait(self.interval):
            self.report_once()

    def close(self):
        self._closing.set()
