"""In-process time-series ring (docs/observability.md "Device runtime").

Every point metric the node exports is a single instantaneous value, so
"what happened 90 seconds ago" — the eviction storm, the compile burst —
was unanswerable without external scrape infrastructure.  This ring
keeps the last ``window_s`` seconds of fixed-interval samples of the
runtime's load-bearing gauges and deltas (device budget split, host
stage, admission depth, batcher occupancy, compile/retrace counts, edge
histogram deltas), served as JSON at /debug/timeseries and rendered by
the zero-dependency dashboard at /debug/dashboard.

Interval pacing and inter-sample math use a monotonic clock (``now_fn``,
perf_counter by default — the PR 2 timing discipline; injectable for
fake-clock tests).  Each sample also carries a ``_wall_stamp`` for
display/correlation only, never subtracted (scripts/check.sh lint).

Memory bound: capacity = ceil(window / interval) + 1 samples of one flat
dict each — an always-on default (5 s x 10 min = 121 samples) costs a
few hundred KB, independent of uptime.
"""

from __future__ import annotations

import math
import time
from collections import deque

from .devobs import _wall_stamp
from .locks import make_lock


class TimeSeriesRing:
    """Fixed-interval ring of flat metric samples.

    ``sample(values)`` appends when at least ~one interval has elapsed
    since the last accepted sample (monotonic clock) and returns whether
    it was accepted — callers may over-poll safely; the ring keeps the
    cadence.  ``force=True`` bypasses the gate (tests, epoch marks)."""

    # Accept samples this fraction of an interval early: Event.wait()
    # jitter must not make an on-cadence sampler skip every other tick.
    INTERVAL_SLACK = 0.9

    def __init__(self, interval_s: float = 5.0, window_s: float = 600.0,
                 now_fn=time.perf_counter):
        self.interval_s = max(float(interval_s), 0.001)
        self.window_s = max(float(window_s), self.interval_s)
        self.capacity = max(
            2, int(math.ceil(self.window_s / self.interval_s)) + 1)
        self._ring: deque = deque(maxlen=self.capacity)
        self._lock = make_lock("timeseries")
        self._now = now_fn
        self._t0 = now_fn()
        self._last_t: float | None = None
        self.samples_total = 0

    def sample(self, values: dict, force: bool = False) -> bool:
        t = self._now()
        with self._lock:
            if not force and self._last_t is not None and \
                    t - self._last_t < self.interval_s * self.INTERVAL_SLACK:
                return False
            self._last_t = t
            self.samples_total += 1
            entry = {"wall": _wall_stamp(),
                     "uptimeS": round(t - self._t0, 3)}
            entry.update(values)
            self._ring.append(entry)
        return True

    def window_covered_s(self) -> float:
        """Monotonic span between the oldest and newest retained sample
        — the "how far back can I see" answer."""
        with self._lock:
            if len(self._ring) < 2:
                return 0.0
            return self._ring[-1]["uptimeS"] - self._ring[0]["uptimeS"]

    def last(self, n: int = 1) -> list[dict]:
        with self._lock:
            return list(self._ring)[-n:]

    def snapshot(self) -> dict:
        """/debug/timeseries: config + the ring, oldest first."""
        with self._lock:
            samples = list(self._ring)
            total = self.samples_total
        covered = samples[-1]["uptimeS"] - samples[0]["uptimeS"] \
            if len(samples) >= 2 else 0.0
        return {"intervalS": self.interval_s, "windowS": self.window_s,
                "capacity": self.capacity,
                "samplesTotal": total,
                "coveredS": round(covered, 3),
                "samples": samples}
