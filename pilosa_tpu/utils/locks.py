"""Named lock factories — the adoption point for the lock-order race
detector (pilosa_tpu/analysis/lockcheck.py, docs/static-analysis.md).

Every lock in the project is created here with a lock-CLASS name
(``fragment``, ``holder``, ``budget``, ``committer-flush``, ...).
Unarmed (the default), these return plain ``threading`` primitives —
zero overhead, zero imports beyond threading.  With
``PILOSA_TPU_LOCKCHECK`` set (``1`` to observe, ``strict`` to fail the
process on violations) they return instrumented primitives that feed
the global acquisition-order graph reported at process exit and at
``/debug/locks``.

This module must stay import-light and cycle-free: it is imported by
every lock-using module, including utils/ siblings.
"""

from __future__ import annotations

import os
import threading

LOCKCHECK_MODE = os.environ.get("PILOSA_TPU_LOCKCHECK", "").strip().lower()
ARMED = LOCKCHECK_MODE not in ("", "0", "off")

if ARMED:
    from ..analysis import lockcheck as _lockcheck


def make_lock(cls_name: str):
    """A non-reentrant lock belonging to lock class ``cls_name``."""
    if ARMED:
        return _lockcheck.CheckedLock(cls_name)
    return threading.Lock()


def make_rlock(cls_name: str):
    """A reentrant lock belonging to lock class ``cls_name``."""
    if ARMED:
        return _lockcheck.CheckedRLock(cls_name)
    return threading.RLock()


def make_condition(cls_name: str, rlock: bool = False):
    """A Condition over a named lock (``rlock=True`` for the
    threading.Condition() default of a reentrant inner lock)."""
    if ARMED:
        return _lockcheck.checked_condition(cls_name, rlock=rlock)
    return threading.Condition(
        threading.RLock() if rlock else threading.Lock())


def report() -> dict:
    """The /debug/locks payload; cheap stub when unarmed."""
    if ARMED:
        return _lockcheck.report()
    return {"mode": LOCKCHECK_MODE or "off", "armed": False,
            "lockClasses": [], "edges": [], "violations": []}
